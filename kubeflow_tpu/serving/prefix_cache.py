"""Host-side prefix trie for the device-resident prefix KV pool.

The continuous decoder keeps the K/V rows of frequently-shared prompt
prefixes (system prompts, few-shot templates) in a fixed-capacity device
pool (:func:`kubeflow_tpu.models.decode.init_prefix_pool`); this module is
the host half: a trie keyed on token prefixes that maps a new prompt to
the deepest reusable pool slot, with LRU eviction and per-entry refcounts
so a prefix an in-flight admission still reads is never evicted under it.

Correctness hinges on causality: the K/V rows at positions ``0..d-1``
depend only on tokens ``0..d-1``, so ANY entry whose key starts with the
first ``d`` prompt tokens serves a ``d``-length prefix from its pool
slot's first ``d`` rows — the trie therefore matches through *interior*
nodes (every node knows the entries passing through it), not only at
entry terminals. That is what makes N requests sharing a system prompt
hit even though each published key diverges after the shared part.

Pure host logic — no jax imports — so the trie is unit-testable without a
device and safe to mutate under the decoder's prefix lock.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(eq=False)  # identity hash: entries live in per-node sets
class PrefixEntry:
    """One cached prefix: ``key`` tokens occupy pool row ``slot`` (dense
    layout) or the refcounted pool blocks ``blocks`` (paged layout —
    ``slot`` is then just a capacity token)."""

    key: tuple[int, ...]
    slot: int
    refs: int = 0       # in-flight admissions reading this slot
    last_used: int = 0  # LRU clock tick
    blocks: tuple[int, ...] | None = None  # paged: KV blocks held
    # Weights epoch the cached K/V was computed under: a live weight
    # push (ContinuousDecoder.update_weights) bumps the decoder's
    # version, and entries stamped with an older one are stale — their
    # bytes answer a model that no longer serves. The decoder refuses
    # and removes stale matches; the cache itself stays version-blind.
    version: int = 0

    def __len__(self) -> int:
        return len(self.key)


@dataclass
class _Node:
    children: dict[int, "_Node"] = field(default_factory=dict)
    # Entries whose key passes through this node (so an interior node can
    # answer "is the path below me cached somewhere?").
    entries: set = field(default_factory=set)


class PrefixCache:
    """Trie + LRU bookkeeping over a fixed number of device pool slots.

    The decoder owns the device pool; this class only decides *which* slot
    serves or receives a prefix. All methods are host-side and O(len(key));
    callers serialize access (the decoder's prefix lock).
    """

    def __init__(self, slots: int, *, min_len: int = 1):
        if slots <= 0:
            raise ValueError("PrefixCache needs at least one slot")
        self.slots = slots
        self.min_len = max(1, int(min_len))
        self._root = _Node()
        self._by_key: dict[tuple[int, ...], PrefixEntry] = {}
        self._free = list(range(slots - 1, -1, -1))
        self._clock = 0
        self.evictions = 0
        # Paged layout: called with the entry on every remove() so its
        # refcounted pool blocks return to the allocator. Fires under
        # whatever lock the caller serializes the cache with — the hook
        # must not re-acquire it.
        self.on_evict = None

    def __len__(self) -> int:
        return len(self._by_key)

    def _tick(self, entry: PrefixEntry) -> None:
        self._clock += 1
        entry.last_used = self._clock

    # -- lookup --------------------------------------------------------

    def match(self, tokens: list[int]) -> tuple[PrefixEntry, int] | None:
        """Longest cached prefix of ``tokens`` usable for suffix prefill.

        Returns ``(entry, depth)`` — reuse the first ``depth`` rows of
        ``entry.slot`` — or None. ``depth`` is capped at ``len(tokens)-1``
        (at least one suffix token must remain to prefill: the last
        prompt position's logits seed generation) and floored at
        ``min_len`` (shorter reuse costs more bookkeeping than prefill).
        The entry is PINNED (refcount +1); callers release() when the
        admission that read the slot has finished.
        """
        node = self._root
        depth = 0
        best: tuple[_Node, int] | None = None
        for tok in tokens[: max(len(tokens) - 1, 0)]:
            child = node.children.get(tok)
            if child is None or not child.entries:
                break
            node = child
            depth += 1
            best = (node, depth)
        if best is None or best[1] < self.min_len:
            return None
        node, depth = best
        entry = max(node.entries, key=lambda e: e.last_used)
        entry.refs += 1
        self._tick(entry)
        return entry, depth

    def has(self, key: tuple[int, ...]) -> bool:
        return tuple(key) in self._by_key

    def touch(self, key: tuple[int, ...]) -> None:
        entry = self._by_key.get(tuple(key))
        if entry is not None:
            self._tick(entry)

    def release(self, entry: PrefixEntry) -> None:
        entry.refs = max(0, entry.refs - 1)

    # -- insert / evict ------------------------------------------------

    def reserve(self, key: tuple[int, ...]) -> PrefixEntry | None:
        """Claim a pool slot for a NEW prefix ``key``.

        Returns the entry whose ``slot`` the caller must now fill on
        device, or None when the key is already cached (its LRU stamp is
        refreshed) or every slot is pinned by an in-flight admission.
        """
        key = tuple(key)
        if len(key) < self.min_len:
            return None
        existing = self._by_key.get(key)
        if existing is not None:
            self._tick(existing)
            return None
        if self._free:
            slot = self._free.pop()
        else:
            victim = self._lru_unpinned()
            if victim is None:
                return None
            self.remove(victim)
            self.evictions += 1
            slot = self._free.pop()
        entry = PrefixEntry(key=key, slot=slot)
        self._tick(entry)
        self._by_key[key] = entry
        node = self._root
        node.entries.add(entry)
        for tok in key:
            node = node.children.setdefault(tok, _Node())
            node.entries.add(entry)
        return entry

    def _lru_unpinned(self) -> PrefixEntry | None:
        candidates = [e for e in self._by_key.values() if e.refs == 0]
        if not candidates:
            return None
        return min(candidates, key=lambda e: e.last_used)

    def entries(self) -> list[PrefixEntry]:
        """Snapshot of every live entry (the weight-swap stale flush
        iterates it; callers hold the same lock as every other call)."""
        return list(self._by_key.values())

    def evict_lru(self) -> bool:
        """Evict the least-recently-used UNPINNED entry (memory-pressure
        reclaim, counted as an eviction). Returns False when every entry
        is pinned or the cache is empty."""
        victim = self._lru_unpinned()
        if victim is None:
            return False
        self.remove(victim)
        self.evictions += 1
        return True

    def remove(self, entry: PrefixEntry) -> None:
        """Drop ``entry`` from the trie and return its slot to the free
        list (explicit removal; eviction accounting is reserve()'s)."""
        if self._by_key.pop(entry.key, None) is None:
            return
        if self.on_evict is not None:
            self.on_evict(entry)
        node = self._root
        node.entries.discard(entry)
        path = [node]
        for tok in entry.key:
            node = node.children.get(tok)
            if node is None:
                break
            node.entries.discard(entry)
            path.append(node)
        # Prune now-empty branches so the trie doesn't grow monotonically.
        for parent, tok in zip(reversed(path[:-1]), reversed(entry.key)):
            child = parent.children.get(tok)
            if child is not None and not child.entries \
                    and not child.children:
                del parent.children[tok]
        self._free.append(entry.slot)
