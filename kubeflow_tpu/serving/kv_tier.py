"""Host-RAM tier of the paged KV cache (HBM -> host demotion).

HBM is the scarce resource (PAPERS.md, "Fine-Tuning and Serving Gemma
on Cloud TPU"); host RAM is plentiful next to it. This module is the
host half of the tiered KV cache: a bounded-byte LRU store of exported
block payloads (the PR-9 handoff arrays, pointed at host memory instead
of a peer), keyed by the token prefix the blocks back.

Two producers feed it:

- **Demotion**: prefix-trie eviction exports the entry's blocks here
  before freeing them, so memory pressure demotes instead of destroys —
  a later trie miss that finds its prefix here re-imports the blocks
  through the ordinary prefix-hit admission (the "second chance" that
  raises effective pool size past HBM at equal device bytes).
- **Suspension**: under low-watermark pressure the decoder exports the
  lowest-priority live stream's KV here (PINNED — a suspended stream's
  bytes must survive until resume, byte-identity depends on them),
  frees its slot and blocks, and parks the request for re-admission.

Payloads are verbatim device bytes (fp arrays or int8 codes+scales), so
promotion is exact by construction — imported blocks are never
recomputed or re-quantized.

Pure host logic — numpy payloads, no jax — callers serialize access
(the decoder's prefix lock), same contract as PrefixCache.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field


def payload_nbytes(payload: dict) -> int:
    """Host bytes a handoff payload occupies (fp ``{"k","v"}`` arrays
    or int8 ``{"q","scale"}`` dicts per side)."""
    total = 0
    stack = [payload]
    while stack:
        node = stack.pop()
        if isinstance(node, dict):
            stack.extend(node.values())
        else:
            total += int(node.nbytes)
    return total


@dataclass
class TierEntry:
    key: tuple[int, ...]
    payload: dict
    prefix_len: int
    nbytes: int
    pinned: bool = False
    last_used: int = field(default=0)
    # Wall-clock of the last touch — the eviction-age histogram's input
    # (how long demoted bytes sat unreferenced before pressure dropped
    # them: the signal for sizing the tier and the cold store under it).
    touched_t: float = field(default=0.0)
    # Weights epoch the payload's K/V was computed under (same contract
    # as PrefixEntry.version): a demoted payload from before a live
    # weight swap must never feed a fresh request's promotion. Pinned
    # suspended-stream payloads are exempt — they ARE the stream's
    # state, and the stream straddles the swap by design.
    version: int = 0


class HostKvTier:
    """Bounded-byte LRU over exported KV payloads, with pins.

    ``capacity_bytes`` bounds the host RAM spent; an insert evicts LRU
    UNPINNED entries until it fits, and refuses (returns False) when
    pinned bytes alone leave no room — the caller then simply loses the
    second chance (demotion) or declines to suspend (suspension checks
    :meth:`can_fit` first).
    """

    def __init__(self, capacity_bytes: int):
        if capacity_bytes <= 0:
            raise ValueError("HostKvTier needs a positive byte budget")
        self.capacity_bytes = int(capacity_bytes)
        self.bytes_in_use = 0
        self.pinned_bytes = 0
        self.high_water_bytes = 0  # peak bytes_in_use (occupancy gauge)
        self.evictions = 0
        self.demotions = 0   # puts from trie eviction / suspension
        self.promotions = 0  # gets that fed a device re-import
        self._by_key: dict[tuple[int, ...], TierEntry] = {}
        self._clock = 0
        # Eviction hooks, fired from _evict_lru under the CALLER's
        # serialization (the decoder's prefix lock) — so they must stay
        # CPU-only and must not call back into this tier:
        # ``on_evict(entry)`` is the fleet economy's demote-to-cold
        # path (pack the payload, publish the directory hint, BEFORE
        # the bytes drop); ``eviction_age_observe(seconds)`` feeds the
        # eviction-age histogram.
        self.on_evict = None
        self.eviction_age_observe = None

    def __len__(self) -> int:
        return len(self._by_key)

    def _tick(self, entry: TierEntry) -> None:
        self._clock += 1
        entry.last_used = self._clock
        entry.touched_t = time.monotonic()

    def has(self, key: tuple[int, ...]) -> bool:
        return tuple(key) in self._by_key

    def can_fit(self, nbytes: int) -> bool:
        """Would ``nbytes`` fit after evicting every unpinned entry?"""
        return self.pinned_bytes + int(nbytes) <= self.capacity_bytes

    # -- insert / evict ------------------------------------------------

    def put(self, key, payload: dict, prefix_len: int, *,
            pinned: bool = False, version: int = 0) -> bool:
        """Store ``payload`` under ``key`` (evicting LRU unpinned
        entries to fit). Returns False when it cannot fit. Re-putting
        an existing key refreshes it (and may pin it). ``version``
        stamps the weights epoch the bytes were computed under."""
        key = tuple(key)
        nbytes = payload_nbytes(payload)
        old = self._by_key.get(key)
        if old is not None:
            self._drop(old)
        if not self.can_fit(nbytes):
            return False
        while self.bytes_in_use + nbytes > self.capacity_bytes:
            if not self._evict_lru():
                return False
        entry = TierEntry(key=key, payload=payload,
                          prefix_len=int(prefix_len), nbytes=nbytes,
                          pinned=pinned, version=int(version))
        self._tick(entry)
        self._by_key[key] = entry
        self.bytes_in_use += nbytes
        self.high_water_bytes = max(self.high_water_bytes,
                                    self.bytes_in_use)
        if pinned:
            self.pinned_bytes += nbytes
        self.demotions += 1
        return True

    def _drop(self, entry: TierEntry) -> None:
        del self._by_key[entry.key]
        self.bytes_in_use -= entry.nbytes
        if entry.pinned:
            self.pinned_bytes -= entry.nbytes

    def _evict_lru(self) -> bool:
        victims = [e for e in self._by_key.values() if not e.pinned]
        if not victims:
            return False
        victim = min(victims, key=lambda e: e.last_used)
        if self.on_evict is not None:
            # Demote-before-drop: the hook (cold-store pack + directory
            # publish) sees the payload while the bytes still exist.
            # Hook failures must not wedge the eviction — losing the
            # cold copy degrades one future miss to a prefill.
            try:
                self.on_evict(victim)
            except Exception:
                pass
        self._drop(victim)
        self.evictions += 1
        if self.eviction_age_observe is not None and victim.touched_t:
            try:
                self.eviction_age_observe(
                    max(0.0, time.monotonic() - victim.touched_t))
            except Exception:
                pass
        return True

    def note_promotion(self) -> None:
        """Count a successful device re-import fed by this tier."""
        self.promotions += 1

    def discard(self, key) -> None:
        """Remove ``key`` outright (a failed/suspended stream died —
        its pinned bytes must drain, not linger until LRU pressure)."""
        entry = self._by_key.get(tuple(key))
        if entry is not None:
            self._drop(entry)

    def entries(self) -> list[TierEntry]:
        """Snapshot of every stored entry (the weight-swap stale flush
        iterates it; callers serialize as with every other method)."""
        return list(self._by_key.values())

    def unpin(self, key) -> None:
        """Make a suspended stream's payload ordinary LRU cache again
        (resume installed it on device; the copy here is now just a
        second chance)."""
        entry = self._by_key.get(tuple(key))
        if entry is not None and entry.pinned:
            entry.pinned = False
            self.pinned_bytes -= entry.nbytes

    # -- lookup --------------------------------------------------------

    def get(self, key) -> TierEntry | None:
        entry = self._by_key.get(tuple(key))
        if entry is not None:
            self._tick(entry)
        return entry

    def match(self, tokens,
              version: int | None = None) -> tuple[TierEntry, int] | None:
        """Deepest stored payload serving a prefix of ``tokens``:
        returns ``(entry, depth)`` — the first ``depth`` positions of
        ``entry.payload`` back ``tokens[:depth]`` — or None.
        ``version`` (when given) skips entries stamped with a different
        weights epoch: a fresh request must never promote KV computed
        under weights the decoder no longer serves; a resuming
        suspended stream passes None (its payload IS its state).

        Causality makes any SHORTER depth of a stored payload valid
        too (position ``i`` depends only on tokens ``0..i``), so an
        entry whose key merely shares a leading run with the prompt
        still serves that run — the same interior matching the trie
        does, which is what lets the prompt that PUBLISHED a prefix
        hit its own demoted payload again. Depth is capped at
        ``len(tokens) - 1`` (one suffix token must remain to prefill).
        """
        cap = len(tokens) - 1
        best: tuple[TierEntry, int] | None = None
        for entry in self._by_key.values():
            if version is not None and entry.version != version:
                continue
            lim = min(entry.prefix_len, cap)
            if best is not None and lim <= best[1]:
                continue
            key, d = entry.key, 0
            while d < lim and key[d] == tokens[d]:
                d += 1
            if d and (best is None or d > best[1]):
                best = (entry, d)
        if best is not None:
            self._tick(best[0])
        return best
