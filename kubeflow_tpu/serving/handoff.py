"""Prompt-KV handoff payloads for disaggregated prefill/decode serving.

A prefill replica computes a prompt's KV once and hands the finished
blocks to a decode replica (models/decode.py:export_blocks /
import_blocks); this module is the host-side envelope around that
transfer:

- in process (DecoderFleet), the handoff dict travels as plain numpy
  arrays — zero copies beyond the device→host fetch;
- across the HTTP fleet (the gateway's two-hop relay), :func:`pack`
  base64-encodes each array into a JSON-safe dict and :func:`unpack`
  restores it, with shapes/dtypes carried explicitly so a corrupt or
  mismatched payload fails loudly at the boundary instead of scattering
  junk into the receiving pool.

The payload layout mirrors the block pool: fp pools ship ``{"k", "v"}``
arrays ``[L, nblk, Bs, H, hd]``; int8 pools ship ``{"q", "scale"}`` per
side — codes and scales travel together, so a quantized handoff is
exact (the importer never re-quantizes).

Pure host logic — numpy only, no jax — importable by the gateway
without touching the serving stack's device deps.
"""

from __future__ import annotations

import base64

import numpy as np

# Envelope schema version: receivers reject anything newer rather than
# guess at a layout (a silent mis-parse would corrupt a KV pool).
# Version 2 added the exporter's mesh shape (``mesh.tpShards``); the
# payload itself stayed host-global — export_blocks device_gets the
# SHARDED pool into one full-KV-head host array, so a sharded export is
# already gathered and any mesh shape can import it (the importer's
# device_put with its own pool sharding IS the reshard). Version-1
# envelopes (no mesh field) therefore stay importable: they are exactly
# a tp=1 export.
HANDOFF_VERSION = 2
_ACCEPTED_VERSIONS = (1, 2)


def _np_dtype(name: str) -> np.dtype:
    """Resolve a dtype NAME (not struct string — ``bfloat16`` has no
    portable struct code) — accelerator dtypes come from ml_dtypes."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # jax dependency, always present here

        return np.dtype(getattr(ml_dtypes, name))


def _pack_array(arr) -> dict:
    a = np.asarray(arr)
    return {
        "dtype": a.dtype.name,
        "shape": list(a.shape),
        "data": base64.b64encode(np.ascontiguousarray(a).tobytes())
        .decode("ascii"),
    }


def _unpack_array(d: dict) -> np.ndarray:
    if not isinstance(d, dict) or "data" not in d:
        raise ValueError("malformed handoff array")
    raw = base64.b64decode(d["data"])
    arr = np.frombuffer(raw, dtype=_np_dtype(d["dtype"]))
    return arr.reshape([int(s) for s in d["shape"]])


def _map_tree(tree, fn):
    if isinstance(tree, dict) and not ("dtype" in tree or "data" in tree
                                      or hasattr(tree, "shape")):
        return {k: _map_tree(v, fn) for k, v in tree.items()}
    return fn(tree)


def pack(handoff: dict) -> dict:
    """JSON-safe envelope for a decoder ``export_prompt`` result: the
    block payload's arrays (k/v, or k.q/k.scale/... when quantized)
    become base64 strings; tokens/prefix_len/block metadata — and the
    exporter's mesh shape — ride alongside for receiver-side
    validation."""
    payload = handoff["payload"]

    def _enc(node):
        if isinstance(node, dict):  # quantized side: {"q", "scale"}
            return {k: _pack_array(v) for k, v in node.items()}
        return _pack_array(node)

    return {
        "version": HANDOFF_VERSION,
        "tokens": [int(t) for t in handoff["tokens"]],
        "prefix_len": int(handoff["prefix_len"]),
        "block_size": int(handoff["block_size"]),
        "kv_dtype": handoff["kv_dtype"],
        # The exporter's mesh shape. Informational for the importer —
        # the payload arrives host-gathered across every mesh shape —
        # but a future envelope that ships per-shard payloads would bump
        # the version, and dashboards read it to attribute handoffs.
        "mesh": {"tpShards": int(handoff.get("tp_shards", 1) or 1),
                 "cpShards": int(handoff.get("cp_shards", 1) or 1),
                 "ppStages": int(handoff.get("pp_stages", 1) or 1)},
        "payload": {side: _enc(payload[side]) for side in ("k", "v")},
    }


def unpack(env: dict) -> dict:
    """Inverse of :func:`pack`. Raises ``ValueError`` on a malformed or
    version-mismatched envelope — the decode server answers that with a
    4xx (and the fleet path degrades to a plain submit) instead of
    importing garbage. Version-1 envelopes (pre-mesh) unpack as tp=1
    exports; the payload layout never changed."""
    if not isinstance(env, dict) or env.get("version") not in \
            _ACCEPTED_VERSIONS:
        raise ValueError(
            f"unsupported handoff envelope "
            f"version={env.get('version') if isinstance(env, dict) else env!r}")
    payload = env.get("payload")
    if not isinstance(payload, dict) or set(payload) != {"k", "v"}:
        raise ValueError("handoff payload must carry 'k' and 'v'")
    mesh = env.get("mesh") or {}
    if not isinstance(mesh, dict):
        raise ValueError("handoff mesh field must be an object")

    def _dec(node):
        if isinstance(node, dict) and "data" not in node:
            return {k: _unpack_array(v) for k, v in node.items()}
        return _unpack_array(node)

    return {
        "tokens": [int(t) for t in env["tokens"]],
        "prefix_len": int(env["prefix_len"]),
        "block_size": int(env["block_size"]),
        "kv_dtype": str(env.get("kv_dtype", "fp")),
        "tp_shards": int(mesh.get("tpShards", 1) or 1),
        "cp_shards": int(mesh.get("cpShards", 1) or 1),
        "pp_stages": int(mesh.get("ppStages", 1) or 1),
        "payload": {side: _dec(payload[side]) for side in ("k", "v")},
    }
