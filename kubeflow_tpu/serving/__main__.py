"""CLI: `python -m kubeflow_tpu.serving --model-name ... --rest-port 8500`.

The container entrypoint the tpu-serving manifest runs
(kubeflow_tpu/manifests/packages/serving.py args)."""

from __future__ import annotations

import argparse

from kubeflow_tpu.serving.engine import EngineConfig
from kubeflow_tpu.serving.server import ModelServer


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--model-name", required=True,
                   help="registry model name (kubeflow_tpu.models)")
    p.add_argument("--model-path", default="",
                   help="checkpoint dir (empty = fresh init, benchmarking)")
    p.add_argument("--rest-port", type=int, default=8500)
    p.add_argument("--grpc-port", type=int, default=9000,
                   help="gRPC predict port (tf-serving :9000 contract); "
                        "-1 disables")
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--batch-timeout-ms", type=float, default=5.0)
    p.add_argument("--max-seq-len", type=int, default=128)
    p.add_argument("--max-new-tokens", type=int, default=16,
                   help="per-request generation cap (0 disables the "
                        "decode path entirely)")
    p.add_argument("--top-k", type=int, default=0)
    p.add_argument("--eos-id", type=int, default=-1,
                   help="token id ending a generation early; -1 disables")
    p.add_argument("--decode-mode", default="continuous",
                   choices=["continuous", "lockstep"],
                   help="continuous: per-request lengths decoupled + "
                        "streaming; lockstep: one compiled call per batch")
    p.add_argument("--decode-chunk", type=int, default=1,
                   help="decode steps fused per device dispatch in "
                        "continuous mode; set ~max-new-tokens on "
                        "high-RTT links")
    p.add_argument("--prefix-cache-slots", type=int, default=0,
                   help="device prefix-KV pool slots for reuse of shared "
                        "prompt prefixes (0 disables); matching prompts "
                        "prefill only their suffix")
    p.add_argument("--prefix-cache-min-len", type=int, default=16,
                   help="shortest prefix worth caching/matching")
    p.add_argument("--prefill-len-buckets", type=int, default=0,
                   help="power-of-two prefill length buckets below "
                        "max-seq-len (0 = pad every prompt to "
                        "max-seq-len)")
    p.add_argument("--speculative-k", type=int, default=0,
                   help="draft tokens verified per fused decode dispatch "
                        "(0 disables speculative decoding); greedy "
                        "outputs are unchanged, throughput multiplies "
                        "with the acceptance rate")
    p.add_argument("--draft-mode", default="ngram",
                   help="speculative draft proposer: 'ngram' (host-side "
                        "prompt/output lookup, zero device cost) or "
                        "'model:<registry-name>' (small draft model)")
    p.add_argument("--kv-layout", default="dense",
                   choices=["dense", "paged"],
                   help="continuous-mode KV layout: 'dense' reserves a "
                        "full-length row per decode slot; 'paged' backs "
                        "requests with fixed-size blocks from a shared "
                        "pool — admission bounded by memory, zero-copy "
                        "prefix sharing, byte-identical greedy outputs")
    p.add_argument("--kv-block-size", type=int, default=16,
                   help="tokens per KV block (paged layout); must divide "
                        "max-seq-len + max-new-tokens")
    p.add_argument("--kv-pool-blocks", type=int, default=0,
                   help="physical blocks in the paged pool (0 = "
                        "dense-parity sizing: batch-size sequences at "
                        "worst case)")
    p.add_argument("--kv-dtype", default="fp", choices=["fp", "int8"],
                   help="paged KV residency precision: 'fp' keeps the "
                        "model dtype (bitwise-parity default); 'int8' "
                        "quantizes blocks with per-position per-head "
                        "scales — ~2x blocks per HBM byte within a "
                        "pinned greedy-token tolerance")
    p.add_argument("--kv-fused-attention", action="store_true",
                   help="fuse the paged decode read into a block-table "
                        "attention kernel (no dense KV gather per step; "
                        "int8 dequantized in-register); numerics are "
                        "f32-equivalent, not bitwise")
    p.add_argument("--serving-role", default="",
                   choices=["", "prefill", "decode"],
                   help="disaggregated-fleet role: 'prefill' runs "
                        "prompt admission only (decode peers pull "
                        "finished prompt KV via :prefill/:import), "
                        "'decode' resumes imported prompts; empty = "
                        "colocated. Requires --kv-layout=paged")
    p.add_argument("--tp-shards", type=int, default=1,
                   help="tensor-parallel shards per replica (continuous "
                        "mode): >1 runs the decoder over a tp-wide "
                        "tensor mesh — weights Megatron-split, the KV "
                        "pool sharded over the KV-head axis; must "
                        "divide the model's kv heads / heads / d_ff "
                        "and the pod needs that many chips")
    p.add_argument("--prefill-chunk-tokens", type=int, default=0,
                   help="chunked prefill (continuous mode, paged "
                        "layout): admit prompts longer than this as a "
                        "chain of bounded chunk dispatches interleaved "
                        "with decode rounds, so a long admission never "
                        "stalls live streams for more than one chunk "
                        "of prefill compute; 0 disables (monolithic "
                        "admission). Token streams stay byte-identical")
    p.add_argument("--max-prompt-len", type=int, default=0,
                   help="prompt-length ceiling (0 = max-seq-len). "
                        "Raising it past max-seq-len requires "
                        "--prefill-chunk-tokens: chunks ride the paged "
                        "block scatter, so only the KV row bounds the "
                        "prompt. Longer prompts are rejected with 413, "
                        "never truncated")
    p.add_argument("--cp-shards", type=int, default=1,
                   help="context-parallel shards: >1 runs each prefill "
                        "chunk's attention ring-style over a sequence "
                        "mesh axis — long-prompt prefill FLOPs scale "
                        "with cp while decode stays tp-only; requires "
                        "--prefill-chunk-tokens and the paged gather "
                        "path; the pod needs tp*cp*pp chips")
    p.add_argument("--pp-stages", type=int, default=1,
                   help="pipeline-parallel decoder stages: >1 shards "
                        "the layer stack AND the KV pool's layer dim "
                        "over a pipeline mesh axis (per-chip weight + "
                        "KV bytes divide by pp); must divide the "
                        "model's n_layers; the pod needs tp*cp*pp "
                        "chips")
    p.add_argument("--host-kv-bytes", type=int, default=0,
                   help="host-RAM KV tier budget in bytes (paged "
                        "layout; 0 disables): prefix evictions demote "
                        "blocks to host memory instead of freeing, "
                        "misses re-import them (second-chance cache), "
                        "and QoS suspensions park live streams' KV "
                        "there until resume")
    p.add_argument("--kv-directory-size", type=int, default=0,
                   help="fleet KV economy: distinct prefix affinity "
                        "keys the prefix->holder directory tracks "
                        "(paged layout; 0 disables). Local misses "
                        "probe directory hints and pull the deepest "
                        "advertised prefix from the holding peer over "
                        "the :kv handoff endpoint, prefilling only "
                        "the tail")
    p.add_argument("--cold-store-ref", default="",
                   help="shared cold content-addressed KV store "
                        "('mem://<name>[?bytes=<n>]'; empty disables): "
                        "host-tier evictions demote payloads there "
                        "before dropping bytes; the weights epoch "
                        "rides the content key, so a live weight push "
                        "invalidates pre-swap blobs by construction")
    p.add_argument("--kv-import-crossover-tokens", type=int, default=0,
                   help="minimum prefill tokens a peer/cold import "
                        "must save over the best local tier before "
                        "the pull is worth its fixed cost; 0 imports "
                        "any strictly deeper match")
    p.add_argument("--qos-tenants", default="",
                   help="multi-tenant QoS spec: 'name=weight[:rate"
                        "[:burst[:priority]]]' comma-separated (empty "
                        "disables QoS); requests carry X-Tenant/"
                        "X-Priority/X-Deadline-Ms headers, buckets "
                        "answer 429 + Retry-After, the pop loop "
                        "orders by weighted fair share + aged "
                        "priority")
    p.add_argument("--qos-aging-s", type=float, default=30.0,
                   help="seconds of queue wait worth one priority "
                        "point (starvation aging; <=0 disables)")
    p.add_argument("--compile-cache-dir", default="",
                   help="persistent compile-cache directory (empty "
                        "disables): a newborn replica replays the "
                        "fingerprint-matched serialized executables "
                        "for its whole decode dispatch set instead of "
                        "cold-compiling it, and records its own "
                        "compiles for the next birth")
    p.add_argument("--weight-peers", default="",
                   help="comma-separated host:port donors to pull the "
                        "boot weights from over :pull (tried in "
                        "order, checkpoint fallback; empty boots from "
                        "the checkpoint)")
    p.add_argument("--weight-pull-timeout-s", type=float, default=30.0,
                   help="per-donor budget for the boot-time weight "
                        "pull before trying the next donor")
    p.add_argument("--stream-timeout-s", type=float, default=60.0,
                   help="default wait for generation results/streams; "
                        "raise under heavy load so memory-deferred "
                        "admissions don't time callers out")
    p.add_argument("--dtype", default="",
                   choices=["", "bfloat16", "float32"],
                   help="compute dtype override; empty keeps the model "
                        "preset's dtype")
    # Metrics are always served at /monitoring/prometheus/metrics; the
    # flag exists so the rendered manifest args stay valid
    # (tf-serving-template.libsonnet enablePrometheus parity).
    p.add_argument("--enable-prometheus", action="store_true")
    args = p.parse_args(argv)
    if args.eos_id >= 0 and args.decode_mode != "continuous":
        # Only the continuous decoder implements early stop; silently
        # generating past EOS would return post-EOS garbage.
        p.error("--eos-id requires --decode-mode=continuous")
    if args.prefix_cache_slots > 0 and args.decode_mode != "continuous":
        # Only the continuous decoder carries the prefix pool; silently
        # ignoring the flag would report cache-off numbers as cache-on.
        p.error("--prefix-cache-slots requires --decode-mode=continuous")
    if args.speculative_k > 0 and args.decode_mode != "continuous":
        # Verification rides the continuous decode state; silently
        # ignoring the flag would report plain-decode numbers as
        # speculative ones.
        p.error("--speculative-k requires --decode-mode=continuous")
    if not (args.draft_mode == "ngram"
            or args.draft_mode.startswith("model:")):
        p.error("--draft-mode must be 'ngram' or 'model:<name>'")
    if args.kv_dtype != "fp" and args.kv_layout != "paged":
        # Quantized residency exists only in the block pool; silently
        # ignoring the flag would report fp memory numbers as int8 ones.
        p.error("--kv-dtype=int8 requires --kv-layout=paged")
    if args.kv_fused_attention and args.kv_layout != "paged":
        # The fused kernel reads through the block table; dense rows
        # have no table to walk.
        p.error("--kv-fused-attention requires --kv-layout=paged")
    if args.serving_role and args.kv_layout != "paged":
        # The prefill→decode handoff rides the paged block pool; a
        # dense replica has no blocks to export or import.
        p.error("--serving-role requires --kv-layout=paged")
    if args.tp_shards < 1:
        p.error("--tp-shards must be >= 1")
    if args.cp_shards < 1:
        p.error("--cp-shards must be >= 1")
    if args.pp_stages < 1:
        p.error("--pp-stages must be >= 1")
    if args.prefill_chunk_tokens < 0:
        p.error("--prefill-chunk-tokens must be >= 0")
    if args.max_prompt_len < 0:
        p.error("--max-prompt-len must be >= 0")
    if args.prefill_chunk_tokens and args.kv_layout != "paged":
        # Chunks scatter through the block table; dense rows have no
        # table to scatter through.
        p.error("--prefill-chunk-tokens requires --kv-layout=paged")
    if (args.max_prompt_len > args.max_seq_len
            and not args.prefill_chunk_tokens):
        # Monolithic prefill is bounded by the compiled width; silently
        # accepting the flag would 413 every long prompt anyway.
        p.error("--max-prompt-len beyond max-seq-len requires "
                "--prefill-chunk-tokens")
    if args.cp_shards > 1 and not args.prefill_chunk_tokens:
        # The sequence axis only carries chunked-prefill attention;
        # silently ignoring the flag would report tp-only numbers as
        # context-parallel ones.
        p.error("--cp-shards requires --prefill-chunk-tokens")
    if args.cp_shards > 1 and args.kv_fused_attention:
        p.error("--cp-shards uses the gathered ring read; drop "
                "--kv-fused-attention")
    if args.pp_stages > 1 and args.decode_mode != "continuous":
        p.error("--pp-stages requires --decode-mode=continuous")
    if args.host_kv_bytes < 0:
        p.error("--host-kv-bytes must be >= 0")
    if args.host_kv_bytes and args.kv_layout != "paged":
        # The tier stores exported BLOCK payloads; dense rows have no
        # blocks to demote or re-import.
        p.error("--host-kv-bytes requires --kv-layout=paged")
    if args.kv_directory_size < 0:
        p.error("--kv-directory-size must be >= 0")
    if args.kv_import_crossover_tokens < 0:
        p.error("--kv-import-crossover-tokens must be >= 0")
    if ((args.kv_directory_size or args.cold_store_ref)
            and args.kv_layout != "paged"):
        # The economy imports land through the paged scatter; dense
        # rows have no block pool to install a pulled prefix into.
        p.error("--kv-directory-size/--cold-store-ref require "
                "--kv-layout=paged")
    if args.cold_store_ref:
        from kubeflow_tpu.serving.cold_store import cold_store_from_ref

        try:
            cold_store_from_ref(args.cold_store_ref)
        except ValueError as e:
            # A typo'd store URL must fail the rollout at flag-parse
            # time, not serve silently without its cold tier.
            p.error(f"--cold-store-ref: {e}")
    if args.qos_tenants:
        if args.decode_mode != "continuous":
            # QoS ordering lives in the continuous pop loop; silently
            # ignoring the flag would serve FIFO while the operator
            # believes fair-share is on.
            p.error("--qos-tenants requires --decode-mode=continuous")
        from kubeflow_tpu.serving.qos import parse_tenants

        try:
            parse_tenants(args.qos_tenants)
        except ValueError as e:
            p.error(f"--qos-tenants: {e}")
    if args.tp_shards > 1 and args.decode_mode != "continuous":
        # Only the continuous decoder builds the tensor mesh; silently
        # ignoring the flag would report single-chip numbers as
        # model-parallel ones.
        p.error("--tp-shards requires --decode-mode=continuous")
    if args.kv_layout == "paged":
        if args.decode_mode != "continuous":
            # Only the continuous decoder carries the block pool;
            # silently ignoring the flag would report dense numbers as
            # paged ones.
            p.error("--kv-layout=paged requires --decode-mode=continuous")
        if args.kv_block_size <= 0:
            p.error("--kv-block-size must be positive")
        total = ((args.max_prompt_len or args.max_seq_len)
                 + args.max_new_tokens)
        if total % args.kv_block_size:
            # Fail at flag-parse time, not at the first generation
            # request (the decoder is built lazily).
            p.error(f"--kv-block-size {args.kv_block_size} must divide "
                    f"max-prompt-len + max-new-tokens = {total}")

    server = ModelServer(
        EngineConfig(
            model=args.model_name,
            checkpoint_dir=args.model_path or None,
            batch_size=args.batch_size,
            max_seq_len=args.max_seq_len,
            max_new_tokens=args.max_new_tokens,
            top_k=args.top_k,
            eos_id=None if args.eos_id < 0 else args.eos_id,
            decode_mode=args.decode_mode,
            decode_chunk=args.decode_chunk,
            prefix_cache_slots=args.prefix_cache_slots,
            prefix_cache_min_len=args.prefix_cache_min_len,
            prefill_len_buckets=args.prefill_len_buckets,
            speculative_k=args.speculative_k,
            draft_mode=args.draft_mode,
            kv_layout=args.kv_layout,
            kv_block_size=args.kv_block_size,
            kv_pool_blocks=args.kv_pool_blocks,
            kv_dtype=args.kv_dtype,
            kv_fused=args.kv_fused_attention,
            stream_timeout_s=args.stream_timeout_s,
            serving_role=args.serving_role,
            tp_shards=args.tp_shards,
            prefill_chunk_tokens=args.prefill_chunk_tokens,
            max_prompt_len=args.max_prompt_len,
            cp_shards=args.cp_shards,
            pp_stages=args.pp_stages,
            host_kv_bytes=args.host_kv_bytes,
            kv_directory_size=args.kv_directory_size,
            cold_store_ref=args.cold_store_ref,
            kv_import_crossover_tokens=args.kv_import_crossover_tokens,
            qos_tenants=args.qos_tenants,
            qos_aging_s=args.qos_aging_s,
            weight_peers=args.weight_peers,
            weight_pull_timeout_s=args.weight_pull_timeout_s,
            compile_cache_dir=args.compile_cache_dir,
            dtype=args.dtype,
        ),
        port=args.rest_port,
        grpc_port=None if args.grpc_port < 0 else args.grpc_port,
        batch_timeout_ms=args.batch_timeout_ms,
    )
    print(f"serving {args.model_name} on REST :{args.rest_port} "
          f"gRPC :{args.grpc_port}")
    server.serve_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
