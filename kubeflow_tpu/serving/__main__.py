"""CLI: `python -m kubeflow_tpu.serving --model-name ... --rest-port 8500`.

The container entrypoint the tpu-serving manifest runs
(kubeflow_tpu/manifests/packages/serving.py args)."""

from __future__ import annotations

import argparse

from kubeflow_tpu.serving.engine import EngineConfig
from kubeflow_tpu.serving.server import ModelServer


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--model-name", required=True,
                   help="registry model name (kubeflow_tpu.models)")
    p.add_argument("--model-path", default="",
                   help="checkpoint dir (empty = fresh init, benchmarking)")
    p.add_argument("--rest-port", type=int, default=8500)
    p.add_argument("--grpc-port", type=int, default=9000,
                   help="gRPC predict port (tf-serving :9000 contract); "
                        "-1 disables")
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--batch-timeout-ms", type=float, default=5.0)
    p.add_argument("--max-seq-len", type=int, default=128)
    args = p.parse_args(argv)

    server = ModelServer(
        EngineConfig(
            model=args.model_name,
            checkpoint_dir=args.model_path or None,
            batch_size=args.batch_size,
            max_seq_len=args.max_seq_len,
        ),
        port=args.rest_port,
        grpc_port=None if args.grpc_port < 0 else args.grpc_port,
        batch_timeout_ms=args.batch_timeout_ms,
    )
    print(f"serving {args.model_name} on REST :{args.rest_port} "
          f"gRPC :{args.grpc_port}")
    server.serve_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
