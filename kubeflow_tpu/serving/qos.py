"""Multi-tenant QoS for the serving path: token-bucket admission,
weighted-fair + priority + deadline ordering, and bounded tenant labels.

The platform is multi-user by design (profiles, IAP, per-namespace
isolation) but the decoder's pop loop was strictly FIFO through one
implicit tenant. This module applies the Gavel fair-share/priority
policies (PAPERS.md, "Heterogeneity-Aware Cluster Scheduling") to
*inference* admission — the SAME aging/fairness primitives the cluster
scheduler's gang queue uses (kubeflow_tpu/scheduler/queue.py, factored
to be import-safe from serving), driven by float seconds instead of
k8s timestamps:

- :class:`TokenBucket` / :class:`QosPolicy` — per-tenant request-rate
  admission. An empty bucket rejects with a computed retry-after, so
  the gateway and model server answer 429 + ``Retry-After`` instead of
  queuing into collapse.
- :func:`order_key` — the pop-loop ordering: weighted fair share across
  tenants (lowest served/weight first) → effective priority with
  starvation aging → FIFO. Backlogged tenants' service converges to
  their weights; a low-priority request behind a high-priority stream
  is eventually first in line.
- :func:`tenant_bucket` — a stable hash of the tenant id into a BOUNDED
  label vocabulary (``t00``..``tNN``) so per-tenant histograms cannot
  explode exposition cardinality (tpu-lint metrics-label-vocab).

Pure host logic — no jax imports — unit-testable without a device and
importable by the gateway without the serving stack's device deps.
"""

from __future__ import annotations

import hashlib
import threading
from dataclasses import dataclass

from kubeflow_tpu.scheduler.queue import aged_priority, fairness_ratio

# Default tenant id for requests that carry none: one implicit tenant,
# exactly the pre-QoS behavior.
DEFAULT_TENANT = "default"

# Bounded tenant-label cardinality for the exposition (tenant ids are
# user-controlled input; raw ids as label values would let one client
# mint unbounded metric families).
TENANT_LABEL_BUCKETS = 16


class DeadlineExceeded(TimeoutError):
    """A request's deadline passed before (or while) it could be
    served; the pop loop sheds it instead of spending decode compute on
    an answer nobody is waiting for. Subclasses TimeoutError so the
    HTTP layers map it to 503 like any other server-side timeout."""


class QosRejected(Exception):
    """Token-bucket admission refused the request. ``retry_after_s`` is
    the earliest time the tenant's bucket holds a token again — the
    HTTP layers answer 429 with a ``Retry-After`` header from it."""

    def __init__(self, tenant: str, retry_after_s: float):
        self.tenant = tenant
        self.retry_after_s = max(0.0, float(retry_after_s))
        super().__init__(
            f"tenant {tenant!r} over admission rate; "
            f"retry after {self.retry_after_s:.1f}s")


def tenant_bucket(tenant: str,
                  buckets: int = TENANT_LABEL_BUCKETS) -> str:
    """Stable bounded label value for a tenant id (``t00``..``tNN``).
    BLAKE2 (not ``hash()``) so gateway, server, and dashboards bucket
    identically across processes and runs."""
    h = hashlib.blake2b((tenant or DEFAULT_TENANT).encode("utf-8"),
                        digest_size=4).digest()
    return f"t{int.from_bytes(h, 'big') % buckets:02d}"


@dataclass
class TenantSpec:
    """One tenant's QoS contract.

    ``weight``: weighted-fair share of decode service (tokens) under
    backlog. ``rate``/``burst``: request-per-second token bucket
    (rate 0 = unlimited). ``priority``: default base priority for the
    tenant's requests (a per-request priority overrides it)."""

    name: str
    weight: float = 1.0
    rate: float = 0.0
    burst: float = 0.0
    priority: int = 0

    def __post_init__(self) -> None:
        if self.weight <= 0:
            raise ValueError(f"tenant {self.name!r}: weight must be > 0")
        if self.rate < 0 or self.burst < 0:
            raise ValueError(
                f"tenant {self.name!r}: rate/burst must be >= 0")


class TokenBucket:
    """Continuous-refill token bucket (monotonic timestamps passed in,
    so tests control the clock). ``rate`` tokens/second refill toward a
    ``burst`` capacity; a take when empty fails with the seconds until
    one token exists."""

    def __init__(self, rate: float, burst: float, now: float = 0.0):
        self.rate = float(rate)
        self.burst = max(float(burst) or max(self.rate, 1.0), 1.0)
        self._tokens = self.burst
        self._t = float(now)

    def try_take(self, now: float, cost: float = 1.0
                 ) -> tuple[bool, float]:
        """(admitted, retry_after_s). rate<=0 always admits."""
        if self.rate <= 0:
            return True, 0.0
        elapsed = max(now - self._t, 0.0)
        self._tokens = min(self.burst, self._tokens + elapsed * self.rate)
        self._t = now
        if self._tokens >= cost:
            self._tokens -= cost
            return True, 0.0
        return False, (cost - self._tokens) / self.rate


def parse_tenants(spec: str) -> dict[str, TenantSpec]:
    """Parse the CLI/manifest tenant string:
    ``name=weight[:rate[:burst[:priority]]]`` comma-separated, e.g.
    ``gold=8:100:200:10,free=1:10`` — the flat form the tpu-serving
    args carry (the CRD's structured ``spec.qos.tenants`` serializes
    to it). Raises ``ValueError`` on malformed entries so a typo fails
    at flag-parse time, not at the first misrouted request."""
    tenants: dict[str, TenantSpec] = {}
    for part in filter(None, (p.strip() for p in spec.split(","))):
        name, sep, rest = part.partition("=")
        name = name.strip()
        if not name or not sep:
            raise ValueError(f"malformed tenant spec {part!r} "
                             "(want name=weight[:rate[:burst[:prio]]])")
        fields = rest.split(":")
        if len(fields) > 4:
            raise ValueError(f"tenant {name!r}: too many fields in "
                             f"{rest!r}")
        try:
            nums = [float(f) for f in fields if f != ""]
        except ValueError:
            raise ValueError(
                f"tenant {name!r}: non-numeric field in {rest!r}"
            ) from None
        nums += [0.0] * (4 - len(nums))
        tenants[name] = TenantSpec(
            name=name, weight=nums[0] or 1.0, rate=nums[1],
            burst=nums[2], priority=int(nums[3]))
    return tenants


def render_tenants(tenants: dict) -> str:
    """Inverse of :func:`parse_tenants` for structured configs (the
    InferenceService operator turns ``spec.qos.tenants`` into the flat
    CLI string). Accepts ``{name: {weight, rate, burst, priority}}``."""
    parts = []
    for name in sorted(tenants):
        t = tenants[name] or {}
        parts.append(
            f"{name}={float(t.get('weight', 1) or 1):g}"
            f":{float(t.get('rate', 0) or 0):g}"
            f":{float(t.get('burst', 0) or 0):g}"
            f":{int(t.get('priority', 0) or 0)}")
    return ",".join(parts)


def order_key(*, served: float, weight: float, priority: float,
              waited_seconds: float, aging_seconds: float,
              submit_t: float) -> tuple:
    """Sort key for one pending request — ascending sort admits first.
    Three forces, strongest first (the scheduler queue's ordering
    applied to inference): weighted fair share across tenants,
    effective priority with starvation aging, FIFO tie-break."""
    return (fairness_ratio(served, weight),
            -aged_priority(priority, waited_seconds, aging_seconds),
            submit_t)


class QosPolicy:
    """Per-tenant admission + ordering policy for a decoder or gateway.

    Unknown tenants fall back to ``default`` (weight 1, unlimited rate,
    priority 0 unless a ``default`` entry overrides it). Bucket state
    is internally locked — submit runs on arbitrary caller threads."""

    def __init__(self, tenants: dict[str, TenantSpec] | str | None = None,
                 *, aging_seconds: float = 30.0):
        if isinstance(tenants, str):
            tenants = parse_tenants(tenants)
        self.tenants = dict(tenants or {})
        self.aging_seconds = float(aging_seconds)
        self._default = self.tenants.get(
            DEFAULT_TENANT, TenantSpec(DEFAULT_TENANT))
        self._buckets: dict[str, TokenBucket] = {}
        self._lock = threading.Lock()

    def spec(self, tenant: str) -> TenantSpec:
        return self.tenants.get(tenant or DEFAULT_TENANT, self._default)

    def base_priority(self, tenant: str, priority: int | None) -> int:
        """Request priority: explicit per-request value wins, else the
        tenant's default."""
        if priority is not None:
            return int(priority)
        return self.spec(tenant).priority

    def try_admit(self, tenant: str, now: float) -> tuple[bool, float]:
        """Token-bucket check for one request; (admitted, retry_after).
        Buckets are per tenant NAME (an unknown tenant gets its own
        bucket at the default spec's rate, so one abusive anonymous id
        cannot drain a shared bucket for everyone else)."""
        tenant = tenant or DEFAULT_TENANT
        spec = self.spec(tenant)
        if spec.rate <= 0:
            return True, 0.0
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                bucket = self._buckets[tenant] = TokenBucket(
                    spec.rate, spec.burst, now)
            return bucket.try_take(now)

    def admit(self, tenant: str, now: float) -> None:
        """:meth:`try_admit`, raising :class:`QosRejected` on refusal."""
        ok, retry = self.try_admit(tenant, now)
        if not ok:
            raise QosRejected(tenant or DEFAULT_TENANT, retry)
