"""Shared container-entrypoint runtime.

Every `python -m kubeflow_tpu.*` binary the manifests reference (controller
managers, web apps, the gateway) builds its apiserver client and serves its
health/metrics port through here — the role cobra/viper + controller-runtime
manager setup plays for the reference's Go binaries
(bootstrap/cmd/kfctl/cmd/root.go:23-40; operator manager flags at
kubeflow/tf-training/tf-job-operator.libsonnet:99-143).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Iterable

from kubeflow_tpu.k8s.client import (
    ClusterConfig,
    HttpK8sClient,
    K8sClient,
    KindRegistry,
)
from kubeflow_tpu.observability.metrics import MetricRegistry, render_prometheus

log = logging.getLogger(__name__)

IN_CLUSTER_TOKEN = "/var/run/secrets/kubernetes.io/serviceaccount/token"
IN_CLUSTER_CA = "/var/run/secrets/kubernetes.io/serviceaccount/ca.crt"


def strip_glog_args(argv: list[str]) -> list[str]:
    """Drop glog-style flags the reference's operator deployments pass
    (`--alsologtostderr -v=1`, tf-job-operator.libsonnet:101-103) so argparse
    entrypoints accept the same manifest args."""
    out = []
    for a in argv:
        if a == "--alsologtostderr" or a.startswith(("-v=", "--v=")):
            continue
        out.append(a)
    return out


def add_client_args(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--apiserver",
        default=os.environ.get("KUBEFLOW_TPU_APISERVER", ""),
        help="apiserver URL; empty = in-cluster config, falling back to "
             "the kubectl-proxy default http://127.0.0.1:8001",
    )
    p.add_argument("--token-path", default="",
                   help="bearer token file (default: in-cluster SA token)")
    p.add_argument("--namespace", default=os.environ.get(
        "KUBEFLOW_TPU_NAMESPACE", "kubeflow"))


def cluster_config_from_args(args) -> ClusterConfig:
    host = args.apiserver
    token = None
    verify: bool | str = True
    token_path = args.token_path or (
        IN_CLUSTER_TOKEN if os.path.exists(IN_CLUSTER_TOKEN) else ""
    )
    if token_path and os.path.exists(token_path):
        with open(token_path) as f:
            token = f.read().strip()
    if not host:
        k8s_host = os.environ.get("KUBERNETES_SERVICE_HOST")
        if k8s_host:
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            host = f"https://{k8s_host}:{port}"
            if os.path.exists(IN_CLUSTER_CA):
                verify = IN_CLUSTER_CA
        else:
            host = "http://127.0.0.1:8001"
    return ClusterConfig(host=host, token=token, verify=verify)


def platform_registry() -> KindRegistry:
    """KindRegistry pre-loaded with every platform CRD kind, so entrypoints
    can resolve REST paths without a discovery round-trip."""
    from kubeflow_tpu.apis.benchmark import benchmark_job_crd
    from kubeflow_tpu.apis.experiment import experiment_crd
    from kubeflow_tpu.apis.jobs import all_job_crds
    from kubeflow_tpu.apis.notebooks import notebook_crd
    from kubeflow_tpu.apis.profiles import profile_crd
    from kubeflow_tpu.apis.tuning import study_job_crd

    registry = KindRegistry()
    for crd in [*all_job_crds(), notebook_crd(), profile_crd(),
                study_job_crd(), benchmark_job_crd(), experiment_crd()]:
        registry.register_crd(crd)
    return registry


def client_from_args(args) -> K8sClient:
    return HttpK8sClient(cluster_config_from_args(args),
                         registry=platform_registry())


class HealthServer:
    """`/healthz` + `/metrics` sidecar port every manager binary exposes (the
    promhttp `/metrics` contract, bootstrap/cmd/bootstrap/app/ksServer.go:1460).

    ``/metrics`` serves through the shared observability renderer: the
    optional ``registry`` (labeled counters/gauges/histograms — the
    operator runtime's reconcile/workqueue instrumentation) plus the
    ``metrics_fn`` dict typed by the ``_total``-suffix rule. That rule
    replaces the old handler, which stamped EVERY metric ``counter`` —
    queue depths and running-controller gauges were mislabeled.
    """

    def __init__(self, port: int, metrics_fn: Callable[[], dict] | None = None,
                 registry: MetricRegistry | None = None):
        self.port = port
        self._metrics_fn = metrics_fn or (lambda: {})
        self._registry = registry
        self._httpd: ThreadingHTTPServer | None = None

    def render_metrics(self) -> str:
        text = self._registry.render() if self._registry is not None else ""
        return text + render_prometheus(self._metrics_fn())

    def start(self) -> None:
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path in ("/healthz", "/readyz", "/livez"):
                    body, ctype = b'{"status":"ok"}', "application/json"
                elif self.path == "/metrics":
                    body = server.render_metrics().encode()
                    ctype = "text/plain"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer(("0.0.0.0", self.port), Handler)
        self.port = self._httpd.server_address[1]  # resolve port 0
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()


def controller_main(
    argv,
    make_controllers: Callable[[K8sClient], Iterable],
    description: str,
    *,
    default_metrics_port: int = 8443,
) -> int:
    """Shared main for every controller-manager entrypoint: build the client,
    construct controllers, run watch loops until signalled (or one pass with
    ``--once``, the mode tests and one-shot reconcile jobs use)."""
    import sys

    argv = strip_glog_args(list(sys.argv[1:] if argv is None else argv))
    p = argparse.ArgumentParser(description=description)
    add_client_args(p)
    p.add_argument("--once", action="store_true",
                   help="single reconcile pass over all objects, then exit")
    p.add_argument("--metrics-port", type=int, default=default_metrics_port,
                   help="health/metrics port (0 = disabled)")
    p.add_argument("--leader-elect", action="store_true",
                   help="hold a coordination.k8s.io Lease before "
                        "reconciling (replicated manager Deployments)")
    p.add_argument("--leader-elect-name", default="",
                   help="lease name (default: derived from the manager)")
    args = p.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    client = client_from_args(args)
    controllers = list(make_controllers(client))

    if args.once:
        total = sum(c.reconcile_all() for c in controllers)
        print(json.dumps({"reconciled": total,
                          "controllers": len(controllers)}))
        return 0

    from kubeflow_tpu.operators.base import OPERATOR_METRICS, run_controllers

    health = None
    if args.metrics_port:
        counts = {"kubeflow_tpu_controllers_running": len(controllers)}
        # The shared operator registry carries every controller's
        # reconcile-latency histogram and workqueue/watch counters,
        # labeled by kind — the runtime signals the cluster scheduler
        # and autoscaler policies consume.
        health = HealthServer(args.metrics_port, lambda: counts,
                              registry=OPERATOR_METRICS)
        health.start()
    elector = None
    lost_leadership = False
    try:
        if args.leader_elect:
            from kubeflow_tpu.operators.leader import LeaderElector

            # Default lease name must identify THIS manager, not the
            # shared "kubeflow-tpu" prefix — different managers electing
            # on one lease would block each other forever.
            lease_name = (args.leader_elect_name
                          or "-".join(description.split()[:2]))
            elector = LeaderElector(client, name=lease_name,
                                    namespace=args.namespace)
            log.info("waiting for leadership on lease %s as %s",
                     lease_name, elector.identity)
            elector.wait_for_leadership()
            elector.start()  # keep renewing in the background
        threads = run_controllers(controllers)
        log.info("running %d controllers: %s", len(controllers),
                 ", ".join(c.kind for c in controllers))
        while any(t.is_alive() for t in threads):
            # Leadership loss is fatal (client-go OnStoppedLeading
            # semantics): a deposed leader must not keep reconciling
            # alongside the new one.
            if elector is not None and not elector.is_leader:
                log.error("lost leadership on lease; shutting down")
                lost_leadership = True
                break
            for t in threads:
                t.join(timeout=1.0)
    except KeyboardInterrupt:
        pass
    finally:
        for c in controllers:
            c.stop()
        if elector:
            elector.release()
        if health:
            health.stop()
    return 1 if lost_leadership else 0
