"""kubeflow-tpu: a TPU-native ML platform with the capabilities of Kubeflow.

A ground-up rebuild of the Kubeflow platform (reference: cheyang/kubeflow)
designed TPU-first:

- ``kfctl``-style deployment CLI over a typed :class:`~kubeflow_tpu.config.kfdef.KfDef`
  config (replaces bootstrap/cmd/kfctl + ksonnet).
- A typed manifest layer (``kubeflow_tpu.manifests``) stamping out Kubernetes
  objects (replaces the jsonnet package tree under kubeflow/).
- CRD training operators (``kubeflow_tpu.operators``) that gang-schedule onto
  contiguous TPU slices and rendezvous through a JAX coordinator over ICI/DCN
  (replaces TFJob/PyTorchJob/MPIJob TF_CONFIG/NCCL/MPI wiring).
- A JAX/XLA compute path (``models``, ``parallel``, ``train``, ``serving``)
  the reference delegated to external container images.
"""

from kubeflow_tpu.version import __version__

__all__ = ["__version__"]
