"""Manifest package core: the prototype/param registry.

Replaces the reference's ksonnet machinery — prototypes with
`@param/@optionalParam` comment headers (e.g.
kubeflow/tf-training/prototypes/tf-job-operator.jsonnet:1-11) instantiated by
`ks generate` / `ks param set` (bootstrap/pkg/kfapp/ksonnet/ksonnet.go:322,488).

Here a *prototype* is a registered Python function taking validated params and
returning a list of Kubernetes objects (plain dicts). Packages live under
``kubeflow_tpu.manifests.packages`` and self-register on import.
"""

from __future__ import annotations

import importlib
import pkgutil
from dataclasses import dataclass
from typing import Any, Callable, Mapping, Sequence


class PrototypeError(Exception):
    pass


class _Required:
    def __repr__(self) -> str:  # pragma: no cover
        return "<required>"


REQUIRED = _Required()


@dataclass(frozen=True)
class ParamSpec:
    """One prototype parameter (@param/@optionalParam analogue)."""

    name: str
    default: Any = REQUIRED
    description: str = ""

    @property
    def required(self) -> bool:
        return self.default is REQUIRED


@dataclass
class Prototype:
    name: str
    description: str
    package: str
    params: tuple[ParamSpec, ...]
    fn: Callable[..., list[dict]]

    def resolve_params(self, overrides: Mapping[str, Any]) -> dict[str, Any]:
        known = {p.name: p for p in self.params}
        unknown = set(overrides) - set(known)
        if unknown:
            raise PrototypeError(
                f"prototype {self.name}: unknown params {sorted(unknown)}; "
                f"known: {sorted(known)}"
            )
        resolved: dict[str, Any] = {}
        missing = []
        for p in self.params:
            if p.name in overrides:
                resolved[p.name] = overrides[p.name]
            elif p.required:
                missing.append(p.name)
            else:
                resolved[p.name] = p.default
        if missing:
            raise PrototypeError(
                f"prototype {self.name}: missing required params {missing}"
            )
        return resolved

    def generate(self, overrides: Mapping[str, Any] | None = None) -> list[dict]:
        objs = self.fn(**self.resolve_params(overrides or {}))
        for o in objs:
            if "apiVersion" not in o or "kind" not in o or "metadata" not in o:
                raise PrototypeError(
                    f"prototype {self.name} produced a non-k8s object: {o}"
                )
        return objs


_REGISTRY: dict[str, Prototype] = {}
_PACKAGES_LOADED = False


def prototype(
    name: str,
    description: str,
    params: Sequence[ParamSpec] = (),
    package: str = "",
) -> Callable[[Callable[..., list[dict]]], Callable[..., list[dict]]]:
    """Decorator registering a manifest-generator function as a prototype."""

    def _register(fn: Callable[..., list[dict]]) -> Callable[..., list[dict]]:
        if name in _REGISTRY:
            raise PrototypeError(f"duplicate prototype {name}")
        pkg = package or fn.__module__.rsplit(".", 1)[-1]
        _REGISTRY[name] = Prototype(
            name=name,
            description=description,
            package=pkg,
            params=tuple(params),
            fn=fn,
        )
        return fn

    return _register


def load_all_packages() -> None:
    """Import every module in manifests.packages so prototypes register."""
    global _PACKAGES_LOADED
    if _PACKAGES_LOADED:
        return
    from kubeflow_tpu.manifests import packages as pkgs

    for mod in pkgutil.iter_modules(pkgs.__path__):
        importlib.import_module(f"{pkgs.__name__}.{mod.name}")
    _PACKAGES_LOADED = True


def get_prototype(name: str) -> Prototype:
    load_all_packages()
    try:
        return _REGISTRY[name]
    except KeyError:
        raise PrototypeError(
            f"unknown prototype {name!r}; available: {sorted(_REGISTRY)}"
        )


def all_prototypes() -> dict[str, Prototype]:
    load_all_packages()
    return dict(_REGISTRY)


def generate(name: str, params: Mapping[str, Any] | None = None) -> list[dict]:
    """Instantiate a prototype (the `ks generate` + `ks show` analogue)."""
    return get_prototype(name).generate(params)


GATEWAY_ROUTE_ANNOTATION = "kubeflow-tpu.org/gateway-route"


def gateway_route(name: str, prefix: str, service: str, rewrite: str = "/",
                  backends: list | None = None, shadow: str = "",
                  strategy: str = "", epsilon: float | None = None,
                  outlier: dict | None = None,
                  affinity_tokens: int | None = None,
                  pressure: int | None = None,
                  kv_pressure: float | None = None,
                  prefill_backends: list | None = None,
                  qos: dict | None = None,
                  splits: list | None = None,
                  shadow_fraction: float | None = None) -> dict:
    """Gateway route annotation for a Service — the platform-wide analogue of
    the `getambassador.io/config` annotations the reference attaches to every
    web-app Service (kubeflow/common/ambassador.libsonnet route pattern). The
    gateway proxy discovers Services carrying this annotation and routes
    `prefix` to them.

    ``backends`` ([{service, weight}, ...]) splits traffic by weight
    (A/B / canary); ``shadow`` mirrors every request fire-and-forget —
    the seldon abtest/shadow prototype surface
    (kubeflow/seldon/prototypes, core.libsonnet:305)."""
    import yaml

    spec: dict = {"name": name, "prefix": prefix, "service": service,
                  "rewrite": rewrite}
    if backends:
        spec["backends"] = backends
    if shadow:
        spec["shadow"] = shadow
    if strategy:
        spec["strategy"] = strategy
    if epsilon is not None:
        spec["epsilon"] = epsilon
    if outlier:
        # {threshold, window}: running z-score anomaly tagging (the
        # seldon outlier-detector-v1alpha2 surface).
        spec["outlier"] = outlier
    if affinity_tokens is not None:
        # prefix-affine replica-pool knobs: leading tokens hashed into
        # the rendezvous routing key, and the per-backend in-flight
        # bound past which the affine pick spills to least-loaded.
        spec["affinity_tokens"] = int(affinity_tokens)
    if pressure is not None:
        spec["pressure"] = int(pressure)
    if kv_pressure is not None:
        # KV-fill fraction past which the affine pick spills (gateway
        # scrapes each backend's real-byte gauges, staleness-bounded).
        spec["kv_pressure"] = float(kv_pressure)
    if prefill_backends:
        # Disaggregated prefill pool: the gateway two-hop relay picks
        # the affine prefill backend here, it pushes prompt KV to the
        # decode backend, then the predict relays to `backends`.
        spec["prefill_backends"] = prefill_backends
    if qos:
        # Per-tenant overload shedding at the gateway:
        # {tenants: {name: {rate, burst}}, default: {rate, burst}} —
        # over-rate requests answer 429 + Retry-After before any
        # upstream work.
        spec["qos"] = qos
    if splits:
        # Progressive delivery: [{version, weight, backends: [...]}]
        # version groups for the hash-split strategy — a request is
        # pinned to one group by stable hash of its affinity key.
        spec["splits"] = splits
    if shadow_fraction is not None:
        spec["shadow_fraction"] = float(shadow_fraction)
    return {
        GATEWAY_ROUTE_ANNOTATION: yaml.safe_dump(spec, sort_keys=True)
    }
