"""Default container images for platform components.

The reference pins external images per component (e.g.
gcr.io/kubeflow-images-public/tf_operator:v0.5.0 at
kubeflow/tf-training/prototypes/tf-job-operator.jsonnet:7). Our platform
components are all served out of one image built from this repo; workloads
default to a JAX+libtpu image (replacing the CUDA tensorflow images).
"""

from kubeflow_tpu.version import __version__

# The platform image: contains kubeflow_tpu and runs components via
# `python -m kubeflow_tpu.<component>`.
PLATFORM = f"ghcr.io/kubeflow-tpu/platform:{__version__}"

# Default workload image: JAX + libtpu (the analogue of the CUDA-built
# tensorflow images the reference defaults to, tf-job-operator.libsonnet:192).
JAX_TPU = "ghcr.io/kubeflow-tpu/jax-tpu:0.9.0"

# Notebook image: JAX + libtpu + jupyter (replaces
# components/tensorflow-notebook-image CUDA matrix).
NOTEBOOK = "ghcr.io/kubeflow-tpu/jax-notebook:0.9.0"

# Serving image: the TPU model server (replaces tensorflow/serving).
SERVING = f"ghcr.io/kubeflow-tpu/serving:{__version__}"

# CI stages (`--target ci` of the platform / jax-tpu recipes): the runtime
# image plus the repo's tests/ and bench harness, so ci/pipeline.yaml's
# tasks have their sources on disk in-cluster (the reference bakes its
# harness into a test-worker image the same way, testing/Dockerfile).
PLATFORM_CI = f"ghcr.io/kubeflow-tpu/platform-ci:{__version__}"
JAX_TPU_CI = "ghcr.io/kubeflow-tpu/jax-tpu-ci:0.9.0"
