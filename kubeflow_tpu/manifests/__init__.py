"""Typed manifest layer: prototypes, packages, overlays (the ksonnet analogue)."""
from kubeflow_tpu.manifests.core import (
    ParamSpec,
    Prototype,
    PrototypeError,
    REQUIRED,
    all_prototypes,
    generate,
    get_prototype,
    load_all_packages,
    prototype,
)

__all__ = [
    "ParamSpec",
    "Prototype",
    "PrototypeError",
    "REQUIRED",
    "all_prototypes",
    "generate",
    "get_prototype",
    "load_all_packages",
    "prototype",
]
