"""Kustomize-style overlays over generated manifests.

The v2 package-manager analogue (bootstrap/pkg/kfapp/kustomize/
kustomize.go:62-170 renders kustomize overlays instead of ksonnet params):
an :class:`Overlay` transforms a prototype's rendered objects —

- ``name_prefix``/``name_suffix`` with reference fixing (RBAC subjects and
  roleRefs, pod serviceAccountName follow renamed targets);
- ``namespace`` retargeting (cluster-scoped kinds untouched);
- ``common_labels`` stamped onto metadata, workload selectors, and pod
  templates (kustomize commonLabels semantics);
- ``common_annotations``;
- ``images`` (repo → replacement reference);
- ``replicas`` by workload name;
- ``patches``: strategic-merge-style deep merges targeted by kind/name.

Overlays ride KfDef components (``component.overlay``), so one prototype
serves many environments — the reference's per-platform kustomize overlay
trees collapsed into config.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Mapping

CLUSTER_SCOPED_KINDS = {
    "Namespace", "CustomResourceDefinition", "ClusterRole",
    "ClusterRoleBinding", "PersistentVolume", "StorageClass",
    "MutatingWebhookConfiguration", "ValidatingWebhookConfiguration",
}

_WORKLOAD_KINDS = {"Deployment", "StatefulSet", "DaemonSet", "Job"}


@dataclass(frozen=True)
class Overlay:
    name_prefix: str = ""
    name_suffix: str = ""
    namespace: str | None = None
    common_labels: Mapping[str, str] = field(default_factory=dict)
    common_annotations: Mapping[str, str] = field(default_factory=dict)
    images: Mapping[str, str] = field(default_factory=dict)
    replicas: Mapping[str, int] = field(default_factory=dict)
    patches: tuple = ()

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "Overlay":
        known = {
            "namePrefix": "name_prefix", "nameSuffix": "name_suffix",
            "namespace": "namespace", "commonLabels": "common_labels",
            "commonAnnotations": "common_annotations", "images": "images",
            "replicas": "replicas", "patches": "patches",
        }
        unknown = set(d) - set(known)
        if unknown:
            raise ValueError(f"unknown overlay fields {sorted(unknown)}")
        kwargs = {known[k]: v for k, v in d.items()}
        if "patches" in kwargs:
            kwargs["patches"] = tuple(kwargs["patches"])
        return cls(**kwargs)

    @property
    def empty(self) -> bool:
        return self == Overlay()


def _deep_merge(dst: dict, patch: Mapping[str, Any]) -> None:
    for k, v in patch.items():
        if v is None:
            dst.pop(k, None)
        elif isinstance(v, Mapping) and isinstance(dst.get(k), dict):
            _deep_merge(dst[k], v)
        else:
            dst[k] = copy.deepcopy(v)


def _matches(target: Mapping[str, Any], obj: dict) -> bool:
    if "kind" in target and obj.get("kind") != target["kind"]:
        return False
    if "name" in target and obj["metadata"].get("name") != target["name"]:
        return False
    return True


def apply_overlay(objs: list[dict], overlay: Overlay) -> list[dict]:
    objs = copy.deepcopy(objs)

    # Pass 1: renames (and remember old→new per kind for reference fixing).
    renames: dict[tuple[str, str], str] = {}
    if overlay.name_prefix or overlay.name_suffix:
        for obj in objs:
            meta = obj.setdefault("metadata", {})
            old = meta.get("name", "")
            new = f"{overlay.name_prefix}{old}{overlay.name_suffix}"
            renames[(obj.get("kind", ""), old)] = new
            meta["name"] = new

    for obj in objs:
        kind = obj.get("kind", "")
        meta = obj.setdefault("metadata", {})

        if overlay.namespace and kind not in CLUSTER_SCOPED_KINDS:
            meta["namespace"] = overlay.namespace

        if overlay.common_labels:
            meta.setdefault("labels", {}).update(overlay.common_labels)
            spec = obj.get("spec", {})
            if kind in _WORKLOAD_KINDS:
                spec.setdefault("selector", {}).setdefault(
                    "matchLabels", {}
                ).update(overlay.common_labels)
                tmpl_meta = spec.setdefault("template", {}).setdefault(
                    "metadata", {}
                )
                tmpl_meta.setdefault("labels", {}).update(
                    overlay.common_labels
                )
            elif kind == "Service" and isinstance(
                spec.get("selector"), dict
            ):
                spec["selector"].update(overlay.common_labels)

        if overlay.common_annotations:
            meta.setdefault("annotations", {}).update(
                overlay.common_annotations
            )

        _fix_references(obj, renames)
        _apply_images(obj, overlay.images)

        if kind in _WORKLOAD_KINDS and meta.get("name") in overlay.replicas:
            obj.setdefault("spec", {})["replicas"] = (
                overlay.replicas[meta["name"]]
            )

    for patch in overlay.patches:
        target = patch.get("target", {})
        body = patch.get("patch", {})
        for obj in objs:
            if _matches(target, obj):
                _deep_merge(obj, body)
    return objs


def _fix_references(obj: dict, renames: Mapping[tuple[str, str], str]) -> None:
    if not renames:
        return
    kind = obj.get("kind", "")
    if kind in ("RoleBinding", "ClusterRoleBinding"):
        ref = obj.get("roleRef", {})
        new = renames.get((ref.get("kind", ""), ref.get("name", "")))
        if new:
            ref["name"] = new
        for subject in obj.get("subjects", []):
            new = renames.get((subject.get("kind", ""),
                               subject.get("name", "")))
            if new:
                subject["name"] = new
    pod_spec = None
    if kind in _WORKLOAD_KINDS:
        pod_spec = obj.get("spec", {}).get("template", {}).get("spec", {})
    elif kind == "Pod":
        pod_spec = obj.get("spec", {})
    if pod_spec:
        sa = pod_spec.get("serviceAccountName")
        new = renames.get(("ServiceAccount", sa)) if sa else None
        if new:
            pod_spec["serviceAccountName"] = new


def _apply_images(obj: dict, images: Mapping[str, str]) -> None:
    if not images:
        return
    pod_spec = (obj.get("spec", {}).get("template", {}).get("spec", {})
                if obj.get("kind") in _WORKLOAD_KINDS
                else obj.get("spec", {}) if obj.get("kind") == "Pod"
                else None)
    if not pod_spec:
        return
    for container in pod_spec.get("containers", []):
        image = container.get("image", "")
        # The tag separator is a ':' after the last '/': splitting on the
        # first ':' would truncate port-qualified registries
        # ('registry:5000/app' must keep repo 'registry:5000/app').
        head, sep, last = image.rpartition("/")
        repo = head + sep + last.split(":")[0].split("@")[0]
        if image in images:
            container["image"] = images[image]
        elif repo in images:
            container["image"] = images[repo]
