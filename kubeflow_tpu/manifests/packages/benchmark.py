"""Benchmark package: the kubebench-equivalent harness.

Analogue of kubeflow/kubebench (kubebench-operator.jsonnet, kubebench-job
prototype :6-23): BenchmarkJob CRD + operator that runs a job template under
measurement, scrapes reported metrics, and records results in status (the
reporter-csv equivalent).
"""

from __future__ import annotations

from kubeflow_tpu.apis.benchmark import benchmark_job_crd
from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.manifests import images
from kubeflow_tpu.manifests.core import ParamSpec, prototype
from kubeflow_tpu.version import API_GROUP, DEFAULT_NAMESPACE


@prototype(
    "benchmark-operator",
    "BenchmarkJob CRD + operator (kubebench-operator analogue)",
    params=[
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("image", images.PLATFORM),
    ],
)
def benchmark_operator(namespace: str, image: str) -> list[dict]:
    name = "benchmark-operator"
    labels = {"app": name}
    return [
        benchmark_job_crd(),
        k8s.service_account(name, namespace, labels),
        k8s.cluster_role(
            name,
            [
                k8s.policy_rule(
                    [API_GROUP], ["benchmarkjobs", "benchmarkjobs/status"], ["*"]
                ),
                k8s.policy_rule(
                    [API_GROUP],
                    ["jaxjobs", "jaxjobs/status", "tfjobs", "pytorchjobs", "mpijobs"],
                    ["*"],
                ),
                k8s.policy_rule([""], ["pods", "pods/log", "events"],
                                ["get", "list", "watch", "create", "patch"]),
            ],
            labels,
        ),
        k8s.cluster_role_binding(name, name, name, namespace),
        k8s.deployment(
            name,
            namespace,
            containers=[
                k8s.container(
                    name,
                    image,
                    command=["python", "-m", "kubeflow_tpu.operators.benchmark"],
                    ports={"metrics": 8443},
                )
            ],
            labels=labels,
            service_account=name,
        ),
    ]
