"""RL package: the RLJob CRD + an example train↔serve RL workload.

The RLJob operator itself rides the training-operator manager (its
Deployment and RBAC live in the ``training-operator`` prototype); this
package ships the CRD and a ready-to-edit CR declaring the full loop —
a learner gang, an elastic preemptible actor pool, the rollout shape,
and the weight-push policy (docs/rl.md).
"""

from __future__ import annotations

from kubeflow_tpu.apis import rl as rl_api
from kubeflow_tpu.manifests import images
from kubeflow_tpu.manifests.core import ParamSpec, prototype
from kubeflow_tpu.version import DEFAULT_NAMESPACE


@prototype(
    "rl-job",
    "RLJob CRD + an example Podracer-style RL workload: learner gang at "
    "high priority pushing live weights into an elastic preemptible "
    "actor pool every K steps",
    params=[
        ParamSpec("name", "rl-smoke"),
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("image", images.PLATFORM),
        ParamSpec("model", "lm-test-tiny", "registry model (the policy)"),
        ParamSpec("learner_replicas", 1, "learner gang size"),
        ParamSpec("learner_priority", rl_api.DEFAULT_LEARNER_PRIORITY,
                  "scheduler priority of the learner gang"),
        ParamSpec("learner_steps", 100, "optimizer steps to run"),
        ParamSpec("actor_replicas", 2, "rollout actors at start"),
        ParamSpec("actor_min_replicas", 1,
                  "elastic floor the scheduler may shrink the pool to"),
        ParamSpec("actor_max_replicas", 4,
                  "elastic ceiling for opportunistic grow"),
        ParamSpec("actor_priority", rl_api.DEFAULT_ACTOR_PRIORITY,
                  "scheduler priority of the actor pool (preemptible)"),
        ParamSpec("push_every_steps", rl_api.DEFAULT_PUSH_EVERY_STEPS,
                  "optimizer steps between live weight pushes"),
        ParamSpec("weights_max_lag", rl_api.DEFAULT_WEIGHTS_MAX_LAG,
                  "max weight-epoch skew before an actor leaves "
                  "rollout routing"),
        ParamSpec("prompt_len", 8, "rollout prompt length"),
        ParamSpec("max_new_tokens", 16, "rollout generation length"),
        ParamSpec("chips_per_replica", 0,
                  "google.com/tpu chips per learner/actor pod (0 = CPU)"),
    ],
)
def rl_job(
    name: str,
    namespace: str,
    image: str,
    model: str,
    learner_replicas: int,
    learner_priority: int,
    learner_steps: int,
    actor_replicas: int,
    actor_min_replicas: int,
    actor_max_replicas: int,
    actor_priority: int,
    push_every_steps: int,
    weights_max_lag: int,
    prompt_len: int,
    max_new_tokens: int,
    chips_per_replica: int,
) -> list[dict]:
    cr = rl_api.rl_job(
        name,
        namespace,
        model,
        image=image,
        learner={
            "replicas": learner_replicas,
            "priority": learner_priority,
            "steps": learner_steps,
            "pushEverySteps": push_every_steps,
            "tpuChipsPerReplica": chips_per_replica,
        },
        actors={
            "replicas": actor_replicas,
            "minReplicas": actor_min_replicas,
            "maxReplicas": actor_max_replicas,
            "priority": actor_priority,
            "tpuChipsPerReplica": chips_per_replica,
            # The live weight-push path swaps under the paged pool's
            # continuous decoder; the operator pins these defaults too.
            "engine": {"kv_layout": "paged"},
        },
        rollout={"promptLen": prompt_len,
                 "maxNewTokens": max_new_tokens},
        weights={"maxLag": weights_max_lag},
    )
    rl_api.validate_rl_job(cr)
    return [rl_api.rl_job_crd(), cr]
