"""Third-party operator package: host an external operator + its CRD.

The reference bundles manifest sets for ecosystem operators — most
prominently spark-operator (/root/reference/kubeflow/spark/
build/spark-operator.yaml: CRD + Deployment + RBAC surface, with
prototypes/spark-operator.jsonnet params). Rather than one hand-written
package per product, the platform hosts ANY such operator through one
generic prototype: its CRD (schema preserved), scoped RBAC, the operator
Deployment, and an Application CR grouping the pieces so the platform's
application tracking reports the operator's readiness like any native
component.
"""

from __future__ import annotations

from kubeflow_tpu.apis.pipelines import PIPELINES_API_VERSION
from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.manifests.core import ParamSpec, prototype
from kubeflow_tpu.version import DEFAULT_NAMESPACE


@prototype(
    "third-party-operator",
    "Host an external operator: CRD + RBAC + Deployment + Application "
    "tracking (the spark-operator package surface, generalized)",
    params=[
        ParamSpec("name", "REQUIRED", "operator name (e.g. spark-operator)"),
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("image", "REQUIRED", "operator image"),
        ParamSpec("crd_group", "REQUIRED",
                  "API group of the operator's CRD (e.g. "
                  "sparkoperator.k8s.io)"),
        ParamSpec("crd_kind", "REQUIRED", "CRD kind (e.g. SparkApplication)"),
        ParamSpec("crd_plural", None,
                  "CRD plural; default = kind lowercased + 's'"),
        ParamSpec("crd_version", "v1",
                  "served CRD version — match the operator's API "
                  "(spark-operator: v1beta2)"),
        ParamSpec("command", None, "container command override (list)"),
        ParamSpec("args", None, "container args (list)"),
        ParamSpec("metrics_port", 0, "prometheus port (0 = none)"),
    ],
)
def third_party_operator(
    name: str,
    namespace: str,
    image: str,
    crd_group: str,
    crd_kind: str,
    crd_plural: str | None,
    crd_version: str,
    command,
    args,
    metrics_port: int,
) -> list[dict]:
    labels = {"app": name, "app.kubernetes.io/name": name}
    plural = crd_plural or crd_kind.lower() + "s"
    # The external CRD: schema is the operator's own business — admit
    # anything under spec/status (exactly how the reference carries
    # spark-operator's CRD, build/spark-operator.yaml); the served
    # version must match the hosted operator's informers.
    crd = k8s.crd(
        group=crd_group,
        kind=crd_kind,
        plural=plural,
        categories=["kubeflow-tpu"],
        versions=[k8s.crd_version(
            crd_version,
            schema={"type": "object",
                    "x-kubernetes-preserve-unknown-fields": True},
            storage=True,
        )],
    )
    annotations = None
    if metrics_port:
        annotations = {"prometheus.io/scrape": "true",
                       "prometheus.io/port": str(metrics_port)}
    return [
        crd,
        k8s.service_account(name, namespace, labels),
        k8s.cluster_role(
            name,
            [
                # The operator owns its group; everything else is the
                # standard workload surface external operators drive.
                k8s.policy_rule([crd_group], ["*"], ["*"]),
                k8s.policy_rule([""], ["pods", "services", "configmaps",
                                       "events"], ["*"]),
                k8s.policy_rule(["apps"], ["deployments", "statefulsets"],
                                ["*"]),
            ],
            labels,
        ),
        k8s.cluster_role_binding(name, name, name, namespace),
        k8s.deployment(
            name,
            namespace,
            containers=[k8s.container(
                name,
                image,
                command=list(command) if command else None,
                args=[str(a) for a in args] if args else None,
                ports={"metrics": metrics_port} if metrics_port else None,
            )],
            labels=labels,
            pod_annotations=annotations,
            service_account=name,
        ),
        # Application CR: the platform's component tracking reports the
        # hosted operator's readiness (application.libsonnet role).
        {
            "apiVersion": PIPELINES_API_VERSION,
            "kind": "Application",
            "metadata": {"name": name, "namespace": namespace,
                         "labels": labels},
            "spec": {
                "selector": {"matchLabels": {"app": name}},
                "componentKinds": [{"group": "apps", "kind": "Deployment"}],
                "descriptor": {"type": "third-party-operator",
                               "description": f"hosted operator for "
                                              f"{crd_group}/{crd_kind}"},
            },
        },
    ]
