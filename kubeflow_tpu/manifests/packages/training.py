"""Training package: job CRDs + the training operator deployment.

The equivalent of the reference's five operator packages —
kubeflow/tf-training/tf-job-operator.libsonnet (CRD :52-97, operator
Deployment :99-143, ConfigMap :180-196, RBAC :200-350, dashboard :353-488),
kubeflow/pytorch-job, kubeflow/mxnet-job, kubeflow/chainer-job,
kubeflow/mpi-job — collapsed into one TPU-native operator that serves all six
job kinds (JaxJob native + five compatibility kinds).

Job prototypes mirror the reference's example prototypes
(kubeflow/examples/prototypes/tf-job-simple-v1beta2.jsonnet,
kubeflow/pytorch-job/prototypes/pytorch-job.jsonnet,
kubeflow/mpi-job/prototypes/mpi-job-custom.jsonnet) with `numGpus` replaced by
TPU accelerator/topology params.
"""

from __future__ import annotations

from kubeflow_tpu.apis import jobs as jobs_api
from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.manifests import images
from kubeflow_tpu.manifests.core import ParamSpec, gateway_route, prototype
from kubeflow_tpu.version import API_GROUP, DEFAULT_NAMESPACE


@prototype(
    "training-operator",
    "Job CRDs (JaxJob/TFJob/PyTorchJob/MXNetJob/ChainerJob/MPIJob) + the "
    "training-operator Deployment, RBAC and config",
    params=[
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("image", images.PLATFORM),
        ParamSpec("replicas", 1, "operator replicas (leader-elected)"),
        ParamSpec("default_workload_image", images.JAX_TPU),
        ParamSpec("cluster_scoped", True, "watch all namespaces (RBAC scope)"),
        ParamSpec("conversion_ca_bundle", "",
                  "base64 CA for the conversion webhook's serving cert "
                  "(render from the platform Issuer's caCertificate); a "
                  "real apiserver requires it to call /convert for the "
                  "served v1beta1 job API"),
    ],
)
def training_operator(
    namespace: str,
    image: str,
    replicas: int,
    default_workload_image: str,
    cluster_scoped: bool,
    conversion_ca_bundle: str,
) -> list[dict]:
    name = "training-operator"
    labels = {"app": name, "app.kubernetes.io/part-of": "kubeflow-tpu"}
    objs: list[dict] = list(jobs_api.all_job_crds(
        conversion_namespace=namespace,
        conversion_ca_bundle=conversion_ca_bundle))

    # ConfigMap (the grpcServerFilePath/default-image config analogue,
    # tf-job-operator.libsonnet:180-196), mounted at /etc/config/config.yaml
    import yaml as _yaml

    objs.append(
        k8s.config_map(
            f"{name}-config",
            namespace,
            {
                "config.yaml": _yaml.safe_dump(
                    {"defaultWorkloadImage": default_workload_image}, sort_keys=True
                )
            },
            labels=labels,
        )
    )

    objs.append(k8s.service_account(name, namespace, labels))
    # The manager also runs the RLJob controller (operators/rl.py),
    # which reconciles RLJobs into learner/actor JaxJob children — so
    # the operator needs the rljobs surface next to the job kinds.
    from kubeflow_tpu.apis import rl as rl_api

    rules = [
        k8s.policy_rule(
            [API_GROUP],
            [p for p in jobs_api.PLURALS.values()]
            + [f"{p}/status" for p in jobs_api.PLURALS.values()]
            + [rl_api.RL_PLURAL, f"{rl_api.RL_PLURAL}/status"],
            ["*"],
        ),
        k8s.policy_rule([""], ["pods", "services", "events", "configmaps"], ["*"]),
        k8s.policy_rule(["apps"], ["deployments", "statefulsets"], ["get", "list", "watch"]),
        # Leader election holds a Lease when running replicated.
        k8s.policy_rule(["coordination.k8s.io"], ["leases"],
                        ["get", "list", "watch", "create", "update"]),
    ]
    if cluster_scoped:
        objs.append(k8s.cluster_role(name, rules, labels))
        objs.append(k8s.cluster_role_binding(name, name, name, namespace))
    else:
        objs.append(k8s.role(name, namespace, rules))
        objs.append(
            k8s.role_binding(
                name,
                namespace,
                name,
                [{"kind": "ServiceAccount", "name": name, "namespace": namespace}],
            )
        )

    objs.append(
        k8s.deployment(
            name,
            namespace,
            containers=[
                k8s.container(
                    name,
                    image,
                    command=["python", "-m", "kubeflow_tpu.operators"],
                    args=["--alsologtostderr", "-v=1"]
                    + (["--leader-elect",
                        "--leader-elect-name", name]
                       if replicas > 1 else []),
                    env={"OPERATOR_CONFIG": "/etc/config/config.yaml"},
                    ports={"metrics": 8443},
                    volume_mounts=[k8s.volume_mount("config", "/etc/config", read_only=True)],
                )
            ],
            replicas=replicas,
            labels=labels,
            service_account=name,
            volumes=[k8s.config_map_volume("config", f"{name}-config")],
            # The manager's HealthServer exposes the operator runtime
            # registry (reconcile latency, workqueue depth/adds/retries,
            # watch reopens, conflicts — labeled by kind) on :8443.
            pod_annotations={
                "prometheus.io/scrape": "true",
                "prometheus.io/path": "/metrics",
                "prometheus.io/port": "8443",
            },
        )
    )
    return objs


@prototype(
    "training-dashboard",
    "Training-job dashboard Service + Deployment with gateway route "
    "(tf-job-dashboard analogue, tf-job-operator.libsonnet:353-488)",
    params=[
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("image", images.PLATFORM),
    ],
)
def training_dashboard(namespace: str, image: str) -> list[dict]:
    name = "training-dashboard"
    labels = {"app": name}
    return [
        k8s.service(
            name,
            namespace,
            selector=labels,
            ports=[{"name": "http", "port": 80, "targetPort": 8085}],
            labels=labels,
            annotations=gateway_route(name, f"/{name}/", f"{name}.{namespace}:80"),
        ),
        k8s.deployment(
            name,
            namespace,
            containers=[
                k8s.container(
                    name,
                    image,
                    command=["python", "-m", "kubeflow_tpu.dashboard.training"],
                    ports={"http": 8085},
                )
            ],
            labels=labels,
            service_account="training-operator",
        ),
    ]


# ---------------------------------------------------------------------------
# Job prototypes
# ---------------------------------------------------------------------------


def _worker_template(image: str, command: list[str], num_tpu_chips: int) -> dict:
    resources = jobs_api.tpu_resources(num_tpu_chips)
    return {
        "spec": {
            "containers": [
                k8s.container("worker", image, command=command, resources=resources)
            ],
            "restartPolicy": "Never",
        }
    }


def _job(
    kind: str,
    name: str,
    namespace: str,
    replica_specs: dict,
    accelerator: str,
    topology: str,
    num_slices: int = 1,
    clean_pod_policy: str = "Running",
) -> dict:
    return {
        "apiVersion": jobs_api.JOBS_API_VERSION,
        "kind": kind,
        "metadata": k8s.metadata(name, namespace),
        "spec": {
            "replicaSpecs": replica_specs,
            "tpu": {
                "accelerator": accelerator,
                "topology": topology,
                "numSlices": num_slices,
            },
            "runPolicy": {"cleanPodPolicy": clean_pod_policy},
        },
    }


_JOB_PARAMS = [
    ParamSpec("name"),
    ParamSpec("namespace", DEFAULT_NAMESPACE),
    ParamSpec("image", images.JAX_TPU),
    ParamSpec("num_workers", 2, "worker pods (one per TPU VM host)"),
    ParamSpec("accelerator", "v5litepod-8", "TPU slice type"),
    ParamSpec("topology", "2x4", "slice chip topology"),
    ParamSpec("num_slices", 1, "multislice count (DCN-connected)"),
    ParamSpec("chips_per_worker", 4, "google.com/tpu chips per worker pod"),
]


@prototype(
    "jax-job-simple",
    "A simple JaxJob running an allreduce smoke workload "
    "(tf-job-simple analogue, kubeflow/examples/prototypes/tf-job-simple-v1beta2.jsonnet)",
    params=_JOB_PARAMS
    + [ParamSpec("command", None, "override container command (list)")],
)
def jax_job_simple(
    name: str,
    namespace: str,
    image: str,
    num_workers: int,
    accelerator: str,
    topology: str,
    num_slices: int,
    chips_per_worker: int,
    command,
) -> list[dict]:
    command = command or [
        "python",
        "-m",
        "kubeflow_tpu.workloads.allreduce_smoke",
    ]
    return [
        _job(
            jobs_api.JAX_JOB_KIND,
            name,
            namespace,
            {
                "Worker": {
                    "replicas": num_workers,
                    "restartPolicy": "OnFailure",
                    "template": _worker_template(image, command, chips_per_worker),
                }
            },
            accelerator,
            topology,
            num_slices,
        )
    ]


@prototype(
    "tf-job",
    "TFJob with Chief/PS/Worker replicas (compat surface of "
    "kubeflow/tf-training; lowered to SPMD on TPU by the operator)",
    params=_JOB_PARAMS + [ParamSpec("num_ps", 0), ParamSpec("command", None)],
)
def tf_job(
    name: str,
    namespace: str,
    image: str,
    num_workers: int,
    accelerator: str,
    topology: str,
    num_slices: int,
    chips_per_worker: int,
    num_ps: int,
    command,
) -> list[dict]:
    command = command or ["python", "-m", "kubeflow_tpu.workloads.tf_cnn"]
    specs = {
        "Worker": {
            "replicas": num_workers,
            "restartPolicy": "OnFailure",
            "template": _worker_template(image, command, chips_per_worker),
        }
    }
    if num_ps:
        specs["PS"] = {
            "replicas": num_ps,
            "restartPolicy": "OnFailure",
            "template": _worker_template(image, command, 0),
        }
    return [
        _job(jobs_api.TF_JOB_KIND, name, namespace, specs, accelerator, topology, num_slices)
    ]


@prototype(
    "pytorch-job",
    "PyTorchJob with Master/Worker replicas on torch-xla "
    "(kubeflow/pytorch-job/prototypes/pytorch-job.jsonnet:8-32 with "
    "numGpus→TPU chips)",
    params=_JOB_PARAMS + [ParamSpec("command", None)],
)
def pytorch_job(
    name: str,
    namespace: str,
    image: str,
    num_workers: int,
    accelerator: str,
    topology: str,
    num_slices: int,
    chips_per_worker: int,
    command,
) -> list[dict]:
    command = command or ["python", "-m", "kubeflow_tpu.workloads.torch_xla_ddp"]
    return [
        _job(
            jobs_api.PYTORCH_JOB_KIND,
            name,
            namespace,
            {
                "Master": {
                    "replicas": 1,
                    "restartPolicy": "OnFailure",
                    "template": _worker_template(image, command, chips_per_worker),
                },
                "Worker": {
                    "replicas": num_workers,
                    "restartPolicy": "OnFailure",
                    "template": _worker_template(image, command, chips_per_worker),
                },
            },
            accelerator,
            topology,
            num_slices,
        )
    ]


@prototype(
    "mpi-job",
    "MPIJob-equivalent: Launcher/Worker allreduce over ICI via JAX collectives "
    "(kubeflow/mpi-job/prototypes/mpi-job-custom.jsonnet:35-59, no "
    "kubectl-delivery needed)",
    params=_JOB_PARAMS + [ParamSpec("command", None)],
)
def mpi_job(
    name: str,
    namespace: str,
    image: str,
    num_workers: int,
    accelerator: str,
    topology: str,
    num_slices: int,
    chips_per_worker: int,
    command,
) -> list[dict]:
    command = command or ["python", "-m", "kubeflow_tpu.workloads.allreduce_bench"]
    # Launcher runs the mpi_launcher wrapper: writes the controller-shipped
    # hostfile, waits for worker DNS, then mpirun (or single-process
    # fallback) — the kubectl-delivery contract completed in-image.
    launcher_command = [
        "python", "-m", "kubeflow_tpu.workloads.mpi_launcher", "--", *command,
    ]
    return [
        _job(
            jobs_api.MPI_JOB_KIND,
            name,
            namespace,
            {
                "Launcher": {
                    "replicas": 1,
                    "restartPolicy": "OnFailure",
                    "template": _worker_template(image, launcher_command, 0),
                },
                "Worker": {
                    "replicas": num_workers,
                    "restartPolicy": "OnFailure",
                    "template": _worker_template(image, command, chips_per_worker),
                },
            },
            accelerator,
            topology,
            num_slices,
        )
    ]


@prototype(
    "mxnet-job",
    "MXNetJob compat surface (kubeflow/mxnet-job/prototypes/mxnet-job.jsonnet:9-12)",
    params=_JOB_PARAMS
    + [ParamSpec("num_schedulers", 1), ParamSpec("num_servers", 1), ParamSpec("command", None)],
)
def mxnet_job(
    name: str,
    namespace: str,
    image: str,
    num_workers: int,
    accelerator: str,
    topology: str,
    num_slices: int,
    chips_per_worker: int,
    num_schedulers: int,
    num_servers: int,
    command,
) -> list[dict]:
    command = command or ["python", "-m", "kubeflow_tpu.workloads.allreduce_smoke"]
    return [
        _job(
            jobs_api.MXNET_JOB_KIND,
            name,
            namespace,
            {
                "Scheduler": {
                    "replicas": num_schedulers,
                    "restartPolicy": "Never",
                    "template": _worker_template(image, command, 0),
                },
                "Server": {
                    "replicas": num_servers,
                    "restartPolicy": "Never",
                    "template": _worker_template(image, command, 0),
                },
                "Worker": {
                    "replicas": num_workers,
                    "restartPolicy": "Never",
                    "template": _worker_template(image, command, chips_per_worker),
                },
            },
            accelerator,
            topology,
            num_slices,
        )
    ]


@prototype(
    "chainer-job",
    "ChainerJob compat surface (kubeflow/chainer-job/prototypes/chainer-job.jsonnet:7-10)",
    params=_JOB_PARAMS + [ParamSpec("command", None)],
)
def chainer_job(
    name: str,
    namespace: str,
    image: str,
    num_workers: int,
    accelerator: str,
    topology: str,
    num_slices: int,
    chips_per_worker: int,
    command,
) -> list[dict]:
    command = command or ["python", "-m", "kubeflow_tpu.workloads.allreduce_smoke"]
    return [
        _job(
            jobs_api.CHAINER_JOB_KIND,
            name,
            namespace,
            {
                "Master": {
                    "replicas": 1,
                    "restartPolicy": "OnFailure",
                    "template": _worker_template(image, command, chips_per_worker),
                },
                "Worker": {
                    "replicas": num_workers,
                    "restartPolicy": "OnFailure",
                    "template": _worker_template(image, command, chips_per_worker),
                },
            },
            accelerator,
            topology,
            num_slices,
        )
    ]


@prototype(
    "slice-healthcheck",
    "Pre-flight TPU slice health probe JaxJob: device counts + timed psum "
    "over ICI (the GPU driver-wait/availability-prober analogue, "
    "openmpi controller.py:74-90, kubeflow-readiness.py:21-37)",
    params=_JOB_PARAMS,
)
def slice_healthcheck(
    name: str,
    namespace: str,
    image: str,
    num_workers: int,
    accelerator: str,
    topology: str,
    num_slices: int,
    chips_per_worker: int,
) -> list[dict]:
    command = [
        "python", "-m", "kubeflow_tpu.workloads.slice_health",
        f"--expect-local-devices={chips_per_worker or 1}",
    ]
    return [
        _job(
            jobs_api.JAX_JOB_KIND,
            name,
            namespace,
            {
                "Worker": {
                    "replicas": num_workers,
                    "restartPolicy": "OnFailure",
                    "template": _worker_template(image, command,
                                                 chips_per_worker),
                },
            },
            accelerator,
            topology,
            num_slices,
        )
    ]
