"""Secure-entrypoint package: certificate lifecycle + managed ingress + DNS.

The analogue of the reference's GCP entrypoint machinery — its largest
single package:

- ``cert-manager`` ↔ /root/reference/kubeflow/gcp/prototypes/cert-manager.jsonnet:1-12
  (upstream cert-manager Deployment with a letsencrypt ACME issuer): here
  the platform's own Issuer/Certificate CRDs + controller.
- ``secure-ingress`` ↔ prototypes/iap-ingress.jsonnet:5-12 +
  kubeflow/gcp/iap.libsonnet:1-1041 (envoy config, backend/cert wiring)
  + components/https-redirect: a gateway terminating TLS with a
  controller-managed certificate (hot-reloaded on rotation), an HTTP
  listener 301ing to HTTPS, the ACME challenge route, and bearer
  identity-token verification (the envoy jwt-auth filter,
  iap.libsonnet:589-600: issuer/audience/jwks_uri/bypass_jwt).
- ``cloud-endpoints`` ↔ prototypes/cloud-endpoints.jsonnet:1-11 (DNS
  records for <name>.endpoints.<project>.cloud.goog): an Endpoint CR the
  controller records into the platform DNS-zone ConfigMap.
"""

from __future__ import annotations

from kubeflow_tpu.apis.certificates import (
    CERT_API_GROUP,
    CERTS_API_VERSION,
    all_cert_crds,
)
from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.manifests import images
from kubeflow_tpu.manifests.core import ParamSpec, prototype
from kubeflow_tpu.version import DEFAULT_NAMESPACE


@prototype(
    "cert-manager",
    "Certificate lifecycle: Issuer/Certificate CRDs + the issuance and "
    "rotation controller (cert-manager.jsonnet analogue)",
    params=[
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("image", images.PLATFORM),
        ParamSpec("acme_url", "https://acme-v02.api.letsencrypt.org/directory",
                  "ACME directory for acme-type issuers "
                  "(cert-manager.jsonnet acmeUrl param)"),
        ParamSpec("acme_email", "", "registration email for acme issuers"),
    ],
)
def cert_manager(namespace: str, image: str, acme_url: str,
                 acme_email: str) -> list[dict]:
    name = "cert-manager"
    labels = {"app": name}
    return [
        *all_cert_crds(),
        k8s.service_account(name, namespace, labels),
        k8s.cluster_role(
            name,
            [
                k8s.policy_rule([CERT_API_GROUP], ["*"], ["*"]),
                k8s.policy_rule([""], ["secrets", "configmaps"], ["*"]),
            ],
            labels,
        ),
        k8s.cluster_role_binding(name, name, name, namespace),
        k8s.deployment(
            name,
            namespace,
            containers=[
                k8s.container(
                    name,
                    image,
                    command=["python", "-m",
                             "kubeflow_tpu.operators.certificate"],
                    args=[f"--namespace={namespace}"],
                    env={"ACME_DIRECTORY_URL": acme_url,
                         "ACME_EMAIL": acme_email},
                )
            ],
            labels=labels,
            service_account=name,
        ),
    ]


@prototype(
    "secure-ingress",
    "Public entrypoint: gateway TLS from a controller-managed certificate "
    "(hot rotation), https-redirect, ACME challenge route, DNS record "
    "(iap-ingress + https-redirect analogue)",
    params=[
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("image", images.PLATFORM),
        ParamSpec("hostname", "kubeflow.example.com",
                  "public hostname the certificate and DNS record cover"),
        ParamSpec("issuer", "platform-ca",
                  "Issuer the certificate references (iap-ingress "
                  "`issuer letsencrypt-prod` analogue)"),
        ParamSpec("issuer_type", "selfSigned", "selfSigned | acme"),
        ParamSpec("duration_seconds", 90 * 24 * 3600,
                  "certificate lifetime (letsencrypt-style 90d)"),
        ParamSpec("renew_before_seconds", 30 * 24 * 3600,
                  "rotate this long before expiry"),
        ParamSpec("replicas", 3),
        ParamSpec("jwt_issuer", "https://gatekeeper.kubeflow-tpu",
                  "iss claim required on bearer id-tokens (the envoy "
                  "jwt-auth filter, iap.libsonnet:589-600)"),
        ParamSpec("jwt_audience", "kubeflow-tpu",
                  "aud claim required on bearer id-tokens "
                  "({{JWT_AUDIENCE}} analogue)"),
        ParamSpec("jwks_uri", "http://gatekeeper:8085/.well-known/jwks.json",
                  "verification-key endpoint (jwks_uri analogue)"),
        ParamSpec("jwt_bypass",
                  '[{"http_method":"GET","path_exact":"/healthz"}]',
                  "JSON method+path list exempt from token checks "
                  "(bypass_jwt analogue)"),
        ParamSpec("disable_jwt_checking", False,
                  "serve without identity-token verification "
                  "(disableJwtChecking param analogue)"),
    ],
)
def secure_ingress(namespace: str, image: str, hostname: str, issuer: str,
                   issuer_type: str, duration_seconds: int,
                   renew_before_seconds: int, replicas: int,
                   jwt_issuer: str, jwt_audience: str, jwks_uri: str,
                   jwt_bypass: str, disable_jwt_checking: bool) -> list[dict]:
    name = "secure-gateway"
    labels = {"app": name, "service": "gateway"}
    cert_secret = f"{name}-tls"
    jwt_args = [] if disable_jwt_checking else [
        f"--jwt-issuer={jwt_issuer}",
        f"--jwt-audience={jwt_audience}",
        f"--jwks-uri={jwks_uri}",
        f"--jwt-bypass={jwt_bypass}",
    ]
    issuer_spec = ({"selfSigned": {"commonName": f"{issuer}.{namespace}"}}
                   if issuer_type == "selfSigned"
                   else {"acme": {}})
    return [
        {
            "apiVersion": CERTS_API_VERSION,
            "kind": "Issuer",
            "metadata": {"name": issuer, "namespace": namespace},
            "spec": issuer_spec,
        },
        {
            "apiVersion": CERTS_API_VERSION,
            "kind": "Certificate",
            "metadata": {"name": name, "namespace": namespace},
            "spec": {
                "secretName": cert_secret,
                "dnsNames": [hostname],
                "issuerRef": {"name": issuer},
                "durationSeconds": duration_seconds,
                "renewBeforeSeconds": renew_before_seconds,
            },
        },
        {
            "apiVersion": CERTS_API_VERSION,
            "kind": "Endpoint",
            "metadata": {"name": name, "namespace": namespace},
            "spec": {"hostname": hostname,
                     "target": f"{name}.{namespace}"},
        },
        # The prototype is self-contained: its own SA with route discovery
        # (services) plus the ACME-challenge ConfigMap read the
        # --serve-acme-challenges flag needs.
        k8s.service_account(name, namespace, labels),
        k8s.cluster_role(
            name,
            [
                k8s.policy_rule([""], ["services"],
                                ["get", "list", "watch"]),
                k8s.policy_rule([""], ["configmaps"], ["get"]),
            ],
            labels,
        ),
        k8s.cluster_role_binding(name, name, name, namespace),
        k8s.service(
            name,
            namespace,
            selector=labels,
            ports=[
                {"name": "https", "port": 443, "targetPort": 8443},
                {"name": "http", "port": 80, "targetPort": 8080},
            ],
            labels=labels,
            service_type="LoadBalancer",
        ),
        k8s.deployment(
            name,
            namespace,
            containers=[
                k8s.container(
                    name,
                    image,
                    command=["python", "-m", "kubeflow_tpu.gateway"],
                    args=[
                        "--port=8443",
                        "--redirect-port=8080",
                        "--admin-port=8877",
                        f"--namespace={namespace}",
                        "--tls-cert=/etc/tls/tls.crt",
                        "--tls-key=/etc/tls/tls.key",
                        "--watch-certs=5",
                        "--serve-acme-challenges",
                        *jwt_args,
                    ],
                    ports={"https": 8443, "http": 8080, "admin": 8877},
                    liveness_probe=k8s.http_probe("/healthz", 8877,
                                                  initial_delay=30),
                    readiness_probe=k8s.http_probe("/healthz", 8877),
                    volume_mounts=[
                        k8s.volume_mount("tls", "/etc/tls", read_only=True)
                    ],
                )
            ],
            replicas=replicas,
            labels=labels,
            service_account=name,
            volumes=[k8s.secret_volume("tls", cert_secret)],
        ),
    ]


@prototype(
    "cloud-endpoints",
    "DNS record for a platform hostname via the Endpoint CR "
    "(cloud-endpoints.jsonnet analogue)",
    params=[
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("hostname", "kubeflow.example.com"),
        ParamSpec("target", "gateway.kubeflow",
                  "service (or address) the hostname resolves to"),
    ],
)
def cloud_endpoints(namespace: str, hostname: str,
                    target: str) -> list[dict]:
    return [{
        "apiVersion": CERTS_API_VERSION,
        "kind": "Endpoint",
        "metadata": {"name": hostname.split(".")[0],
                     "namespace": namespace},
        "spec": {"hostname": hostname, "target": target},
    }]
