"""Common package: API gateway, central dashboard, usage reporting, echo server.

The analogue of kubeflow/common — ambassador gateway
(ambassador.libsonnet:7-226), centraldashboard (centraldashboard.libsonnet),
spartakus anonymous usage reporter (spartakus.libsonnet:1-122), echo-server.

The gateway here is our own: a reverse proxy that discovers routes from
`kubeflow-tpu.org/gateway-route` Service annotations (the getambassador.io/config
pattern) and fronts every platform web app on one port.
"""

from __future__ import annotations

from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.manifests import images
from kubeflow_tpu.manifests.core import ParamSpec, gateway_route, prototype
from kubeflow_tpu.version import DEFAULT_NAMESPACE


@prototype(
    "gateway",
    "API gateway: annotation-discovered reverse proxy fronting all platform "
    "UIs/APIs (ambassador analogue, kubeflow/common/ambassador.libsonnet:7-226)",
    params=[
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("image", images.PLATFORM),
        ParamSpec("replicas", 3, "gateway replicas (ambassador default 3)"),
        ParamSpec("service_type", "ClusterIP", "ClusterIP | NodePort | LoadBalancer"),
        ParamSpec("tls_secret", "",
                  "TLS Secret (tls.crt/tls.key) for HTTPS termination — "
                  "the iap-ingress/cert-manager role (empty = HTTP)"),
    ],
)
def gateway(namespace: str, image: str, replicas: int, service_type: str,
            tls_secret: str) -> list[dict]:
    name = "gateway"
    labels = {"app": name, "service": "gateway"}
    tls_args, tls_mounts, tls_volumes = [], [], []
    if tls_secret:
        tls_args = ["--tls-cert=/etc/tls/tls.crt",
                    "--tls-key=/etc/tls/tls.key"]
        tls_mounts = [k8s.volume_mount("tls", "/etc/tls", read_only=True)]
        tls_volumes = [k8s.secret_volume("tls", tls_secret)]
    return [
        k8s.service_account(name, namespace, labels),
        k8s.cluster_role(
            name,
            [k8s.policy_rule([""], ["services"], ["get", "list", "watch"])],
            labels,
        ),
        k8s.cluster_role_binding(name, name, name, namespace),
        k8s.service(
            name,
            namespace,
            selector=labels,
            ports=[{"name": "http", "port": 80, "targetPort": 8080}],
            labels=labels,
            service_type=service_type,
        ),
        k8s.service(
            f"{name}-admin",
            namespace,
            selector=labels,
            ports=[{"name": "admin", "port": 8877, "targetPort": 8877}],
            labels=labels,
        ),
        k8s.deployment(
            name,
            namespace,
            containers=[
                k8s.container(
                    name,
                    image,
                    command=["python", "-m", "kubeflow_tpu.gateway"],
                    args=["--port=8080", "--admin-port=8877",
                          f"--namespace={namespace}"] + tls_args,
                    ports={"http": 8080, "admin": 8877},
                    liveness_probe=k8s.http_probe("/healthz", 8877, initial_delay=30),
                    readiness_probe=k8s.http_probe("/healthz", 8877),
                    volume_mounts=tls_mounts or None,
                )
            ],
            replicas=replicas,
            labels=labels,
            service_account=name,
            volumes=tls_volumes or None,
        ),
    ]


@prototype(
    "centraldashboard",
    "Central dashboard web app (kubeflow/common/centraldashboard.libsonnet)",
    params=[
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("image", images.PLATFORM),
    ],
)
def centraldashboard(namespace: str, image: str) -> list[dict]:
    name = "centraldashboard"
    labels = {"app": name}
    return [
        k8s.service_account(name, namespace, labels),
        k8s.cluster_role(
            name,
            [
                k8s.policy_rule(
                    [""], ["pods", "events", "namespaces", "nodes"], ["get", "list", "watch"]
                ),
                k8s.policy_rule(
                    ["kubeflow-tpu.org"], ["*"], ["get", "list", "watch"]
                ),
            ],
            labels,
        ),
        k8s.cluster_role_binding(name, name, name, namespace),
        k8s.service(
            name,
            namespace,
            selector=labels,
            ports=[{"name": "http", "port": 80, "targetPort": 8082}],
            labels=labels,
            annotations=gateway_route(name, "/", f"{name}.{namespace}:80"),
        ),
        k8s.deployment(
            name,
            namespace,
            containers=[
                k8s.container(
                    name,
                    image,
                    command=["python", "-m", "kubeflow_tpu.dashboard"],
                    ports={"http": 8082},
                    liveness_probe=k8s.http_probe("/healthz", 8082, initial_delay=30),
                )
            ],
            labels=labels,
            service_account=name,
        ),
    ]


@prototype(
    "usage-reporter",
    "Anonymous usage reporter, opt-in (spartakus analogue, "
    "kubeflow/common/spartakus.libsonnet:1-122)",
    params=[
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("image", images.PLATFORM),
        ParamSpec("usage_id", "unknown_cluster"),
        ParamSpec("report_usage", False, "actually send reports (default off)"),
    ],
)
def usage_reporter(
    namespace: str, image: str, usage_id: str, report_usage: bool
) -> list[dict]:
    name = "usage-reporter"
    labels = {"app": name}
    return [
        k8s.service_account(name, namespace, labels),
        k8s.cluster_role(
            name, [k8s.policy_rule([""], ["nodes"], ["get", "list"])], labels
        ),
        k8s.cluster_role_binding(name, name, name, namespace),
        k8s.deployment(
            name,
            namespace,
            containers=[
                k8s.container(
                    name,
                    image,
                    command=["python", "-m", "kubeflow_tpu.utils.usage_reporter"],
                    args=[
                        f"--usage-id={usage_id}",
                        f"--enabled={'true' if report_usage else 'false'}",
                    ],
                )
            ],
            labels=labels,
            service_account=name,
        ),
    ]


@prototype(
    "echo-server",
    "Echo server for gateway/auth debugging (components/echo-server)",
    params=[
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("image", images.PLATFORM),
    ],
)
def echo_server(namespace: str, image: str) -> list[dict]:
    name = "echo-server"
    labels = {"app": name}
    return [
        k8s.service(
            name,
            namespace,
            selector=labels,
            ports=[{"name": "http", "port": 80, "targetPort": 8083}],
            labels=labels,
            annotations=gateway_route(name, "/echo/", f"{name}.{namespace}:80"),
        ),
        k8s.deployment(
            name,
            namespace,
            containers=[
                k8s.container(
                    name,
                    image,
                    command=["python", "-m", "kubeflow_tpu.utils.echo_server"],
                    ports={"http": 8083},
                )
            ],
            labels=labels,
        ),
    ]


@prototype(
    "bootstrapper",
    "In-cluster deploy REST service backing click-to-deploy "
    "(bootstrap/cmd/bootstrap/app/ksServer.go:1452-1460 analogue)",
    params=[
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("image", images.PLATFORM),
    ],
)
def bootstrapper(namespace: str, image: str) -> list[dict]:
    name = "bootstrapper"
    labels = {"app": name}
    return [
        k8s.service_account(name, namespace, labels),
        # The bootstrapper applies arbitrary platform manifests on request.
        k8s.cluster_role_binding(name, "cluster-admin", name, namespace),
        k8s.service(
            name,
            namespace,
            selector=labels,
            ports=[{"name": "http", "port": 80, "targetPort": 8085}],
            labels=labels,
            annotations=gateway_route(
                name, "/kfctl/", f"{name}.{namespace}:80", rewrite="/kfctl/"
            ),
        ),
        _bootstrapper_deployment(name, namespace, image, labels),
    ]


def _bootstrapper_deployment(name, namespace, image, labels) -> dict:
    container = k8s.container(
        name,
        image,
        command=["python", "-m", "kubeflow_tpu.bootstrap",
                 "--port", "8085"],
        ports={"http": 8085},
    )
    # App dirs survive container restarts (the reference persists app state
    # to a source repo, ksServer.go SaveAppToRepo:1006 — an emptyDir keeps
    # restart continuity; point a PVC here for real durability).
    container["volumeMounts"] = [
        {"name": "apps", "mountPath": "/var/lib/kubeflow-tpu"}
    ]
    deployment = k8s.deployment(
        name,
        namespace,
        containers=[container],
        labels=labels,
        service_account=name,
    )
    deployment["spec"]["template"]["spec"]["volumes"] = [
        {"name": "apps", "emptyDir": {}}
    ]
    return deployment
