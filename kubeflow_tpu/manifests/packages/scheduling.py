"""Scheduling package: the cluster scheduler's CRD, policy, RBAC and
Deployment.

The scheduler (``python -m kubeflow_tpu.scheduler``) is the placement
authority for every training-job kind: capacity model over heterogeneous
TPU slice pools, weighted-fair priority queue with starvation aging,
all-or-nothing gang admission, and priority preemption riding the
gang-coordinated SIGTERM checkpoint path (docs/scheduling.md).
"""

from __future__ import annotations

from kubeflow_tpu.apis import jobs as jobs_api
from kubeflow_tpu.apis import scheduling as sched_api
from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.manifests import images
from kubeflow_tpu.manifests.core import ParamSpec, prototype
from kubeflow_tpu.version import API_GROUP, DEFAULT_NAMESPACE


@prototype(
    "scheduler",
    "SchedulingPolicy CRD + default policy + the cluster-scheduler "
    "Deployment and RBAC (gang placement, priorities, preemption)",
    params=[
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("image", images.PLATFORM),
        ParamSpec("replicas", 1, "scheduler replicas (leader-elected)"),
        ParamSpec("scheduling_period_seconds", 5,
                  "queue scan cadence when no event fires"),
        ParamSpec("aging_seconds", 300,
                  "queue-wait seconds worth one priority point "
                  "(starvation aging; 0 disables)"),
        ParamSpec("preemption_enabled", True,
                  "higher-priority gangs may evict lower-priority ones"),
        ParamSpec("requeue_backoff_seconds", 10,
                  "delay before a preempted gang is eligible again"),
        ParamSpec("grace_period_seconds", 30,
                  "SIGTERM→SIGKILL eviction grace (the checkpoint window)"),
    ],
)
def scheduler(
    namespace: str,
    image: str,
    replicas: int,
    scheduling_period_seconds: int,
    aging_seconds: int,
    preemption_enabled: bool,
    requeue_backoff_seconds: int,
    grace_period_seconds: int,
) -> list[dict]:
    name = "scheduler"
    labels = {"app": name, "app.kubernetes.io/part-of": "kubeflow-tpu"}
    objs: list[dict] = [sched_api.scheduling_policy_crd()]
    objs.append(sched_api.scheduling_policy(
        "default", namespace,
        schedulingPeriodSeconds=scheduling_period_seconds,
        agingSeconds=aging_seconds,
        preemption={
            "enabled": preemption_enabled,
            "requeueBackoffSeconds": requeue_backoff_seconds,
            "gracePeriodSeconds": grace_period_seconds,
        },
    ))
    objs.append(k8s.service_account(name, namespace, labels))
    rules = [
        # Placement decisions: annotation patches + status.scheduling
        # mirrors on every job kind, and the policy it reconciles.
        k8s.policy_rule(
            [API_GROUP],
            [p for p in jobs_api.PLURALS.values()]
            + [f"{p}/status" for p in jobs_api.PLURALS.values()]
            + [sched_api.SCHEDULING_POLICY_PLURAL,
               f"{sched_api.SCHEDULING_POLICY_PLURAL}/status"],
            ["*"],
        ),
        # Victim marking + evictions; nodes feed the capacity model.
        k8s.policy_rule([""], ["pods", "pods/status", "pods/eviction",
                               "events"], ["*"]),
        k8s.policy_rule([""], ["nodes"], ["get", "list", "watch"]),
        # Leader election holds a Lease when running replicated.
        k8s.policy_rule(["coordination.k8s.io"], ["leases"],
                        ["get", "list", "watch", "create", "update"]),
    ]
    objs.append(k8s.cluster_role(name, rules, labels))
    objs.append(k8s.cluster_role_binding(name, name, name, namespace))
    objs.append(
        k8s.deployment(
            name,
            namespace,
            containers=[
                k8s.container(
                    name,
                    image,
                    command=["python", "-m", "kubeflow_tpu.scheduler"],
                    args=["--alsologtostderr", "-v=1"]
                    + (["--leader-elect", "--leader-elect-name", name]
                       if replicas > 1 else []),
                    ports={"metrics": 8444},
                )
            ],
            replicas=replicas,
            labels=labels,
            service_account=name,
            # The manager's HealthServer exposes the scheduler decision
            # metrics (queue depth/wait by queue, placement latency,
            # preemptions/requeues by reason) next to the operator
            # runtime registry on :8444.
            pod_annotations={
                "prometheus.io/scrape": "true",
                "prometheus.io/path": "/metrics",
                "prometheus.io/port": "8444",
            },
        )
    )
    return objs
