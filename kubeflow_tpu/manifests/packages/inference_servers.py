"""Third-party inference-server package.

The reference ships manifest packages for external serving systems —
seldon (kubeflow/seldon/core.libsonnet), nvidia-inference-server,
openvino — that are GPU/x86 products with no TPU analogue to port. What
their packages actually provide is "run an arbitrary inference image with
the platform's routing/monitoring glue"; this package keeps that capability
as one generic prototype: any OCI inference server + its ports, wired with
the gateway route, prometheus annotations, and optional TPU resources.
"""

from __future__ import annotations

from kubeflow_tpu.apis.jobs import tpu_resources
from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.manifests.core import ParamSpec, gateway_route, prototype
from kubeflow_tpu.version import DEFAULT_NAMESPACE


@prototype(
    "inference-server",
    "Generic third-party inference server Deployment + routed Service "
    "(the seldon/nvidia/openvino package family generalized)",
    params=[
        ParamSpec("name"),
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("image", "REQUIRED", "inference server image"),
        ParamSpec("port", 8080, "HTTP predict port"),
        ParamSpec("command", None, "container command override (list)"),
        ParamSpec("args", None, "container args (list)"),
        ParamSpec("replicas", 1),
        ParamSpec("num_tpu_chips", 0, "google.com/tpu per replica"),
        ParamSpec("route_prefix", "", "gateway prefix (default /<name>/)"),
    ],
)
def inference_server(
    name: str,
    namespace: str,
    image: str,
    port: int,
    command,
    args,
    replicas: int,
    num_tpu_chips: int,
    route_prefix: str,
) -> list[dict]:
    labels = {"app": name, "app.kubernetes.io/component": "inference"}
    prefix = route_prefix or f"/{name}/"
    container = k8s.container(
        name,
        image,
        command=list(command) if command else None,
        args=[str(a) for a in args] if args else None,
        ports={"http": port},
        resources=tpu_resources(num_tpu_chips),
    )
    return [
        k8s.deployment(
            name,
            namespace,
            containers=[container],
            replicas=replicas,
            labels=labels,
        ),
        k8s.service(
            name,
            namespace,
            selector=labels,
            ports=[{"name": "http", "port": port, "targetPort": port}],
            labels=labels,
            annotations={
                **gateway_route(name, prefix, f"{name}.{namespace}:{port}"),
                "prometheus.io/scrape": "true",
                "prometheus.io/port": str(port),
            },
        ),
    ]
