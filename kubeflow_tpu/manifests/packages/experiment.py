"""Experiment package: the self-tuning engine's CRD + RBAC + example CR.

Katib's Experiment layered over kubebench measured runs, fused into one
CRD (see apis/experiment.py). The controller itself rides in the
training-operator manager (operators/__main__.py) — this package ships
what a cluster needs to admit Experiments: the CRD, a ClusterRole that
can run trials (JaxJobs) and promote winners (InferenceService spec
writes), and a worked example CR tuning the decode-tps scenario.
"""

from __future__ import annotations

from kubeflow_tpu.apis.experiment import experiment, experiment_crd
from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.manifests.core import ParamSpec, prototype
from kubeflow_tpu.version import API_GROUP, DEFAULT_NAMESPACE


@prototype(
    "experiment",
    "Experiment CRD + RBAC + example CR: knob search over a bench_serving "
    "scenario, winner promoted through the rollout controller",
    params=[
        ParamSpec("name", "decode-knobs"),
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("scenario", "decode-tps"),
        ParamSpec("algorithm", "tpe",
                  "random|grid|hyperband|bayesianoptimization|tpe"),
        ParamSpec("max_trials", 12),
        ParamSpec("seed", 0),
        ParamSpec("target", "", "InferenceService the winner promotes to"),
    ],
)
def experiment_package(name: str, namespace: str, scenario: str,
                       algorithm: str, max_trials: int, seed: int,
                       target: str) -> list[dict]:
    rbac_name = "experiment-controller"
    labels = {"app": rbac_name}
    promotion = {"target": target, "minImprovementPercent": 1.0} \
        if target else None
    return [
        experiment_crd(),
        k8s.service_account(rbac_name, namespace, labels),
        k8s.cluster_role(
            rbac_name,
            [
                k8s.policy_rule(
                    [API_GROUP],
                    ["experiments", "experiments/status"], ["*"]),
                # Trials are preemptible JaxJobs.
                k8s.policy_rule(
                    [API_GROUP], ["jaxjobs", "jaxjobs/status"], ["*"]),
                # Promotion writes the candidate version onto the target
                # InferenceService; the rollout controller walks it.
                k8s.policy_rule(
                    [API_GROUP],
                    ["inferenceservices", "inferenceservices/status"],
                    ["get", "list", "watch", "update", "patch"]),
                k8s.policy_rule([""], ["events"], ["create", "patch"]),
            ],
            labels,
        ),
        k8s.cluster_role_binding(rbac_name, rbac_name, rbac_name,
                                 namespace),
        experiment(
            name, namespace, scenario,
            algorithm=algorithm,
            max_trials=int(max_trials),
            seed=int(seed),
            promotion=promotion,
        ),
    ]
