"""Notebooks package: notebook-controller + jupyter-web-app.

The analogue of kubeflow/jupyter (JupyterHub StatefulSet, jupyter.libsonnet:128-160,
spawner config :10-33) and components/{notebook-controller,jupyter-web-app}.
TPU-native: notebook images ship JAX + libtpu (replacing the CUDA tensorflow
notebook matrix, components/tensorflow-notebook-image), and notebooks can
request google.com/tpu chips.
"""

from __future__ import annotations

from kubeflow_tpu.apis.notebooks import notebook_crd
from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.manifests import images
from kubeflow_tpu.manifests.core import ParamSpec, gateway_route, prototype
from kubeflow_tpu.version import API_GROUP, DEFAULT_NAMESPACE


@prototype(
    "notebook-controller",
    "Notebook CRD + controller: materialises Notebook CRs as StatefulSet + "
    "Service with gateway routes (components/notebook-controller analogue)",
    params=[
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("image", images.PLATFORM),
    ],
)
def notebook_controller(namespace: str, image: str) -> list[dict]:
    name = "notebook-controller"
    labels = {"app": name}
    return [
        notebook_crd(),
        k8s.service_account(name, namespace, labels),
        k8s.cluster_role(
            name,
            [
                k8s.policy_rule([API_GROUP], ["notebooks", "notebooks/status"], ["*"]),
                k8s.policy_rule([""], ["services", "events"], ["*"]),
                k8s.policy_rule(["apps"], ["statefulsets"], ["*"]),
                k8s.policy_rule([""], ["pods"], ["get", "list", "watch"]),
            ],
            labels,
        ),
        k8s.cluster_role_binding(name, name, name, namespace),
        k8s.deployment(
            name,
            namespace,
            containers=[
                k8s.container(
                    name,
                    image,
                    command=["python", "-m", "kubeflow_tpu.operators.notebook"],
                    ports={"metrics": 8443},
                )
            ],
            labels=labels,
            service_account=name,
        ),
    ]


@prototype(
    "jupyter-web-app",
    "Notebook CRUD web UI: lists/creates/deletes Notebook CRs + PVCs "
    "(components/jupyter-web-app routes.py:33-168 analogue)",
    params=[
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("image", images.PLATFORM),
        ParamSpec("default_notebook_image", images.NOTEBOOK),
    ],
)
def jupyter_web_app(namespace: str, image: str, default_notebook_image: str) -> list[dict]:
    name = "jupyter-web-app"
    labels = {"app": name}
    return [
        k8s.service_account(name, namespace, labels),
        k8s.cluster_role(
            name,
            [
                k8s.policy_rule([API_GROUP], ["notebooks"], ["*"]),
                k8s.policy_rule(
                    [""],
                    ["persistentvolumeclaims", "namespaces", "pods", "pods/log", "events"],
                    ["get", "list", "watch", "create", "delete"],
                ),
                k8s.policy_rule(["storage.k8s.io"], ["storageclasses"], ["get", "list"]),
            ],
            labels,
        ),
        k8s.cluster_role_binding(name, name, name, namespace),
        k8s.config_map(
            f"{name}-config",
            namespace,
            {"defaultNotebookImage": default_notebook_image},
            labels=labels,
        ),
        k8s.service(
            name,
            namespace,
            selector=labels,
            ports=[{"name": "http", "port": 80, "targetPort": 5000}],
            labels=labels,
            annotations=gateway_route(name, "/jupyter/", f"{name}.{namespace}:80"),
        ),
        k8s.deployment(
            name,
            namespace,
            containers=[
                k8s.container(
                    name,
                    image,
                    command=["python", "-m", "kubeflow_tpu.webapps.jupyter"],
                    args=[f"--default-image={default_notebook_image}"],
                    ports={"http": 5000},
                    liveness_probe=k8s.http_probe("/healthz", 5000, initial_delay=30),
                )
            ],
            labels=labels,
            service_account=name,
        ),
    ]
