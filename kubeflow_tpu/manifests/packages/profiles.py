"""Profiles package: multi-tenancy controller.

Analogue of kubeflow/profiles + components/profile-controller
(Reconcile at profile_controller.go:108-206, generateRole :207).
"""

from __future__ import annotations

from kubeflow_tpu.apis.profiles import profile_crd
from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.manifests import images
from kubeflow_tpu.manifests.core import ParamSpec, prototype
from kubeflow_tpu.version import API_GROUP, DEFAULT_NAMESPACE


@prototype(
    "profile-controller",
    "Profile CRD + controller: per-user namespace + namespaced-admin "
    "Role/RoleBinding (+ quota) per Profile CR",
    params=[
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("image", images.PLATFORM),
    ],
)
def profile_controller(namespace: str, image: str) -> list[dict]:
    name = "profile-controller"
    labels = {"app": name}
    return [
        profile_crd(),
        k8s.service_account(name, namespace, labels),
        k8s.cluster_role(
            name,
            [
                k8s.policy_rule([API_GROUP], ["profiles", "profiles/status"], ["*"]),
                k8s.policy_rule([""], ["namespaces", "resourcequotas", "events"], ["*"]),
                k8s.policy_rule(
                    ["rbac.authorization.k8s.io"], ["roles", "rolebindings"], ["*"]
                ),
            ],
            labels,
        ),
        k8s.cluster_role_binding(name, name, name, namespace),
        k8s.deployment(
            name,
            namespace,
            containers=[
                k8s.container(
                    name,
                    image,
                    command=["python", "-m", "kubeflow_tpu.operators.profile"],
                    ports={"metrics": 8443},
                )
            ],
            labels=labels,
            service_account=name,
        ),
    ]
