"""Pipelines package: workflow DAGs + Application aggregation.

Analogue of the reference's argo + application packages
(kubeflow/argo/argo.libsonnet:89-165 deploys the workflow-controller;
kubeflow/application/application.libsonnet:14-60 defines the Application CR
the final `kfctl apply` step instantiates, scripts/kfctl.sh:498-508).
"""

from __future__ import annotations

from kubeflow_tpu.apis.pipelines import (
    application_crd,
    scheduled_workflow_crd,
    workflow_crd,
)
from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.manifests import images
from kubeflow_tpu.manifests.core import ParamSpec, prototype
from kubeflow_tpu.version import API_GROUP, DEFAULT_NAMESPACE


@prototype(
    "pipeline-operator",
    "Workflow + Application CRDs and their controller "
    "(argo workflow-controller analogue)",
    params=[
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("image", images.PLATFORM),
        ParamSpec("artifact_claim", "kubeflow-artifacts",
                  "PVC backing the workflow artifact store (the minio "
                  "role); mounted into the operator and every task pod"),
        ParamSpec("artifact_claim_size", "50Gi"),
    ],
)
def pipeline_operator(namespace: str, image: str, artifact_claim: str,
                      artifact_claim_size: str) -> list[dict]:
    name = "pipeline-operator"
    labels = {"app": name}
    return [
        workflow_crd(),
        scheduled_workflow_crd(),
        application_crd(),
        # The artifact store's backing volume (minio.libsonnet's PVC
        # role): one shared filesystem for the operator (output indexing)
        # and every task pod (output writing / input resolution).
        {
            "apiVersion": "v1",
            "kind": "PersistentVolumeClaim",
            "metadata": {"name": artifact_claim, "namespace": namespace,
                         "labels": labels},
            "spec": {
                "accessModes": ["ReadWriteMany"],
                "resources": {
                    "requests": {"storage": artifact_claim_size}
                },
            },
        },
        k8s.service_account(name, namespace, labels),
        k8s.cluster_role(
            name,
            [
                k8s.policy_rule(
                    [API_GROUP],
                    ["workflows", "workflows/status",
                     "scheduledworkflows", "scheduledworkflows/status",
                     "applications", "applications/status"],
                    ["*"],
                ),
                # Tasks create job CRs / Deployments / Services on behalf
                # of the workflow.
                k8s.policy_rule(
                    [API_GROUP],
                    ["jaxjobs", "jaxjobs/status", "tfjobs", "pytorchjobs",
                     "mxnetjobs", "chainerjobs", "mpijobs"],
                    ["*"],
                ),
                k8s.policy_rule(
                    ["apps"], ["deployments", "statefulsets"], ["*"]
                ),
                k8s.policy_rule(
                    [""], ["services", "events"],
                    ["get", "list", "watch", "create", "patch"],
                ),
                # Durable run records (persistence-agent role) live in
                # ConfigMaps that outlast their Workflow CRs.
                k8s.policy_rule([""], ["configmaps"], ["*"]),
            ],
            labels,
        ),
        k8s.cluster_role_binding(name, name, name, namespace),
        k8s.deployment(
            name,
            namespace,
            containers=[
                k8s.container(
                    name,
                    image,
                    command=["python", "-m", "kubeflow_tpu.operators.pipeline"],
                    env={"KUBEFLOW_ARTIFACT_ROOT": "/artifacts"},
                    ports={"metrics": 8443},
                    volume_mounts=[
                        k8s.volume_mount("kubeflow-artifacts", "/artifacts")
                    ],
                )
            ],
            labels=labels,
            service_account=name,
            volumes=[{
                "name": "kubeflow-artifacts",
                "persistentVolumeClaim": {"claimName": artifact_claim},
            }],
        ),
    ]


@prototype(
    "scheduled-workflow",
    "Cron-scheduled Workflow stamping with run history "
    "(pipeline-scheduledworkflow + persistenceagent analogue, "
    "kubeflow/pipeline/pipeline-scheduledworkflow.libsonnet:1-60)",
    params=[
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("name", "nightly-train"),
        ParamSpec("schedule", "0 2 * * *", "5-field cron, UTC"),
        ParamSpec("max_concurrency", 1),
        ParamSpec("history_limit", 10,
                  "completed runs + records retained"),
        ParamSpec("image", images.JAX_TPU),
    ],
)
def scheduled_workflow(namespace: str, name: str, schedule: str,
                       max_concurrency: int, history_limit: int,
                       image: str) -> list[dict]:
    # Default stamped workflow: one single-worker JaxJob smoke train —
    # the canned-example role of kubeflow/examples prototypes; users
    # replace workflowSpec.tasks with their own DAG.
    from kubeflow_tpu.manifests.core import generate

    job = generate("jax-job-simple", {
        "name": f"{name}-train", "namespace": namespace, "image": image,
        "num_workers": 1,
    })[0]
    # No fixed name/namespace: each stamped run must get its own
    # '{workflow}-{task}' job — a shared name would make run N+1 adopt
    # run N's completed job and no-op.
    job["metadata"].pop("name", None)
    job["metadata"].pop("namespace", None)
    return [{
        "apiVersion": f"{API_GROUP}/v1",
        "kind": "ScheduledWorkflow",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {
            "schedule": schedule,
            "maxConcurrency": max_concurrency,
            "historyLimit": history_limit,
            "workflowSpec": {
                "tasks": [{"name": "train", "resource": job}],
            },
        },
    }]


@prototype(
    "application",
    "Application CR aggregating the deployed platform "
    "(application.libsonnet:14-60; applied last, kfctl.sh:498-508)",
    params=[
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("name", "kubeflow-tpu"),
    ],
)
def application(namespace: str, name: str) -> list[dict]:
    return [{
        "apiVersion": f"{API_GROUP}/v1",
        "kind": "Application",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {
            "selector": {"matchLabels": {}},
            "descriptor": {
                "type": "kubeflow-tpu",
                "description": "TPU-native ML platform deployment",
            },
        },
    }]
