"""Serving package: TPU model server deployment + service.

The analogue of kubeflow/tf-serving — model-server Deployment with gRPC :9000
and REST :8500 (tf-serving-template.libsonnet:29-49), model loaded from
GCS/S3/PVC (prototypes/tf-serving-gcp.jsonnet:8), TCP liveness probe on the
gRPC port (:70-75), prometheus monitoring (:127-130), gateway/istio routing
(tf-serving-service-template.libsonnet) — with tensorflow/serving replaced by
our TPU inference engine (kubeflow_tpu.serving) and nvidia.com/gpu variants
replaced by google.com/tpu.
"""

from __future__ import annotations

from kubeflow_tpu.apis.jobs import tpu_resources
from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.manifests import images
from kubeflow_tpu.manifests.core import ParamSpec, gateway_route, prototype
from kubeflow_tpu.version import DEFAULT_NAMESPACE

GRPC_PORT = 9000
REST_PORT = 8500


@prototype(
    "tpu-serving",
    "TPU model server Deployment: gRPC :9000 + REST :8500, model from "
    "gs://|s3://|pvc path, prometheus metrics, TPU resources",
    params=[
        ParamSpec("name"),
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("model_path", "", "gs://, s3://, /pvc/ or local model dir"),
        ParamSpec("model_name", "", "served model name (defaults to `name`)"),
        ParamSpec("image", images.SERVING),
        ParamSpec("replicas", 1),
        ParamSpec("num_tpu_chips", 1, "google.com/tpu chips per replica (0 = CPU)"),
        ParamSpec("batch_size", 8, "max server-side batch size"),
        ParamSpec("batch_timeout_ms", 5, "batching window"),
        ParamSpec("prefix_cache_slots", 0,
                  "device prefix-KV pool slots (0 disables prefix reuse)"),
        ParamSpec("prefix_cache_min_len", 16,
                  "shortest prompt prefix worth caching"),
        ParamSpec("prefill_len_buckets", 0,
                  "power-of-two prefill length buckets below the max "
                  "sequence length (0 = fixed-length prefill)"),
        ParamSpec("speculative_k", 0,
                  "draft tokens verified per fused decode dispatch "
                  "(0 disables speculative decoding)"),
        ParamSpec("draft_mode", "ngram",
                  "speculative draft proposer: ngram or "
                  "model:<registry-name>"),
        ParamSpec("kv_layout", "dense",
                  "KV-cache layout: dense (full-length row per decode "
                  "slot) or paged (block pool; admission by memory, "
                  "zero-copy prefix sharing)"),
        ParamSpec("kv_block_size", 16,
                  "tokens per KV block (paged layout)"),
        ParamSpec("kv_pool_blocks", 0,
                  "physical blocks in the paged pool (0 = dense-parity "
                  "sizing)"),
        ParamSpec("kv_dtype", "fp",
                  "paged KV residency precision: fp (bitwise-parity "
                  "default) or int8 (~2x blocks per HBM byte within a "
                  "pinned greedy tolerance)"),
        ParamSpec("serving_role", "",
                  "disaggregated-fleet role: 'prefill' (prompt "
                  "admission only; decode peers pull finished prompt "
                  "KV via :prefill/:import) or 'decode'; empty = "
                  "colocated. Requires kv_layout=paged"),
        ParamSpec("tp_shards", 1,
                  "tensor-parallel shards per replica: >1 runs the "
                  "decoder over a tp-chip mesh (weights Megatron-"
                  "split, KV pool sharded by KV head); size "
                  "num_tpu_chips to match"),
        ParamSpec("kv_fused_attention", False,
                  "fuse the paged decode read into the block-table "
                  "attention kernel (no dense KV gather per step)"),
        ParamSpec("prefill_chunk_tokens", 0,
                  "chunked prefill: split long admissions into bounded "
                  "chunks interleaved with decode dispatches (0 "
                  "disables; requires kv_layout=paged)"),
        ParamSpec("max_prompt_len", 0,
                  "longest admissible prompt (0 = the prefill window); "
                  "beyond the prefill window requires chunked prefill"),
        ParamSpec("cp_shards", 1,
                  "context-parallel shards for chunk prefill attention "
                  "(>1 rings the span attention across cp chips; "
                  "chips per replica = tp*cp*pp)"),
        ParamSpec("pp_stages", 1,
                  "pipeline-parallel decoder stages (>1 shards stacked "
                  "layers and the KV pool's layer dim across pp "
                  "chips)"),
        ParamSpec("host_kv_bytes", 0,
                  "host-RAM KV tier budget in bytes (paged layout; 0 "
                  "disables): evictions demote blocks to host memory, "
                  "misses re-import them, QoS suspensions park live "
                  "streams' KV there — size the pod's memory request "
                  "to cover it"),
        ParamSpec("kv_directory_size", 0,
                  "fleet KV economy: affinity keys the prefix->holder "
                  "directory tracks (0 disables; requires "
                  "kv_layout=paged). Local misses pull the deepest "
                  "advertised prefix from the holding peer via :kv"),
        ParamSpec("cold_store_ref", "",
                  "shared cold content-addressed KV store "
                  "('mem://<name>[?bytes=<n>]'; empty disables): "
                  "host-tier evictions demote payloads there; the "
                  "weights epoch rides the content key so live pushes "
                  "invalidate by construction"),
        ParamSpec("kv_import_crossover_tokens", 0,
                  "minimum prefill tokens a peer/cold import must save "
                  "over the best local tier before the pull is worth "
                  "its fixed cost (0 = any strictly deeper match)"),
        ParamSpec("qos_tenants", "",
                  "multi-tenant QoS: 'name=weight[:rate[:burst"
                  "[:priority]]]' comma-separated (empty disables); "
                  "requests carry X-Tenant/X-Priority/X-Deadline-Ms"),
        ParamSpec("qos_aging_s", 30.0,
                  "seconds of queue wait worth one priority point "
                  "(starvation aging)"),
        ParamSpec("compile_cache_dir", "",
                  "persistent compile-cache directory (empty disables): "
                  "mounted as a node-shared hostPath so a newborn "
                  "replica replays the fleet's serialized executables "
                  "instead of cold-compiling its dispatch set"),
        ParamSpec("weight_peers", "",
                  "comma-separated host:port donors a newborn pulls its "
                  "weights from over :pull before falling back to the "
                  "checkpoint (empty = checkpoint boot)"),
        ParamSpec("enable_prometheus", True),
        ParamSpec("dtype", "bfloat16"),
    ],
)
def tpu_serving(
    name: str,
    namespace: str,
    model_path: str,
    model_name: str,
    image: str,
    replicas: int,
    num_tpu_chips: int,
    batch_size: int,
    batch_timeout_ms: int,
    prefix_cache_slots: int,
    prefix_cache_min_len: int,
    prefill_len_buckets: int,
    speculative_k: int,
    draft_mode: str,
    kv_layout: str,
    kv_block_size: int,
    kv_pool_blocks: int,
    kv_dtype: str,
    serving_role: str,
    tp_shards: int,
    kv_fused_attention: bool,
    prefill_chunk_tokens: int,
    max_prompt_len: int,
    cp_shards: int,
    pp_stages: int,
    host_kv_bytes: int,
    kv_directory_size: int,
    cold_store_ref: str,
    kv_import_crossover_tokens: int,
    qos_tenants: str,
    qos_aging_s: float,
    compile_cache_dir: str,
    weight_peers: str,
    enable_prometheus: bool,
    dtype: str,
) -> list[dict]:
    model_name = model_name or name
    labels = {"app": name, "service": "tpu-serving"}
    resources = tpu_resources(num_tpu_chips)
    args = [
        f"--model-name={model_name}",
        f"--model-path={model_path}",
        f"--grpc-port={GRPC_PORT}",
        f"--rest-port={REST_PORT}",
        f"--batch-size={batch_size}",
        f"--batch-timeout-ms={batch_timeout_ms}",
        f"--prefix-cache-slots={prefix_cache_slots}",
        f"--prefix-cache-min-len={prefix_cache_min_len}",
        f"--prefill-len-buckets={prefill_len_buckets}",
        f"--speculative-k={speculative_k}",
        f"--draft-mode={draft_mode}",
        f"--kv-layout={kv_layout}",
        f"--kv-block-size={kv_block_size}",
        f"--kv-pool-blocks={kv_pool_blocks}",
        f"--kv-dtype={kv_dtype}",
        f"--tp-shards={tp_shards}",
        f"--dtype={dtype}",
    ]
    if serving_role:
        args.insert(-1, f"--serving-role={serving_role}")
    if kv_fused_attention:
        args.insert(-1, "--kv-fused-attention")
    if prefill_chunk_tokens:
        args.insert(-1, f"--prefill-chunk-tokens={prefill_chunk_tokens}")
    if max_prompt_len:
        args.insert(-1, f"--max-prompt-len={max_prompt_len}")
    if cp_shards > 1:
        args.insert(-1, f"--cp-shards={cp_shards}")
    if pp_stages > 1:
        args.insert(-1, f"--pp-stages={pp_stages}")
    if host_kv_bytes:
        args.insert(-1, f"--host-kv-bytes={host_kv_bytes}")
    if kv_directory_size:
        args.insert(-1, f"--kv-directory-size={kv_directory_size}")
    if cold_store_ref:
        args.insert(-1, f"--cold-store-ref={cold_store_ref}")
    if kv_import_crossover_tokens:
        args.insert(-1, "--kv-import-crossover-tokens="
                    f"{kv_import_crossover_tokens}")
    if qos_tenants:
        args.insert(-1, f"--qos-tenants={qos_tenants}")
        args.insert(-1, f"--qos-aging-s={qos_aging_s}")
    if compile_cache_dir:
        args.insert(-1, f"--compile-cache-dir={compile_cache_dir}")
    if weight_peers:
        args.insert(-1, f"--weight-peers={weight_peers}")
    if enable_prometheus:
        args.append("--enable-prometheus")
    # The compile cache is node-shared state, not pod state: every
    # replica scheduled on the node mounts the same hostPath, so the
    # first compile on the node is the LAST one any sibling pays.
    volumes = mounts = None
    if compile_cache_dir:
        volumes = [k8s.host_path_volume("compile-cache", compile_cache_dir)]
        mounts = [k8s.volume_mount("compile-cache", compile_cache_dir)]
    pod_annotations = (
        {
            "prometheus.io/scrape": "true",
            "prometheus.io/path": "/monitoring/prometheus/metrics",
            "prometheus.io/port": str(REST_PORT),
        }
        if enable_prometheus
        else None
    )
    return [
        k8s.deployment(
            name,
            namespace,
            containers=[
                k8s.container(
                    name,
                    image,
                    command=["python", "-m", "kubeflow_tpu.serving"],
                    args=args,
                    ports={"grpc": GRPC_PORT, "rest": REST_PORT},
                    resources=resources,
                    liveness_probe=k8s.tcp_probe(GRPC_PORT, initial_delay=30),
                    readiness_probe=k8s.http_probe(
                        f"/v1/models/{model_name}", REST_PORT, initial_delay=20
                    ),
                    volume_mounts=mounts,
                )
            ],
            replicas=replicas,
            labels=labels,
            pod_annotations=pod_annotations,
            volumes=volumes,
        ),
        k8s.service(
            name,
            namespace,
            selector=labels,
            ports=[
                {"name": "grpc", "port": GRPC_PORT, "targetPort": GRPC_PORT},
                {"name": "rest", "port": REST_PORT, "targetPort": REST_PORT},
            ],
            labels=labels,
            # Gateway route + service-level scrape annotations: the
            # prometheus service discovery (kubernetes-services job)
            # scrapes replicas through the Service as well, so the
            # decoder's histograms reach the autoscaler even when pod
            # discovery is off.
            annotations={
                **gateway_route(
                    name, f"/models/{name}/",
                    f"{name}.{namespace}:{REST_PORT}"),
                **({"prometheus.io/scrape": "true",
                    "prometheus.io/path":
                        "/monitoring/prometheus/metrics",
                    "prometheus.io/port": str(REST_PORT)}
                   if enable_prometheus else {}),
            },
        ),
    ]


@prototype(
    "batch-predict",
    "Batch prediction Job over a dataset (kubeflow/tf-batch-predict analogue)",
    params=[
        ParamSpec("name"),
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("model_path"),
        ParamSpec("input_path"),
        ParamSpec("output_path"),
        ParamSpec("image", images.SERVING),
        ParamSpec("num_tpu_chips", 1),
        ParamSpec("batch_size", 64),
    ],
)
def batch_predict(
    name: str,
    namespace: str,
    model_path: str,
    input_path: str,
    output_path: str,
    image: str,
    num_tpu_chips: int,
    batch_size: int,
) -> list[dict]:
    resources = tpu_resources(num_tpu_chips)
    return [
        {
            "apiVersion": "batch/v1",
            "kind": "Job",
            "metadata": k8s.metadata(name, namespace, {"app": name}),
            "spec": {
                "backoffLimit": 2,
                "template": {
                    "metadata": {"labels": {"app": name}},
                    "spec": k8s.pod_spec(
                        [
                            k8s.container(
                                name,
                                image,
                                command=["python", "-m", "kubeflow_tpu.serving.batch_predict"],
                                args=[
                                    f"--model-path={model_path}",
                                    f"--input-path={input_path}",
                                    f"--output-path={output_path}",
                                    f"--batch-size={batch_size}",
                                ],
                                resources=resources,
                            )
                        ],
                        restart_policy="Never",
                    ),
                },
            },
        }
    ]


@prototype(
    "serving-route",
    "Traffic-split route for model serving: weighted A/B or canary "
    "variants plus an optional shadow mirror (the seldon "
    "abtest/mab/shadow prototype surface, kubeflow/seldon/prototypes/"
    "serve-ab-test.jsonnet, core.libsonnet:305)",
    params=[
        ParamSpec("name"),
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("prefix", None, "route prefix; default /models/<name>/"),
        ParamSpec("primary_service", None,
                  "host:port of the main variant; default <name>.<ns>:8500"),
        ParamSpec("canary_service", "", "host:port of the B/canary variant"),
        ParamSpec("canary_weight", 10,
                  "percent of traffic to the canary (0-100)"),
        ParamSpec("shadow_service", "",
                  "host:port mirrored fire-and-forget"),
        ParamSpec("strategy", "weighted",
                  "weighted (static split) or epsilon-greedy "
                  "(multi-armed bandit over the variants)"),
        ParamSpec("epsilon", 0.1,
                  "bandit exploration rate (epsilon-greedy only)"),
        ParamSpec("outlier_threshold", 0.0,
                  "z-score beyond which a prediction request is tagged "
                  "an outlier (seldon outlier-detector surface); 0 "
                  "disables"),
        ParamSpec("outlier_window", 100,
                  "sliding baseline window for the outlier score"),
    ],
)
def serving_route(
    name: str,
    namespace: str,
    prefix: str | None,
    primary_service: str | None,
    canary_service: str,
    canary_weight: int,
    shadow_service: str,
    strategy: str,
    epsilon: float,
    outlier_threshold: float,
    outlier_window: int,
) -> list[dict]:
    prefix = prefix or f"/models/{name}/"
    primary = primary_service or f"{name}.{namespace}:{REST_PORT}"
    if not 0 <= int(canary_weight) <= 100:
        raise ValueError(f"canary_weight {canary_weight} not in [0, 100]")
    if strategy not in ("weighted", "epsilon-greedy"):
        raise ValueError(f"unknown strategy {strategy!r}")
    if strategy == "epsilon-greedy" and not canary_service:
        # One backend is nothing to explore — the gateway would silently
        # fall back to plain routing while the user believes a bandit runs.
        raise ValueError("epsilon-greedy needs a canary_service variant")
    backends = None
    if canary_service:
        backends = [
            {"service": primary, "weight": 100 - int(canary_weight)},
            {"service": canary_service, "weight": int(canary_weight)},
        ]
    if float(outlier_threshold) < 0:
        raise ValueError("outlier_threshold must be >= 0")
    if float(outlier_threshold) > 0 and int(outlier_window) < 2:
        # The gateway would reject (and silently drop) the whole route at
        # refresh time — fail at render instead.
        raise ValueError("outlier_window must be >= 2")
    route = gateway_route(
        f"{name}-route", prefix, primary,
        backends=backends, shadow=shadow_service or "",
        strategy=strategy if strategy != "weighted" else "",
        epsilon=float(epsilon) if strategy == "epsilon-greedy" else None,
        outlier=({"threshold": float(outlier_threshold),
                  "window": int(outlier_window)}
                 if float(outlier_threshold) > 0 else None),
    )
    # Selector-less carrier Service: exists only to hold the route
    # annotation the gateway discovers (the variants are full Services of
    # their own deployments).
    return [
        k8s.service(
            f"{name}-route", namespace, selector={},
            ports=[{"name": "http", "port": REST_PORT}],
            labels={"app": f"{name}-route"},
            annotations=route,
        )
    ]
