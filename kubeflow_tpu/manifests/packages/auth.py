"""Auth package: basic-auth gatekeeper + admission webhook.

Analogues of components/gatekeeper (AuthServer.go:32-210 — login form +
cookie sessions fronting the gateway), kubeflow/common/basic-auth.libsonnet,
and components/gcp-admission-webhook (main.go:131-158 — mutating webhook
injecting cloud credentials into pods labeled for it; here it also injects
TPU env defaults).
"""

from __future__ import annotations

from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.manifests import images
from kubeflow_tpu.manifests.core import ParamSpec, prototype
from kubeflow_tpu.version import DEFAULT_NAMESPACE


@prototype(
    "gatekeeper",
    "Auth server: /login form + cookie sessions, id-token issuance with "
    "a JWKS endpoint and key rotation (components/gatekeeper AuthServer "
    "+ the token-issuing half of IAP, iap.libsonnet:589-600)",
    params=[
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("image", images.PLATFORM),
        ParamSpec("username", "admin"),
        ParamSpec("password_hash", "", "bcrypt/sha256 hash; empty disables login"),
        ParamSpec("issuer", "https://gatekeeper.kubeflow-tpu",
                  "iss claim on issued id-tokens"),
        ParamSpec("audience", "kubeflow-tpu",
                  "default aud claim on issued id-tokens"),
        ParamSpec("token_ttl", 3600, "max id-token lifetime, seconds"),
        ParamSpec("rotate_seconds", 24 * 3600,
                  "signing-key rotation interval; retired keys stay in "
                  "the JWKS until their tokens expire (0 = manual "
                  "rotation via POST /rotate)"),
    ],
)
def gatekeeper(namespace: str, image: str, username: str,
               password_hash: str, issuer: str, audience: str,
               token_ttl: int, rotate_seconds: int) -> list[dict]:
    name = "gatekeeper"
    labels = {"app": name}
    return [
        k8s.secret(
            f"{name}-login",
            namespace,
            {"username": username, "passwordHash": password_hash},
        ),
        k8s.service(
            name,
            namespace,
            selector=labels,
            ports=[{"name": "http", "port": 8085, "targetPort": 8085}],
            labels=labels,
        ),
        k8s.deployment(
            name,
            namespace,
            containers=[
                k8s.container(
                    name,
                    image,
                    command=["python", "-m", "kubeflow_tpu.auth.gatekeeper"],
                    args=[
                        "--port=8085",
                        f"--issuer={issuer}",
                        f"--audience={audience}",
                        f"--token-ttl={token_ttl}",
                        f"--rotate-seconds={rotate_seconds}",
                    ],
                    env={"LOGIN_SECRET_PATH": "/etc/login"},
                    ports={"http": 8085},
                    volume_mounts=[k8s.volume_mount("login", "/etc/login", read_only=True)],
                )
            ],
            labels=labels,
            volumes=[k8s.secret_volume("login", f"{name}-login")],
        ),
    ]


@prototype(
    "admission-webhook",
    "Mutating webhook injecting credentials + TPU env defaults into labeled "
    "pods (gcp-admission-webhook / credentials-pod-preset analogue)",
    params=[
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("image", images.PLATFORM),
        ParamSpec(
            "ca_bundle",
            "",
            "base64 CA for the webhook serving cert; when empty the webhook "
            "server self-signs at startup and patches this config in-cluster",
        ),
    ],
)
def admission_webhook(namespace: str, image: str, ca_bundle: str) -> list[dict]:
    name = "admission-webhook"
    labels = {"app": name}
    # With no pre-issued bundle, the server self-signs at startup and
    # patches its CA into the in-cluster clientConfigs (webhook + job-CRD
    # conversion stanzas) — which needs update RBAC on those objects.
    self_sign = not ca_bundle
    args = ["--port=8443"]
    rbac: list[dict] = []
    if self_sign:
        from kubeflow_tpu.apis.jobs import API_GROUP, PLURALS

        args += ["--self-sign", "--patch-ca", f"--namespace={namespace}"]
        # Pinned with resourceNames to exactly what patch_ca_bundles
        # touches — this webhook's own config and the job CRDs' conversion
        # stanzas. Unpinned update on all webhooks/CRDs would let a
        # compromised pod rewrite any admission clientConfig cluster-wide
        # (cluster-admin-adjacent).
        rbac = [
            k8s.cluster_role(name, [
                k8s.policy_rule(["admissionregistration.k8s.io"],
                                ["mutatingwebhookconfigurations"],
                                ["get", "update"],
                                resource_names=[name]),
                k8s.policy_rule(["apiextensions.k8s.io"],
                                ["customresourcedefinitions"],
                                ["get", "update"],
                                resource_names=sorted(
                                    f"{plural}.{API_GROUP}"
                                    for plural in PLURALS.values())),
            ], labels),
            k8s.cluster_role_binding(name, name, name, namespace),
        ]
    return [
        k8s.service_account(name, namespace, labels),
        *rbac,
        k8s.service(
            name,
            namespace,
            selector=labels,
            ports=[{"name": "https", "port": 443, "targetPort": 8443}],
            labels=labels,
        ),
        k8s.deployment(
            name,
            namespace,
            containers=[
                k8s.container(
                    name,
                    image,
                    command=["python", "-m", "kubeflow_tpu.auth.webhook"],
                    args=args,
                    ports={"https": 8443},
                )
            ],
            labels=labels,
            service_account=name,
        ),
        {
            "apiVersion": "admissionregistration.k8s.io/v1",
            "kind": "MutatingWebhookConfiguration",
            "metadata": k8s.metadata(name, labels=labels),
            "webhooks": [
                {
                    "name": f"{name}.kubeflow-tpu.org",
                    "admissionReviewVersions": ["v1"],
                    "sideEffects": "None",
                    # Ignore so pod creation is never blocked while the
                    # webhook bootstraps its self-signed cert and patches
                    # caBundle (the reference's webhook also mutates
                    # best-effort, gcp-admission-webhook/main.go:131-158).
                    "failurePolicy": "Ignore",
                    "clientConfig": {
                        "service": {
                            "name": name,
                            "namespace": namespace,
                            "path": "/mutate",
                        },
                        **({"caBundle": ca_bundle} if ca_bundle else {}),
                    },
                    "rules": [
                        {
                            "apiGroups": [""],
                            "apiVersions": ["v1"],
                            "operations": ["CREATE"],
                            "resources": ["pods"],
                        }
                    ],
                    "objectSelector": {
                        "matchLabels": {"kubeflow-tpu.org/inject-credentials": "true"}
                    },
                }
            ],
        },
    ]
