"""InferenceService package: fleet-serving CRD + CR prototype.

The declarative face of the replicated decoder pool: one
``inference-service`` prototype renders the InferenceService CRD and a
CR declaring model, replica range, engine knobs, prefix-affine router
knobs, and autoscale targets — the operator
(kubeflow_tpu.operators.inference) does the rest. The reference's
closest shape is a tf-serving Deployment with a hand-set ``replicas``
(tf-serving-template.libsonnet:29-49); this is that surface with the
replica count handed to a metric-driven control loop.
"""

from __future__ import annotations

from kubeflow_tpu.apis.inference import (
    inference_service,
    inference_service_crd,
)
from kubeflow_tpu.manifests.core import ParamSpec, prototype
from kubeflow_tpu.version import DEFAULT_NAMESPACE


@prototype(
    "inference-service",
    "Replicated model-serving fleet: InferenceService CRD + CR — N "
    "model-server replicas behind a prefix-affine gateway route, "
    "autoscaled on queue-wait/TTFT p99 and KV-byte utilization",
    params=[
        ParamSpec("name"),
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("model", "", "served model name (defaults to `name`)"),
        ParamSpec("model_path", "", "gs://, s3://, /pvc/ or local model dir"),
        ParamSpec("replicas", 1, "initial replica count"),
        ParamSpec("min_replicas", 1, "autoscaler floor"),
        ParamSpec("max_replicas", 4, "autoscaler ceiling"),
        ParamSpec("num_tpu_chips", 1,
                  "google.com/tpu chips per replica (0 = CPU)"),
        ParamSpec("tp_shards", 1,
                  "tensor-parallel shards per replica "
                  "(spec.engine.tpShards): >1 serves the model over a "
                  "tp-chip mesh so it no longer has to fit one chip's "
                  "HBM; the operator sizes each replica pod to tp "
                  "chips unless num_tpu_chips pins it"),
        ParamSpec("affinity_tokens", 32,
                  "leading prompt tokens hashed into the rendezvous "
                  "routing key (>= the prefix cache min length, so "
                  "every cacheable prefix maps to one replica)"),
        ParamSpec("pressure", 8,
                  "per-replica in-flight bound past which the affine "
                  "pick spills to the least-loaded replica (0 = never)"),
        ParamSpec("kv_pressure", 0.0,
                  "KV-fill fraction past which the gateway spills the "
                  "affine pick (scraped from the real-byte gauges, "
                  "staleness-bounded; 0 = ignore)"),
        ParamSpec("prefill_replicas", 0,
                  "disaggregated prefill-pool size (0 = colocated). "
                  "With a prefill pool, `replicas`/min/max size the "
                  "decode pool and prompts ride the two-hop KV handoff"),
        ParamSpec("prefill_max_replicas", 0,
                  "prefill-pool autoscaler ceiling (0 = max_replicas)"),
        ParamSpec("host_kv_bytes", 0,
                  "host-RAM KV tier budget per replica in bytes "
                  "(spec.engine.hostKvBytes; 0 disables): evictions "
                  "demote KV blocks to host memory, misses re-import "
                  "them, QoS suspensions park live streams there"),
        ParamSpec("qos_tenants", "",
                  "multi-tenant QoS spec 'name=weight[:rate[:burst"
                  "[:priority]]]' comma-separated (spec.qos.tenants; "
                  "empty disables): fair-share admission in every "
                  "replica + per-tenant 429 shedding at the gateway "
                  "route"),
        ParamSpec("qos_aging_s", 30.0,
                  "seconds of queue wait worth one priority point "
                  "(spec.qos.agingSeconds)"),
        ParamSpec("queue_wait_p99_ms", 500.0,
                  "scale-up breach threshold on the queue-wait p99 "
                  "(prefill pool in a role split)"),
        ParamSpec("ttft_p99_ms", 2000.0,
                  "scale-up breach threshold on the TTFT p99 "
                  "(prefill pool in a role split)"),
        ParamSpec("inter_token_p99_ms", 500.0,
                  "scale-up breach threshold on the inter-token p99 "
                  "(decode pool in a role split)"),
        ParamSpec("kv_bytes_utilization", 0.85,
                  "scale-up breach threshold on KV bytes in use / total"),
        ParamSpec("scale_down_ratio", 0.5,
                  "hysteresis band: scale down only when every signal "
                  "is under target * this ratio"),
        ParamSpec("cooldown_seconds", 60.0,
                  "minimum gap between a scale event and a scale-down"),
        ParamSpec("scrape_period_seconds", 10.0,
                  "autoscaler reconcile/scrape cadence"),
    ],
)
def inference_service_proto(
    name: str,
    namespace: str,
    model: str,
    model_path: str,
    replicas: int,
    min_replicas: int,
    max_replicas: int,
    num_tpu_chips: int,
    tp_shards: int,
    affinity_tokens: int,
    pressure: int,
    kv_pressure: float,
    prefill_replicas: int,
    prefill_max_replicas: int,
    host_kv_bytes: int,
    qos_tenants: str,
    qos_aging_s: float,
    queue_wait_p99_ms: float,
    ttft_p99_ms: float,
    inter_token_p99_ms: float,
    kv_bytes_utilization: float,
    scale_down_ratio: float,
    cooldown_seconds: float,
    scrape_period_seconds: float,
) -> list[dict]:
    roles = None
    if prefill_replicas > 0:
        # Role split: `replicas`/min/max size the decode pool; the
        # prefill pool gets its own range. Both pools ride the paged KV
        # layout the prefill→decode block handoff requires (the
        # operator pins kv_layout and serving_role per pool).
        roles = {
            "prefill": {
                "replicas": int(prefill_replicas),
                "maxReplicas": int(prefill_max_replicas
                                   or max_replicas),
            },
            "decode": {"replicas": int(replicas)},
        }
    engine = {}
    if tp_shards > 1:
        engine["tpShards"] = int(tp_shards)
    if host_kv_bytes > 0:
        # The tier rides the paged block pool; pin the layout so a
        # hand-rendered CR can't silently ask for an impossible tier.
        engine["hostKvBytes"] = int(host_kv_bytes)
        engine.setdefault("kv_layout", "paged")
    qos = None
    if qos_tenants:
        from kubeflow_tpu.serving.qos import parse_tenants

        qos = {
            "agingSeconds": float(qos_aging_s),
            "tenants": {
                t.name: {"weight": t.weight, "rate": t.rate,
                         "burst": t.burst, "priority": t.priority}
                for t in parse_tenants(qos_tenants).values()
            },
        }
    cr = inference_service(
        name, namespace, model or name,
        model_path=model_path,
        replicas=replicas,
        min_replicas=min_replicas,
        max_replicas=max_replicas,
        tpu_chips_per_replica=num_tpu_chips,
        engine=engine or None,
        affinity_tokens=affinity_tokens,
        pressure=pressure,
        kv_pressure=kv_pressure,
        roles=roles,
        qos=qos,
        autoscale={
            "queueWaitP99Ms": float(queue_wait_p99_ms),
            "ttftP99Ms": float(ttft_p99_ms),
            "interTokenP99Ms": float(inter_token_p99_ms),
            "kvBytesUtilization": float(kv_bytes_utilization),
            "scaleDownRatio": float(scale_down_ratio),
            "cooldownSeconds": float(cooldown_seconds),
            "scrapePeriodSeconds": float(scrape_period_seconds),
        },
    )
    return [inference_service_crd(), cr]
