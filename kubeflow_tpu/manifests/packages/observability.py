"""Observability package: metric-collector + prometheus.

Analogue of metric-collector/ (availability prober exporting the
`kubeflow_availability` gauge, kubeflow-readiness.py:21-37, deployed by
kubeflow/gcp/prototypes/metric-collector.jsonnet) and the prometheus deploy
prototype (kubeflow/gcp/prototypes/prometheus.jsonnet). Extended for TPU:
the collector also probes TPU device health per node.
"""

from __future__ import annotations

from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.manifests import images
from kubeflow_tpu.manifests.core import ParamSpec, prototype
from kubeflow_tpu.version import DEFAULT_NAMESPACE


@prototype(
    "metric-collector",
    "Availability prober: exports kubeflow_availability (+ TPU slice health) "
    "prometheus gauges on :8000 (metric-collector analogue)",
    params=[
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("image", images.PLATFORM),
        ParamSpec("target_url", "http://gateway.kubeflow/healthz", "endpoint to probe"),
        ParamSpec("interval_seconds", 30),
    ],
)
def metric_collector(
    namespace: str, image: str, target_url: str, interval_seconds: int
) -> list[dict]:
    name = "metric-collector"
    labels = {"app": name}
    return [
        k8s.service_account(name, namespace, labels),
        k8s.cluster_role(
            name,
            [k8s.policy_rule([""], ["nodes", "pods"], ["get", "list"])],
            labels,
        ),
        k8s.cluster_role_binding(name, name, name, namespace),
        k8s.service(
            name,
            namespace,
            selector=labels,
            ports=[{"name": "metrics", "port": 8000, "targetPort": 8000}],
            labels=labels,
            annotations={
                "prometheus.io/scrape": "true",
                "prometheus.io/port": "8000",
            },
        ),
        k8s.deployment(
            name,
            namespace,
            containers=[
                k8s.container(
                    name,
                    image,
                    command=["python", "-m", "kubeflow_tpu.observability.collector"],
                    args=[
                        f"--target-url={target_url}",
                        f"--interval={interval_seconds}",
                        "--port=8000",
                    ],
                    ports={"metrics": 8000},
                )
            ],
            labels=labels,
            service_account=name,
        ),
    ]


@prototype(
    "prometheus",
    "Prometheus server scraping annotated pods/services "
    "(kubeflow/gcp/prototypes/prometheus.jsonnet analogue)",
    params=[
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("image", "prom/prometheus:v2.45.0"),
        ParamSpec("retention", "15d"),
        ParamSpec("storage", "10Gi"),
    ],
)
def prometheus(namespace: str, image: str, retention: str, storage: str) -> list[dict]:
    name = "prometheus"
    labels = {"app": name}
    scrape_config = """\
global:
  scrape_interval: 30s
scrape_configs:
  - job_name: kubernetes-pods
    kubernetes_sd_configs: [{role: pod}]
    relabel_configs:
      - source_labels: [__meta_kubernetes_pod_annotation_prometheus_io_scrape]
        action: keep
        regex: "true"
      - source_labels: [__address__, __meta_kubernetes_pod_annotation_prometheus_io_port]
        action: replace
        target_label: __address__
        regex: ([^:]+)(?::\\d+)?;(\\d+)
        replacement: $1:$2
      - source_labels: [__meta_kubernetes_pod_annotation_prometheus_io_path]
        action: replace
        target_label: __metrics_path__
        regex: (.+)
  - job_name: kubernetes-services
    kubernetes_sd_configs: [{role: service}]
    relabel_configs:
      - source_labels: [__meta_kubernetes_service_annotation_prometheus_io_scrape]
        action: keep
        regex: "true"
"""
    return [
        k8s.service_account(name, namespace, labels),
        k8s.cluster_role(
            name,
            [
                k8s.policy_rule(
                    [""],
                    ["nodes", "services", "endpoints", "pods"],
                    ["get", "list", "watch"],
                )
            ],
            labels,
        ),
        k8s.cluster_role_binding(name, name, name, namespace),
        k8s.config_map(f"{name}-config", namespace, {"prometheus.yml": scrape_config}, labels),
        k8s.pvc(f"{name}-data", namespace, storage),
        k8s.service(
            name,
            namespace,
            selector=labels,
            ports=[{"name": "http", "port": 9090, "targetPort": 9090}],
            labels=labels,
        ),
        k8s.deployment(
            name,
            namespace,
            containers=[
                k8s.container(
                    name,
                    image,
                    args=[
                        "--config.file=/etc/prometheus/prometheus.yml",
                        f"--storage.tsdb.retention.time={retention}",
                        "--storage.tsdb.path=/prometheus",
                    ],
                    ports={"http": 9090},
                    volume_mounts=[
                        k8s.volume_mount("config", "/etc/prometheus", read_only=True),
                        k8s.volume_mount("data", "/prometheus"),
                    ],
                )
            ],
            labels=labels,
            service_account=name,
            volumes=[
                k8s.config_map_volume("config", f"{name}-config"),
                k8s.pvc_volume("data", f"{name}-data"),
            ],
        ),
    ]


@prototype(
    "tensorboard",
    "TensorBoard deployment reading logs from gs://|pvc path "
    "(kubeflow/tensorboard analogue; serves JAX profiler traces)",
    params=[
        ParamSpec("name", "tensorboard"),
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("log_dir", "", "gs:// or pvc path with event files / xprof traces"),
        ParamSpec("image", images.JAX_TPU),
    ],
)
def tensorboard(name: str, namespace: str, log_dir: str, image: str) -> list[dict]:
    labels = {"app": name}
    from kubeflow_tpu.manifests.core import gateway_route

    return [
        k8s.service(
            name,
            namespace,
            selector=labels,
            ports=[{"name": "http", "port": 80, "targetPort": 6006}],
            labels=labels,
            annotations=gateway_route(name, f"/{name}/", f"{name}.{namespace}:80"),
        ),
        k8s.deployment(
            name,
            namespace,
            containers=[
                k8s.container(
                    name,
                    image,
                    command=["tensorboard"],
                    args=[f"--logdir={log_dir}", "--port=6006", "--bind_all"],
                    ports={"http": 6006},
                )
            ],
            labels=labels,
        ),
    ]
