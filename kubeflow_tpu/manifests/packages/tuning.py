"""Tuning package: the Katib-equivalent HP search stack.

Analogue of kubeflow/katib (vizier.libsonnet:28-380,
studyjobcontroller.libsonnet:14-147). Where Katib runs a vizier-core manager +
MySQL + per-algorithm suggestion Deployments, our stack is leaner and
TPU-native: one study-controller that embeds the suggestion algorithms
(random/grid/hyperband/bayesianoptimization — parity with
suggestion.libsonnet:3-10) and persists study state in the StudyJob status,
spawning JaxJob trials. An optional standalone suggestion service mirrors the
reference's pluggable-algorithm deployment shape.
"""

from __future__ import annotations

from kubeflow_tpu.apis.tuning import study_job_crd
from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.manifests import images
from kubeflow_tpu.manifests.core import ParamSpec, gateway_route, prototype
from kubeflow_tpu.version import API_GROUP, DEFAULT_NAMESPACE


@prototype(
    "study-controller",
    "StudyJob CRD + controller with embedded suggestion algorithms "
    "(random/grid/hyperband/bayesianoptimization)",
    params=[
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("image", images.PLATFORM),
    ],
)
def study_controller(namespace: str, image: str) -> list[dict]:
    name = "study-controller"
    labels = {"app": name}
    return [
        study_job_crd(),
        k8s.service_account(name, namespace, labels),
        k8s.cluster_role(
            name,
            [
                k8s.policy_rule([API_GROUP], ["studyjobs", "studyjobs/status"], ["*"]),
                k8s.policy_rule(
                    [API_GROUP],
                    ["jaxjobs", "jaxjobs/status", "tfjobs", "pytorchjobs", "mpijobs"],
                    ["*"],
                ),
                k8s.policy_rule([""], ["events"], ["create", "patch"]),
            ],
            labels,
        ),
        k8s.cluster_role_binding(name, name, name, namespace),
        k8s.deployment(
            name,
            namespace,
            containers=[
                k8s.container(
                    name,
                    image,
                    command=["python", "-m", "kubeflow_tpu.operators.study"],
                    ports={"metrics": 8443},
                )
            ],
            labels=labels,
            service_account=name,
        ),
    ]


@prototype(
    "suggestion-service",
    "Standalone suggestion service Deployment+Service for one algorithm "
    "(vizier suggestion-<algo> analogue, kubeflow/katib/suggestion.libsonnet)",
    params=[
        ParamSpec("algorithm", "random", "random|grid|hyperband|bayesianoptimization"),
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("image", images.PLATFORM),
    ],
)
def suggestion_service(algorithm: str, namespace: str, image: str) -> list[dict]:
    name = f"suggestion-{algorithm}"
    labels = {"app": name, "component": "suggestion"}
    return [
        k8s.service(
            name,
            namespace,
            selector=labels,
            ports=[{"name": "api", "port": 6789, "targetPort": 6789}],
            labels=labels,
        ),
        k8s.deployment(
            name,
            namespace,
            containers=[
                k8s.container(
                    name,
                    image,
                    command=["python", "-m", "kubeflow_tpu.tuning.service"],
                    args=[f"--algorithm={algorithm}", "--port=6789"],
                    ports={"api": 6789},
                )
            ],
            labels=labels,
        ),
    ]


@prototype(
    "study-ui",
    "Study results UI behind the gateway (katib UI analogue)",
    params=[
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("image", images.PLATFORM),
    ],
)
def study_ui(namespace: str, image: str) -> list[dict]:
    name = "study-ui"
    labels = {"app": name}
    return [
        k8s.service(
            name,
            namespace,
            selector=labels,
            ports=[{"name": "http", "port": 80, "targetPort": 8089}],
            labels=labels,
            annotations=gateway_route(name, "/study/", f"{name}.{namespace}:80"),
        ),
        k8s.deployment(
            name,
            namespace,
            containers=[
                k8s.container(
                    name,
                    image,
                    command=["python", "-m", "kubeflow_tpu.webapps.study"],
                    ports={"http": 8089},
                )
            ],
            labels=labels,
            service_account="study-controller",
        ),
    ]
