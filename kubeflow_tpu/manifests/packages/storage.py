"""Storage package: shared-filesystem volumes for checkpoints/datasets.

The analogue of the reference's storage prototypes — the Filestore PV
(kubeflow/gcp/google-cloud-filestore-pv.libsonnet, prototype
google-cloud-filestore-pv.jsonnet) and NFS-backed PVs its jupyter/pipeline
stacks mount. TPU training leans on these harder than the reference did:
orbax checkpoints and KTPU token corpora live on exactly these volumes.
"""

from __future__ import annotations

from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.manifests.core import ParamSpec, prototype
from kubeflow_tpu.version import DEFAULT_NAMESPACE


@prototype(
    "nfs-volume",
    "NFS-backed PersistentVolume + Claim (filestore/NFS PV analogue, "
    "kubeflow/gcp/google-cloud-filestore-pv.libsonnet)",
    params=[
        ParamSpec("name", "kubeflow-shared"),
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("server", "REQUIRED", "NFS/Filestore server IP or host"),
        ParamSpec("path", "/shared", "export path"),
        ParamSpec("capacity", "1Ti"),
    ],
)
def nfs_volume(name: str, namespace: str, server: str, path: str,
               capacity: str) -> list[dict]:
    labels = {"app": name}
    pv = {
        "apiVersion": "v1",
        "kind": "PersistentVolume",
        "metadata": {"name": name, "labels": labels},
        "spec": {
            "capacity": {"storage": capacity},
            "accessModes": ["ReadWriteMany"],
            "persistentVolumeReclaimPolicy": "Retain",
            "nfs": {"server": server, "path": path},
        },
    }
    claim = k8s.pvc(name, namespace, capacity,
                    access_modes=("ReadWriteMany",), storage_class="")
    claim["spec"]["volumeName"] = name
    return [pv, claim]


@prototype(
    "checkpoint-pvc",
    "Namespaced ReadWriteMany claim for orbax checkpoints / token corpora",
    params=[
        ParamSpec("name", "checkpoints"),
        ParamSpec("namespace", DEFAULT_NAMESPACE),
        ParamSpec("size", "500Gi"),
        ParamSpec("storage_class", "", "empty = cluster default"),
    ],
)
def checkpoint_pvc(name: str, namespace: str, size: str,
                   storage_class: str) -> list[dict]:
    return [k8s.pvc(name, namespace, size,
                    access_modes=("ReadWriteMany",),
                    storage_class=storage_class or None)]
