"""Golden-snapshot tooling for the manifest layer.

The reference's jsonnet tests compare generated objects to golden literals
(kubeflow/tf-training/tests/tf-job_test.jsonnet). Here each snapshot case is a
(prototype, params) pair rendered to canonical YAML; `--update` rewrites
tests/golden/*.yaml, and tests/test_manifests.py::test_golden_snapshots
compares byte-for-byte.
"""

from __future__ import annotations

import argparse
import os
import sys

import yaml

from kubeflow_tpu.manifests.core import generate

# case name -> (prototype, params)
SNAPSHOT_CASES: dict[str, tuple[str, dict]] = {
    "training-operator": ("training-operator", {}),
    "scheduler": ("scheduler", {}),
    "jax-job-simple": (
        "jax-job-simple",
        {"name": "smoke", "num_workers": 4, "accelerator": "v5litepod-16", "topology": "4x4"},
    ),
    "tf-job": ("tf-job", {"name": "bert", "num_workers": 4, "num_ps": 2}),
    "pytorch-job": ("pytorch-job", {"name": "llama", "num_workers": 3}),
    "mpi-job": ("mpi-job", {"name": "allreduce", "num_workers": 2}),
    "gateway": ("gateway", {}),
    "centraldashboard": ("centraldashboard", {}),
    "tpu-serving": (
        "tpu-serving",
        {"name": "bert", "model_path": "gs://models/bert", "num_tpu_chips": 4},
    ),
    "tpu-serving-warm": (
        "tpu-serving",
        {"name": "bert", "model_path": "gs://models/bert",
         "num_tpu_chips": 4,
         "compile_cache_dir": "/var/cache/kubeflow-tpu/compile",
         "weight_peers": "bert-r0.kubeflow:8500,bert-r1.kubeflow:8500"},
    ),
    "pipeline-operator": ("pipeline-operator", {}),
    "scheduled-workflow": (
        "scheduled-workflow",
        {"name": "nightly", "schedule": "30 2 * * *"},
    ),
    "tensorboard": ("tensorboard", {"log_dir": "gs://bucket/logs"}),
    "application": ("application", {}),
    "bootstrapper": ("bootstrapper", {}),
    "jupyter-web-app": ("jupyter-web-app", {}),
    "slice-healthcheck": ("slice-healthcheck", {"name": "preflight"}),
    "inference-server": (
        "inference-server",
        {"name": "external", "image": "example/infer:1", "port": 8080},
    ),
    "inference-service": (
        "inference-service",
        {"name": "llama", "model_path": "gs://models/llama",
         "replicas": 2, "min_replicas": 1, "max_replicas": 4,
         "num_tpu_chips": 4, "tp_shards": 4},
    ),
    "inference-service-disagg": (
        "inference-service",
        {"name": "llama", "model_path": "gs://models/llama",
         "replicas": 3, "min_replicas": 1, "max_replicas": 6,
         "num_tpu_chips": 4, "prefill_replicas": 2,
         "prefill_max_replicas": 4, "kv_pressure": 0.85},
    ),
    "rl-job": (
        "rl-job",
        {"name": "podracer", "model": "lm-test-tiny",
         "actor_replicas": 2, "actor_max_replicas": 4,
         "push_every_steps": 2},
    ),
    "nfs-volume": ("nfs-volume", {"server": "10.0.0.2"}),
    "serving-route": (
        "serving-route",
        {"name": "bert", "canary_service": "bert-v2.kubeflow:8500",
         "canary_weight": 10, "shadow_service": "bert-shadow.kubeflow:8500"},
    ),
    "serving-route-bandit": (
        "serving-route",
        {"name": "bert", "canary_service": "bert-v2.kubeflow:8500",
         "strategy": "epsilon-greedy", "epsilon": 0.2},
    ),
    "serving-route-outlier": (
        "serving-route",
        {"name": "bert", "outlier_threshold": 3.0, "outlier_window": 50},
    ),
    "spark-operator": (
        "third-party-operator",
        {"name": "spark-operator",
         "image": "ghcr.io/kubeflow/spark-operator:v1beta2-1.3.8-3.1.1",
         "crd_group": "sparkoperator.k8s.io",
         "crd_kind": "SparkApplication",
         "crd_version": "v1beta2",
         "args": ["-logtostderr", "-enable-metrics=true"],
         "metrics_port": 10254},
    ),
    "experiment": (
        "experiment",
        {"name": "decode-knobs", "scenario": "decode-tps",
         "algorithm": "tpe", "max_trials": 12, "seed": 7,
         "target": "llama"},
    ),
    "cert-manager": ("cert-manager", {}),
    "gatekeeper": ("gatekeeper", {"password_hash": "0" * 64}),
    "admission-webhook": ("admission-webhook", {}),
    "secure-ingress": (
        "secure-ingress",
        {"hostname": "kubeflow.example.com", "issuer": "platform-ca"},
    ),
    "cloud-endpoints": (
        "cloud-endpoints",
        {"hostname": "kubeflow.example.com", "target": "gateway.kubeflow"},
    ),
}


def render_case(case_name: str) -> str:
    proto, params = SNAPSHOT_CASES[case_name]
    objs = generate(proto, params)
    return yaml.safe_dump_all(objs, sort_keys=True, default_flow_style=False)


def golden_dir() -> str:
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(__file__)))
    return os.path.join(repo_root, "tests", "golden")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--update", action="store_true", help="rewrite golden files")
    args = ap.parse_args(argv)
    gdir = golden_dir()
    os.makedirs(gdir, exist_ok=True)
    drift = []
    for case in SNAPSHOT_CASES:
        rendered = render_case(case)
        path = os.path.join(gdir, f"{case}.yaml")
        if args.update:
            with open(path, "w") as f:
                f.write(rendered)
            print(f"wrote {path}")
        else:
            existing = open(path).read() if os.path.exists(path) else None
            if existing != rendered:
                drift.append(case)
    if drift:
        print(f"golden drift: {drift}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
