"""Gatekeeper: `python -m kubeflow_tpu.auth.gatekeeper --port=8085`.

The basic-auth gateway (components/gatekeeper/auth/AuthServer.go:32-210)
PLUS the platform's identity-token issuer — the half of IAP the envoy
`jwt-auth` filter consumes (kubeflow/gcp/iap.libsonnet:589-600): signed
short-lived ES256 id-tokens for users and service accounts, published
verification keys, zero-downtime key rotation. Routes:

- ``GET  /login``   login form
- ``POST /login``   form {username, password} → Set-Cookie + redirect
- ``GET  /auth``    forward-auth check: 200 if the session cookie verifies
- ``GET  /logout``  clears the session
- ``POST /token``   id-token grant: Basic credentials, a valid session
  cookie, or a JSON ``{service_account, key}`` pair (the platform's
  service-account flow — the reference's prober exchanges an IAM SA key
  for a Google id-token the same way, kubeflow-readiness.py:21-37);
  body/query may carry ``audience`` and ``ttl_seconds``
- ``GET  /.well-known/jwks.json``  verification keys (RFC 7517)
- ``POST /rotate``  activate a fresh signing key (credentialed); retired
  keys stay published until every token they signed has expired
- ``GET  /healthz``
"""

from __future__ import annotations

import argparse
import hashlib
import hmac
import json
import os
import secrets
import sys
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from kubeflow_tpu.auth.tokens import SigningKeyRing
from kubeflow_tpu.runtime import strip_glog_args

COOKIE_NAME = "kubeflow-tpu-auth"
DEFAULT_SECRET_PATH = os.environ.get("LOGIN_SECRET_PATH", "/etc/login")
DEFAULT_ISSUER = "https://gatekeeper.kubeflow-tpu"
DEFAULT_AUDIENCE = "kubeflow-tpu"
DEFAULT_TOKEN_TTL = 3600

_LOGIN_FORM = """<!doctype html>
<html><head><title>kubeflow-tpu login</title></head>
<body><h2>Sign in to kubeflow-tpu</h2>
<form method="post" action="/login">
  <label>Username <input name="username" autocomplete="username"></label><br>
  <label>Password <input name="password" type="password"
         autocomplete="current-password"></label><br>
  <button type="submit">Sign in</button>
</form>{message}</body></html>
"""


class AuthService:
    """Credential check + HMAC cookie sessions."""

    def __init__(self, username: str, password_hash: str,
                 *, session_seconds: float = 24 * 3600.0,
                 signing_key: bytes | None = None,
                 service_accounts: dict[str, str] | None = None):
        self.username = username
        self.password_hash = password_hash  # sha256 hexdigest
        self.session_seconds = session_seconds
        self._key = signing_key or secrets.token_bytes(32)
        # name -> key (the mounted SA credential; comparison is
        # constant-time). The platform's stand-in for IAM SA keys.
        self.service_accounts = dict(service_accounts or {})

    @classmethod
    def from_secret_dir(cls, path: str) -> "AuthService":
        """Load the mounted login Secret: files `username` and either
        `passwordhash` (sha256 hex) or `password` (plaintext, hashed
        here); every `sa-<name>` file is a service-account key."""
        def read(name: str) -> str | None:
            fp = os.path.join(path, name)
            if os.path.exists(fp):
                with open(fp) as f:
                    return f.read().strip()
            return None

        username = read("username") or "admin"
        # The login Secret (manifests/packages/auth.py) mounts the key
        # as the file `passwordHash`; accept the all-lowercase spelling
        # too for hand-made secrets.
        pwhash = read("passwordHash") or read("passwordhash")
        if pwhash is None:
            pw = read("password")
            if pw is None:
                raise FileNotFoundError(
                    f"no password/passwordhash under {path}"
                )
            pwhash = hashlib.sha256(pw.encode()).hexdigest()
        sas = {}
        for fn in sorted(os.listdir(path)) if os.path.isdir(path) else []:
            # An empty key file (provisioning half-done) must not create
            # an account mintable with key "" — skip it.
            if fn.startswith("sa-") and read(fn):
                sas[fn[3:]] = read(fn)
        return cls(username, pwhash, service_accounts=sas)

    def check_login(self, username: str, password: str) -> bool:
        got = hashlib.sha256(password.encode()).hexdigest()
        # Compare utf-8 encoded bytes: compare_digest raises TypeError on
        # non-ASCII str operands, so a unicode username must 401, not
        # crash the handler thread.
        return (hmac.compare_digest(username.encode(),
                                    self.username.encode())
                and hmac.compare_digest(got, self.password_hash))

    def check_service_account(self, name: str, key: str) -> bool:
        want = self.service_accounts.get(name)
        return (bool(want) and bool(key)
                and hmac.compare_digest(key.encode(), want.encode()))

    def issue_cookie(self, now: float | None = None) -> str:
        expires = int((now or time.time()) + self.session_seconds)
        payload = f"{self.username}|{expires}"
        sig = hmac.new(self._key, payload.encode(),
                       hashlib.sha256).hexdigest()
        return f"{payload}|{sig}"

    def verify_cookie(self, token: str, now: float | None = None) -> bool:
        parts = token.split("|")
        if len(parts) != 3:
            return False
        payload = f"{parts[0]}|{parts[1]}"
        want = hmac.new(self._key, payload.encode(),
                        hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want, parts[2]):
            return False
        try:
            return (now or time.time()) < int(parts[1])
        except ValueError:
            return False


def _cookie_from_header(header: str | None) -> str | None:
    for part in (header or "").split(";"):
        name, _, value = part.strip().partition("=")
        if name == COOKIE_NAME:
            return value
    return None


def _basic_credentials(header: str | None) -> tuple[str, str] | None:
    if not header or not header.startswith("Basic "):
        return None
    import base64

    try:
        decoded = base64.b64decode(header[6:], validate=True).decode("utf-8")
    except (ValueError, UnicodeDecodeError):
        return None
    user, sep, password = decoded.partition(":")
    return (user, password) if sep else None


def make_server(auth: AuthService, port: int, *,
                ring: SigningKeyRing | None = None,
                audience: str = DEFAULT_AUDIENCE,
                token_ttl: int = DEFAULT_TOKEN_TTL) -> ThreadingHTTPServer:
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _send(self, code: int, body: bytes, ctype="text/html",
                  extra: dict | None = None) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (extra or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path in ("/healthz", "/readyz"):
                self._send(200, b'{"status":"ok"}', "application/json")
            elif self.path == "/.well-known/jwks.json" and ring is not None:
                self._send(200, json.dumps(ring.jwks()).encode(),
                           "application/json")
            elif self.path.startswith("/login"):
                self._send(200, _LOGIN_FORM.format(message="").encode())
            elif self.path == "/auth":
                token = _cookie_from_header(self.headers.get("Cookie"))
                if token and auth.verify_cookie(token):
                    self._send(200, b'{"authorized":true}',
                               "application/json")
                else:
                    self._send(401, b'{"authorized":false}',
                               "application/json")
            elif self.path == "/logout":
                self._send(
                    302, b"", extra={
                        "Location": "/login",
                        "Set-Cookie": f"{COOKIE_NAME}=; Path=/; Max-Age=0",
                    },
                )
            else:
                self._send(404, b"not found", "text/plain")

        def _grant_subject(self, payload: dict, *,
                           allow_service_account: bool = True
                           ) -> str | None:
            """Which identity may have a token: Basic credentials, a
            valid session cookie, or a service-account key. None = no
            acceptable credential presented. Admin operations pass
            ``allow_service_account=False`` — an SA key is a token-grant
            credential, not an operator credential."""
            creds = _basic_credentials(self.headers.get("Authorization"))
            if creds and auth.check_login(*creds):
                return creds[0]
            sa, key = payload.get("service_account"), payload.get("key")
            if (allow_service_account
                    and isinstance(sa, str) and isinstance(key, str)
                    and auth.check_service_account(sa, key)):
                return f"system:serviceaccount:{sa}"
            cookie = _cookie_from_header(self.headers.get("Cookie"))
            if cookie and auth.verify_cookie(cookie):
                return auth.username
            username = payload.get("username")
            password = payload.get("password")
            if (isinstance(username, str) and isinstance(password, str)
                    and auth.check_login(username, password)):
                return username
            return None

        def _content_length(self) -> int:
            try:
                return max(0, int(self.headers.get("Content-Length", 0)))
            except (TypeError, ValueError):
                return 0  # garbage header: treat as no body, don't crash

        def _read_json(self) -> dict:
            length = self._content_length()
            raw = self.rfile.read(length) if length else b""
            try:
                payload = json.loads(raw) if raw else {}
            except ValueError:
                return {}
            return payload if isinstance(payload, dict) else {}

        def _token(self) -> None:
            if ring is None:
                self._send(404, b'{"error":"no token issuer"}',
                           "application/json")
                return
            payload = self._read_json()
            subject = self._grant_subject(payload)
            if subject is None:
                self._send(401, b'{"error":"invalid credentials"}',
                           "application/json")
                return
            try:
                ttl = int(payload.get("ttl_seconds", token_ttl)
                          or token_ttl)
            except (TypeError, ValueError):
                self._send(400, b'{"error":"bad ttl_seconds"}',
                           "application/json")
                return
            ttl = max(1, min(ttl, token_ttl))
            aud = str(payload.get("audience") or audience)
            token = ring.issue(subject, aud, ttl_seconds=ttl)
            self._send(200, json.dumps({
                "id_token": token, "token_type": "Bearer",
                "expires_in": ttl, "subject": subject,
            }).encode(), "application/json")

        def _rotate(self) -> None:
            if ring is None:
                self._send(404, b'{"error":"no token issuer"}',
                           "application/json")
                return
            if self._grant_subject(self._read_json(),
                                   allow_service_account=False) is None:
                self._send(401, b'{"error":"invalid credentials"}',
                           "application/json")
                return
            kid = ring.rotate()
            pruned = ring.prune()
            self._send(200, json.dumps(
                {"active_kid": kid, "pruned": pruned}).encode(),
                "application/json")

        def do_POST(self):
            if self.path == "/token":
                self._token()
                return
            if self.path == "/rotate":
                self._rotate()
                return
            if self.path != "/login":
                self._send(404, b"not found", "text/plain")
                return
            length = self._content_length()
            form = urllib.parse.parse_qs(
                self.rfile.read(length).decode("utf-8", "replace")
            )
            username = (form.get("username") or [""])[0]
            password = (form.get("password") or [""])[0]
            if auth.check_login(username, password):
                cookie = auth.issue_cookie()
                self._send(
                    302, b"", extra={
                        "Location": "/",
                        "Set-Cookie": (
                            f"{COOKIE_NAME}={cookie}; Path=/; HttpOnly"
                        ),
                    },
                )
            else:
                self._send(
                    401,
                    _LOGIN_FORM.format(
                        message="<p>Invalid credentials.</p>"
                    ).encode(),
                )

    return ThreadingHTTPServer(("0.0.0.0", port), Handler)


def main(argv=None) -> int:
    argv = strip_glog_args(list(sys.argv[1:] if argv is None else argv))
    p = argparse.ArgumentParser(description="gatekeeper auth server")
    p.add_argument("--port", type=int, default=8085)
    p.add_argument("--secret-path", default=DEFAULT_SECRET_PATH)
    p.add_argument("--issuer", default=DEFAULT_ISSUER,
                   help="iss claim on issued id-tokens")
    p.add_argument("--audience", default=DEFAULT_AUDIENCE,
                   help="default aud claim on issued id-tokens")
    p.add_argument("--token-ttl", type=int, default=DEFAULT_TOKEN_TTL,
                   help="max id-token lifetime in seconds")
    p.add_argument("--rotate-seconds", type=float, default=0.0,
                   help="rotate the signing key on this interval "
                        "(0 = only via POST /rotate); retired keys stay "
                        "in the JWKS until their tokens expire")
    args = p.parse_args(argv)

    auth = AuthService.from_secret_dir(args.secret_path)
    ring = SigningKeyRing(args.issuer)
    if args.rotate_seconds > 0:
        import threading

        def rotate_loop():
            while True:
                time.sleep(args.rotate_seconds)
                ring.rotate()
                ring.prune()

        # tpu-lint: disable=thread-no-join -- process-lifetime rotation loop; dies with the process
        threading.Thread(target=rotate_loop, daemon=True).start()
    httpd = make_server(auth, args.port, ring=ring,
                        audience=args.audience, token_ttl=args.token_ttl)
    print(json.dumps({"msg": "gatekeeper up", "port": args.port,
                      "user": auth.username, "issuer": args.issuer,
                      "kid": ring.active_kid}))
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
