"""Gatekeeper: `python -m kubeflow_tpu.auth.gatekeeper --port=8085`.

The basic-auth gateway (components/gatekeeper/auth/AuthServer.go:32-210):
a login form POSTs credentials checked against the mounted login secret; on
success an HMAC-signed session cookie is set. The gateway forward-auths every
request against ``/auth`` (200 = session valid). Routes:

- ``GET  /login``   login form
- ``POST /login``   form {username, password} → Set-Cookie + redirect
- ``GET  /auth``    forward-auth check: 200 if the session cookie verifies
- ``GET  /logout``  clears the session
- ``GET  /healthz``
"""

from __future__ import annotations

import argparse
import hashlib
import hmac
import json
import os
import secrets
import sys
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from kubeflow_tpu.runtime import strip_glog_args

COOKIE_NAME = "kubeflow-tpu-auth"
DEFAULT_SECRET_PATH = os.environ.get("LOGIN_SECRET_PATH", "/etc/login")

_LOGIN_FORM = """<!doctype html>
<html><head><title>kubeflow-tpu login</title></head>
<body><h2>Sign in to kubeflow-tpu</h2>
<form method="post" action="/login">
  <label>Username <input name="username" autocomplete="username"></label><br>
  <label>Password <input name="password" type="password"
         autocomplete="current-password"></label><br>
  <button type="submit">Sign in</button>
</form>{message}</body></html>
"""


class AuthService:
    """Credential check + HMAC cookie sessions."""

    def __init__(self, username: str, password_hash: str,
                 *, session_seconds: float = 24 * 3600.0,
                 signing_key: bytes | None = None):
        self.username = username
        self.password_hash = password_hash  # sha256 hexdigest
        self.session_seconds = session_seconds
        self._key = signing_key or secrets.token_bytes(32)

    @classmethod
    def from_secret_dir(cls, path: str) -> "AuthService":
        """Load the mounted login Secret: files `username` and either
        `passwordhash` (sha256 hex) or `password` (plaintext, hashed here)."""
        def read(name: str) -> str | None:
            fp = os.path.join(path, name)
            if os.path.exists(fp):
                with open(fp) as f:
                    return f.read().strip()
            return None

        username = read("username") or "admin"
        pwhash = read("passwordhash")
        if pwhash is None:
            pw = read("password")
            if pw is None:
                raise FileNotFoundError(
                    f"no password/passwordhash under {path}"
                )
            pwhash = hashlib.sha256(pw.encode()).hexdigest()
        return cls(username, pwhash)

    def check_login(self, username: str, password: str) -> bool:
        got = hashlib.sha256(password.encode()).hexdigest()
        return (hmac.compare_digest(username, self.username)
                and hmac.compare_digest(got, self.password_hash))

    def issue_cookie(self, now: float | None = None) -> str:
        expires = int((now or time.time()) + self.session_seconds)
        payload = f"{self.username}|{expires}"
        sig = hmac.new(self._key, payload.encode(),
                       hashlib.sha256).hexdigest()
        return f"{payload}|{sig}"

    def verify_cookie(self, token: str, now: float | None = None) -> bool:
        parts = token.split("|")
        if len(parts) != 3:
            return False
        payload = f"{parts[0]}|{parts[1]}"
        want = hmac.new(self._key, payload.encode(),
                        hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want, parts[2]):
            return False
        try:
            return (now or time.time()) < int(parts[1])
        except ValueError:
            return False


def _cookie_from_header(header: str | None) -> str | None:
    for part in (header or "").split(";"):
        name, _, value = part.strip().partition("=")
        if name == COOKIE_NAME:
            return value
    return None


def make_server(auth: AuthService, port: int) -> ThreadingHTTPServer:
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _send(self, code: int, body: bytes, ctype="text/html",
                  extra: dict | None = None) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (extra or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path in ("/healthz", "/readyz"):
                self._send(200, b'{"status":"ok"}', "application/json")
            elif self.path.startswith("/login"):
                self._send(200, _LOGIN_FORM.format(message="").encode())
            elif self.path == "/auth":
                token = _cookie_from_header(self.headers.get("Cookie"))
                if token and auth.verify_cookie(token):
                    self._send(200, b'{"authorized":true}',
                               "application/json")
                else:
                    self._send(401, b'{"authorized":false}',
                               "application/json")
            elif self.path == "/logout":
                self._send(
                    302, b"", extra={
                        "Location": "/login",
                        "Set-Cookie": f"{COOKIE_NAME}=; Path=/; Max-Age=0",
                    },
                )
            else:
                self._send(404, b"not found", "text/plain")

        def do_POST(self):
            if self.path != "/login":
                self._send(404, b"not found", "text/plain")
                return
            length = int(self.headers.get("Content-Length", 0))
            form = urllib.parse.parse_qs(
                self.rfile.read(length).decode("utf-8", "replace")
            )
            username = (form.get("username") or [""])[0]
            password = (form.get("password") or [""])[0]
            if auth.check_login(username, password):
                cookie = auth.issue_cookie()
                self._send(
                    302, b"", extra={
                        "Location": "/",
                        "Set-Cookie": (
                            f"{COOKIE_NAME}={cookie}; Path=/; HttpOnly"
                        ),
                    },
                )
            else:
                self._send(
                    401,
                    _LOGIN_FORM.format(
                        message="<p>Invalid credentials.</p>"
                    ).encode(),
                )

    return ThreadingHTTPServer(("0.0.0.0", port), Handler)


def main(argv=None) -> int:
    argv = strip_glog_args(list(sys.argv[1:] if argv is None else argv))
    p = argparse.ArgumentParser(description="gatekeeper auth server")
    p.add_argument("--port", type=int, default=8085)
    p.add_argument("--secret-path", default=DEFAULT_SECRET_PATH)
    args = p.parse_args(argv)

    auth = AuthService.from_secret_dir(args.secret_path)
    httpd = make_server(auth, args.port)
    print(json.dumps({"msg": "gatekeeper up", "port": args.port,
                      "user": auth.username}))
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
