"""Identity tokens: ES256 JWTs + JWKS — the IAP identity layer.

The reference's front door verifies Google-signed identity JWTs at the
envoy proxy (/root/reference/kubeflow/gcp/iap.libsonnet:589-600: `jwt-auth`
filter with issuer/audiences/jwks_uri and a bypass path list), and its
availability prober authenticates through that layer with a
service-account id-token (metric-collector/service-readiness/
kubeflow-readiness.py:21-37). This module is the platform-native core of
that function:

- :class:`SigningKeyRing` — the gatekeeper's signing side: ES256 (P-256)
  keypairs with stable ``kid``s, zero-downtime rotation (retired keys
  stay published in the JWKS until every token they signed has expired),
  and short-lived id-token issuance.
- :func:`verify` — the proxy's verifying side: signature against a JWKS,
  issuer/audience/expiry with clock skew, algorithm pinned to ES256 (an
  ``alg: none`` or HMAC downgrade is rejected before any crypto runs).

Uses the ``cryptography`` package (present in the base image); imports are
function-local like :mod:`kubeflow_tpu.auth.pki`.
"""

from __future__ import annotations

import base64
import hashlib
import json
import threading
import time
from typing import Callable, Mapping

ALG = "ES256"
# Longest token TTL the issuer will grant — also how long a retired
# signing key must stay published before it can be pruned from the JWKS.
MAX_TTL_SECONDS = 24 * 3600


class TokenError(Exception):
    """Verification failure; str() is a short machine-greppable reason."""


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode("ascii")


def _unb64url(text: str) -> bytes:
    pad = "=" * (-len(text) % 4)
    return base64.urlsafe_b64decode(text + pad)


def _int_to_b64url(n: int) -> str:
    return _b64url(n.to_bytes(32, "big"))


class SigningKeyRing:
    """ES256 signing keys with JWKS publication and rotation.

    ``rotate()`` makes a fresh key active; previous keys are retired but
    remain in the JWKS until ``prune()`` observes that every token they
    could have signed has expired (retire time + MAX_TTL). Verifiers that
    re-fetch the JWKS on an unknown ``kid`` therefore see no outage at
    any point in the rotation.
    """

    def __init__(self, issuer: str, *, clock: Callable[[], float] = time.time):
        self.issuer = issuer
        self.clock = clock
        self._lock = threading.Lock()
        self._keys: dict[str, object] = {}     # kid -> EC private key
        self._retired_at: dict[str, float] = {}
        self._active_kid = ""
        self.rotate()

    # -- key lifecycle ------------------------------------------------------

    def rotate(self) -> str:
        """Generate + activate a new signing key; returns its kid."""
        from cryptography.hazmat.primitives import serialization
        from cryptography.hazmat.primitives.asymmetric import ec

        key = ec.generate_private_key(ec.SECP256R1())
        spki = key.public_key().public_bytes(
            serialization.Encoding.DER,
            serialization.PublicFormat.SubjectPublicKeyInfo,
        )
        kid = hashlib.sha256(spki).hexdigest()[:16]
        with self._lock:
            if self._active_kid:
                self._retired_at[self._active_kid] = self.clock()
            self._keys[kid] = key
            self._active_kid = kid
        return kid

    def prune(self) -> list[str]:
        """Drop retired keys no live token can still reference."""
        cutoff = self.clock() - MAX_TTL_SECONDS
        with self._lock:
            dead = [kid for kid, t in self._retired_at.items()
                    if t < cutoff]
            for kid in dead:
                del self._keys[kid]
                del self._retired_at[kid]
        return dead

    @property
    def active_kid(self) -> str:
        with self._lock:
            return self._active_kid

    def jwks(self) -> dict:
        """Public keys as an RFC 7517 key set (active + retired)."""
        with self._lock:
            keys = []
            for kid, key in self._keys.items():
                nums = key.public_key().public_numbers()
                keys.append({
                    "kty": "EC", "crv": "P-256", "alg": ALG, "use": "sig",
                    "kid": kid,
                    "x": _int_to_b64url(nums.x),
                    "y": _int_to_b64url(nums.y),
                })
            return {"keys": keys}

    # -- issuance -----------------------------------------------------------

    def issue(self, subject: str, audience: str | list[str], *,
              ttl_seconds: int = 3600, claims: Mapping | None = None) -> str:
        """Sign a short-lived id-token for ``subject``/``audience``."""
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import ec, utils

        ttl = max(1, min(int(ttl_seconds), MAX_TTL_SECONDS))
        now = int(self.clock())
        payload = dict(claims or {})
        payload.update({
            "iss": self.issuer, "sub": subject, "aud": audience,
            "iat": now, "exp": now + ttl,
        })
        with self._lock:
            kid = self._active_kid
            key = self._keys[kid]
        header = {"alg": ALG, "typ": "JWT", "kid": kid}
        signing_input = (
            _b64url(json.dumps(header, separators=(",", ":")).encode())
            + "."
            + _b64url(json.dumps(payload, separators=(",", ":")).encode())
        )
        der = key.sign(signing_input.encode("ascii"),
                       ec.ECDSA(hashes.SHA256()))
        r, s = utils.decode_dss_signature(der)
        sig = r.to_bytes(32, "big") + s.to_bytes(32, "big")
        return signing_input + "." + _b64url(sig)


def _public_key_from_jwk(jwk: Mapping):
    from cryptography.hazmat.primitives.asymmetric import ec

    if jwk.get("kty") != "EC" or jwk.get("crv") != "P-256":
        raise TokenError("unsupported-key")
    x = int.from_bytes(_unb64url(jwk["x"]), "big")
    y = int.from_bytes(_unb64url(jwk["y"]), "big")
    return ec.EllipticCurvePublicNumbers(
        x, y, ec.SECP256R1()
    ).public_key()


def decode_unverified(token: str) -> tuple[dict, dict]:
    """Parse (header, payload) WITHOUT verification — for kid routing
    only; never trust the result for authorization."""
    parts = token.split(".")
    if len(parts) != 3:
        raise TokenError("malformed")
    try:
        header = json.loads(_unb64url(parts[0]))
        payload = json.loads(_unb64url(parts[1]))
    except (ValueError, UnicodeDecodeError):
        raise TokenError("malformed") from None
    if not isinstance(header, dict) or not isinstance(payload, dict):
        raise TokenError("malformed")
    return header, payload


def verify(token: str, jwks: Mapping, *, issuer: str, audience: str,
           now: float | None = None, skew_seconds: float = 60.0) -> dict:
    """Verify signature + claims; returns the payload or raises TokenError.

    The algorithm is pinned: only ES256 against an EC/P-256 JWKS key is
    accepted, so ``alg: none`` and HMAC-with-public-key downgrades fail
    as ``bad-alg`` before any signature math.
    """
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec, utils

    header, payload = decode_unverified(token)
    if header.get("alg") != ALG:
        raise TokenError("bad-alg")
    kid = header.get("kid", "")
    jwk = next((k for k in jwks.get("keys", []) if k.get("kid") == kid),
               None)
    if jwk is None:
        raise TokenError("unknown-kid")
    try:
        sig = _unb64url(token.rsplit(".", 1)[1])
    except ValueError:
        raise TokenError("bad-signature") from None
    if len(sig) != 64:
        raise TokenError("bad-signature")
    der = utils.encode_dss_signature(
        int.from_bytes(sig[:32], "big"), int.from_bytes(sig[32:], "big")
    )
    signing_input = token.rsplit(".", 1)[0].encode("ascii")
    try:
        _public_key_from_jwk(jwk).verify(der, signing_input,
                                         ec.ECDSA(hashes.SHA256()))
    except InvalidSignature:
        raise TokenError("bad-signature") from None

    if payload.get("iss") != issuer:
        raise TokenError("bad-issuer")
    aud = payload.get("aud")
    if not (aud == audience or (isinstance(aud, list) and audience in aud)):
        raise TokenError("bad-audience")
    t = time.time() if now is None else now
    try:
        exp = float(payload["exp"])
    except (KeyError, TypeError, ValueError):
        raise TokenError("no-expiry") from None
    if t > exp + skew_seconds:
        raise TokenError("expired")
    nbf = payload.get("nbf", payload.get("iat"))
    if nbf is not None:
        try:
            if t < float(nbf) - skew_seconds:
                raise TokenError("not-yet-valid")
        except (TypeError, ValueError):
            raise TokenError("malformed") from None
    return payload
