"""Auth runtimes: gatekeeper login/session server and the mutating
admission webhook (components/gatekeeper/auth/AuthServer.go,
components/gcp-admission-webhook/main.go analogues)."""
