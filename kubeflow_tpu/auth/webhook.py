"""Mutating admission + CRD conversion webhook:
`python -m kubeflow_tpu.auth.webhook`.

The gcp-admission-webhook analogue (components/gcp-admission-webhook/
main.go:131-158, patch ops :51-53): pods labeled
`kubeflow-tpu.org/cred-secret=<name>` get that Secret mounted plus
GOOGLE_APPLICATION_CREDENTIALS pointed at it (the credentials-pod-preset
surface); TPU-requesting containers get safe env defaults. Speaks the
AdmissionReview v1 protocol on POST /mutate, and the ConversionReview
v1 protocol on POST /convert — the structural converter a REAL
apiserver calls for the job CRDs' multi-version story (the fake
apiserver converts in-process with the same registered functions).
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import ssl
import sys
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from kubeflow_tpu.runtime import strip_glog_args

CRED_LABEL = "kubeflow-tpu.org/cred-secret"
CRED_MOUNT_PATH = "/var/secrets/platform"
CRED_VOLUME = "platform-creds"
TPU_RESOURCE = "google.com/tpu"


def _env_patch(container: dict, idx: int, name: str, value: str) -> list[dict]:
    existing = container.get("env")
    entry = {"name": name, "value": value}
    if existing is None:
        return [{"op": "add", "path": f"/spec/containers/{idx}/env",
                 "value": [entry]}]
    if any(e.get("name") == name for e in existing):
        return []
    return [{"op": "add", "path": f"/spec/containers/{idx}/env/-",
             "value": entry}]


def mutate_pod(pod: dict) -> list[dict]:
    """JSONPatch ops for one pod (empty = no mutation)."""
    patches: list[dict] = []
    spec = pod.get("spec", {})
    containers = spec.get("containers", [])
    secret = pod.get("metadata", {}).get("labels", {}).get(CRED_LABEL)

    if secret:
        volumes = spec.get("volumes")
        vol = {"name": CRED_VOLUME, "secret": {"secretName": secret}}
        if volumes is None:
            patches.append({"op": "add", "path": "/spec/volumes",
                            "value": [vol]})
        elif not any(v.get("name") == CRED_VOLUME for v in volumes):
            patches.append({"op": "add", "path": "/spec/volumes/-",
                            "value": vol})
        for i, c in enumerate(containers):
            mounts = c.get("volumeMounts")
            mount = {"name": CRED_VOLUME, "mountPath": CRED_MOUNT_PATH,
                     "readOnly": True}
            if mounts is None:
                patches.append({
                    "op": "add",
                    "path": f"/spec/containers/{i}/volumeMounts",
                    "value": [mount],
                })
            elif not any(m.get("name") == CRED_VOLUME for m in mounts):
                patches.append({
                    "op": "add",
                    "path": f"/spec/containers/{i}/volumeMounts/-",
                    "value": mount,
                })
            patches.extend(_env_patch(
                c, i, "GOOGLE_APPLICATION_CREDENTIALS",
                f"{CRED_MOUNT_PATH}/key.json",
            ))

    # TPU env defaults for containers requesting chips.
    for i, c in enumerate(containers):
        limits = c.get("resources", {}).get("limits", {})
        if TPU_RESOURCE in limits:
            patches.extend(_env_patch(c, i, "TPU_MIN_LOG_LEVEL", "1"))
            patches.extend(_env_patch(c, i, "JAX_PLATFORMS", "tpu,cpu"))
    return patches


def review_response(review: dict) -> dict:
    """AdmissionReview request → AdmissionReview response."""
    request = review.get("request", {})
    uid = request.get("uid", "")
    obj = request.get("object", {}) or {}
    response: dict = {"uid": uid, "allowed": True}
    if obj.get("kind", "Pod") == "Pod":
        patches = mutate_pod(obj)
        if patches:
            response["patchType"] = "JSONPatch"
            response["patch"] = base64.b64encode(
                json.dumps(patches).encode()
            ).decode()
    return {
        "apiVersion": review.get("apiVersion",
                                 "admission.k8s.io/v1"),
        "kind": "AdmissionReview",
        "response": response,
    }


def convert_response(review: dict) -> dict:
    """ConversionReview request → response, via the converters the API
    packages register with the client layer (apis/jobs.convert_job)."""
    # Importing the API packages registers their converters.
    from kubeflow_tpu.apis import jobs as _jobs  # noqa: F401
    from kubeflow_tpu.k8s.client import ApiError, KindRegistry

    request = review.get("request") or {}
    if not isinstance(request, dict):
        request = {}
    uid = request.get("uid", "")
    desired = request.get("desiredAPIVersion", "")
    converted, failure = [], None
    for obj in request.get("objects") or []:
        if not isinstance(obj, dict):
            # Malformed input must produce the protocol's Failed result,
            # not a handler crash and a dropped connection.
            failure = "objects entries must be objects"
            break
        try:
            converted.append(KindRegistry.convert(obj, desired))
        except ApiError as e:
            failure = e.message or str(e)
            break
    response: dict = {"uid": uid}
    if failure is None:
        response["result"] = {"status": "Success"}
        response["convertedObjects"] = converted
    else:
        response["result"] = {"status": "Failed", "message": failure}
    return {
        "apiVersion": review.get("apiVersion",
                                 "apiextensions.k8s.io/v1"),
        "kind": "ConversionReview",
        "response": response,
    }


def make_server(port: int, *, certfile: str = "",
                keyfile: str = "") -> ThreadingHTTPServer:
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _send(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path in ("/healthz", "/readyz"):
                self._send(200, {"status": "ok"})
            else:
                self._send(404, {"error": "not found"})

        def do_POST(self):
            if self.path not in ("/mutate", "/convert"):
                self._send(404, {"error": "not found"})
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                review = json.loads(self.rfile.read(length) or b"{}")
                handler = (review_response if self.path == "/mutate"
                           else convert_response)
                self._send(200, handler(review))
            except (ValueError, KeyError) as e:
                self._send(400, {"error": str(e)})

    httpd = ThreadingHTTPServer(("0.0.0.0", port), Handler)
    if certfile and keyfile:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(certfile, keyfile)
        httpd.socket = ctx.wrap_socket(httpd.socket, server_side=True)
    return httpd


def _mint_ca_and_leaf(namespace: str, service: str):
    """Generate a webhook serving CA + leaf for the Service DNS names.
    Returns (KeyCert ca, KeyCert leaf, base64 CA bundle)."""
    from kubeflow_tpu.auth import pki

    ca = pki.make_ca(f"{service}-ca.{namespace}")
    leaf = pki.issue(ca, [
        f"{service}.{namespace}.svc",
        f"{service}.{namespace}.svc.cluster.local",
        service,
    ], duration_seconds=365 * 24 * 3600)
    bundle = base64.b64encode(ca.cert_pem.encode()).decode()
    return ca, leaf, bundle


def self_sign(namespace: str, service: str = "admission-webhook"):
    """Generate a webhook serving CA + leaf for the Service DNS names.
    Returns (KeyCert leaf, base64 CA bundle)."""
    _ca, leaf, bundle = _mint_ca_and_leaf(namespace, service)
    return leaf, bundle


def ensure_shared_ca(client, namespace: str,
                     service: str = "admission-webhook",
                     secret_name: str = "admission-webhook-tls"):
    """Cluster-wide self-sign: ONE CA/leaf per deployment, not one per
    pod. With ``--self-sign`` and ``replicas > 1`` each pod used to mint
    its own CA and race :func:`patch_ca_bundles` — whichever pod patched
    last won the clientConfigs while its peers kept serving leaves from
    a different root, so a fraction of admission/conversion dials failed
    TLS verification forever. Persisting CA + leaf in a Secret makes the
    mint a cluster-wide once: every pod first loads the Secret; on miss
    it mints and ``create``s, and the apiserver's create-conflict (409)
    picks the single winner — losers throw their candidate away and load
    the winner's. Returns (KeyCert leaf, base64 CA bundle, created)."""
    from kubeflow_tpu.auth.pki import KeyCert
    from kubeflow_tpu.k8s.client import ApiError

    def _load(secret):
        data = secret.get("data", {}) or {}

        def field(key):
            return base64.b64decode(data.get(key, "")).decode()

        leaf = KeyCert(key_pem=field("tls.key"), cert_pem=field("tls.crt"),
                       ca_pem=field("ca.crt"))
        if not (leaf.key_pem and leaf.cert_pem and leaf.ca_pem):
            raise ValueError(
                f"secret {secret_name} is missing tls.key/tls.crt/ca.crt")
        return leaf, base64.b64encode(leaf.ca_pem.encode()).decode()

    existing = client.get_or_none("v1", "Secret", secret_name, namespace)
    if existing is not None:
        return (*_load(existing), False)
    ca, leaf, bundle = _mint_ca_and_leaf(namespace, service)
    secret = {
        "apiVersion": "v1",
        "kind": "Secret",
        "metadata": {"name": secret_name, "namespace": namespace,
                     "labels": {"app": service}},
        "type": "kubernetes.io/tls",
        "data": {
            "tls.crt": base64.b64encode(leaf.cert_pem.encode()).decode(),
            "tls.key": base64.b64encode(leaf.key_pem.encode()).decode(),
            "ca.crt": base64.b64encode(ca.cert_pem.encode()).decode(),
            # CA key rides along so a future rotation can re-issue
            # leaves under the SAME root without re-patching bundles.
            "ca.key": base64.b64encode(ca.key_pem.encode()).decode(),
        },
    }
    try:
        client.create(secret)
    except ApiError as e:
        if e.code != 409:
            raise
        # Lost the race: a peer pod created it between our get and
        # create. Its CA is the cluster's CA now — load it.
        return (*_load(client.get("v1", "Secret", secret_name, namespace)),
                False)
    return leaf, bundle, True


def patch_ca_bundles(client, ca_bundle_b64: str,
                     webhook_name: str = "admission-webhook"
                     ) -> tuple[int, int]:
    """Write the serving CA into every in-cluster clientConfig that dials
    this webhook: the MutatingWebhookConfiguration AND each job CRD's
    conversion stanza — the cert-manager-CA-injector role, done by the
    webhook itself (the manifest's `ca_bundle` param may stay empty).
    Returns (patched, failed); the caller retries while failed > 0 —
    CRD conversion has no failurePolicy escape, so a stale bundle must
    converge, not wait for a lucky restart. Network errors count as
    failures (requests exceptions are OSErrors), never crashes."""
    from kubeflow_tpu.apis.jobs import API_GROUP, PLURALS
    from kubeflow_tpu.k8s.client import ApiError

    patched, failed = 0, 0
    try:
        mwc = client.get_or_none(
            "admissionregistration.k8s.io/v1",
            "MutatingWebhookConfiguration", webhook_name)
        if mwc is not None:
            changed = False
            for wh in mwc.get("webhooks", []):
                cc = wh.setdefault("clientConfig", {})
                if cc.get("caBundle") != ca_bundle_b64:
                    cc["caBundle"] = ca_bundle_b64
                    changed = True
            if changed:
                client.update(mwc)
                patched += 1
    except (ApiError, OSError):
        failed += 1
    for plural in PLURALS.values():
        try:
            crd = client.get_or_none(
                "apiextensions.k8s.io/v1", "CustomResourceDefinition",
                f"{plural}.{API_GROUP}")
            if crd is None:
                continue
            webhook = (crd.get("spec", {}).get("conversion", {})
                       .get("webhook"))
            if webhook is None:
                continue
            cc = webhook.setdefault("clientConfig", {})
            if cc.get("caBundle") != ca_bundle_b64:
                cc["caBundle"] = ca_bundle_b64
                client.update(crd)
                patched += 1
        except (ApiError, OSError):
            failed += 1
    return patched, failed


def main(argv=None) -> int:
    argv = strip_glog_args(list(sys.argv[1:] if argv is None else argv))
    p = argparse.ArgumentParser(description="mutating admission webhook")
    p.add_argument("--port", type=int, default=8443)
    p.add_argument("--tls-cert", default="",
                   help="TLS cert path (with --tls-key; plain HTTP if "
                        "unset and --self-sign absent)")
    p.add_argument("--tls-key", default="")
    p.add_argument("--self-sign", action="store_true",
                   help="generate a serving CA + leaf at startup and "
                        "serve TLS with it")
    p.add_argument("--patch-ca", action="store_true",
                   help="write the serving CA into the in-cluster "
                        "MutatingWebhookConfiguration and job-CRD "
                        "conversion clientConfigs (requires --self-sign)")
    p.add_argument("--pod-namespace",
                   default=os.environ.get("POD_NAMESPACE", ""),
                   help="namespace for self-signed Service DNS names "
                        "(default: POD_NAMESPACE env, else --namespace)")
    p.add_argument("--patch-retry-seconds", type=float, default=30.0,
                   help="retry cadence while any caBundle patch is "
                        "failing (CRD conversion has no failurePolicy "
                        "escape — the bundle must converge)")
    from kubeflow_tpu.runtime import add_client_args, client_from_args

    add_client_args(p)  # --apiserver/--token-path/--namespace (in-cluster aware)
    args = p.parse_args(argv)

    certfile, keyfile = args.tls_cert, args.tls_key
    bundle = ""
    client = None
    ca_secret_shared = False
    if args.self_sign:
        import tempfile

        ns = args.pod_namespace or args.namespace
        if args.patch_ca:
            # Replicated deployments MUST share one CA: per-pod minting
            # races patch_ca_bundles and strands peers on an unpatched
            # root. First writer persists CA+leaf in a Secret
            # (create-conflict picks the winner); everyone else loads.
            client = client_from_args(args)
            try:
                leaf, bundle, _created = ensure_shared_ca(client, ns)
                ca_secret_shared = True
            except (OSError, ValueError) as e:
                # Secret API unreachable at boot: fall back to a local
                # mint so the pod comes up; the patch retry loop keeps
                # converging the bundle.
                print(json.dumps({"msg": "shared-CA secret unavailable, "
                                         "self-signing locally",
                                  "error": str(e)}), flush=True)
                leaf, bundle = self_sign(ns)
        else:
            leaf, bundle = self_sign(ns)
        cert_f = tempfile.NamedTemporaryFile("w", suffix=".pem",
                                             delete=False)
        cert_f.write(leaf.chain_pem)
        cert_f.close()
        key_f = tempfile.NamedTemporaryFile("w", suffix=".pem",
                                            delete=False)
        key_f.write(leaf.key_pem)
        key_f.close()
        certfile, keyfile = cert_f.name, key_f.name

    httpd = make_server(args.port, certfile=certfile, keyfile=keyfile)
    if args.self_sign:
        # The SSLContext holds the loaded chain; don't leave key
        # material on disk for the container lifetime.
        for path in (certfile, keyfile):
            try:
                os.unlink(path)
            except OSError:
                pass
    patched = failed = 0
    if args.patch_ca and bundle:
        if client is None:
            client = client_from_args(args)
        patched, failed = patch_ca_bundles(client, bundle)
        if failed:
            import threading

            def retry_loop():
                while True:
                    import time as _time

                    _time.sleep(args.patch_retry_seconds)
                    _p, f = patch_ca_bundles(client, bundle)
                    if f == 0:
                        return

            threading.Thread(target=retry_loop, daemon=True).start()

    print(json.dumps({"msg": "admission webhook up", "port": args.port,
                      "tls": bool(certfile),
                      "self_signed": args.self_sign,
                      "ca_secret_shared": ca_secret_shared,
                      "ca_bundles_patched": patched,
                      "ca_patches_failed": failed}), flush=True)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
