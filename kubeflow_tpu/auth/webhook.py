"""Mutating admission + CRD conversion webhook:
`python -m kubeflow_tpu.auth.webhook`.

The gcp-admission-webhook analogue (components/gcp-admission-webhook/
main.go:131-158, patch ops :51-53): pods labeled
`kubeflow-tpu.org/cred-secret=<name>` get that Secret mounted plus
GOOGLE_APPLICATION_CREDENTIALS pointed at it (the credentials-pod-preset
surface); TPU-requesting containers get safe env defaults. Speaks the
AdmissionReview v1 protocol on POST /mutate, and the ConversionReview
v1 protocol on POST /convert — the structural converter a REAL
apiserver calls for the job CRDs' multi-version story (the fake
apiserver converts in-process with the same registered functions).
"""

from __future__ import annotations

import argparse
import base64
import json
import ssl
import sys
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from kubeflow_tpu.runtime import strip_glog_args

CRED_LABEL = "kubeflow-tpu.org/cred-secret"
CRED_MOUNT_PATH = "/var/secrets/platform"
CRED_VOLUME = "platform-creds"
TPU_RESOURCE = "google.com/tpu"


def _env_patch(container: dict, idx: int, name: str, value: str) -> list[dict]:
    existing = container.get("env")
    entry = {"name": name, "value": value}
    if existing is None:
        return [{"op": "add", "path": f"/spec/containers/{idx}/env",
                 "value": [entry]}]
    if any(e.get("name") == name for e in existing):
        return []
    return [{"op": "add", "path": f"/spec/containers/{idx}/env/-",
             "value": entry}]


def mutate_pod(pod: dict) -> list[dict]:
    """JSONPatch ops for one pod (empty = no mutation)."""
    patches: list[dict] = []
    spec = pod.get("spec", {})
    containers = spec.get("containers", [])
    secret = pod.get("metadata", {}).get("labels", {}).get(CRED_LABEL)

    if secret:
        volumes = spec.get("volumes")
        vol = {"name": CRED_VOLUME, "secret": {"secretName": secret}}
        if volumes is None:
            patches.append({"op": "add", "path": "/spec/volumes",
                            "value": [vol]})
        elif not any(v.get("name") == CRED_VOLUME for v in volumes):
            patches.append({"op": "add", "path": "/spec/volumes/-",
                            "value": vol})
        for i, c in enumerate(containers):
            mounts = c.get("volumeMounts")
            mount = {"name": CRED_VOLUME, "mountPath": CRED_MOUNT_PATH,
                     "readOnly": True}
            if mounts is None:
                patches.append({
                    "op": "add",
                    "path": f"/spec/containers/{i}/volumeMounts",
                    "value": [mount],
                })
            elif not any(m.get("name") == CRED_VOLUME for m in mounts):
                patches.append({
                    "op": "add",
                    "path": f"/spec/containers/{i}/volumeMounts/-",
                    "value": mount,
                })
            patches.extend(_env_patch(
                c, i, "GOOGLE_APPLICATION_CREDENTIALS",
                f"{CRED_MOUNT_PATH}/key.json",
            ))

    # TPU env defaults for containers requesting chips.
    for i, c in enumerate(containers):
        limits = c.get("resources", {}).get("limits", {})
        if TPU_RESOURCE in limits:
            patches.extend(_env_patch(c, i, "TPU_MIN_LOG_LEVEL", "1"))
            patches.extend(_env_patch(c, i, "JAX_PLATFORMS", "tpu,cpu"))
    return patches


def review_response(review: dict) -> dict:
    """AdmissionReview request → AdmissionReview response."""
    request = review.get("request", {})
    uid = request.get("uid", "")
    obj = request.get("object", {}) or {}
    response: dict = {"uid": uid, "allowed": True}
    if obj.get("kind", "Pod") == "Pod":
        patches = mutate_pod(obj)
        if patches:
            response["patchType"] = "JSONPatch"
            response["patch"] = base64.b64encode(
                json.dumps(patches).encode()
            ).decode()
    return {
        "apiVersion": review.get("apiVersion",
                                 "admission.k8s.io/v1"),
        "kind": "AdmissionReview",
        "response": response,
    }


def convert_response(review: dict) -> dict:
    """ConversionReview request → response, via the converters the API
    packages register with the client layer (apis/jobs.convert_job)."""
    # Importing the API packages registers their converters.
    from kubeflow_tpu.apis import jobs as _jobs  # noqa: F401
    from kubeflow_tpu.k8s.client import ApiError, KindRegistry

    request = review.get("request") or {}
    if not isinstance(request, dict):
        request = {}
    uid = request.get("uid", "")
    desired = request.get("desiredAPIVersion", "")
    converted, failure = [], None
    for obj in request.get("objects") or []:
        if not isinstance(obj, dict):
            # Malformed input must produce the protocol's Failed result,
            # not a handler crash and a dropped connection.
            failure = "objects entries must be objects"
            break
        try:
            converted.append(KindRegistry.convert(obj, desired))
        except ApiError as e:
            failure = e.message or str(e)
            break
    response: dict = {"uid": uid}
    if failure is None:
        response["result"] = {"status": "Success"}
        response["convertedObjects"] = converted
    else:
        response["result"] = {"status": "Failed", "message": failure}
    return {
        "apiVersion": review.get("apiVersion",
                                 "apiextensions.k8s.io/v1"),
        "kind": "ConversionReview",
        "response": response,
    }


def make_server(port: int, *, certfile: str = "",
                keyfile: str = "") -> ThreadingHTTPServer:
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _send(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path in ("/healthz", "/readyz"):
                self._send(200, {"status": "ok"})
            else:
                self._send(404, {"error": "not found"})

        def do_POST(self):
            if self.path not in ("/mutate", "/convert"):
                self._send(404, {"error": "not found"})
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                review = json.loads(self.rfile.read(length) or b"{}")
                handler = (review_response if self.path == "/mutate"
                           else convert_response)
                self._send(200, handler(review))
            except (ValueError, KeyError) as e:
                self._send(400, {"error": str(e)})

    httpd = ThreadingHTTPServer(("0.0.0.0", port), Handler)
    if certfile and keyfile:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(certfile, keyfile)
        httpd.socket = ctx.wrap_socket(httpd.socket, server_side=True)
    return httpd


def main(argv=None) -> int:
    argv = strip_glog_args(list(sys.argv[1:] if argv is None else argv))
    p = argparse.ArgumentParser(description="mutating admission webhook")
    p.add_argument("--port", type=int, default=8443)
    p.add_argument("--tls-cert", default="",
                   help="TLS cert path (with --tls-key; plain HTTP if unset)")
    p.add_argument("--tls-key", default="")
    args = p.parse_args(argv)

    httpd = make_server(args.port, certfile=args.tls_cert,
                        keyfile=args.tls_key)
    print(json.dumps({"msg": "admission webhook up", "port": args.port,
                      "tls": bool(args.tls_cert)}))
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
