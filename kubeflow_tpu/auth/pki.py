"""X.509 issuance primitives for the certificate controller.

The reference delegates certificate lifecycle to cert-manager
(/root/reference/kubeflow/gcp/prototypes/cert-manager.jsonnet:1-12 deploys
the upstream controller with an ACME letsencrypt issuer;
iap.libsonnet:1-1041 wires the resulting secrets into the ingress). This
platform issues in-process: a self-signed CA per Issuer CR and leaf
certificates signed by it, with the rotation state machine living in
:mod:`kubeflow_tpu.operators.certificates`.

Uses the ``cryptography`` package (present in the base image); imports are
function-local so the rest of the platform never pays for (or fails on)
it — anything importing this module is already certificate machinery.
"""

from __future__ import annotations

from dataclasses import dataclass
from datetime import datetime, timedelta, timezone

_EC_CURVE = "secp256r1"  # small keys, fast issuance; TLS-universal


@dataclass(frozen=True)
class KeyCert:
    """PEM-encoded private key + certificate (and the issuing CA chain)."""

    key_pem: str
    cert_pem: str
    ca_pem: str = ""

    @property
    def chain_pem(self) -> str:
        """Leaf followed by CA — what a TLS server presents."""
        return self.cert_pem + self.ca_pem


def _new_key():
    from cryptography.hazmat.primitives.asymmetric import ec

    return ec.generate_private_key(ec.SECP256R1())


def _key_pem(key) -> str:
    from cryptography.hazmat.primitives import serialization

    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    ).decode()


def _cert_pem(cert) -> str:
    from cryptography.hazmat.primitives import serialization

    return cert.public_bytes(serialization.Encoding.PEM).decode()


def make_ca(common_name: str, *, days: int = 3650) -> KeyCert:
    """Self-signed CA — the Issuer CR's root of trust."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes
    from cryptography.x509.oid import NameOID

    key = _new_key()
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, common_name)]
    )
    now = datetime.now(timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - timedelta(minutes=5))
        .not_valid_after(now + timedelta(days=days))
        .add_extension(x509.BasicConstraints(ca=True, path_length=0),
                       critical=True)
        .add_extension(
            x509.KeyUsage(
                digital_signature=True, key_cert_sign=True, crl_sign=True,
                content_commitment=False, key_encipherment=False,
                data_encipherment=False, key_agreement=False,
                encipher_only=False, decipher_only=False,
            ),
            critical=True,
        )
        .sign(key, hashes.SHA256())
    )
    pem = _cert_pem(cert)
    return KeyCert(key_pem=_key_pem(key), cert_pem=pem, ca_pem=pem)


def issue(
    ca: KeyCert,
    dns_names: list[str],
    *,
    duration_seconds: int,
    common_name: str | None = None,
) -> KeyCert:
    """Issue a leaf certificate for ``dns_names`` signed by ``ca``."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.serialization import (
        load_pem_private_key,
    )
    from cryptography.x509.oid import (
        ExtendedKeyUsageOID,
        NameOID,
    )

    if not dns_names:
        raise ValueError("certificate needs at least one dnsName")
    ca_key = load_pem_private_key(ca.key_pem.encode(), password=None)
    ca_cert = x509.load_pem_x509_certificate(ca.cert_pem.encode())
    key = _new_key()
    now = datetime.now(timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(x509.Name([x509.NameAttribute(
            NameOID.COMMON_NAME, common_name or dns_names[0])]))
        .issuer_name(ca_cert.subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - timedelta(minutes=5))
        .not_valid_after(now + timedelta(seconds=duration_seconds))
        .add_extension(
            x509.SubjectAlternativeName(
                [x509.DNSName(n) for n in dns_names]),
            critical=False,
        )
        .add_extension(x509.BasicConstraints(ca=False, path_length=None),
                       critical=True)
        .add_extension(
            x509.ExtendedKeyUsage([ExtendedKeyUsageOID.SERVER_AUTH]),
            critical=False,
        )
        .sign(ca_key, hashes.SHA256())
    )
    return KeyCert(key_pem=_key_pem(key), cert_pem=_cert_pem(cert),
                   ca_pem=ca.cert_pem)


def cert_info(cert_pem: str) -> dict:
    """Expiry/identity facts the rotation state machine keys on."""
    from cryptography import x509
    from cryptography.x509.oid import ExtensionOID

    cert = x509.load_pem_x509_certificate(cert_pem.encode())
    try:
        san = cert.extensions.get_extension_for_oid(
            ExtensionOID.SUBJECT_ALTERNATIVE_NAME
        ).value
        dns_names = san.get_values_for_type(x509.DNSName)
    except x509.ExtensionNotFound:
        dns_names = []
    return {
        "serial": format(cert.serial_number, "x"),
        "not_before": cert.not_valid_before_utc,
        "not_after": cert.not_valid_after_utc,
        "dns_names": list(dns_names),
        "issuer": cert.issuer.rfc4514_string(),
    }
