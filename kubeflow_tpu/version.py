"""Platform version and API-group constants."""
__version__ = "0.1.0"

# API group for all CRDs this platform owns (the analogue of kubeflow.org in
# the reference, e.g. kubeflow/tf-training/tf-job-operator.libsonnet:55).
API_GROUP = "kubeflow-tpu.org"
DEFAULT_NAMESPACE = "kubeflow"
