"""Loss functions.

Cross entropy is computed in float32 from bf16 logits with the max-subtracted
logsumexp, plus the z-loss regularizer that keeps logits from drifting when
training in low precision. Masked positions (label < 0) contribute zero and
are excluded from the normalizer — the convention the data pipeline's padding
relies on.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _nll_and_lse(logits, labels):
    """Per-position (nll, lse) in fp32 — the shared numerical core of the
    full and chunked CE paths. The subtracted max must be the SAME
    stop-gradient value when added back, else grad(lse) gains a spurious
    one_hot(argmax) term. Negative labels gather index 0; callers mask."""
    logits32 = logits.astype(jnp.float32)
    m = jax.lax.stop_gradient(jnp.max(logits32, axis=-1, keepdims=True))
    lse = jnp.log(jnp.sum(jnp.exp(logits32 - m), axis=-1)) + m[..., 0]
    label_logit = jnp.take_along_axis(
        logits32, jnp.maximum(labels, 0)[..., None], axis=-1
    )[..., 0]
    return lse - label_logit, lse


def softmax_cross_entropy(
    logits, labels, *, z_loss: float = 0.0, where=None
):
    """Mean token cross entropy.

    logits: [..., V]; labels: [...] int32, negative = ignore. Returns
    (loss, metrics dict with "loss", "z_loss", "tokens").
    """
    nll, lse = _nll_and_lse(logits, labels)

    mask = labels >= 0
    if where is not None:
        mask = mask & where
    maskf = mask.astype(jnp.float32)
    tokens = jnp.maximum(jnp.sum(maskf), 1.0)
    loss = jnp.sum(nll * maskf) / tokens

    metrics = {"loss": loss, "tokens": tokens}
    if z_loss:
        zl = z_loss * jnp.sum(jnp.square(lse) * maskf) / tokens
        metrics["z_loss"] = zl
        loss = loss + zl
    return loss, metrics


def chunked_lm_head_loss(x, head, labels, *, z_loss: float = 0.0,
                         n_chunks: int = 4):
    """LM head matmul + cross entropy without ever materializing the full
    ``[N, V]`` logits tensor.

    At vocab 32k and 8k tokens per step the fp32 logits alone are >1GB of
    HBM live across the whole backward pass. Here rows are processed in
    ``n_chunks`` chunks under ``jax.checkpoint``: forward keeps only the
    per-chunk scalar sums, and backward *recomputes* each chunk's logits
    when it needs them — peak logits memory drops by the chunk factor for
    one extra head matmul per chunk. Numerics match
    :func:`softmax_cross_entropy` (same max-shifted logsumexp in fp32,
    same z-loss, same negative-label masking).

    x: [N, D] final hidden states; head: [D, V]; labels: [N] int32
    (negative = ignore). Returns (loss, metrics) like the unchunked path.
    """
    n, d = x.shape
    if n % n_chunks:
        raise ValueError(f"rows {n} not divisible by n_chunks {n_chunks}")
    xc = x.reshape(n_chunks, n // n_chunks, d)
    lc = labels.reshape(n_chunks, n // n_chunks)

    @jax.checkpoint
    def chunk_sums(xi, li):
        nll, lse = _nll_and_lse(xi @ head, li)
        maskf = (li >= 0).astype(jnp.float32)
        return (
            jnp.sum(nll * maskf),
            jnp.sum(jnp.square(lse) * maskf),
            jnp.sum(maskf),
        )

    def body(carry, inp):
        nll, zsq, tok = chunk_sums(*inp)
        return (carry[0] + nll, carry[1] + zsq, carry[2] + tok), None

    zero = jnp.zeros((), jnp.float32)
    (nll_sum, zsq_sum, tok_sum), _ = jax.lax.scan(
        body, (zero, zero, zero), (xc, lc)
    )
    tokens = jnp.maximum(tok_sum, 1.0)
    loss = nll_sum / tokens
    metrics = {"loss": loss, "tokens": tokens}
    if z_loss:
        zl = z_loss * zsq_sum / tokens
        metrics["z_loss"] = zl
        loss = loss + zl
    return loss, metrics
