"""Loss functions.

Cross entropy is computed in float32 from bf16 logits with the max-subtracted
logsumexp, plus the z-loss regularizer that keeps logits from drifting when
training in low precision. Masked positions (label < 0) contribute zero and
are excluded from the normalizer — the convention the data pipeline's padding
relies on.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def softmax_cross_entropy(
    logits, labels, *, z_loss: float = 0.0, where=None
):
    """Mean token cross entropy.

    logits: [..., V]; labels: [...] int32, negative = ignore. Returns
    (loss, metrics dict with "loss", "z_loss", "tokens").
    """
    logits32 = logits.astype(jnp.float32)
    # The subtracted max must be the SAME stop-gradient value when added
    # back, else grad(lse) gains a spurious one_hot(argmax) term.
    m = jax.lax.stop_gradient(jnp.max(logits32, axis=-1, keepdims=True))
    shifted = logits32 - m
    lse = jnp.log(jnp.sum(jnp.exp(shifted), axis=-1)) + m[..., 0]
    label_logit = jnp.take_along_axis(
        logits32, jnp.maximum(labels, 0)[..., None], axis=-1
    )[..., 0]
    nll = lse - label_logit

    mask = labels >= 0
    if where is not None:
        mask = mask & where
    maskf = mask.astype(jnp.float32)
    tokens = jnp.maximum(jnp.sum(maskf), 1.0)
    loss = jnp.sum(nll * maskf) / tokens

    metrics = {"loss": loss, "tokens": tokens}
    if z_loss:
        zl = z_loss * jnp.sum(jnp.square(lse) * maskf) / tokens
        metrics["z_loss"] = zl
        loss = loss + zl
    return loss, metrics
