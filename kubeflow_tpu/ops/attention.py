"""Flash attention for TPU: pallas MXU kernel or blockwise XLA.

Three implementations behind one API:

- ``"pallas"``: the tiled TPU flash kernel (fused forward AND backward,
  causal block skipping — blocks above the diagonal are never computed, so
  attention flops halve at long sequence). This is the long-sequence
  training path: at seq1024+ the XLA single-block path pays the full
  [T, S] score matmuls in fwd, bwd, and the flash recompute, which is
  where the deep model's MFU went at realistic context (VERDICT r3 #1).
  GQA folds the query-head group into the batch so keys/values are never
  materialized at H_q width.
- ``"xla"`` (and the auto default off-TPU): blockwise online softmax over
  kv blocks with ``lax.scan``; backward recomputes p from the saved
  logsumexp. At ``block_k == T`` the scan collapses to a single fused
  block — the measured-fastest short-sequence configuration (27.3k vs
  23.8k tok/s at block_k=128 on the shallow flagship).
- ``"plain"``: materialized [T, S] scores — fastest when T is small and
  O(T·S) memory is irrelevant.

History: round 2's hand-written pallas kernel lost catastrophically inside
the full flagship train step (1.2k vs 27.3k tok/s; git history has it) —
it had no causal skipping and a recompute-everything backward. Round 4's
rematch with a block-skipping fused-backward kernel wins at depth and
realistic sequence length: +6-9 MFU points on flagship-deep at seq1024.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30
# Default kv block widths when the caller leaves block_k=None: the XLA
# blockwise path takes DEFAULT_BLOCK_K (callers with known-static sequence
# lengths should pass block_k == seq_len — single block, measured fastest
# on v5e; 2048 keeps memory O(T·2048) for long sequences), the TPU kernels
# take DEFAULT_KERNEL_BLOCK_K (1024-wide tiles measured faster than 2048
# at seq≥2048, and VMEM-safe).
DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 2048
DEFAULT_KERNEL_BLOCK_K = 1024


def _causal_mask(q_start, k_start, bq, bk):
    q_pos = q_start + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = k_start + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return q_pos >= k_pos


# ---------------------------------------------------------------------------
# Blockwise XLA path (CPU fallback + backward recomputation)
# ---------------------------------------------------------------------------


def _kv_blocks(x, nk, block_k):
    # [BKV, S, ...] -> iteration-major [nk, BKV, block_k, ...]
    bkv = x.shape[0]
    return x.reshape(bkv, nk, block_k, *x.shape[2:]).swapaxes(0, 1)


def _flash_fwd_xla(q, k, v, kvm, *, causal, scale, block_k):
    """Same online-softmax accumulation as the kernel, as a scan over kv
    blocks. q: [BKV, G, T, D]; k,v: [BKV, S, D]; kvm: [BKV, S, 1]."""
    bkv, g, t, d = q.shape
    s_len = k.shape[1]
    block_k = min(block_k, s_len)
    if s_len % block_k:
        block_k = s_len  # odd lengths: single block, still O(T·block) mem
    nk = s_len // block_k
    q32 = q.astype(jnp.float32)

    def step(carry, blk):
        m, l, acc = carry
        k_b, v_b, kvm_b, j = blk
        s = jnp.einsum("bgqd,bkd->bgqk", q32, k_b,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            mask = _causal_mask(0, j * block_k, t, block_k)
            s = jnp.where(mask[None, None], s, _NEG_INF)
        s = jnp.where(kvm_b[..., 0][:, None, None, :] > 0, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.einsum("bgqk,bkd->bgqd", p, v_b)
        return (m_new, l, acc), None

    init = (
        jnp.full((bkv, g, t, 1), _NEG_INF, jnp.float32),
        jnp.zeros((bkv, g, t, 1), jnp.float32),
        jnp.zeros((bkv, g, t, d), jnp.float32),
    )
    (m, l, acc), _ = lax.scan(
        step, init,
        (_kv_blocks(k.astype(jnp.float32), nk, block_k),
         _kv_blocks(v.astype(jnp.float32), nk, block_k),
         _kv_blocks(kvm, nk, block_k),
         jnp.arange(nk)),
    )
    # Rows with every key masked never saw a finite score (m stayed at
    # _NEG_INF, p degenerated to exp(0)=1 per key): return zeros, not mean(V).
    valid = m > _NEG_INF / 2
    out = jnp.where(valid, acc / l, 0.0).astype(q.dtype)
    lse = jnp.where(valid, m + jnp.log(l), _NEG_INF)
    return out, lse


def _flash_bwd_xla(q, k, v, kvm, out, lse, g_out, *, causal, scale, block_k):
    """Flash backward: recompute p blockwise from lse; scan over kv blocks."""
    bkv, g, t, d = q.shape
    s_len = k.shape[1]
    block_k = min(block_k, s_len)
    if s_len % block_k:
        block_k = s_len
    nk = s_len // block_k
    q32, g32 = q.astype(jnp.float32), g_out.astype(jnp.float32)
    delta = jnp.sum(g32 * out.astype(jnp.float32), axis=-1, keepdims=True)

    def step(dq, blk):
        k_b, v_b, kvm_b, j = blk
        s = jnp.einsum("bgqd,bkd->bgqk", q32, k_b,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            mask = _causal_mask(0, j * block_k, t, block_k)
            s = jnp.where(mask[None, None], s, _NEG_INF)
        s = jnp.where(kvm_b[..., 0][:, None, None, :] > 0, s, _NEG_INF)
        # All-masked rows carry lse=_NEG_INF; exp(s-lse) would degenerate to
        # 1 per key — their p (and so dk/dv/dq contributions) must be zero.
        p = jnp.where(lse > _NEG_INF / 2, jnp.exp(s - lse), 0.0)
        dp = jnp.einsum("bgqd,bkd->bgqk", g32, v_b)
        ds = p * (dp - delta) * scale
        dq = dq + jnp.einsum("bgqk,bkd->bgqd", ds, k_b)
        dk_b = jnp.einsum("bgqk,bgqd->bkd", ds, q32)
        dv_b = jnp.einsum("bgqk,bgqd->bkd", p, g32)
        return dq, (dk_b, dv_b)

    dq, (dk_blocks, dv_blocks) = lax.scan(
        step, jnp.zeros((bkv, g, t, d), jnp.float32),
        (_kv_blocks(k.astype(jnp.float32), nk, block_k),
         _kv_blocks(v.astype(jnp.float32), nk, block_k),
         _kv_blocks(kvm, nk, block_k),
         jnp.arange(nk)),
    )
    dk = dk_blocks.swapaxes(0, 1).reshape(bkv, s_len, d)
    dv = dv_blocks.swapaxes(0, 1).reshape(bkv, s_len, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# Pallas TPU kernel path (fused bwd + causal block skipping)
# ---------------------------------------------------------------------------


def _pallas_supported(q, k, kv_mask) -> bool:
    """The tiled kernel wants TPU, lane-width head_dim, and MXU-aligned
    sequence tiles; anything else routes to the XLA path."""
    try:
        if jax.devices()[0].platform != "tpu":
            return False
    except RuntimeError:
        return False
    _b, t, _hq, d = q.shape
    s_len = k.shape[1]
    return (kv_mask is None and d % 128 == 0
            and t % 128 == 0 and t >= 128 and s_len % 128 == 0)


def _pallas_flash(q, k, v, *, causal, scale, block):
    """q: [B, T, Hq, D]; k, v: [B, S, Hkv, D] → [B, T, Hq, D] via the
    pallas TPU flash kernel (jax.experimental.pallas.ops.tpu). The kernel
    is MHA; GQA folds the query-head group into the kernel's head axis
    ([B·Hkv, G, T, D]) with K/V broadcast across the group (XLA
    materializes the broadcast for the kernel call, but the gradient sums
    straight back to the [B, S, Hkv, D] layout). Block width 1024 measured
    fastest at seq1024/2048 on v5e (vs 512: +0.5-0.9 MFU pt; vs 256:
    -4.3 pts)."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes,
        flash_attention as _kernel,
    )

    b, t, hq, d = q.shape
    s_len, hkv = k.shape[1], k.shape[2]
    group = hq // hkv
    # [B, T, Hq, D] -> [B·Hkv, G, T, D]; K/V -> [B·Hkv, 1, S, D] broadcast
    # over the group axis (the kernel's "heads" dim).
    qf = (q.transpose(0, 2, 1, 3)
          .reshape(b, hkv, group, t, d)
          .reshape(b * hkv, group, t, d))
    kf = jnp.broadcast_to(
        k.transpose(0, 2, 1, 3).reshape(b * hkv, 1, s_len, d),
        (b * hkv, group, s_len, d))
    vf = jnp.broadcast_to(
        v.transpose(0, 2, 1, 3).reshape(b * hkv, 1, s_len, d),
        (b * hkv, group, s_len, d))
    bq = min(block, t)
    bk = min(block, s_len)
    sizes = BlockSizes(
        block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bk, block_k_dkv=bk,
        block_q_dkv=bq, block_k_major_dq=bk, block_k_dq=bk,
        block_q_dq=bq,
    )
    out = _kernel(qf, kf, vf, causal=causal, sm_scale=scale,
                  block_sizes=sizes)
    return (out.reshape(b, hkv, group, t, d)
            .reshape(b, hq, t, d)
            .transpose(0, 2, 1, 3))


@functools.lru_cache(maxsize=32)
def _splash_kernel(group: int, t: int, s_len: int, causal: bool,
                   block: int):
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk,
        splash_attention_mask as ml,
    )

    if causal:
        heads = [ml.CausalMask((t, s_len)) for _ in range(group)]
    else:
        heads = [ml.FullMask((t, s_len)) for _ in range(group)]
    blk = min(block, t, s_len)
    sizes = sk.BlockSizes(
        block_q=blk, block_kv=blk, block_kv_compute=blk,
        block_q_dkv=blk, block_kv_dkv=blk, block_kv_dkv_compute=blk,
        block_q_dq=blk, block_kv_dq=blk,
    )
    return sk.make_splash_mqa_single_device(
        mask=ml.MultiHeadMask(heads), block_sizes=sizes,
        residual_checkpoint_name="attn_res",
    )


def _splash_flash(q, k, v, *, causal, scale, block):
    """GQA-native splash attention: one kernel per kv head with the query
    group riding the kernel's head axis — K/V are never materialized at
    H_q width (the flash-kernel path broadcasts them ``group``×). The
    kernel checkpoints its residuals under the name ``"attn_res"`` so the
    "llm_res" remat policy can keep them across the backward instead of
    re-running the forward kernel."""
    b, t, hq, d = q.shape
    s_len, hkv = k.shape[1], k.shape[2]
    group = hq // hkv
    kernel = _splash_kernel(group, t, s_len, causal, block)
    # Splash takes pre-scaled queries ([B, Hkv, G, T, D] vs K/V
    # [B, Hkv, S, D]); vmap over batch then kv-head.
    qf = ((q * scale).astype(q.dtype)
          .transpose(0, 2, 1, 3)
          .reshape(b, hkv, group, t, d))
    kf = k.transpose(0, 2, 1, 3)
    vf = v.transpose(0, 2, 1, 3)
    out = jax.vmap(jax.vmap(kernel))(qf, kf, vf)  # [B, Hkv, G, T, D]
    return (out.reshape(b, hq, t, d).transpose(0, 2, 1, 3))


# ---------------------------------------------------------------------------
# Public op with custom VJP
# ---------------------------------------------------------------------------


def _plain_attention(q, k, v, kvm, *, causal, scale):
    """Reference path: materialize the [G,T,S] score matrix. On TPU this is
    often the fastest choice at moderate T — one fused softmax over a single
    large MXU matmul pair beats a sequential scan of small blocks — at the
    cost of O(T·S) activation memory. q: [BKV, G, T, D]; k,v: [BKV, S, D]."""
    s = jnp.einsum("bgqd,bkd->bgqk", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * scale
    t, s_len = q.shape[2], k.shape[1]
    if causal:
        mask = _causal_mask(0, 0, t, s_len)
        s = jnp.where(mask[None, None], s, _NEG_INF)
    s = jnp.where(kvm[..., 0][:, None, None, :] > 0, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    valid = m > _NEG_INF / 2  # all-masked rows → zeros, matching flash
    p = jnp.exp(s - jnp.where(valid, m, 0.0))
    p = jnp.where(valid, p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bgqk,bkd->bgqd", p, v.astype(jnp.float32))
    out = jnp.where(valid, acc / jnp.where(l == 0, 1.0, l), 0.0)
    return out.astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash(q, k, v, kvm, causal, scale, block_q, block_k):
    out, _ = _flash_fwd_xla(q, k, v, kvm, causal=causal, scale=scale,
                            block_k=block_k)
    return out


def _flash_vjp_fwd(q, k, v, kvm, causal, scale, block_q, block_k):
    out, lse = _flash_fwd_xla(q, k, v, kvm, causal=causal, scale=scale,
                              block_k=block_k)
    return out, (q, k, v, kvm, out, lse)


def _flash_vjp_bwd(causal, scale, block_q, block_k, res, g):
    q, k, v, kvm, out, lse = res
    dq, dk, dv = _flash_bwd_xla(q, k, v, kvm, out, lse, g, causal=causal,
                                scale=scale, block_k=block_k)
    return dq, dk, dv, jnp.zeros_like(kvm)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


# ---------------------------------------------------------------------------
# Paged block-table decode attention (models/decode.py's fused read path)
# ---------------------------------------------------------------------------
#
# The paged KV layout stores K/V in a pool of fixed-size blocks; slot
# ``b``'s virtual position ``p`` lives at block ``table[b, p // Bs]``,
# offset ``p % Bs``. The reference read path gathers the whole virtual
# row ``[B, MB*Bs, Hkv, hd]`` per layer per decode step before dense
# attention — at serving shapes that materialization IS the decode
# bandwidth bill. The fused paths below walk the table instead and
# compute span attention one block at a time with an online softmax, so
# the dense view never exists:
#
# - ``"xla"``: a ``lax.scan`` over table columns (any backend) — each
#   step touches one ``[B, Bs, Hkv, hd]`` block.
# - ``"pallas"``: the TPU kernel. The block table and per-row positions
#   ride scalar prefetch so the index_map DMAs exactly the physical
#   block each grid step needs; int8 pools are dequantized in-register
#   (scale broadcast over the lane dim) between the DMA and the MXU.
#
# Pools may be quantized: ``{"q": int8 [N, Bs, Hkv, hd], "scale": f32
# [N, Bs, Hkv]}`` with one abs-max scale per (position, kv head).
# Numerics: scores/softmax/accumulation in f32 (an online softmax is not
# bitwise-identical to the one-shot reference, which is why
# models/decode.py keeps the gather path as the pinned-parity default).


def _kv_payload(pool):
    """The payload array of a (possibly quantized) block pool."""
    return pool["q"] if isinstance(pool, dict) else pool


def _read_block(pool, blk):
    """Gather ONE physical block per row ([B] ids → [B, Bs, Hkv, hd] f32),
    dequantizing int8 payloads against their per-position scales."""
    if isinstance(pool, dict):
        return (pool["q"][blk].astype(jnp.float32)
                * pool["scale"][blk][..., None])
    return pool[blk].astype(jnp.float32)


def _paged_decode_xla(qg, k_pool, v_pool, table, pos, sm_scale):
    """Blockwise online-softmax walk of the table. qg: [B, Hkv, G, hd];
    pools: [N, Bs, Hkv, hd] (or quantized dicts); table: [B, MB]; pos:
    [B] (row attends virtual positions <= pos). Returns [B, Hkv, G, hd]
    f32 — no ``[B, MB*Bs]`` view is ever built."""
    n, bs = _kv_payload(k_pool).shape[0], _kv_payload(k_pool).shape[1]
    b, hkv, g, hd = qg.shape
    mb = table.shape[1]
    q32 = qg.astype(jnp.float32)

    def step(carry, j):
        m, l, acc = carry
        # Sentinel entries (>= N, the unallocated marker) clamp to the
        # last block; the junk they surface sits past ``pos`` where the
        # span mask already excludes it.
        blk = jnp.clip(table[:, j], 0, n - 1)
        k_b = _read_block(k_pool, blk)
        v_b = _read_block(v_pool, blk)
        s = jnp.einsum("bkgd,bskd->bkgs", q32, k_b,
                       preferred_element_type=jnp.float32) * sm_scale
        span = j * bs + jnp.arange(bs)[None, :]
        s = jnp.where((span <= pos[:, None])[:, None, None, :], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.einsum("bkgs,bskd->bkgd", p, v_b)
        return (m_new, l, acc), None

    init = (
        jnp.full((b, hkv, g, 1), _NEG_INF, jnp.float32),
        jnp.zeros((b, hkv, g, 1), jnp.float32),
        jnp.zeros((b, hkv, g, hd), jnp.float32),
    )
    (m, l, acc), _ = lax.scan(step, init, jnp.arange(mb))
    ok = m > _NEG_INF / 2  # pos >= 0 keeps slot 0 live, but stay defensive
    return jnp.where(ok, acc / jnp.where(l == 0.0, 1.0, l), 0.0)


def _paged_decode_pallas(qg, k_pool, v_pool, table, pos, sm_scale,
                         interpret=False):
    """TPU kernel twin of :func:`_paged_decode_xla`. Grid is
    ``(B, Hkv, MB)`` with the table column innermost; the scalar-prefetched
    table drives each step's K/V DMA (the gather never exists, not even
    blockwise on host), and int8 tiles are dequantized in-register."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    quant = isinstance(k_pool, dict)
    kq = _kv_payload(k_pool)
    n, bs, hkv, hd = kq.shape
    b, _, g, _ = qg.shape
    mb = table.shape[1]
    # Head-major pools: one (block, head) tile [Bs, hd] is a contiguous
    # DMA. Scales get a trailing singleton so their tile is 2D.
    kt = kq.transpose(0, 2, 1, 3)
    vt = _kv_payload(v_pool).transpose(0, 2, 1, 3)
    operands = [kt, vt]
    if quant:
        operands += [k_pool["scale"].transpose(0, 2, 1)[..., None],
                     v_pool["scale"].transpose(0, 2, 1)[..., None]]

    def kernel(tbl_ref, pos_ref, q_ref, k_ref, v_ref, *rest):
        if quant:
            ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
        else:
            o_ref, m_ref, l_ref, acc_ref = rest
        row = pl.program_id(0)
        j = pl.program_id(2)

        @pl.when(j == 0)
        def _init():
            m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
            l_ref[:] = jnp.zeros_like(l_ref)
            acc_ref[:] = jnp.zeros_like(acc_ref)

        k = k_ref[:].astype(jnp.float32)
        v = v_ref[:].astype(jnp.float32)
        if quant:  # in-register dequant: [Bs, 1] scale over the lane dim
            k = k * ks_ref[:]
            v = v * vs_ref[:]
        s = jnp.dot(q_ref[:].astype(jnp.float32), k.T,
                    preferred_element_type=jnp.float32) * sm_scale
        span = j * bs + lax.broadcasted_iota(jnp.int32, (1, bs), 1)
        s = jnp.where(span <= pos_ref[row], s, _NEG_INF)
        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jnp.dot(
            p, v, preferred_element_type=jnp.float32)
        m_ref[:] = m_new

        @pl.when(j == mb - 1)
        def _flush():
            l = l_ref[:]
            ok = m_ref[:] > _NEG_INF / 2
            o_ref[:] = jnp.where(
                ok, acc_ref[:] / jnp.where(l == 0.0, 1.0, l), 0.0)

    def _blk(tbl, _pos, row, j):
        # Sentinel entries clamp like the XLA path; the span mask hides
        # whatever the clamped DMA brings in.
        return jnp.minimum(tbl[row, j], n - 1)

    in_specs = [
        pl.BlockSpec((None, None, g, hd),
                     lambda row, h, j, tbl, pos: (row, h, 0, 0)),
        pl.BlockSpec((None, None, bs, hd),
                     lambda row, h, j, tbl, pos: (_blk(tbl, pos, row, j),
                                                  h, 0, 0)),
        pl.BlockSpec((None, None, bs, hd),
                     lambda row, h, j, tbl, pos: (_blk(tbl, pos, row, j),
                                                  h, 0, 0)),
    ]
    if quant:
        in_specs += [
            pl.BlockSpec((None, None, bs, 1),
                         lambda row, h, j, tbl, pos: (_blk(tbl, pos, row, j),
                                                      h, 0, 0)),
            pl.BlockSpec((None, None, bs, 1),
                         lambda row, h, j, tbl, pos: (_blk(tbl, pos, row, j),
                                                      h, 0, 0)),
        ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hkv, mb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((None, None, g, hd),
                               lambda row, h, j, tbl, pos: (row, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((g, 1), jnp.float32),
            pltpu.VMEM((g, 1), jnp.float32),
            pltpu.VMEM((g, hd), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, hkv, g, hd), jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(table.astype(jnp.int32), pos.astype(jnp.int32), qg, *operands)


def _paged_kernel_supported(k_pool) -> bool:
    """The real (non-interpret) kernel wants a TPU and lane-aligned
    tiles; everything else rides the XLA walk."""
    try:
        if jax.devices()[0].platform != "tpu":
            return False
    except RuntimeError:
        return False
    payload = _kv_payload(k_pool)
    _n, bs, _hkv, hd = payload.shape
    return hd % 128 == 0 and bs % 8 == 0


def _paged_decode_local(qg, k_pool, v_pool, table, pos, sm_scale,
                        implementation, interpret):
    """Single-shard dispatch of the block walk (also the per-shard body
    of the mesh twin): qg [B, Hkv, G, hd] against [N, Bs, Hkv, hd]
    pools."""
    if implementation is None:
        implementation = ("pallas" if _paged_kernel_supported(k_pool)
                          else "xla")
    if implementation == "pallas":
        return _paged_decode_pallas(qg, k_pool, v_pool, table, pos,
                                    sm_scale, interpret=interpret)
    if implementation == "xla":
        return _paged_decode_xla(qg, k_pool, v_pool, table, pos, sm_scale)
    raise ValueError(f"unknown implementation {implementation!r}")


def _pool_head_specs(pool, axis: str, lead: int = 2):
    """PartitionSpec pytree sharding a block pool on its KV-head dim
    (``lead`` dims before it: [N, Bs] here, [L, N, Bs] for stacked
    pools). Quantized pools shard codes AND scales by the same axis —
    they ride the same block ids, so the split is one move."""
    from jax.sharding import PartitionSpec as P

    head = [None] * lead + [axis]
    if isinstance(pool, dict):
        return {"q": P(*head, None), "scale": P(*head)}
    return P(*head, None)


def _shard_heads(mesh, axis: str, n_kv_heads: int) -> int:
    """Validate the KV-head axis divides over ``axis`` and return the
    shard count (1 = mesh absent or axis unsplit)."""
    if mesh is None:
        return 1
    shards = int(mesh.shape.get(axis, 1))
    if shards > 1 and n_kv_heads % shards:
        raise ValueError(
            f"{n_kv_heads} kv heads not divisible by {shards} shards "
            f"on mesh axis {axis!r}")
    return shards


def paged_decode_attention(q, k_pool, v_pool, table, pos, *,
                           n_kv_heads: int, scale: float | None = None,
                           implementation: str | None = None,
                           interpret: bool = False,
                           mesh=None, axis: str = "tensor"):
    """Fused single-token attention over a paged KV pool.

    q: [B, Hq, hd] (one decode token per row, already rotary-embedded);
    k_pool/v_pool: [N, Bs, Hkv, hd] block pools, or quantized dicts
    ``{"q": int8, "scale": f32 [N, Bs, Hkv]}``; table: [B, MB] block
    table (entries >= N are unallocated sentinels); pos: [B] — row ``b``
    attends virtual positions ``<= pos[b]``. Returns [B, Hq, hd] f32.

    ``implementation``: None (auto: pallas on TPU for supported shapes,
    else xla), "pallas", or "xla". Both walk the block table with an
    online softmax — the gathered ``[B, MB*Bs, Hkv, hd]`` view is never
    materialized, which is the point.

    ``mesh`` (with ``axis`` sized > 1) selects the tensor-parallel twin:
    the pool is sharded over the KV-head dim and each shard walks the
    SAME block table over its local heads under ``shard_map``. The
    online-softmax state (m/l/acc) is per-head, so the walk needs no
    cross-shard collective at all — the output stays head-sharded for
    the row-parallel ``wo`` matmul, whose psum is the block's one
    reduction. Per-shard results are bitwise-equal to the single-device
    kernel's corresponding head slices."""
    b, hq, hd = q.shape
    if hq % n_kv_heads:
        raise ValueError(
            f"query heads {hq} not a multiple of kv heads {n_kv_heads}")
    group = hq // n_kv_heads
    sm_scale = (hd ** -0.5) if scale is None else scale
    qg = q.reshape(b, n_kv_heads, group, hd)
    if _shard_heads(mesh, axis, n_kv_heads) > 1:
        from jax.sharding import PartitionSpec as P

        from kubeflow_tpu.parallel.collectives import shard_map

        def _local(qg_l, k_l, v_l, tbl, pos_l):
            return _paged_decode_local(qg_l, k_l, v_l, tbl, pos_l,
                                       sm_scale, implementation, interpret)

        out = shard_map(
            _local, mesh=mesh,
            in_specs=(P(None, axis, None, None),
                      _pool_head_specs(k_pool, axis),
                      _pool_head_specs(v_pool, axis), P(), P()),
            out_specs=P(None, axis, None, None),
            axis_names=frozenset({axis}),
        )(qg, k_pool, v_pool, table, pos)
    else:
        out = _paged_decode_local(qg, k_pool, v_pool, table, pos, sm_scale,
                                  implementation, interpret)
    return out.reshape(b, hq, hd)


def _paged_span_xla(qg, k_pool, v_pool, table, pos, sm_scale):
    """Blockwise online-softmax walk for an S-wide query span. qg:
    [B, S, Hkv, G, hd]; pools: [N, Bs, Hkv, hd] (or quantized dicts);
    table: [B, MB]; pos: [B] — row ``b``'s span token ``s`` attends
    virtual positions ``<= pos[b] + s`` (its own just-written K/V
    included). Returns [B, S, Hkv, G, hd] f32; the dense
    ``[B, MB*Bs]`` view is never built."""
    n, bs = _kv_payload(k_pool).shape[0], _kv_payload(k_pool).shape[1]
    b, s_w, hkv, g, hd = qg.shape
    mb = table.shape[1]
    q32 = qg.astype(jnp.float32)
    # Per-(row, span-token) attention limit.
    limit = pos[:, None] + jnp.arange(s_w)[None, :]  # [B, S]

    def step(carry, j):
        m, l, acc = carry
        blk = jnp.clip(table[:, j], 0, n - 1)  # sentinels clamp; masked
        k_b = _read_block(k_pool, blk)
        v_b = _read_block(v_pool, blk)
        s = jnp.einsum("bskgd,bzkd->bkgsz", q32, k_b,
                       preferred_element_type=jnp.float32) * sm_scale
        span = j * bs + jnp.arange(bs)[None, None, :]
        mask = span <= limit[:, :, None]  # [B, S, Bs]
        s = jnp.where(mask[:, None, None, :, :], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.einsum("bkgsz,bzkd->bkgsd", p, v_b)
        return (m_new, l, acc), None

    init = (
        jnp.full((b, hkv, g, s_w, 1), _NEG_INF, jnp.float32),
        jnp.zeros((b, hkv, g, s_w, 1), jnp.float32),
        jnp.zeros((b, hkv, g, s_w, hd), jnp.float32),
    )
    (m, l, acc), _ = lax.scan(step, init, jnp.arange(mb))
    ok = m > _NEG_INF / 2  # rows parked past the table see no key
    out = jnp.where(ok, acc / jnp.where(l == 0.0, 1.0, l), 0.0)
    return out.transpose(0, 3, 1, 2, 4)  # [B, S, Hkv, G, hd]


def paged_span_attention(q, k_pool, v_pool, table, pos, *,
                         n_kv_heads: int, scale: float | None = None,
                         mesh=None, axis: str = "tensor"):
    """Fused S-wide attention over a paged KV pool — the span sibling of
    :func:`paged_decode_attention` (verify scoring reads [slots, K]
    spans, suffix prefill reads one [1, S] span; both previously paid
    the dense gather every layer).

    q: [B, S, Hq, hd] (already rotary-embedded, K/V for the span already
    scattered into the pool); pools/table as in
    :func:`paged_decode_attention`; pos: [B] — span token ``s`` of row
    ``b`` attends virtual positions ``<= pos[b] + s``. Returns
    [B, S, Hq, hd] f32. XLA block walk on every backend (the S-wide
    kernel shares the decode kernel's contract and can ride the same
    scalar-prefetch scheme later; the walk already removes the dense
    materialization, which is the bandwidth bill).

    ``mesh``/``axis``: the tensor-parallel twin, identical contract to
    :func:`paged_decode_attention`'s — each shard walks the same table
    over its local KV heads, no collective until the output
    projection."""
    b, s_w, hq, hd = q.shape
    if hq % n_kv_heads:
        raise ValueError(
            f"query heads {hq} not a multiple of kv heads {n_kv_heads}")
    group = hq // n_kv_heads
    sm_scale = (hd ** -0.5) if scale is None else scale
    qg = q.reshape(b, s_w, n_kv_heads, group, hd)
    if _shard_heads(mesh, axis, n_kv_heads) > 1:
        from jax.sharding import PartitionSpec as P

        from kubeflow_tpu.parallel.collectives import shard_map

        def _local(qg_l, k_l, v_l, tbl, pos_l):
            return _paged_span_xla(qg_l, k_l, v_l, tbl, pos_l, sm_scale)

        out = shard_map(
            _local, mesh=mesh,
            in_specs=(P(None, None, axis, None, None),
                      _pool_head_specs(k_pool, axis),
                      _pool_head_specs(v_pool, axis), P(), P()),
            out_specs=P(None, None, axis, None, None),
            axis_names=frozenset({axis}),
        )(qg, k_pool, v_pool, table, pos)
    else:
        out = _paged_span_xla(qg, k_pool, v_pool, table, pos, sm_scale)
    return out.reshape(b, s_w, hq, hd)


def ring_span_attention(q, k, v, pos, *, n_kv_heads: int,
                        scale: float | None = None,
                        mesh=None, axis: str = "sequence"):
    """Context-parallel exact span attention — the chunked-prefill ring.

    q: [B, S, Hq, hd] (one prefill chunk, already rotary-embedded; its
    K/V already scattered into the pool); k, v: [B, T, Hkv, hd] — the
    gathered, dequantized virtual rows (T = block_table width × block
    size, junk beyond the written span is exact zeros); pos: [B] — span
    token ``s`` of row ``b`` attends virtual positions ``<= pos[b] + s``
    (its own just-written K/V included). Returns [B, S, Hq, hd] f32.

    ``mesh`` with a ``sequence`` axis sized > 1 selects the ring twin:
    the query chunk is sharded S/cp per device and the K/V view T/cp per
    device; each device folds all cp K/V blocks with
    ring_attention's collective-permute online-softmax core
    (parallel/ring_attention.py:_block_attn), so per-device attention
    memory is O(S/cp × T/cp) and one replica's max prompt scales with
    cp. The span mask is computed from GLOBAL positions
    (ring_attention.py:span_bias), so the result is the same math as the
    dense read — f32-equivalent, not bitwise (online-softmax
    accumulation order differs), the same caveat as the fused block-walk
    kernels. GQA broadcasts K/V to query-head width before the ring
    (chunk views are bounded, so the width cost is the q block's)."""
    from kubeflow_tpu.parallel.ring_attention import (
        _block_attn,
        span_bias,
    )

    b, s_w, hq, hd = q.shape
    t_w = k.shape[1]
    if hq % n_kv_heads:
        raise ValueError(
            f"query heads {hq} not a multiple of kv heads {n_kv_heads}")
    group = hq // n_kv_heads
    sm_scale = (hd ** -0.5) if scale is None else scale
    # [B, T, H, hd] -> f32 [B, Hq, T, hd] with K/V at query-head width.
    qh = q.astype(jnp.float32).transpose(0, 2, 1, 3)
    kh = jnp.repeat(k.astype(jnp.float32), group, axis=2).transpose(0, 2, 1, 3)
    vh = jnp.repeat(v.astype(jnp.float32), group, axis=2).transpose(0, 2, 1, 3)

    def _fold_all(qh_l, kh_l, vh_l, pos_l, q_start, k_start):
        m0 = jnp.full((b, hq, qh_l.shape[2], 1), _NEG_INF, jnp.float32)
        num0 = jnp.zeros(qh_l.shape, jnp.float32)
        den0 = jnp.zeros((b, hq, qh_l.shape[2], 1), jnp.float32)
        bias = span_bias(pos_l, q_start, k_start,
                         qh_l.shape[2], kh_l.shape[2])[:, None]
        return _block_attn(qh_l, kh_l, vh_l, bias, m0, num0, den0, sm_scale)

    shards = int(mesh.shape.get(axis, 1)) if mesh is not None else 1
    if shards <= 1:
        m, num, den = _fold_all(qh, kh, vh, pos, 0, 0)
        return (num / den).transpose(0, 2, 1, 3)

    if s_w % shards or t_w % shards:
        raise ValueError(
            f"chunk width {s_w} and virtual width {t_w} must divide the "
            f"{shards}-way {axis!r} axis")
    from jax.sharding import PartitionSpec as P

    from kubeflow_tpu.parallel.collectives import (
        axis_size,
        shard_map,
    )

    def _ring(qh_l, kh_l, vh_l, pos_l):
        n = axis_size(axis)
        idx = lax.axis_index(axis)
        s_loc, t_loc = qh_l.shape[2], kh_l.shape[2]

        def step(carry, i):
            k_blk, v_blk, m, num, den = carry
            # Block i arrived from device (idx + i) mod n — its global
            # key offset; the query offset is this device's fixed chunk
            # slice. Global coordinates keep the mask exact across the
            # ring, fully-masked far blocks flush to exact zero when a
            # real block folds (the finite -1e30 trick).
            src = (idx + i) % n
            bias = span_bias(pos_l, idx * s_loc, src * t_loc,
                             s_loc, t_loc)[:, None]
            m, num, den = _block_attn(qh_l, k_blk, v_blk, bias,
                                      m, num, den, sm_scale)
            perm = [(j, (j - 1) % n) for j in range(n)]
            k_nxt = lax.ppermute(k_blk, axis_name=axis, perm=perm)
            v_nxt = lax.ppermute(v_blk, axis_name=axis, perm=perm)
            return (k_nxt, v_nxt, m, num, den), None

        m0 = jnp.full((b, hq, s_loc, 1), _NEG_INF, jnp.float32)
        num0 = jnp.zeros(qh_l.shape, jnp.float32)
        den0 = jnp.zeros((b, hq, s_loc, 1), jnp.float32)
        (_k, _v, m, num, den), _ = lax.scan(
            step, (kh_l, vh_l, m0, num0, den0), jnp.arange(n))
        return num / den

    out = shard_map(
        _ring, mesh=mesh,
        in_specs=(P(None, None, axis, None), P(None, None, axis, None),
                  P(None, None, axis, None), P()),
        out_specs=P(None, None, axis, None),
        axis_names=frozenset({axis}),
    )(qh, kh, vh, pos)
    return out.transpose(0, 2, 1, 3)


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = True,
    scale: float | None = None,
    kv_mask=None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int | None = None,
    implementation: str | None = None,
):
    """Multi-head / grouped-query flash attention.

    q: [B, T, H_q, D]; k, v: [B, S, H_kv, D] with H_q a multiple of H_kv.
    ``kv_mask``: optional [B, S], truthy = attend (padding mask for BERT /
    batched serving). Returns [B, T, H_q, D]. ``implementation``:

    - None — auto: the splash kernel on TPU for supported shapes at
      T ≥ 512 (where its causal block skipping and GQA-native layout win;
      measured +5 to +18 MFU pts on flagship-deep), blockwise XLA
      otherwise.
    - "splash" — GQA-native tiled TPU kernel (fused bwd, block-sparse
      causal masking, residuals checkpoint-nameable as "attn_res").
    - "pallas" — tiled TPU flash kernel (fused bwd + causal block
      skipping; K/V broadcast to H_q width).
    - "xla" — blockwise online-softmax scan (any backend, any shape).
    - "plain" — materialized scores.

    TPU-kernel picks fall back to the XLA path off-TPU or for
    masked/unaligned shapes, so one model definition runs everywhere.
    """
    b, t, hq, d = q.shape
    s_len, hkv = k.shape[1], k.shape[2]
    if hq % hkv:
        raise ValueError(f"query heads {hq} not a multiple of kv heads {hkv}")
    group = hq // hkv
    scale = (d**-0.5) if scale is None else scale

    pallas_ok = _pallas_supported(q, k, kv_mask)
    if implementation is None and t >= 512 and pallas_ok:
        implementation = "splash"
    if implementation in ("splash", "pallas") and pallas_ok:
        # block_k=None → per-path measured-best default: 1024-wide tiles
        # here (2048 is slower at seq≥2048 and a VMEM risk), 2048 on the
        # XLA fallback below. An explicit block_k is honored as given.
        kernel_block = DEFAULT_KERNEL_BLOCK_K if block_k is None else block_k
        if implementation == "pallas":
            return _pallas_flash(q, k, v, causal=causal, scale=scale,
                                 block=kernel_block)
        return _splash_flash(q, k, v, causal=causal, scale=scale,
                             block=kernel_block)
    if block_k is None:
        block_k = DEFAULT_BLOCK_K

    if kv_mask is None:
        kvm = jnp.ones((b, s_len), jnp.float32)
    else:
        kvm = kv_mask.astype(jnp.float32)
    kvm = jnp.repeat(kvm[:, None], hkv, axis=1).reshape(b * hkv, s_len, 1)

    # [B, T, Hq, D] -> [B*Hkv, G, T, D]; K/V -> [B*Hkv, S, D].
    qf = (
        q.transpose(0, 2, 1, 3)
        .reshape(b, hkv, group, t, d)
        .reshape(b * hkv, group, t, d)
    )
    kf = k.transpose(0, 2, 1, 3).reshape(b * hkv, s_len, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * hkv, s_len, d)

    if implementation == "plain":
        # Materialized scores; plain autodiff (no flash recompute) — the
        # short-sequence fast path where O(T·S) memory is cheap.
        out = _plain_attention(qf, kf, vf, kvm, causal=causal, scale=scale)
    else:
        out = _flash(qf, kf, vf, kvm, causal, scale, block_q, block_k)
    return (
        out.reshape(b, hkv, group, t, d)
        .reshape(b, hq, t, d)
        .transpose(0, 2, 1, 3)
    )
