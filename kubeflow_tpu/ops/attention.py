"""Flash attention for TPU, as blockwise XLA (online softmax over kv blocks).

Forward accumulates the online softmax over kv blocks with ``lax.scan``;
backward is the flash recomputation from the saved logsumexp, also blockwise,
so activation memory stays O(T·block) at any sequence length. GQA is native:
inputs are folded to [B·H_kv, group, T, D] so grouped keys/values are never
materialized at H_q width.

Why no hand-written kernel: a pallas MXU kernel of this op was benchmarked
against this path inside the full flagship train step on v5e and lost
catastrophically through this toolchain (1.2k vs 27.3k tok/s end-to-end;
git history has the kernel). XLA tiles the scan's matmuls onto the MXU
itself, and at ``block_k == T`` the scan collapses to a single fused block —
the measured-fastest configuration (27.3k vs 23.8k tok/s at block_k=128).

``implementation="plain"`` materializes the [T, S] scores — the fastest
choice for short sequences where O(T·S) memory is cheap.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30
# Default kv block width for the blockwise paths. Callers with known-static
# sequence lengths should pass block_k == seq_len (single block — measured
# fastest on v5e); the default keeps memory O(T·2048) for long sequences.
DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 2048


def _causal_mask(q_start, k_start, bq, bk):
    q_pos = q_start + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = k_start + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return q_pos >= k_pos


# ---------------------------------------------------------------------------
# Blockwise XLA path (CPU fallback + backward recomputation)
# ---------------------------------------------------------------------------


def _kv_blocks(x, nk, block_k):
    # [BKV, S, ...] -> iteration-major [nk, BKV, block_k, ...]
    bkv = x.shape[0]
    return x.reshape(bkv, nk, block_k, *x.shape[2:]).swapaxes(0, 1)


def _flash_fwd_xla(q, k, v, kvm, *, causal, scale, block_k):
    """Same online-softmax accumulation as the kernel, as a scan over kv
    blocks. q: [BKV, G, T, D]; k,v: [BKV, S, D]; kvm: [BKV, S, 1]."""
    bkv, g, t, d = q.shape
    s_len = k.shape[1]
    block_k = min(block_k, s_len)
    if s_len % block_k:
        block_k = s_len  # odd lengths: single block, still O(T·block) mem
    nk = s_len // block_k
    q32 = q.astype(jnp.float32)

    def step(carry, blk):
        m, l, acc = carry
        k_b, v_b, kvm_b, j = blk
        s = jnp.einsum("bgqd,bkd->bgqk", q32, k_b,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            mask = _causal_mask(0, j * block_k, t, block_k)
            s = jnp.where(mask[None, None], s, _NEG_INF)
        s = jnp.where(kvm_b[..., 0][:, None, None, :] > 0, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.einsum("bgqk,bkd->bgqd", p, v_b)
        return (m_new, l, acc), None

    init = (
        jnp.full((bkv, g, t, 1), _NEG_INF, jnp.float32),
        jnp.zeros((bkv, g, t, 1), jnp.float32),
        jnp.zeros((bkv, g, t, d), jnp.float32),
    )
    (m, l, acc), _ = lax.scan(
        step, init,
        (_kv_blocks(k.astype(jnp.float32), nk, block_k),
         _kv_blocks(v.astype(jnp.float32), nk, block_k),
         _kv_blocks(kvm, nk, block_k),
         jnp.arange(nk)),
    )
    # Rows with every key masked never saw a finite score (m stayed at
    # _NEG_INF, p degenerated to exp(0)=1 per key): return zeros, not mean(V).
    valid = m > _NEG_INF / 2
    out = jnp.where(valid, acc / l, 0.0).astype(q.dtype)
    lse = jnp.where(valid, m + jnp.log(l), _NEG_INF)
    return out, lse


def _flash_bwd_xla(q, k, v, kvm, out, lse, g_out, *, causal, scale, block_k):
    """Flash backward: recompute p blockwise from lse; scan over kv blocks."""
    bkv, g, t, d = q.shape
    s_len = k.shape[1]
    block_k = min(block_k, s_len)
    if s_len % block_k:
        block_k = s_len
    nk = s_len // block_k
    q32, g32 = q.astype(jnp.float32), g_out.astype(jnp.float32)
    delta = jnp.sum(g32 * out.astype(jnp.float32), axis=-1, keepdims=True)

    def step(dq, blk):
        k_b, v_b, kvm_b, j = blk
        s = jnp.einsum("bgqd,bkd->bgqk", q32, k_b,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            mask = _causal_mask(0, j * block_k, t, block_k)
            s = jnp.where(mask[None, None], s, _NEG_INF)
        s = jnp.where(kvm_b[..., 0][:, None, None, :] > 0, s, _NEG_INF)
        # All-masked rows carry lse=_NEG_INF; exp(s-lse) would degenerate to
        # 1 per key — their p (and so dk/dv/dq contributions) must be zero.
        p = jnp.where(lse > _NEG_INF / 2, jnp.exp(s - lse), 0.0)
        dp = jnp.einsum("bgqd,bkd->bgqk", g32, v_b)
        ds = p * (dp - delta) * scale
        dq = dq + jnp.einsum("bgqk,bkd->bgqd", ds, k_b)
        dk_b = jnp.einsum("bgqk,bgqd->bkd", ds, q32)
        dv_b = jnp.einsum("bgqk,bgqd->bkd", p, g32)
        return dq, (dk_b, dv_b)

    dq, (dk_blocks, dv_blocks) = lax.scan(
        step, jnp.zeros((bkv, g, t, d), jnp.float32),
        (_kv_blocks(k.astype(jnp.float32), nk, block_k),
         _kv_blocks(v.astype(jnp.float32), nk, block_k),
         _kv_blocks(kvm, nk, block_k),
         jnp.arange(nk)),
    )
    dk = dk_blocks.swapaxes(0, 1).reshape(bkv, s_len, d)
    dv = dv_blocks.swapaxes(0, 1).reshape(bkv, s_len, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# Public op with custom VJP
# ---------------------------------------------------------------------------


def _plain_attention(q, k, v, kvm, *, causal, scale):
    """Reference path: materialize the [G,T,S] score matrix. On TPU this is
    often the fastest choice at moderate T — one fused softmax over a single
    large MXU matmul pair beats a sequential scan of small blocks — at the
    cost of O(T·S) activation memory. q: [BKV, G, T, D]; k,v: [BKV, S, D]."""
    s = jnp.einsum("bgqd,bkd->bgqk", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * scale
    t, s_len = q.shape[2], k.shape[1]
    if causal:
        mask = _causal_mask(0, 0, t, s_len)
        s = jnp.where(mask[None, None], s, _NEG_INF)
    s = jnp.where(kvm[..., 0][:, None, None, :] > 0, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    valid = m > _NEG_INF / 2  # all-masked rows → zeros, matching flash
    p = jnp.exp(s - jnp.where(valid, m, 0.0))
    p = jnp.where(valid, p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bgqk,bkd->bgqd", p, v.astype(jnp.float32))
    out = jnp.where(valid, acc / jnp.where(l == 0, 1.0, l), 0.0)
    return out.astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash(q, k, v, kvm, causal, scale, block_q, block_k):
    out, _ = _flash_fwd_xla(q, k, v, kvm, causal=causal, scale=scale,
                            block_k=block_k)
    return out


def _flash_vjp_fwd(q, k, v, kvm, causal, scale, block_q, block_k):
    out, lse = _flash_fwd_xla(q, k, v, kvm, causal=causal, scale=scale,
                              block_k=block_k)
    return out, (q, k, v, kvm, out, lse)


def _flash_vjp_bwd(causal, scale, block_q, block_k, res, g):
    q, k, v, kvm, out, lse = res
    dq, dk, dv = _flash_bwd_xla(q, k, v, kvm, out, lse, g, causal=causal,
                                scale=scale, block_k=block_k)
    return dq, dk, dv, jnp.zeros_like(kvm)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = True,
    scale: float | None = None,
    kv_mask=None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    implementation: str | None = None,
):
    """Multi-head / grouped-query flash attention.

    q: [B, T, H_q, D]; k, v: [B, S, H_kv, D] with H_q a multiple of H_kv.
    ``kv_mask``: optional [B, S], truthy = attend (padding mask for BERT /
    batched serving). Returns [B, T, H_q, D]. ``implementation``: None
    (auto = blockwise flash), "xla" (same), "plain" (materialized scores).
    """
    b, t, hq, d = q.shape
    s_len, hkv = k.shape[1], k.shape[2]
    if hq % hkv:
        raise ValueError(f"query heads {hq} not a multiple of kv heads {hkv}")
    group = hq // hkv
    scale = (d**-0.5) if scale is None else scale

    if kv_mask is None:
        kvm = jnp.ones((b, s_len), jnp.float32)
    else:
        kvm = kv_mask.astype(jnp.float32)
    kvm = jnp.repeat(kvm[:, None], hkv, axis=1).reshape(b * hkv, s_len, 1)

    # [B, T, Hq, D] -> [B*Hkv, G, T, D]; K/V -> [B*Hkv, S, D].
    qf = (
        q.transpose(0, 2, 1, 3)
        .reshape(b, hkv, group, t, d)
        .reshape(b * hkv, group, t, d)
    )
    kf = k.transpose(0, 2, 1, 3).reshape(b * hkv, s_len, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * hkv, s_len, d)

    if implementation == "plain":
        # Materialized scores; plain autodiff (no flash recompute) — the
        # short-sequence fast path where O(T·S) memory is cheap.
        out = _plain_attention(qf, kf, vf, kvm, causal=causal, scale=scale)
    else:
        out = _flash(qf, kf, vf, kvm, causal, scale, block_q, block_k)
    return (
        out.reshape(b, hkv, group, t, d)
        .reshape(b, hq, t, d)
        .transpose(0, 2, 1, 3)
    )
