"""Flash attention for TPU.

Forward is a pallas kernel tiled for the MXU: grid over (batch×kv-head×group,
q-blocks, kv-blocks), online-softmax state carried in VMEM scratch across the
innermost (sequential) grid dimension, causal blocks above the diagonal
skipped. GQA is native: the grid's leading dim enumerates query heads while
the K/V BlockSpec index maps fold the group dim away (``b // group``), so
grouped keys/values are never materialized at H_q — and never vmapped, which
would multiply VMEM residency by the group size.

Backward is the flash recomputation, expressed blockwise with ``lax.scan`` so
activation memory stays O(T·block) and XLA tiles the matmuls onto the MXU
itself.

The pure-jax path (`implementation="xla"`) runs the same blockwise math and is
the fallback for the CPU fake slice, for head dims off the 128-lane grid, and
for short/odd sequence lengths.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
# 128×128 blocks map exactly onto the MXU tile and keep Mosaic's register
# allocator happy — 512-wide score blocks spill hundreds of MB (measured:
# 208M spill slots at block 512, seq 2048, v5e).
DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128


NUM_LANES = 128


def _causal_mask(q_start, k_start, bq, bk):
    q_pos = q_start + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = k_start + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return q_pos >= k_pos


def _lanes(x, width):
    """Widen a lane-replicated [rows, NUM_LANES] stat to [rows, width]."""
    if width == x.shape[-1]:
        return x
    if width < x.shape[-1]:
        return x[:, :width]
    return pltpu.repeat(x, width // x.shape[-1], axis=1)


# ---------------------------------------------------------------------------
# Pallas forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, kvm_ref, o_ref, lse_ref, m_scr, l_scr,
                acc_scr, *, causal: bool, scale: float, block_q: int,
                block_k: int):
    i = pl.program_id(2)  # q block
    j = pl.program_id(3)  # kv block
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # NOTE: no @pl.when around the compute — predicating the main body makes
    # Mosaic stack-allocate the full operands (55MB scoped-vmem blowups) and
    # fall off the pipelined path. Causality is enforced by the mask alone;
    # above-diagonal blocks contribute exp(-inf)=0.
    q = q_ref[0, 0].astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    if causal:
        mask = _causal_mask(i * block_q, j * block_k, block_q, block_k)
        s = jnp.where(mask, s, _NEG_INF)
    # Key-padding mask: kvm is [block_k, 1] with 1.0 = valid.
    s = jnp.where(kvm_ref[0][:, 0][None, :] > 0, s, _NEG_INF)
    # Row stats kept lane-replicated [block_q, NUM_LANES]: single-lane
    # vectors are pathological for the VPU.
    m_prev = m_scr[:]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - _lanes(m_new, block_k))
    corr = jnp.exp(m_prev - m_new)
    l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
    d = acc_scr.shape[-1]
    acc_scr[:] = acc_scr[:] * _lanes(corr, d) + jax.lax.dot_general(
        p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_scr[:] = m_new

    @pl.when(j == nk - 1)
    def _finish():
        l = l_scr[:]
        valid = m_scr[:] > _NEG_INF / 2  # all-masked rows → zeros
        d_out = acc_scr.shape[-1]
        o_ref[0, 0] = jnp.where(
            _lanes(valid, d_out),
            acc_scr[:] / _lanes(l, d_out),
            0.0,
        ).astype(o_ref.dtype)
        lse_ref[0, 0] = jnp.where(
            valid[:, :1], m_scr[:, :1] + jnp.log(l[:, :1]), _NEG_INF
        )


def _flash_fwd_pallas(q, k, v, kvm, *, causal, scale, block_q, block_k,
                      interpret):
    """q: [BKV, G, T, D]; k,v: [BKV, S, D]; kvm: [BKV, S, 1]
    → (out [BKV, G, T, D], lse [BKV, G, T, 1])."""
    bkv, g, t, d = q.shape
    s_len = k.shape[1]
    block_q = min(block_q, t)
    block_k = min(block_k, s_len)
    # 4D grid with affine index maps (a folded bh dim with div/mod maps
    # defeats Mosaic's block-reuse analysis — measured 34x slower).
    grid = (bkv, g, pl.cdiv(t, block_q), pl.cdiv(s_len, block_k))
    kernel = functools.partial(
        _fwd_kernel, causal=causal, scale=scale, block_q=block_q,
        block_k=block_k,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            # K/V shared across the group dim h.
            pl.BlockSpec((1, block_k, d), lambda b, h, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda b, h, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, 1), lambda b, h, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            # lse carried with a trailing singleton: TPU lowering needs the
            # last two block dims (8,128)-aligned or equal to the array dims.
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bkv, g, t, d), q.dtype),
            jax.ShapeDtypeStruct((bkv, g, t, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, NUM_LANES), jnp.float32),
            pltpu.VMEM((block_q, NUM_LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        # Only the kv dim carries state (online-softmax scratch).
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v, kvm)
    return out, lse


# ---------------------------------------------------------------------------
# Blockwise XLA path (CPU fallback + backward recomputation)
# ---------------------------------------------------------------------------


def _kv_blocks(x, nk, block_k):
    # [BKV, S, ...] -> iteration-major [nk, BKV, block_k, ...]
    bkv = x.shape[0]
    return x.reshape(bkv, nk, block_k, *x.shape[2:]).swapaxes(0, 1)


def _flash_fwd_xla(q, k, v, kvm, *, causal, scale, block_k):
    """Same online-softmax accumulation as the kernel, as a scan over kv
    blocks. q: [BKV, G, T, D]; k,v: [BKV, S, D]; kvm: [BKV, S, 1]."""
    bkv, g, t, d = q.shape
    s_len = k.shape[1]
    block_k = min(block_k, s_len)
    if s_len % block_k:
        block_k = s_len  # odd lengths: single block, still O(T·block) mem
    nk = s_len // block_k
    q32 = q.astype(jnp.float32)

    def step(carry, blk):
        m, l, acc = carry
        k_b, v_b, kvm_b, j = blk
        s = jnp.einsum("bgqd,bkd->bgqk", q32, k_b,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            mask = _causal_mask(0, j * block_k, t, block_k)
            s = jnp.where(mask[None, None], s, _NEG_INF)
        s = jnp.where(kvm_b[..., 0][:, None, None, :] > 0, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.einsum("bgqk,bkd->bgqd", p, v_b)
        return (m_new, l, acc), None

    init = (
        jnp.full((bkv, g, t, 1), _NEG_INF, jnp.float32),
        jnp.zeros((bkv, g, t, 1), jnp.float32),
        jnp.zeros((bkv, g, t, d), jnp.float32),
    )
    (m, l, acc), _ = lax.scan(
        step, init,
        (_kv_blocks(k.astype(jnp.float32), nk, block_k),
         _kv_blocks(v.astype(jnp.float32), nk, block_k),
         _kv_blocks(kvm, nk, block_k),
         jnp.arange(nk)),
    )
    # Rows with every key masked never saw a finite score (m stayed at
    # _NEG_INF, p degenerated to exp(0)=1 per key): return zeros, not mean(V).
    valid = m > _NEG_INF / 2
    out = jnp.where(valid, acc / l, 0.0).astype(q.dtype)
    lse = jnp.where(valid, m + jnp.log(l), _NEG_INF)
    return out, lse


def _flash_bwd_xla(q, k, v, kvm, out, lse, g_out, *, causal, scale, block_k):
    """Flash backward: recompute p blockwise from lse; scan over kv blocks."""
    bkv, g, t, d = q.shape
    s_len = k.shape[1]
    block_k = min(block_k, s_len)
    if s_len % block_k:
        block_k = s_len
    nk = s_len // block_k
    q32, g32 = q.astype(jnp.float32), g_out.astype(jnp.float32)
    delta = jnp.sum(g32 * out.astype(jnp.float32), axis=-1, keepdims=True)

    def step(dq, blk):
        k_b, v_b, kvm_b, j = blk
        s = jnp.einsum("bgqd,bkd->bgqk", q32, k_b,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            mask = _causal_mask(0, j * block_k, t, block_k)
            s = jnp.where(mask[None, None], s, _NEG_INF)
        s = jnp.where(kvm_b[..., 0][:, None, None, :] > 0, s, _NEG_INF)
        # All-masked rows carry lse=_NEG_INF; exp(s-lse) would degenerate to
        # 1 per key — their p (and so dk/dv/dq contributions) must be zero.
        p = jnp.where(lse > _NEG_INF / 2, jnp.exp(s - lse), 0.0)
        dp = jnp.einsum("bgqd,bkd->bgqk", g32, v_b)
        ds = p * (dp - delta) * scale
        dq = dq + jnp.einsum("bgqk,bkd->bgqd", ds, k_b)
        dk_b = jnp.einsum("bgqk,bgqd->bkd", ds, q32)
        dv_b = jnp.einsum("bgqk,bgqd->bkd", p, g32)
        return dq, (dk_b, dv_b)

    dq, (dk_blocks, dv_blocks) = lax.scan(
        step, jnp.zeros((bkv, g, t, d), jnp.float32),
        (_kv_blocks(k.astype(jnp.float32), nk, block_k),
         _kv_blocks(v.astype(jnp.float32), nk, block_k),
         _kv_blocks(kvm, nk, block_k),
         jnp.arange(nk)),
    )
    dk = dk_blocks.swapaxes(0, 1).reshape(bkv, s_len, d)
    dv = dv_blocks.swapaxes(0, 1).reshape(bkv, s_len, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# Public op with custom VJP
# ---------------------------------------------------------------------------


def _use_pallas(t: int, s: int, d: int, block_q: int, block_k: int,
                implementation: str | None) -> bool:
    if implementation == "pallas":
        return True
    # auto currently = XLA blockwise: measured on v5e (B4 T2048 H16 D128,
    # causal) it runs at 9.0ms vs 10.2ms for the hand-written reference
    # pallas kernel — XLA's fusion of the scan already saturates the MXU.
    # The in-repo pallas kernel is opt-in until it beats the XLA path.
    return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, kvm, causal, scale, block_q, block_k, impl):
    out, _ = _flash_fwd(q, k, v, kvm, causal, scale, block_q, block_k, impl)
    return out


def _flash_fwd(q, k, v, kvm, causal, scale, block_q, block_k, impl):
    t, s = q.shape[2], k.shape[1]
    if _use_pallas(t, s, q.shape[-1], min(block_q, t), min(block_k, s), impl):
        out, lse = _flash_fwd_pallas(
            q, k, v, kvm, causal=causal, scale=scale, block_q=block_q,
            block_k=block_k, interpret=jax.default_backend() != "tpu",
        )
    else:
        out, lse = _flash_fwd_xla(q, k, v, kvm, causal=causal, scale=scale,
                                  block_k=block_k)
    return out, lse


def _flash_vjp_fwd(q, k, v, kvm, causal, scale, block_q, block_k, impl):
    out, lse = _flash_fwd(q, k, v, kvm, causal, scale, block_q, block_k, impl)
    return out, (q, k, v, kvm, out, lse)


def _flash_vjp_bwd(causal, scale, block_q, block_k, impl, res, g):
    q, k, v, kvm, out, lse = res
    dq, dk, dv = _flash_bwd_xla(q, k, v, kvm, out, lse, g, causal=causal,
                                scale=scale, block_k=block_k)
    return dq, dk, dv, jnp.zeros_like(kvm)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = True,
    scale: float | None = None,
    kv_mask=None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    implementation: str | None = None,
):
    """Multi-head / grouped-query flash attention.

    q: [B, T, H_q, D]; k, v: [B, S, H_kv, D] with H_q a multiple of H_kv.
    ``kv_mask``: optional [B, S], truthy = attend (padding mask for BERT /
    batched serving). Returns [B, T, H_q, D]. ``implementation``: None
    (auto), "pallas", "xla".
    """
    b, t, hq, d = q.shape
    s_len, hkv = k.shape[1], k.shape[2]
    if hq % hkv:
        raise ValueError(f"query heads {hq} not a multiple of kv heads {hkv}")
    group = hq // hkv
    scale = (d**-0.5) if scale is None else scale

    if kv_mask is None:
        kvm = jnp.ones((b, s_len), jnp.float32)
    else:
        kvm = kv_mask.astype(jnp.float32)
    kvm = jnp.repeat(kvm[:, None], hkv, axis=1).reshape(b * hkv, s_len, 1)

    # [B, T, Hq, D] -> [B*Hkv, G, T, D]; K/V -> [B*Hkv, S, D].
    qf = (
        q.transpose(0, 2, 1, 3)
        .reshape(b, hkv, group, t, d)
        .reshape(b * hkv, group, t, d)
    )
    kf = k.transpose(0, 2, 1, 3).reshape(b * hkv, s_len, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * hkv, s_len, d)

    out = _flash(qf, kf, vf, kvm, causal, scale, block_q, block_k,
                 implementation)
    return (
        out.reshape(b, hkv, group, t, d)
        .reshape(b, hq, t, d)
        .transpose(0, 2, 1, 3)
    )
