"""TPU compute kernels.

The reference platform ships no kernels at all — its compute lives inside
imported container images (tf_cnn_benchmarks, TF ModelServer; SURVEY.md §2.2).
This package is the compute path those images provided, built TPU-first:
pallas kernels for the ops XLA won't fuse optimally on its own, pure-jax
fallbacks everywhere so the same model code runs on the CPU fake slice.

- :mod:`~kubeflow_tpu.ops.attention` — flash attention (pallas MXU kernel,
  online softmax, causal/GQA), blockwise custom-VJP backward.
- :mod:`~kubeflow_tpu.ops.norms` — RMSNorm / LayerNorm (fused pallas RMSNorm).
- :mod:`~kubeflow_tpu.ops.rotary` — rotary position embeddings.
- :mod:`~kubeflow_tpu.ops.losses` — stable cross entropy with z-loss.
"""

from kubeflow_tpu.ops.attention import flash_attention
from kubeflow_tpu.ops.losses import softmax_cross_entropy
from kubeflow_tpu.ops.norms import layer_norm, rms_norm
from kubeflow_tpu.ops.rotary import apply_rotary, rotary_frequencies

__all__ = [
    "flash_attention",
    "softmax_cross_entropy",
    "layer_norm",
    "rms_norm",
    "apply_rotary",
    "rotary_frequencies",
]
