"""Normalization ops.

RMSNorm ships both as a fused pallas kernel (one HBM round-trip: read x,
write y — mean-of-squares, rsqrt, and the weight multiply all happen in VMEM)
and as pure jax. LayerNorm is pure jax; XLA's fusion handles it well and it
only appears in the BERT family.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def rms_norm(x, weight, *, eps: float = 1e-6, implementation: str | None = None):
    """y = x / rms(x) * weight over the last dim. x: [..., D], weight: [D].

    Auto is the pure-XLA path: measured inside the full flagship train step
    on v5e, XLA's fused norm edges out the pallas kernel (27.5k vs 27.0k
    tok/s end-to-end) — XLA already fuses the norm into its neighbors, and
    the kernel boundary blocks that. The kernel stays opt-in
    (``implementation="pallas"``) for standalone-norm workloads."""
    if implementation == "pallas":
        return _rms_norm_fused(x, weight, eps)
    return _rms_norm_xla(x, weight, eps)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _rms_norm_fused(x, weight, eps):
    # Autodiff must not see the pallas_call (no reverse-mode rule); the
    # backward is the closed-form VJP below.
    return _rms_norm_pallas(x, weight, eps=eps,
                            interpret=jax.default_backend() != "tpu")


def _rms_norm_fused_fwd(x, weight, eps):
    return _rms_norm_fused(x, weight, eps), (x, weight)


def _rms_norm_fused_bwd(eps, res, g):
    x, weight = res
    x32 = x.astype(jnp.float32)
    g32 = g.astype(jnp.float32)
    w32 = weight.astype(jnp.float32)
    r = jax.lax.rsqrt(jnp.mean(jnp.square(x32), axis=-1, keepdims=True) + eps)
    gw = g32 * w32
    # d/dx [x·r(x)·w]: r·gw − r³·x·mean(gw·x)
    dx = r * gw - (r**3) * x32 * jnp.mean(gw * x32, axis=-1, keepdims=True)
    dw = jnp.sum(g32 * x32 * r, axis=tuple(range(x32.ndim - 1)))
    return dx.astype(x.dtype), dw.astype(weight.dtype)


_rms_norm_fused.defvjp(_rms_norm_fused_fwd, _rms_norm_fused_bwd)


def _rms_norm_xla(x, weight, eps):
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    y = x32 * jax.lax.rsqrt(var + eps)
    return (y * weight.astype(jnp.float32)).astype(x.dtype)


def _rms_kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    y = x * jax.lax.rsqrt(var + eps)
    o_ref[:] = (y * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("eps", "interpret"))
def _rms_norm_pallas(x, weight, *, eps, interpret):
    orig_shape = x.shape
    d = orig_shape[-1]
    rows = x.size // d
    x2 = x.reshape(rows, d)
    # Keep the f32 working set well under the 16M scoped-vmem limit: in/out
    # blocks + float32 intermediates ≈ 12·rows·d bytes.
    block_rows = max(8, min(rows, 524_288 // d))
    if rows % block_rows:
        block_rows = rows
    out = pl.pallas_call(
        functools.partial(_rms_kernel, eps=eps),
        grid=(pl.cdiv(rows, block_rows),),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((d,), lambda i: (0,), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_rows, d), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, d), x.dtype),
        interpret=interpret,
    )(x2, weight)
    return out.reshape(orig_shape)


def layer_norm(x, weight, bias, *, eps: float = 1e-6):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (y * weight.astype(jnp.float32) + bias.astype(jnp.float32)).astype(
        x.dtype
    )
