"""Rotary position embeddings (RoPE).

Pure jax: two multiplies and an add per element — XLA fuses this into the
surrounding projection matmuls, so a pallas kernel would buy nothing here.
Frequencies are precomputed once per model and closed over by the jitted step.
"""

from __future__ import annotations

import jax.numpy as jnp


def rotary_frequencies(head_dim: int, max_len: int, *, theta: float = 10000.0):
    """cos/sin tables [max_len, head_dim//2], float32."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    angles = jnp.outer(jnp.arange(max_len, dtype=jnp.float32), inv_freq)
    return jnp.cos(angles), jnp.sin(angles)


def apply_rotary(x, cos, sin, *, positions=None):
    """Rotate pairs (x[..., :D/2], x[..., D/2:]). x: [B, T, H, D].

    ``positions`` ([B, T] int) selects rows of the tables; defaults to
    0..T-1 (training); decoding passes the absolute positions.
    """
    t = x.shape[1]
    if positions is None:
        c = cos[:t][None, :, None, :]
        s = sin[:t][None, :, None, :]
    else:
        c = cos[positions][:, :, None, :]
        s = sin[positions][:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    rotated = jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)
    return rotated.astype(x.dtype)
