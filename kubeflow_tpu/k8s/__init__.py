"""Kubernetes object model, API clients, and the in-process fake apiserver."""
from kubeflow_tpu.k8s import objects
from kubeflow_tpu.k8s.client import ApiError, K8sClient
from kubeflow_tpu.k8s.fake import FakeApiServer

__all__ = ["objects", "K8sClient", "ApiError", "FakeApiServer"]
