"""Typed builders for Kubernetes objects.

This is the foundation of the manifest layer: where the reference composes raw
JSON through jsonnet functions (e.g. kubeflow/common/ambassador.libsonnet,
kubeflow/tf-training/tf-job-operator.libsonnet), we compose plain Python dicts
through small, explicit builder functions. Manifests stay inspectable (dicts in,
dicts out), diffable, and trivially golden-testable.

Only fields the platform actually uses are modeled; everything is a vanilla
dict so callers can always reach in and set exotic fields directly.
"""

from __future__ import annotations

import base64
from typing import Any, Mapping, Sequence

# ---------------------------------------------------------------------------
# Small helpers
# ---------------------------------------------------------------------------


def _clean(d: dict) -> dict:
    """Drop None-valued keys so optional arguments vanish from output."""
    return {k: v for k, v in d.items() if v is not None}


def metadata(
    name: str,
    namespace: str | None = None,
    labels: Mapping[str, str] | None = None,
    annotations: Mapping[str, str] | None = None,
) -> dict:
    return _clean(
        {
            "name": name,
            "namespace": namespace,
            "labels": dict(labels) if labels else None,
            "annotations": dict(annotations) if annotations else None,
        }
    )


def object_ref(obj: Mapping[str, Any]) -> dict:
    """An ownerReference to `obj` (controller=true, like controller-runtime)."""
    return {
        "apiVersion": obj["apiVersion"],
        "kind": obj["kind"],
        "name": obj["metadata"]["name"],
        "uid": obj["metadata"].get("uid", ""),
        "controller": True,
        "blockOwnerDeletion": True,
    }


# ---------------------------------------------------------------------------
# Core workload objects
# ---------------------------------------------------------------------------


def container(
    name: str,
    image: str,
    command: Sequence[str] | None = None,
    args: Sequence[str] | None = None,
    env: Mapping[str, str] | None = None,
    env_from_field: Mapping[str, str] | None = None,
    ports: Mapping[str, int] | None = None,
    resources: Mapping[str, Any] | None = None,
    volume_mounts: Sequence[Mapping[str, str]] | None = None,
    working_dir: str | None = None,
    liveness_probe: dict | None = None,
    readiness_probe: dict | None = None,
    image_pull_policy: str | None = None,
) -> dict:
    """A container spec.

    ``env`` maps name->literal value; ``env_from_field`` maps name->fieldPath
    (downward API), used e.g. to give each TPU worker its own pod IP/name.
    ``ports`` maps port-name -> containerPort.
    """
    env_list: list[dict] = []
    for k, v in (env or {}).items():
        env_list.append({"name": k, "value": str(v)})
    for k, path in (env_from_field or {}).items():
        env_list.append({"name": k, "valueFrom": {"fieldRef": {"fieldPath": path}}})
    return _clean(
        {
            "name": name,
            "image": image,
            "command": list(command) if command else None,
            "args": list(args) if args else None,
            "workingDir": working_dir,
            "env": env_list or None,
            "ports": [
                {"name": n, "containerPort": p} for n, p in (ports or {}).items()
            ]
            or None,
            "resources": dict(resources) if resources else None,
            "volumeMounts": [dict(v) for v in volume_mounts] if volume_mounts else None,
            "livenessProbe": liveness_probe,
            "readinessProbe": readiness_probe,
            "imagePullPolicy": image_pull_policy,
        }
    )


def tcp_probe(port: int, initial_delay: int = 15, period: int = 10) -> dict:
    """TCP liveness probe, mirroring the serving probe at
    kubeflow/tf-serving/tf-serving-template.libsonnet:70-75."""
    return {
        "tcpSocket": {"port": port},
        "initialDelaySeconds": initial_delay,
        "periodSeconds": period,
    }


def http_probe(path: str, port: int, initial_delay: int = 10, period: int = 10) -> dict:
    return {
        "httpGet": {"path": path, "port": port},
        "initialDelaySeconds": initial_delay,
        "periodSeconds": period,
    }


def pod_spec(
    containers: Sequence[dict],
    service_account: str | None = None,
    volumes: Sequence[dict] | None = None,
    node_selector: Mapping[str, str] | None = None,
    restart_policy: str | None = None,
    scheduler_name: str | None = None,
    host_network: bool | None = None,
    subdomain: str | None = None,
    hostname: str | None = None,
    tolerations: Sequence[dict] | None = None,
    init_containers: Sequence[dict] | None = None,
) -> dict:
    return _clean(
        {
            "containers": list(containers),
            "initContainers": list(init_containers) if init_containers else None,
            "serviceAccountName": service_account,
            "volumes": list(volumes) if volumes else None,
            "nodeSelector": dict(node_selector) if node_selector else None,
            "restartPolicy": restart_policy,
            "schedulerName": scheduler_name,
            "hostNetwork": host_network,
            "subdomain": subdomain,
            "hostname": hostname,
            "tolerations": list(tolerations) if tolerations else None,
        }
    )


def pod(
    name: str,
    namespace: str,
    spec: dict,
    labels: Mapping[str, str] | None = None,
    annotations: Mapping[str, str] | None = None,
    owner: Mapping[str, Any] | None = None,
) -> dict:
    meta = metadata(name, namespace, labels, annotations)
    if owner is not None:
        meta["ownerReferences"] = [object_ref(owner)]
    return {"apiVersion": "v1", "kind": "Pod", "metadata": meta, "spec": spec}


def deployment(
    name: str,
    namespace: str,
    containers: Sequence[dict],
    replicas: int = 1,
    labels: Mapping[str, str] | None = None,
    pod_labels: Mapping[str, str] | None = None,
    pod_annotations: Mapping[str, str] | None = None,
    service_account: str | None = None,
    volumes: Sequence[dict] | None = None,
    node_selector: Mapping[str, str] | None = None,
) -> dict:
    pod_labels = dict(pod_labels or labels or {"app": name})
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": metadata(name, namespace, labels),
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": pod_labels},
            "template": {
                "metadata": _clean(
                    {
                        "labels": pod_labels,
                        "annotations": dict(pod_annotations)
                        if pod_annotations
                        else None,
                    }
                ),
                "spec": pod_spec(
                    containers,
                    service_account=service_account,
                    volumes=volumes,
                    node_selector=node_selector,
                ),
            },
        },
    }


def stateful_set(
    name: str,
    namespace: str,
    containers: Sequence[dict],
    service_name: str,
    replicas: int = 1,
    labels: Mapping[str, str] | None = None,
    service_account: str | None = None,
    volumes: Sequence[dict] | None = None,
    volume_claim_templates: Sequence[dict] | None = None,
) -> dict:
    sel = dict(labels or {"app": name})
    spec: dict = {
        "serviceName": service_name,
        "replicas": replicas,
        "selector": {"matchLabels": sel},
        "template": {
            "metadata": {"labels": sel},
            "spec": pod_spec(containers, service_account=service_account, volumes=volumes),
        },
    }
    if volume_claim_templates:
        spec["volumeClaimTemplates"] = list(volume_claim_templates)
    return {
        "apiVersion": "apps/v1",
        "kind": "StatefulSet",
        "metadata": metadata(name, namespace, labels),
        "spec": spec,
    }


def service(
    name: str,
    namespace: str,
    selector: Mapping[str, str],
    ports: Sequence[Mapping[str, Any]],
    labels: Mapping[str, str] | None = None,
    annotations: Mapping[str, str] | None = None,
    cluster_ip: str | None = None,
    service_type: str | None = None,
) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": metadata(name, namespace, labels, annotations),
        "spec": _clean(
            {
                "selector": dict(selector),
                "ports": [dict(p) for p in ports],
                "clusterIP": cluster_ip,
                "type": service_type,
            }
        ),
    }


def headless_service(
    name: str,
    namespace: str,
    selector: Mapping[str, str],
    ports: Sequence[Mapping[str, Any]],
    labels: Mapping[str, str] | None = None,
) -> dict:
    """Headless service for stable per-pod DNS — the rendezvous substrate for
    TPU workers (the analogue of the per-replica services tf-operator creates)."""
    return service(
        name, namespace, selector, ports, labels=labels, cluster_ip="None"
    )


def config_map(
    name: str, namespace: str, data: Mapping[str, str], labels: Mapping[str, str] | None = None
) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": metadata(name, namespace, labels),
        "data": {k: str(v) for k, v in data.items()},
    }


def secret(
    name: str,
    namespace: str,
    string_data: Mapping[str, str],
    secret_type: str = "Opaque",
) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Secret",
        "metadata": metadata(name, namespace),
        "type": secret_type,
        "stringData": {k: str(v) for k, v in string_data.items()},
    }


def secret_data(sec: Mapping) -> dict[str, str]:
    """Decode a Secret's payload to plain strings.

    A real apiserver never returns ``stringData`` (it is write-only) and
    base64-encodes ``data``; the in-process fake stores ``stringData``
    verbatim. Controllers must read through this helper so they behave
    identically against both.
    """
    out: dict[str, str] = dict(sec.get("stringData") or {})
    for k, v in (sec.get("data") or {}).items():
        if k in out:
            continue
        try:
            out[k] = base64.b64decode(v, validate=True).decode("utf-8")
        except (ValueError, TypeError, UnicodeDecodeError):
            # ``data`` is strictly base64-of-UTF-8 here (real apiserver
            # semantics; fakes/tests write ``stringData``). Anything else
            # — binary payloads like a .p12 keystore, corrupt values — is
            # omitted rather than handed to a distant parser as garbage
            # text; a caller that needs the key gets a clear KeyError.
            continue
    return out


def namespace_obj(name: str, labels: Mapping[str, str] | None = None) -> dict:
    return {"apiVersion": "v1", "kind": "Namespace", "metadata": metadata(name, labels=labels)}


def node(name: str, labels: Mapping[str, str] | None = None, *,
         tpu_chips: int = 0, unschedulable: bool = False,
         ready: bool = True) -> dict:
    """A Node object the scheduler's capacity model reads: TPU hosts carry
    the GKE accelerator/topology labels plus a slice label grouping hosts
    into one contiguous slice, and advertise their chips in
    status.capacity (tests and the fake cluster mint these)."""
    obj: dict = {
        "apiVersion": "v1", "kind": "Node",
        "metadata": metadata(name, labels=labels),
        "status": {
            "conditions": [{"type": "Ready",
                            "status": "True" if ready else "False"}],
        },
    }
    if tpu_chips:
        obj["status"]["capacity"] = {"google.com/tpu": tpu_chips}
    if unschedulable:
        obj["spec"] = {"unschedulable": True}
    return obj


def pvc(name: str, namespace: str, storage: str,
        access_modes: Sequence[str] = ("ReadWriteOnce",),
        storage_class: str | None = None) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "PersistentVolumeClaim",
        "metadata": metadata(name, namespace),
        "spec": _clean(
            {
                "accessModes": list(access_modes),
                "resources": {"requests": {"storage": storage}},
                "storageClassName": storage_class,
            }
        ),
    }


# ---------------------------------------------------------------------------
# RBAC
# ---------------------------------------------------------------------------


def service_account(name: str, namespace: str, labels: Mapping[str, str] | None = None) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "ServiceAccount",
        "metadata": metadata(name, namespace, labels),
    }


def policy_rule(api_groups: Sequence[str], resources: Sequence[str], verbs: Sequence[str],
                resource_names: Sequence[str] | None = None) -> dict:
    rule = {
        "apiGroups": list(api_groups),
        "resources": list(resources),
        "verbs": list(verbs),
    }
    if resource_names:
        # Pin get/update grants to named objects — RBAC least privilege
        # for controllers that only ever touch their own config objects.
        rule["resourceNames"] = list(resource_names)
    return rule


def cluster_role(name: str, rules: Sequence[dict], labels: Mapping[str, str] | None = None) -> dict:
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "ClusterRole",
        "metadata": metadata(name, labels=labels),
        "rules": list(rules),
    }


def role(name: str, namespace: str, rules: Sequence[dict]) -> dict:
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "Role",
        "metadata": metadata(name, namespace),
        "rules": list(rules),
    }


def cluster_role_binding(name: str, role_name: str, sa_name: str, sa_namespace: str) -> dict:
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "ClusterRoleBinding",
        "metadata": metadata(name),
        "roleRef": {
            "apiGroup": "rbac.authorization.k8s.io",
            "kind": "ClusterRole",
            "name": role_name,
        },
        "subjects": [
            {"kind": "ServiceAccount", "name": sa_name, "namespace": sa_namespace}
        ],
    }


def role_binding(name: str, namespace: str, role_name: str, subjects: Sequence[dict]) -> dict:
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "RoleBinding",
        "metadata": metadata(name, namespace),
        "roleRef": {
            "apiGroup": "rbac.authorization.k8s.io",
            "kind": "Role",
            "name": role_name,
        },
        "subjects": list(subjects),
    }


# ---------------------------------------------------------------------------
# CRDs
# ---------------------------------------------------------------------------


def crd(
    group: str,
    kind: str,
    plural: str,
    versions: Sequence[dict],
    scope: str = "Namespaced",
    short_names: Sequence[str] | None = None,
    categories: Sequence[str] | None = None,
    conversion: dict | None = None,
) -> dict:
    """A CustomResourceDefinition (apiextensions v1).

    The reference defines its CRDs in v1beta1 with a stored + served version
    pair and printer columns (kubeflow/tf-training/tf-job-operator.libsonnet:52-97);
    we model the same surface in the v1 schema.
    """
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": metadata(f"{plural}.{group}"),
        "spec": _clean(
            {
                "group": group,
                "scope": scope,
                "names": _clean(
                    {
                        "kind": kind,
                        "plural": plural,
                        "singular": kind.lower(),
                        "shortNames": list(short_names) if short_names else None,
                        "categories": list(categories) if categories else None,
                    }
                ),
                "versions": list(versions),
                "conversion": conversion,
            }
        ),
    }


def crd_conversion_webhook(service_name: str, namespace: str,
                           path: str = "/convert",
                           ca_bundle: str = "") -> dict:
    """spec.conversion stanza calling a conversion webhook — what a REAL
    apiserver needs to convert between served versions with different
    schemas (strategy None only rewrites apiVersion)."""
    client_config: dict = {"service": {"name": service_name,
                                       "namespace": namespace,
                                       "path": path}}
    if ca_bundle:
        client_config["caBundle"] = ca_bundle
    return {
        "strategy": "Webhook",
        "webhook": {
            "clientConfig": client_config,
            "conversionReviewVersions": ["v1"],
        },
    }


def crd_version(
    name: str,
    schema: dict | None = None,
    served: bool = True,
    storage: bool = False,
    printer_columns: Sequence[dict] | None = None,
    status_subresource: bool = True,
) -> dict:
    v: dict = {"name": name, "served": served, "storage": storage}
    if status_subresource:
        v["subresources"] = {"status": {}}
    if schema is not None:
        v["schema"] = {"openAPIV3Schema": schema}
    if printer_columns:
        v["additionalPrinterColumns"] = list(printer_columns)
    return v


def printer_column(name: str, json_path: str, col_type: str = "string") -> dict:
    return {"name": name, "type": col_type, "jsonPath": json_path}


# ---------------------------------------------------------------------------
# Volumes
# ---------------------------------------------------------------------------


def config_map_volume(name: str, config_map_name: str) -> dict:
    return {"name": name, "configMap": {"name": config_map_name}}


def secret_volume(name: str, secret_name: str) -> dict:
    return {"name": name, "secret": {"secretName": secret_name}}


def empty_dir_volume(name: str, medium: str | None = None) -> dict:
    return {"name": name, "emptyDir": _clean({"medium": medium})}


def pvc_volume(name: str, claim: str) -> dict:
    return {"name": name, "persistentVolumeClaim": {"claimName": claim}}


def host_path_volume(name: str, path: str,
                     path_type: str = "DirectoryOrCreate") -> dict:
    return {"name": name, "hostPath": {"path": path, "type": path_type}}


def volume_mount(name: str, mount_path: str, read_only: bool | None = None,
                 sub_path: str | None = None) -> dict:
    return _clean(
        {"name": name, "mountPath": mount_path, "readOnly": read_only, "subPath": sub_path}
    )


# ---------------------------------------------------------------------------
# Keys / identity helpers used across client, fake server, and controllers
# ---------------------------------------------------------------------------


def gvk(obj: Mapping[str, Any]) -> tuple[str, str]:
    """(apiVersion, kind)."""
    return obj["apiVersion"], obj["kind"]


def obj_key(obj: Mapping[str, Any]) -> str:
    """Stable identity string: apiVersion/kind/namespace/name."""
    m = obj.get("metadata", {})
    return "/".join(
        [obj["apiVersion"], obj["kind"], m.get("namespace", ""), m["name"]]
    )
