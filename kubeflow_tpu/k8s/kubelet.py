"""Local pod executor for the fake cluster — the E2E "fake kubelet".

The reference's CI gets real-workload coverage by provisioning actual
clusters per run (testing/install_minikube.sh, testing/deploy_kubeflow.py:49
on a GCE VM); nothing in its tree can run a workload without one. This module
closes that gap for the fake apiserver: it schedules Pending pods by
launching their container command as a local subprocess — with the
operator-injected rendezvous env rewritten to loopback — and mirrors the
process result into pod status, so controller E2E tests (JaxJob gang →
`jax.distributed.initialize` → psum → Succeeded) run multi-process on one
machine with no cluster and no TPUs (SURVEY.md §4: the multi-node-without-
hardware capability the reference lacks).

Scope: one container per pod, command+args+env only (no volumes, probes, or
images — the command runs against the repo's own interpreter). That is
exactly the surface the training operators exercise.
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import threading
import time
from dataclasses import dataclass, field

from kubeflow_tpu.k8s.client import ApiError, K8sClient

POD_API = "v1"

# Env vars whose values embed pod DNS hostnames (``pod.job.ns[:port]``) that
# only resolve inside a cluster; the kubelet rewrites the host part to
# loopback so every process rendezvouses on the local machine.
_ADDRESS_ENV = (
    "JAX_COORDINATOR_ADDRESS",
    "MEGASCALE_COORDINATOR_ADDRESS",
    "MASTER_ADDR",
    "DMLC_PS_ROOT_URI",
    "CHAINERMN_MASTER_ADDR",
)

# Env vars holding a bare rendezvous port. On real pod IPs every gang can
# bind the same well-known port; mapped onto ONE loopback host they
# collide across concurrently-running (or TIME_WAIT-lingering) gangs, so
# the kubelet remaps each gang's ports to free ones — consistently for
# every pod of the gang, and consistently with the ports embedded in the
# _ADDRESS_ENV values.
_PORT_ENV = (
    "JAX_COORDINATOR_PORT",
    "MASTER_PORT",
    "DMLC_PS_ROOT_PORT",
    "CHAINERMN_MASTER_PORT",
)


def _loopback(value: str) -> str:
    """``host[:port]`` → ``127.0.0.1[:port]`` (host part dropped)."""
    host, sep, port = value.partition(":")
    return f"127.0.0.1{sep}{port}" if sep else "127.0.0.1"


# Tail of a pod's output kept in status.log (the kubectl-logs analogue).
# Matches the 64KB spool window so a few-hundred-step per-step training
# log survives whole — the preemption-resume AND elastic-shrink E2Es
# read every per-step loss (and the reshard event line) out of it.
_LOG_TAIL = 65536


@dataclass
class _Running:
    proc: subprocess.Popen
    pod_name: str
    namespace: str
    # stdout spools to an unlinked temp file, not a PIPE: a pod writing more
    # than the ~64KB pipe buffer would otherwise block on write until the
    # kubelet timeout kills it (verbose-but-healthy workloads would fail).
    out_file: object = None
    # (namespace, owning job) — keys the gang's remapped rendezvous ports.
    gang: tuple | None = None
    started: float = field(default_factory=time.monotonic)


class FakeKubelet:
    """Runs Pending pods from a :class:`FakeApiServer` as local subprocesses.

    ``extra_env`` is overlaid on every container (tests use it to force the
    virtual CPU platform); ``cpu_devices_per_pod`` provisions that many JAX
    CPU devices per process so an N-pod gang forms an N×M-device slice.
    """

    def __init__(
        self,
        client: K8sClient,
        *,
        extra_env: dict[str, str] | None = None,
        cpu_devices_per_pod: int | None = None,
        timeout: float = 120.0,
    ) -> None:
        self.client = client
        self.extra_env = dict(extra_env or {})
        self.cpu_devices_per_pod = cpu_devices_per_pod
        self.timeout = timeout
        self._running: dict[tuple[str, str], _Running] = {}
        # (namespace, owning-job, original-port) -> remapped free port.
        self._gang_ports: dict[tuple[str, str, str], int] = {}
        self._stop = threading.Event()

    @staticmethod
    def _gang_key(pod: dict) -> tuple[str, str]:
        refs = pod["metadata"].get("ownerReferences") or []
        owner = refs[0]["name"] if refs else pod["metadata"]["name"]
        return (pod["metadata"].get("namespace", ""), owner)

    def _gang_port(self, pod: dict, orig: str) -> int:
        """A free local port for this gang's ``orig`` rendezvous port,
        stable across every pod sharing the owning job (one generation;
        entries are pruned when the gang's last pod is reaped, so a
        restarted gang gets fresh ports instead of inheriting a slot
        something else may hold by now)."""
        key = (*self._gang_key(pod), orig)
        port = self._gang_ports.get(key)
        if port is None:
            import socket

            issued = set(self._gang_ports.values())
            while True:
                with socket.socket() as s:
                    s.bind(("127.0.0.1", 0))
                    port = s.getsockname()[1]
                if port not in issued:
                    break  # never hand two gangs the same port
            self._gang_ports[key] = port
        return port

    def _prune_gang_ports(self, gang: tuple[str, str] | None) -> None:
        """Drop a gang's port mappings once none of its pods run."""
        if gang is None:
            return
        if any(r.gang == gang for r in self._running.values()):
            return
        self._gang_ports = {k: v for k, v in self._gang_ports.items()
                            if k[:2] != gang}

    # ------------------------------------------------------------------
    # scheduling
    # ------------------------------------------------------------------

    def _child_env(self, pod: dict) -> dict[str, str]:
        env = dict(os.environ)
        # Never let the session's real-TPU plumbing leak into workers.
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        if self.cpu_devices_per_pod:
            env["XLA_FLAGS"] = (
                env.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count="
                f"{self.cpu_devices_per_pod}"
            ).strip()
        container = pod["spec"]["containers"][0]
        for item in container.get("env", []):
            name, value = item["name"], str(item.get("value", ""))
            if name in _ADDRESS_ENV:
                value = _loopback(value)
                host, sep, port = value.partition(":")
                if sep and port.isdigit():
                    value = f"{host}:{self._gang_port(pod, port)}"
            elif name in _PORT_ENV and value.isdigit():
                value = str(self._gang_port(pod, value))
            env[name] = value
        env.update(self.extra_env)
        return env

    def _spawn(self, pod: dict) -> None:
        container = pod["spec"]["containers"][0]
        argv = list(container.get("command", []))
        argv += [str(a) for a in container.get("args", [])]
        if argv and argv[0] in ("python", "python3"):
            argv[0] = sys.executable
        if not argv:
            self._set_phase(pod, "Failed", exit_code=127,
                            log="container has no command or args")
            return
        out_file = tempfile.TemporaryFile()  # binary: tail-seek is exact
        try:
            proc = subprocess.Popen(
                argv,
                env=self._child_env(pod),
                stdout=out_file,
                stderr=subprocess.STDOUT,
            )
        except (OSError, ValueError) as e:  # nonexistent binary, bad argv …
            out_file.close()
            self._set_phase(pod, "Failed", exit_code=127, log=str(e))
            return
        key = (pod["metadata"]["namespace"], pod["metadata"]["name"])
        self._running[key] = _Running(proc, key[1], key[0],
                                      out_file=out_file,
                                      gang=self._gang_key(pod))
        self._set_phase(pod, "Running")

    def _set_phase(self, pod: dict, phase: str,
                   exit_code: int | None = None, log: str = "",
                   reason: str | None = None,
                   disruption_target: bool = False) -> None:
        name = pod["metadata"]["name"]
        ns = pod["metadata"]["namespace"]
        try:
            current = self.client.get(POD_API, "Pod", name, ns)
        except ApiError:
            return  # pod deleted under us (gang restart / job teardown)
        status = current.setdefault("status", {})
        status["phase"] = phase
        if reason is not None:
            status["reason"] = reason
        if disruption_target:
            # The condition the eviction API sets on a real cluster —
            # one of the signals JobController._is_preempted keys on.
            conds = [c for c in status.get("conditions", [])
                     if c.get("type") != "DisruptionTarget"]
            conds.append({"type": "DisruptionTarget", "status": "True",
                          "reason": reason or "EvictionByEvictionAPI"})
            status["conditions"] = conds
        if exit_code is not None:
            container = current["spec"]["containers"][0]
            status["containerStatuses"] = [{
                "name": container.get("name", "main"),
                "state": {"terminated": {"exitCode": exit_code}},
            }]
        if log:
            status["log"] = log[-_LOG_TAIL:]
        self.client.update_status(current)

    @staticmethod
    def _read_tail(run: "_Running") -> str:
        """Drain the pod's spooled output (last 64KB) and close the file."""
        if run.out_file is None:
            return ""
        out = FakeKubelet._peek_tail(run)
        run.out_file.close()
        return out

    @staticmethod
    def _peek_tail(run: "_Running") -> str:
        """The pod's spooled output so far (last 64KB) WITHOUT closing —
        live-log streaming for still-running pods (the `kubectl logs`
        view tests use to observe a training loop mid-run)."""
        size = run.out_file.seek(0, 2)
        run.out_file.seek(max(0, size - 65536))
        return run.out_file.read().decode("utf-8", "replace")

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def step(self) -> int:
        """One scheduling pass: start Pending pods, reap finished ones.
        Returns the number of still-running pods."""
        for pod in self.client.list(POD_API, "Pod"):
            key = (pod["metadata"]["namespace"], pod["metadata"]["name"])
            phase = pod.get("status", {}).get("phase", "Pending")
            if phase == "Pending" and key not in self._running:
                self._spawn(pod)
        for key, run in list(self._running.items()):
            rc = run.proc.poll()
            if rc is None:
                if time.monotonic() - run.started > self.timeout:
                    run.proc.kill()
                    run.proc.wait()  # reap; also flushes remaining output
                    rc = -9
                else:
                    # Live log streaming: publish the output tail while
                    # the pod runs, so observers (tests, the dashboard)
                    # can follow a long-running workload without waiting
                    # for exit.
                    out = self._peek_tail(run)
                    if out:
                        pod = self.client.get_or_none(
                            POD_API, "Pod", key[1], key[0])
                        if (pod is not None
                                and (pod.get("status", {}).get("log")
                                     or "") != out[-_LOG_TAIL:]):
                            self._set_phase(pod, "Running", log=out)
                    continue
            # Only the tail survives into status.log — don't materialize
            # a long-running pod's full output.
            out = self._read_tail(run)
            pod = {"metadata": {"namespace": key[0], "name": key[1]}}
            try:
                pod = self.client.get(POD_API, "Pod", key[1], key[0])
            except ApiError:
                pod = None
            if pod is not None:
                self._set_phase(
                    pod, "Succeeded" if rc == 0 else "Failed",
                    exit_code=rc, log=out,
                )
            gang = run.gang
            del self._running[key]
            self._prune_gang_ports(gang)
        return len(self._running)

    # A real kubelet's default grace when neither the eviction request nor
    # the pod spec names one.
    DEFAULT_GRACE_SECONDS = 30.0

    def evict(self, name: str, namespace: str = "kubeflow",
              reason: str = "Preempted",
              grace_seconds: float | None = None) -> bool:
        """Eviction delivered the way a real kubelet does: SIGTERM first,
        then a grace window for the workload to finish its in-flight step
        and save (the train loop's graceful-shutdown path), then SIGKILL.
        ``grace_seconds=None`` honors the pod's own
        ``spec.terminationGracePeriodSeconds`` (default 30) — so the
        gang-coordinated checkpoint path is exercised by eviction exactly
        as the pod requested it, not by a hand-picked test constant.

        The pod is marked Failed with ``reason`` plus a DisruptionTarget
        condition — the signals the JobController's gang logic keys
        preemption handling on (restart without burning backoffLimit) —
        regardless of how the process exited, matching what a reclaimed
        node reports.

        Returns False without killing anything if the pod is not actively
        running (already finished or never started): fabricating a
        preemption on a completed pod would make the controller restart a
        job that succeeded. A finished-but-unreaped process is left for
        ``step()`` to reap with its real exit status."""
        import subprocess

        key = (namespace, name)
        run = self._running.get(key)
        if run is None or run.proc.poll() is not None:
            return False
        if grace_seconds is None:
            try:
                pod_spec = self.client.get(POD_API, "Pod", name,
                                           namespace).get("spec", {})
            except ApiError:
                pod_spec = {}
            grace_seconds = float(pod_spec.get(
                "terminationGracePeriodSeconds",
                self.DEFAULT_GRACE_SECONDS))
        del self._running[key]
        self._prune_gang_ports(run.gang)
        run.proc.terminate()  # SIGTERM: the grace window starts
        try:
            rc = run.proc.wait(timeout=max(0.0, grace_seconds))
        except subprocess.TimeoutExpired:
            run.proc.kill()
            run.proc.wait()
            rc = 137
        log = self._read_tail(run)  # always drain+close the spool
        try:
            pod = self.client.get(POD_API, "Pod", name, namespace)
        except ApiError:
            return True  # evicted; pod object deleted concurrently
        self._set_phase(pod, "Failed", exit_code=rc, log=log,
                        reason=reason, disruption_target=True)
        return True

    def evict_node(self, node_name: str, *,
                   grace_seconds: float | None = None,
                   reason: str = "NodeShutdown") -> list[str]:
        """Node-kill churn helper: evict every running pod bound to
        ``node_name`` (spec.nodeName), the way a reclaimed host takes its
        whole gang share down at once. Returns the evicted pod names."""
        evicted = []
        for key, run in list(self._running.items()):
            try:
                pod = self.client.get(POD_API, "Pod", run.pod_name,
                                      run.namespace)
            except ApiError:
                continue
            if pod.get("spec", {}).get("nodeName") != node_name:
                continue
            if self.evict(run.pod_name, run.namespace, reason=reason,
                          grace_seconds=grace_seconds):
                evicted.append(run.pod_name)
        return evicted

    def run_until_idle(self, *, reconcile=None, deadline: float = 180.0,
                       poll: float = 0.2) -> None:
        """Drive scheduling (and an optional controller ``reconcile_all``
        callback) until no pod is Pending or Running, or the deadline hits."""
        end = time.monotonic() + deadline
        while time.monotonic() < end:
            running = self.step()
            if reconcile is not None:
                reconcile()
            pending = [
                p for p in self.client.list(POD_API, "Pod")
                if p.get("status", {}).get("phase", "Pending")
                in ("Pending", "Running")
            ]
            if not pending and not running:
                return
            time.sleep(poll)
        raise TimeoutError(
            f"pods still active after {deadline}s: "
            f"{[(r.namespace, r.pod_name) for r in self._running.values()]}"
        )

    def shutdown(self) -> None:
        for run in self._running.values():
            if run.proc.poll() is None:
                run.proc.kill()
                run.proc.wait()  # reap — no zombies across a test session
            if run.out_file is not None:
                run.out_file.close()
        self._running.clear()
