"""In-process fake Kubernetes apiserver.

The envtest analogue (SURVEY.md §4: the reference tests controllers against a
kubebuilder envtest apiserver, components/profile-controller/
profile_controller_suite_test.go). This fake implements the same
:class:`~kubeflow_tpu.k8s.client.K8sClient` surface the real HTTP backend
does, with faithful-enough semantics for controller correctness tests:

- uid / resourceVersion / creationTimestamp assignment, optimistic-concurrency
  conflicts on stale resourceVersion
- status as a subresource (spec updates don't clobber status and vice versa)
- namespace existence enforcement, label-selector list filtering
- ownerReference cascade deletion (foreground, synchronous)
- watch streams with ADDED/MODIFIED/DELETED events
- CRD registration: applying a CRD makes its kind servable
"""

from __future__ import annotations

import copy
import datetime
import threading
import uuid
from typing import Any, Mapping

from kubeflow_tpu.k8s.client import (
    ApiError,
    K8sClient,
    KindRegistry,
    WatchEvent,
    WatchStream,
    match_labels,
    merge_patch,
)


def _now() -> str:
    return (
        datetime.datetime.now(datetime.timezone.utc)
        .replace(microsecond=0)
        .isoformat()
        .replace("+00:00", "Z")
    )


class FakeApiServer(K8sClient):
    def __init__(self) -> None:
        self._store: dict[tuple[str, str, str, str], dict] = {}
        self._registry = KindRegistry()
        self._lock = threading.RLock()
        self._rv = 0
        # (api_version, kind, namespace-or-"") -> list of streams
        self._watchers: dict[tuple[str, str, str], list[WatchStream]] = {}

    @property
    def registry(self) -> KindRegistry:
        return self._registry

    # ------------------------------------------------------------------
    # internals
    # ------------------------------------------------------------------

    def _storage_av(self, api_version: str, kind: str) -> str:
        """The apiVersion objects of ``kind`` are stored at. A request at
        a served non-storage version is normalized here (and converted at
        the read/write boundary); an unserved version is rejected the way
        a real apiserver 404s it."""
        storage = self._registry.storage_api_version(kind)
        if storage is None or api_version == storage:
            return api_version
        if not self._registry.served(kind, api_version):
            raise ApiError.not_found(
                f"{kind} is not served at {api_version}")
        return storage

    def _key(self, api_version: str, kind: str, namespace: str | None,
             name: str) -> tuple[str, str, str, str]:
        ns = namespace or "" if self._registry.namespaced(kind) else ""
        return (self._storage_av(api_version, kind), kind, ns, name)

    def _next_rv(self) -> str:
        self._rv += 1
        return str(self._rv)

    def _notify(self, event_type: str, obj: dict) -> None:
        api_version, kind = obj["apiVersion"], obj["kind"]
        ns = obj["metadata"].get("namespace", "")
        scopes = (ns, "") if ns else ("",)
        for scope in scopes:
            for stream in self._watchers.get((api_version, kind, scope), []):
                # Streams opened at a served non-storage version see
                # events converted to the version they asked for.
                requested = getattr(stream, "requested_api_version",
                                    api_version)
                stream.push(WatchEvent(event_type, self._registry.convert(
                    copy.deepcopy(obj), requested)))

    def _register_crd_locked(self, crd: dict) -> None:
        """Register (or re-register) a CRD; if its storage version moved,
        migrate existing objects to the new storage key — a real
        apiserver keeps serving pre-existing objects across a
        storage-version flip, so the fake must not strand them under the
        old key."""
        kind = crd["spec"]["names"]["kind"]
        old = self._registry.storage_api_version(kind)
        self._registry.register_crd(crd)
        new = self._registry.storage_api_version(kind)
        if not old or not new or old == new:
            return
        moved = [(k, o) for k, o in self._store.items()
                 if k[1] == kind and k[0] == old]
        for key, obj in moved:
            del self._store[key]
            converted = self._registry.convert(obj, new)
            self._store[(new, kind, key[2], key[3])] = converted
        # Watchers registered under the old storage key must follow, or
        # pre-flip streams would silently stop receiving events (each
        # stream still converts to ITS requested version on push).
        for (av, k, scope), streams in list(self._watchers.items()):
            if k == kind and av == old:
                self._watchers.setdefault((new, k, scope),
                                          []).extend(streams)
                del self._watchers[(av, k, scope)]

    def _check_namespace(self, obj: Mapping[str, Any]) -> None:
        kind = obj["kind"]
        if not self._registry.namespaced(kind):
            return
        ns = obj["metadata"].get("namespace")
        if not ns:
            raise ApiError.invalid(f"{kind} {obj['metadata'].get('name')}: namespace required")
        if ("v1", "Namespace", "", ns) not in self._store:
            raise ApiError.not_found(f"namespace {ns} not found")

    # ------------------------------------------------------------------
    # CRUD
    # ------------------------------------------------------------------

    def create(self, obj: dict) -> dict:
        obj = copy.deepcopy(obj)
        m = obj.setdefault("metadata", {})
        if "name" not in m and "generateName" in m:
            m["name"] = m["generateName"] + uuid.uuid4().hex[:6]
        with self._lock:
            self._check_namespace(obj)
            requested_av = obj["apiVersion"]
            key = self._key(requested_av, obj["kind"], m.get("namespace"), m["name"])
            obj = self._registry.convert(obj, key[0])  # to storage version
            if key in self._store:
                raise ApiError.already_exists(
                    f"{obj['kind']} {m.get('namespace', '')}/{m['name']} already exists"
                )
            m = obj["metadata"]
            m["uid"] = str(uuid.uuid4())
            m["resourceVersion"] = self._next_rv()
            m["creationTimestamp"] = _now()
            self._store[key] = obj
            if obj["kind"] == "CustomResourceDefinition":
                self._register_crd_locked(obj)
            self._notify("ADDED", obj)
            return self._registry.convert(copy.deepcopy(obj), requested_av)

    def get(self, api_version: str, kind: str, name: str, namespace: str | None = None) -> dict:
        with self._lock:
            key = self._key(api_version, kind, namespace, name)
            if key not in self._store:
                raise ApiError.not_found(f"{kind} {namespace or ''}/{name} not found")
            return self._registry.convert(
                copy.deepcopy(self._store[key]), api_version)

    def list(
        self,
        api_version: str,
        kind: str,
        namespace: str | None = None,
        label_selector: Mapping[str, str] | None = None,
    ) -> list[dict]:
        with self._lock:
            storage_av = self._storage_av(api_version, kind)
            out = []
            for (av, k, ns, _), obj in self._store.items():
                if av != storage_av or k != kind:
                    continue
                if namespace and ns != namespace:
                    continue
                if match_labels(obj, label_selector):
                    out.append(self._registry.convert(
                        copy.deepcopy(obj), api_version))
            out.sort(key=lambda o: (o["metadata"].get("namespace", ""), o["metadata"]["name"]))
            return out

    def _update(self, obj: dict, subresource: str | None) -> dict:
        obj = copy.deepcopy(obj)
        m = obj["metadata"]
        requested_av = obj["apiVersion"]
        with self._lock:
            key = self._key(requested_av, obj["kind"], m.get("namespace"), m["name"])
            obj = self._registry.convert(obj, key[0])  # to storage version
            m = obj["metadata"]
            existing = self._store.get(key)
            if existing is None:
                raise ApiError.not_found(
                    f"{obj['kind']} {m.get('namespace', '')}/{m['name']}"
                    " not found")
            sent_rv = m.get("resourceVersion")
            if sent_rv is not None and sent_rv != existing["metadata"]["resourceVersion"]:
                raise ApiError.conflict(
                    f"{obj['kind']} {m['name']}: resourceVersion {sent_rv} is stale"
                )
            if subresource == "status":
                new = copy.deepcopy(existing)
                new["status"] = copy.deepcopy(obj.get("status", {}))
            else:
                new = obj
                # status is a subresource: a plain update cannot change it
                if "status" in existing:
                    new["status"] = copy.deepcopy(existing["status"])
                else:
                    new.pop("status", None)
            for immutable in ("uid", "creationTimestamp"):
                new["metadata"][immutable] = existing["metadata"][immutable]
            new["metadata"]["resourceVersion"] = self._next_rv()
            self._store[key] = new
            if new["kind"] == "CustomResourceDefinition":
                self._register_crd_locked(new)
            self._notify("MODIFIED", new)
            return self._registry.convert(copy.deepcopy(new), requested_av)

    def update(self, obj: dict) -> dict:
        return self._update(obj, subresource=None)

    def update_status(self, obj: dict) -> dict:
        return self._update(obj, subresource="status")

    def patch(self, api_version: str, kind: str, name: str, patch: dict,
              namespace: str | None = None) -> dict:
        with self._lock:
            current = self.get(api_version, kind, name, namespace)
            patched = merge_patch(current, patch)
            # merge-patching may not change resourceVersion semantics: patch
            # always applies to latest, so drop any stale rv from the patch
            patched["metadata"]["resourceVersion"] = current["metadata"]["resourceVersion"]
            if "status" in patch:
                with_status = self._update(patched, subresource="status")
                if set(patch.keys()) - {"status"}:
                    patched["metadata"]["resourceVersion"] = (
                        with_status["metadata"]["resourceVersion"])
                    return self._update(patched, subresource=None)
                return with_status
            return self._update(patched, subresource=None)

    def delete(self, api_version: str, kind: str, name: str, namespace: str | None = None) -> None:
        with self._lock:
            key = self._key(api_version, kind, namespace, name)
            obj = self._store.pop(key, None)
            if obj is None:
                raise ApiError.not_found(f"{kind} {namespace or ''}/{name} not found")
            self._notify("DELETED", obj)
            self._cascade_delete(obj)
            if kind == "Namespace":
                self._delete_namespace_contents(name)

    def _cascade_delete(self, owner: dict) -> None:
        owner_uid = owner["metadata"]["uid"]
        owner_ns = owner["metadata"].get("namespace", "")
        doomed = []
        for key, obj in self._store.items():
            # Real GC scoping: a namespaced owner only cascades within its
            # own namespace (ownerReferences never cross namespaces, and a
            # namespaced owner cannot own cluster-scoped objects); a
            # cluster-scoped owner cascades to children in EVERY namespace.
            child_ns = obj["metadata"].get("namespace", "")
            if owner_ns and child_ns != owner_ns:
                continue
            for ref in obj["metadata"].get("ownerReferences", []):
                if ref.get("uid") == owner_uid or (
                    not ref.get("uid")
                    and ref.get("kind") == owner["kind"]
                    and ref.get("name") == owner["metadata"]["name"]
                ):
                    doomed.append(key)
                    break
        for key in doomed:
            obj = self._store.pop(key, None)
            if obj is not None:
                self._notify("DELETED", obj)
                self._cascade_delete(obj)

    def _delete_namespace_contents(self, ns: str) -> None:
        doomed = [k for k, o in self._store.items() if o["metadata"].get("namespace") == ns]
        for key in doomed:
            obj = self._store.pop(key, None)
            if obj is not None:
                self._notify("DELETED", obj)

    # ------------------------------------------------------------------
    # watch
    # ------------------------------------------------------------------

    def watch(self, api_version: str, kind: str, namespace: str | None = None) -> WatchStream:
        # Unknown kinds fail loudly (a watch opened before its CRD is
        # applied would otherwise be keyed at the wrong version and hang
        # silently empty after registration).
        self._registry.namespaced(kind)
        scope = namespace or ""
        key = (self._storage_av(api_version, kind), kind, scope)

        def _on_stop() -> None:
            with self._lock:
                streams = self._watchers.get(key, [])
                if stream in streams:
                    streams.remove(stream)

        stream = WatchStream(on_stop=_on_stop)
        stream.requested_api_version = api_version
        with self._lock:
            self._watchers.setdefault(key, []).append(stream)
            # replay current state as ADDED events (informer initial-list)
            for obj in self.list(api_version, kind, namespace or None):
                stream.push(WatchEvent("ADDED", obj))
        return stream

    # ------------------------------------------------------------------
    # test helpers
    # ------------------------------------------------------------------

    def all_objects(self) -> list[dict]:
        with self._lock:
            return [copy.deepcopy(o) for o in self._store.values()]

    def ensure_namespace(self, name: str) -> None:
        if self.get_or_none("v1", "Namespace", name) is None:
            self.create({"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": name}})
