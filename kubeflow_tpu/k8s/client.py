"""Kubernetes API client layer.

The reference's Go components all speak to the apiserver through client-go
(bootstrap/pkg/apis/apps/group.go kube client helpers); its Python components
use the official kubernetes client (components/openmpi-controller). Neither is
available here, so the platform ships its own thin client:

- :class:`K8sClient` — the abstract CRUD+watch surface every controller, the
  CLI apply path, and web apps are written against.
- :class:`HttpK8sClient` — a real apiserver backend over HTTP (requests),
  resolving REST paths from a kind→plural registry.
- :class:`kubeflow_tpu.k8s.fake.FakeApiServer` — an in-process backend with
  identical semantics, used by unit tests (the envtest analogue, SURVEY.md §4).
"""

from __future__ import annotations

import copy
import json
import logging
import queue
import random
import threading
from dataclasses import dataclass
from typing import Any, Callable, Iterator, Mapping


class ApiError(Exception):
    """Kubernetes-style API error with an HTTP status code."""

    def __init__(self, code: int, reason: str, message: str = ""):
        super().__init__(f"{code} {reason}: {message}")
        self.code = code
        self.reason = reason
        self.message = message

    @classmethod
    def not_found(cls, what: str) -> "ApiError":
        return cls(404, "NotFound", what)

    @classmethod
    def conflict(cls, what: str) -> "ApiError":
        return cls(409, "Conflict", what)

    @classmethod
    def already_exists(cls, what: str) -> "ApiError":
        return cls(409, "AlreadyExists", what)

    @classmethod
    def invalid(cls, what: str) -> "ApiError":
        return cls(422, "Invalid", what)

    @property
    def transient(self) -> bool:
        """True for errors a well-behaved client retries (the client-go
        IsTooManyRequests / IsServerTimeout / IsInternalError family):
        apiserver load-shedding (429), request timeouts (408) and 5xx —
        never schema rejections, which retrying cannot heal."""
        return self.code in (408, 429) or self.code >= 500


@dataclass(frozen=True)
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED
    object: dict


# Built-in kind → (plural, namespaced). CRD kinds are registered at runtime.
_BUILTIN_KINDS: dict[str, tuple[str, bool]] = {
    "Pod": ("pods", True),
    "Service": ("services", True),
    "ConfigMap": ("configmaps", True),
    "Secret": ("secrets", True),
    "Namespace": ("namespaces", False),
    "Node": ("nodes", False),
    "PersistentVolumeClaim": ("persistentvolumeclaims", True),
    "ResourceQuota": ("resourcequotas", True),
    "ServiceAccount": ("serviceaccounts", True),
    "Deployment": ("deployments", True),
    "StatefulSet": ("statefulsets", True),
    "Job": ("jobs", True),
    "CronJob": ("cronjobs", True),
    "Event": ("events", True),
    "Lease": ("leases", True),
    "Role": ("roles", True),
    "RoleBinding": ("rolebindings", True),
    "ClusterRole": ("clusterroles", False),
    "ClusterRoleBinding": ("clusterrolebindings", False),
    "CustomResourceDefinition": ("customresourcedefinitions", False),
    "MutatingWebhookConfiguration": ("mutatingwebhookconfigurations", False),
    "ValidatingWebhookConfiguration": ("validatingwebhookconfigurations", False),
}


# kind -> convert(obj, to_api_version) for kinds whose CRD serves multiple
# versions with DIFFERENT schemas. API packages self-register at import
# (apis/jobs.py registers the job-kind converter); kinds without an entry
# convert by apiVersion rewrite alone (the k8s `conversion: None` strategy
# for identical schemas).
_CONVERTERS: dict[str, Callable[[dict, str], dict]] = {}


def register_converter(kind: str,
                       fn: Callable[[dict, str], dict]) -> None:
    _CONVERTERS[kind] = fn


class KindRegistry:
    """Resolves kind → REST plural/scope and the served/storage version
    set; extended when CRDs are applied. The storage machinery (the fake
    apiserver) keys every object at the STORAGE version and converts to
    whatever served version a reader asks for — the
    tf-job-operator.libsonnet:52-97 store-v1beta1/serve-v1beta2 model."""

    def __init__(self) -> None:
        self._kinds = dict(_BUILTIN_KINDS)
        # kind -> (group, {version: served}, storage_version)
        self._versions: dict[str, tuple[str, dict[str, bool], str]] = {}
        self._lock = threading.Lock()

    def register_crd(self, crd_obj: Mapping[str, Any]) -> None:
        spec = crd_obj["spec"]
        kind = spec["names"]["kind"]
        plural = spec["names"]["plural"]
        namespaced = spec.get("scope", "Namespaced") == "Namespaced"
        group = spec.get("group", "")
        served: dict[str, bool] = {}
        storage = ""
        for v in spec.get("versions", []):
            served[v["name"]] = bool(v.get("served", True))
            if v.get("storage"):
                storage = v["name"]
        with self._lock:
            self._kinds[kind] = (plural, namespaced)
            if group and storage:
                self._versions[kind] = (group, served, storage)

    def storage_api_version(self, kind: str) -> str | None:
        """`group/version` the cluster stores this kind at; None for
        builtins and single-version kinds registered without a CRD."""
        with self._lock:
            info = self._versions.get(kind)
        return f"{info[0]}/{info[2]}" if info else None

    def served(self, kind: str, api_version: str) -> bool:
        with self._lock:
            info = self._versions.get(kind)
        if info is None:
            return True  # no version metadata: accept as before
        group, versions, _storage = info
        g, _, v = api_version.rpartition("/")
        return g == group and versions.get(v, False)

    @staticmethod
    def convert(obj: dict, to_api_version: str) -> dict:
        """Convert ``obj`` to ``to_api_version`` (deep-copying); identity
        when already there. Kinds without a registered converter get the
        apiVersion rewritten (identical-schema versions)."""
        if obj.get("apiVersion") == to_api_version:
            return obj
        fn = _CONVERTERS.get(obj.get("kind", ""))
        if fn is not None:
            return fn(obj, to_api_version)
        out = copy.deepcopy(obj)
        out["apiVersion"] = to_api_version
        return out

    def plural(self, kind: str) -> str:
        try:
            with self._lock:
                return self._kinds[kind][0]
        except KeyError:
            raise ApiError.not_found(f"no REST mapping for kind {kind}")

    def kind_for_plural(self, plural: str) -> str:
        """Reverse mapping (REST path segment → kind), for HTTP frontends."""
        with self._lock:
            for kind, (p, _namespaced) in self._kinds.items():
                if p == plural:
                    return kind
        raise ApiError.not_found(f"unknown resource {plural!r}")

    def namespaced(self, kind: str) -> bool:
        try:
            with self._lock:
                return self._kinds[kind][1]
        except KeyError:
            raise ApiError.not_found(f"no REST mapping for kind {kind}")


class K8sClient:
    """Abstract CRUD + watch surface.

    Objects are plain dicts with apiVersion/kind/metadata, exactly as built by
    :mod:`kubeflow_tpu.k8s.objects`.
    """

    def create(self, obj: dict) -> dict:
        raise NotImplementedError

    def get(self, api_version: str, kind: str, name: str, namespace: str | None = None) -> dict:
        raise NotImplementedError

    def list(
        self,
        api_version: str,
        kind: str,
        namespace: str | None = None,
        label_selector: Mapping[str, str] | None = None,
    ) -> list[dict]:
        raise NotImplementedError

    def update(self, obj: dict) -> dict:
        raise NotImplementedError

    def update_status(self, obj: dict) -> dict:
        raise NotImplementedError

    def patch(self, api_version: str, kind: str, name: str, patch: dict,
              namespace: str | None = None) -> dict:
        raise NotImplementedError

    def delete(self, api_version: str, kind: str, name: str, namespace: str | None = None) -> None:
        raise NotImplementedError

    def watch(
        self, api_version: str, kind: str, namespace: str | None = None
    ) -> "WatchStream":
        raise NotImplementedError

    # ------------------------------------------------------------------
    # Conveniences shared by all backends
    # ------------------------------------------------------------------

    def apply(self, obj: dict) -> dict:
        """Create-or-update (the `ks apply` / kubectl-apply analogue used by
        the deployment engine, bootstrap/pkg/kfapp/ksonnet/ksonnet.go:132-175)."""
        m = obj["metadata"]
        try:
            existing = self.get(
                obj["apiVersion"], obj["kind"], m["name"], m.get("namespace")
            )
        except ApiError as e:
            if e.code != 404:
                raise
            return self.create(obj)
        merged = copy.deepcopy(obj)
        merged["metadata"]["resourceVersion"] = existing["metadata"].get("resourceVersion")
        return self.update(merged)

    def get_or_none(self, api_version: str, kind: str, name: str,
                    namespace: str | None = None) -> dict | None:
        try:
            return self.get(api_version, kind, name, namespace)
        except ApiError as e:
            if e.code == 404:
                return None
            raise

    def delete_if_exists(self, api_version: str, kind: str, name: str,
                         namespace: str | None = None) -> bool:
        try:
            self.delete(api_version, kind, name, namespace)
            return True
        except ApiError as e:
            if e.code == 404:
                return False
            raise


class WatchStream:
    """Iterator of WatchEvents with a stop handle, backed by a queue."""

    def __init__(self, on_stop: Callable[[], None] | None = None):
        self._q: "queue.Queue[WatchEvent | None]" = queue.Queue()
        self._on_stop = on_stop
        self._stopped = threading.Event()

    def push(self, event: WatchEvent) -> None:
        self._q.put(event)

    def stop(self) -> None:
        if not self._stopped.is_set():
            self._stopped.set()
            if self._on_stop:
                self._on_stop()
            self._q.put(None)

    @property
    def stopped(self) -> bool:
        """True once stop() ran — consumers distinguish a deliberate stop
        from a dropped connection (the stream ending without stop)."""
        return self._stopped.is_set()

    def wait_stopped(self, timeout: float | None = None) -> bool:
        return self._stopped.wait(timeout)

    def __iter__(self) -> Iterator[WatchEvent]:
        while True:
            item = self._q.get()
            if item is None:
                return
            yield item

    def next(self, timeout: float | None = None) -> WatchEvent | None:
        """Get the next event, or None on timeout/stop."""
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None


def match_labels(obj: Mapping[str, Any], selector: Mapping[str, str] | None) -> bool:
    if not selector:
        return True
    labels = obj.get("metadata", {}).get("labels", {}) or {}
    return all(labels.get(k) == v for k, v in selector.items())


def merge_patch(base: dict, patch: Mapping[str, Any]) -> dict:
    """RFC 7386 JSON merge patch (nulls delete keys). Matches kubectl
    `--type=merge`, which is all the platform's controllers need."""
    out = copy.deepcopy(base)

    def _merge(dst: dict, src: Mapping[str, Any]) -> None:
        for k, v in src.items():
            if v is None:
                dst.pop(k, None)
            elif isinstance(v, Mapping) and isinstance(dst.get(k), dict):
                _merge(dst[k], v)
            else:
                dst[k] = copy.deepcopy(v)

    _merge(out, patch)
    return out


def retry_on_conflict(client: K8sClient, fn: Callable[[K8sClient], Any],
                      attempts: int = 5) -> Any:
    """Run ``fn(client)`` and retry it on 409 Conflict — the client-go
    ``retry.RetryOnConflict`` analogue.

    ``fn`` must be a refetch-and-reapply closure: read the LATEST object
    inside the call, apply the change, write. A closure that reuses a
    captured stale object would conflict forever; refetching inside makes
    every attempt race against fresh state, so a lost optimistic-concurrency
    race costs one extra round-trip instead of parking the object until the
    next resync.
    """
    for attempt in range(attempts):
        try:
            return fn(client)
        except ApiError as e:
            if e.code != 409 or attempt == attempts - 1:
                raise


# ---------------------------------------------------------------------------
# Real-cluster backend
# ---------------------------------------------------------------------------


def _api_prefix(api_version: str) -> str:
    return f"/api/{api_version}" if "/" not in api_version else f"/apis/{api_version}"


@dataclass
class ClusterConfig:
    """Connection parameters for a real apiserver (or our HTTP fake served
    over a socket). Token/CA handling mirrors the in-cluster convention."""

    host: str = "http://127.0.0.1:8001"  # `kubectl proxy` default
    token: str | None = None
    verify: bool | str = True


class HttpK8sClient(K8sClient):
    """Talks to a real apiserver over HTTP.

    Path layout: /api/v1/... for core, /apis/<group>/<version>/... otherwise;
    namespaced resources under /namespaces/<ns>/. Watches use
    ?watch=true chunked JSON streams.
    """

    def __init__(self, config: ClusterConfig | None = None, registry: KindRegistry | None = None):
        import requests

        self._cfg = config or ClusterConfig()
        self._registry = registry or KindRegistry()
        self._session = requests.Session()
        if self._cfg.token:
            self._session.headers["Authorization"] = f"Bearer {self._cfg.token}"
        self._session.verify = self._cfg.verify

    # -- path building ---------------------------------------------------

    def _path(self, api_version: str, kind: str, namespace: str | None,
              name: str | None = None) -> str:
        plural = self._registry.plural(kind)
        parts = [_api_prefix(api_version)]
        if self._registry.namespaced(kind) and namespace:
            parts.append(f"/namespaces/{namespace}")
        parts.append(f"/{plural}")
        if name:
            parts.append(f"/{name}")
        return "".join(parts)

    def _request(self, method: str, path: str, body: dict | None = None,
                 params: dict | None = None,
                 content_type: str = "application/json") -> dict:
        url = self._cfg.host + path
        resp = self._session.request(
            method,
            url,
            json=body,
            params=params,
            headers={"Content-Type": content_type},
            timeout=60,
        )
        if resp.status_code >= 400:
            try:
                status = resp.json()
                raise ApiError(resp.status_code,
                               status.get("reason", "Error"),
                               status.get("message", resp.text))
            except ValueError:
                raise ApiError(resp.status_code, "Error", resp.text)
        return resp.json() if resp.content else {}

    # -- CRUD ------------------------------------------------------------

    def create(self, obj: dict) -> dict:
        m = obj["metadata"]
        path = self._path(obj["apiVersion"], obj["kind"], m.get("namespace"))
        created = self._request("POST", path, body=obj)
        if obj["kind"] == "CustomResourceDefinition":
            self._registry.register_crd(obj)
        return created

    def get(self, api_version: str, kind: str, name: str, namespace: str | None = None) -> dict:
        return self._request("GET", self._path(api_version, kind, namespace, name))

    def list(self, api_version: str, kind: str,
             namespace: str | None = None,
             label_selector: Mapping[str, str] | None = None) -> list[dict]:
        params = {}
        if label_selector:
            params["labelSelector"] = ",".join(f"{k}={v}" for k, v in label_selector.items())
        result = self._request("GET", self._path(api_version, kind, namespace), params=params)
        items = result.get("items", [])
        for it in items:  # list items omit apiVersion/kind; restore them
            it.setdefault("apiVersion", api_version)
            it.setdefault("kind", kind)
        return items

    def update(self, obj: dict) -> dict:
        m = obj["metadata"]
        updated = self._request(
            "PUT",
            self._path(obj["apiVersion"], obj["kind"],
                       m.get("namespace"), m["name"]),
            body=obj,
        )
        if obj["kind"] == "CustomResourceDefinition":
            self._registry.register_crd(obj)
        return updated

    def update_status(self, obj: dict) -> dict:
        m = obj["metadata"]
        path = self._path(obj["apiVersion"], obj["kind"], m.get("namespace"), m["name"]) + "/status"
        return self._request("PUT", path, body=obj)

    def patch(self, api_version: str, kind: str, name: str, patch: dict,
              namespace: str | None = None) -> dict:
        return self._request(
            "PATCH",
            self._path(api_version, kind, namespace, name),
            body=patch,
            content_type="application/merge-patch+json",
        )

    def delete(self, api_version: str, kind: str, name: str, namespace: str | None = None) -> None:
        self._request("DELETE", self._path(api_version, kind, namespace, name))

    # Reconnect tuning for dropped watch streams.
    watch_backoff_base = 0.1
    watch_backoff_max = 5.0

    def watch(self, api_version: str, kind: str, namespace: str | None = None) -> WatchStream:
        """Watch with auto-reconnect: a dropped connection (apiserver
        restart, LB idle-timeout, transient 5xx) is retried with jittered
        exponential backoff, and every reconnect pushes a synthetic relist
        (current objects as ADDED events) so level-triggered consumers
        re-observe anything that changed while the stream was down — the
        client-go reflector ListAndWatch loop. The stream only ends when
        the caller stops it."""
        path = self._path(api_version, kind, namespace)
        url = self._cfg.host + path
        holder: dict = {}

        def _on_stop() -> None:
            # abort the in-flight chunked read so the thread + connection are
            # released immediately instead of idling until the 1h timeout
            resp = holder.get("resp")
            if resp is not None:
                try:
                    resp.close()
                except Exception:
                    pass

        stream = WatchStream(on_stop=_on_stop)

        def _relist() -> None:
            for obj in self.list(api_version, kind, namespace):
                stream.push(WatchEvent("ADDED", obj))

        def _run() -> None:
            backoff = self.watch_backoff_base
            connected_before = False
            try:
                while not stream.stopped:
                    try:
                        resp = self._session.get(
                            url, params={"watch": "true"}, stream=True,
                            timeout=3600,
                        )
                        holder["resp"] = resp
                        if resp.status_code >= 400:
                            raise ApiError(resp.status_code, "WatchFailed",
                                           resp.text[:200])
                        if connected_before:
                            # Events between drop and reconnect are gone; a
                            # fresh watch starts at "now", so replay current
                            # state for the consumer to reconcile against.
                            _relist()
                        connected_before = True
                        for line in resp.iter_lines():
                            if stream.stopped:
                                return
                            if not line:
                                continue
                            evt = json.loads(line)
                            stream.push(WatchEvent(evt["type"], evt["object"]))
                            backoff = self.watch_backoff_base
                    except Exception as e:
                        if stream.stopped:
                            return
                        logging.warning("watch %s dropped: %s; reconnecting",
                                        path, e)
                    if stream.stopped:
                        return
                    stream.wait_stopped(backoff * (0.5 + random.random()))
                    backoff = min(backoff * 2, self.watch_backoff_max)
            finally:
                stream.stop()

        t = threading.Thread(target=_run, daemon=True)
        t.start()
        return stream

    @property
    def registry(self) -> KindRegistry:
        return self._registry
