"""Fault-injection decorator over any :class:`~kubeflow_tpu.k8s.client.K8sClient`.

The chaos-mesh/toxiproxy analogue for the platform's control plane: wraps a
backend (usually :class:`~kubeflow_tpu.k8s.fake.FakeApiServer`) and injects
deterministic, seeded faults so controller hardening — workqueue backoff,
conflict retry, watch reconnect + relist, create idempotency — is *proved*
by tests instead of assumed:

- transient API errors (429 TooManyRequests / 500 InternalError /
  503 ServiceUnavailable) on any verb, at per-verb rates;
- added latency;
- extra optimistic-concurrency conflicts on update/update_status (the write
  does NOT land — the caller must refetch and reapply);
- "error after success" on create: the object IS created but the caller
  sees a 500 — the nastiest real-world case, where a blind retry produces a
  duplicate unless the controller tolerates 409 AlreadyExists;
- watch-stream drops: a fated stream dies after a seeded number of events,
  exactly as a severed apiserver connection would.

Every injected fault and every operation that reached the inner backend is
recorded in a journal for assertions (did the controller create this pod
twice? how many faults did it absorb?).
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Mapping

from kubeflow_tpu.k8s.client import (
    ApiError,
    K8sClient,
    WatchStream,
)

# Transient statuses a well-behaved client must retry (client-go's
# IsTooManyRequests / IsInternalError / IsServiceUnavailable family).
TRANSIENT_ERRORS = (
    (429, "TooManyRequests"),
    (500, "InternalError"),
    (503, "ServiceUnavailable"),
)


@dataclass
class FaultRecord:
    """One journal entry: an API call and what chaos did to it."""

    verb: str
    kind: str
    name: str
    namespace: str
    fault: str | None  # None = passed through untouched
    code: int = 0      # HTTP code of the outcome (0 = success)
    landed: bool = False  # the inner backend actually executed the op


@dataclass
class ChaosRates:
    """Per-call fault probabilities. ``per_verb_error`` overrides
    ``error_rate`` for specific verbs (create/get/list/update/
    update_status/patch/delete/watch)."""

    error_rate: float = 0.0
    conflict_rate: float = 0.0        # update/update_status only
    error_after_create_rate: float = 0.0
    watch_drop_rate: float = 0.0      # probability a new stream is drop-fated
    latency_seconds: float = 0.0      # max added latency per call
    per_verb_error: Mapping[str, float] = field(default_factory=dict)

    def error_for(self, verb: str) -> float:
        return float(self.per_verb_error.get(verb, self.error_rate))


class ChaosK8sClient(K8sClient):
    """Decorates an inner client with seeded fault injection.

    Same seed + same single-threaded call sequence → same fault sequence,
    so soak failures reproduce. The journal records every call; helpers
    :meth:`faults` and :meth:`landed` slice it for assertions.
    """

    def __init__(self, inner: K8sClient, *, seed: int = 0,
                 rates: ChaosRates | None = None, **rate_kwargs):
        self.inner = inner
        self.rates = rates or ChaosRates(**rate_kwargs)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.journal: list[FaultRecord] = []
        self._streams: list[tuple[WatchStream, WatchStream]] = []

    # -- configuration / inspection ------------------------------------

    def set_rates(self, **kwargs) -> None:
        """Adjust fault rates mid-test (e.g. turn the apiserver hostile
        only after a controller is healthy)."""
        with self._lock:
            for key, value in kwargs.items():
                if not hasattr(self.rates, key):
                    raise TypeError(f"unknown chaos rate {key!r}")
                setattr(self.rates, key, value)

    def faults(self, verb: str | None = None) -> list[FaultRecord]:
        with self._lock:
            return [r for r in self.journal
                    if r.fault and (verb is None or r.verb == verb)]

    def landed(self, verb: str | None = None,
               kind: str | None = None) -> list[FaultRecord]:
        """Journal entries whose operation actually executed on the inner
        backend (including create-then-error faults)."""
        with self._lock:
            return [r for r in self.journal
                    if r.landed and (verb is None or r.verb == verb)
                    and (kind is None or r.kind == kind)]

    def drop_watches(self) -> int:
        """Sever every live watch stream now (apiserver restart). Returns
        the number of streams dropped."""
        with self._lock:
            streams, self._streams = self._streams, []
        for inner_stream, outer in streams:
            self._record("watch", "", "", "", "drop", 0, False)
            inner_stream.stop()
            outer.stop()
        return len(streams)

    # -- fault machinery -----------------------------------------------

    def _record(self, verb, kind, name, namespace, fault, code, landed):
        rec = FaultRecord(verb, kind, name or "", namespace or "",
                          fault, code, landed)
        with self._lock:
            self.journal.append(rec)
        return rec

    def _roll(self, p: float) -> bool:
        with self._lock:
            return p > 0 and self._rng.random() < p

    def _pre_fault(self, verb: str, kind: str, name: str,
                   namespace: str) -> None:
        """Latency + transient error + injected conflict, before the inner
        call — none of these let the operation land."""
        rates = self.rates
        if rates.latency_seconds > 0:
            with self._lock:
                delay = self._rng.uniform(0, rates.latency_seconds)
            time.sleep(delay)
        if self._roll(rates.error_for(verb)):
            with self._lock:
                code, reason = self._rng.choice(TRANSIENT_ERRORS)
            self._record(verb, kind, name, namespace, reason, code, False)
            raise ApiError(code, reason,
                           f"chaos: injected {reason} on {verb} {kind}")
        if verb in ("update", "update_status") and self._roll(
                rates.conflict_rate):
            self._record(verb, kind, name, namespace, "Conflict", 409, False)
            raise ApiError.conflict(
                f"chaos: injected conflict on {verb} {kind} {name}")

    def _call(self, verb, kind, name, namespace, op):
        self._pre_fault(verb, kind, name, namespace)
        try:
            result = op()
        except ApiError as e:
            # Real backend error (404/409/...): journal it as landed=False
            # so duplicate-side-effect assertions only count true writes.
            self._record(verb, kind, name, namespace, None, e.code, False)
            raise
        self._record(verb, kind, name, namespace, None, 0, True)
        return result

    # -- CRUD ----------------------------------------------------------

    def create(self, obj: dict) -> dict:
        kind = obj.get("kind", "")
        m = obj.get("metadata", {})
        name, ns = m.get("name", ""), m.get("namespace", "")
        self._pre_fault("create", kind, name, ns)
        try:
            created = self.inner.create(obj)
        except ApiError as e:
            self._record("create", kind, name, ns, None, e.code, False)
            raise
        if self._roll(self.rates.error_after_create_rate):
            # The write landed but the response was lost — the retry will
            # see 409 AlreadyExists and must treat it as success.
            self._record("create", kind, name, ns,
                         "ErrorAfterSuccess", 500, True)
            raise ApiError(500, "InternalError",
                           f"chaos: response lost after create of "
                           f"{kind} {name} (object exists)")
        self._record("create", kind, name, ns, None, 0, True)
        return created

    def get(self, api_version, kind, name, namespace=None):
        return self._call("get", kind, name, namespace,
                          lambda: self.inner.get(api_version, kind, name,
                                                 namespace))

    def list(self, api_version, kind, namespace=None, label_selector=None):
        return self._call("list", kind, "", namespace,
                          lambda: self.inner.list(api_version, kind,
                                                  namespace, label_selector))

    def update(self, obj: dict) -> dict:
        m = obj.get("metadata", {})
        return self._call("update", obj.get("kind", ""), m.get("name", ""),
                          m.get("namespace"), lambda: self.inner.update(obj))

    def update_status(self, obj: dict) -> dict:
        m = obj.get("metadata", {})
        return self._call("update_status", obj.get("kind", ""),
                          m.get("name", ""), m.get("namespace"),
                          lambda: self.inner.update_status(obj))

    def patch(self, api_version, kind, name, patch, namespace=None):
        return self._call("patch", kind, name, namespace,
                          lambda: self.inner.patch(api_version, kind, name,
                                                   patch, namespace))

    def delete(self, api_version, kind, name, namespace=None):
        return self._call("delete", kind, name, namespace,
                          lambda: self.inner.delete(api_version, kind, name,
                                                    namespace))

    # -- watch ---------------------------------------------------------

    def watch(self, api_version, kind, namespace=None) -> WatchStream:
        self._pre_fault("watch", kind, "", namespace)
        inner_stream = self.inner.watch(api_version, kind, namespace)
        drop_after: int | None = None
        if self._roll(self.rates.watch_drop_rate):
            with self._lock:
                drop_after = self._rng.randint(1, 20)
        outer = WatchStream(on_stop=inner_stream.stop)
        entry = (inner_stream, outer)
        with self._lock:
            self._streams.append(entry)
        self._record("watch", kind, "", namespace, None, 0, True)

        def _forward() -> None:
            n = 0
            for event in inner_stream:
                outer.push(event)
                n += 1
                if drop_after is not None and n >= drop_after:
                    self._record("watch", kind, "", namespace,
                                 "drop", 0, False)
                    inner_stream.stop()
                    break
            with self._lock:
                if entry in self._streams:
                    self._streams.remove(entry)
            outer.stop()

        threading.Thread(target=_forward, daemon=True).start()
        return outer

    # -- passthrough ---------------------------------------------------

    @property
    def registry(self):
        return self.inner.registry

    def __getattr__(self, attr):
        # Test helpers (ensure_namespace, all_objects, ...) reach the
        # backend untouched — chaos only applies to the client surface.
        return getattr(self.inner, attr)


# The name the chaos soak reads naturally: ChaosApiServer(FakeApiServer()).
ChaosApiServer = ChaosK8sClient
