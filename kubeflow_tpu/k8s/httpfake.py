"""HTTP frontend for the fake apiserver — K8s REST semantics over a socket.

Serves a :class:`~kubeflow_tpu.k8s.fake.FakeApiServer` with the real
apiserver's path layout (``/api/v1/...`` core, ``/apis/<group>/<v>/...``
groups, ``/namespaces/<ns>/`` scoping, ``/status`` subresource,
``?labelSelector=``, ``?watch=true`` chunked JSON streams) so the real HTTP
backend (:class:`~kubeflow_tpu.k8s.client.HttpK8sClient`) — path building,
error mapping, watch streaming and all — is exercised against in-process
state. The envtest analogue for the HTTP layer (the reference only tests
client-go against kubebuilder envtest, profile_controller_suite_test.go),
and a zero-dependency local dev apiserver:

    python -m kubeflow_tpu.k8s.httpfake --port 8001
"""

from __future__ import annotations

import argparse
import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from kubeflow_tpu.k8s.client import ApiError
from kubeflow_tpu.k8s.fake import FakeApiServer

_PATH_RE = re.compile(
    r"^(?:/api/(?P<core_version>[^/]+)|/apis/(?P<group>[^/]+)/(?P<version>[^/]+))"
    r"(?:/namespaces/(?P<namespace>[^/]+))?"
    r"/(?P<plural>[^/]+)"
    r"(?:/(?P<name>[^/]+))?"
    r"(?:/(?P<subresource>status))?$"
)


def _status_body(code: int, reason: str, message: str) -> dict:
    return {"kind": "Status", "apiVersion": "v1", "code": code,
            "reason": reason, "message": message}


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "kubeflow-tpu-fake-apiserver"
    fake: FakeApiServer  # set by make_handler
    # A real apiserver closes watch connections after --min-request-timeout
    # (watches must survive that); None = streams live until the client
    # hangs up. Tests set this on the handler class to exercise the HTTP
    # client's reconnect + relist path.
    watch_timeout_seconds: float | None = None

    def log_message(self, *args):  # quiet
        pass

    # -- plumbing ------------------------------------------------------

    def _send_json(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_body(self) -> dict:
        length = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(length) or b"{}")

    def _route(self):
        """(api_version, kind, namespace, name, subresource, query)."""
        url = urlparse(self.path)
        m = _PATH_RE.match(url.path)
        if not m:
            raise ApiError(404, "NotFound", f"no route {url.path}")
        g = m.groupdict()
        if g["core_version"]:
            api_version = g["core_version"]
        else:
            api_version = f"{g['group']}/{g['version']}"
        # Cluster-scoped CRUD on namespaces arrives as the plural itself.
        plural = g["plural"]
        kind = self._kind_for(plural)
        return (api_version, kind, g["namespace"], g["name"],
                g["subresource"], parse_qs(url.query))

    def _kind_for(self, plural: str) -> str:
        return self.fake.registry.kind_for_plural(plural)

    # -- methods -------------------------------------------------------

    def do_GET(self):
        try:
            api_version, kind, ns, name, _sub, query = self._route()
            if name:
                self._send_json(
                    200, self.fake.get(api_version, kind, name, ns)
                )
                return
            if query.get("watch", ["false"])[0] == "true":
                self._stream_watch(api_version, kind, ns)
                return
            selector = None
            if "labelSelector" in query:
                selector = dict(
                    part.split("=", 1)
                    for part in query["labelSelector"][0].split(",")
                )
            items = self.fake.list(api_version, kind, ns,
                                   label_selector=selector)
            self._send_json(200, {
                "apiVersion": api_version, "kind": f"{kind}List",
                "items": items,
            })
        except ApiError as e:
            self._send_json(e.code, _status_body(e.code, e.reason, e.message))

    def do_POST(self):
        try:
            obj = self._read_body()
            self._send_json(201, self.fake.create(obj))
        except ApiError as e:
            self._send_json(e.code, _status_body(e.code, e.reason, e.message))

    def do_PUT(self):
        try:
            _api, _kind, _ns, _name, sub, _q = self._route()
            obj = self._read_body()
            if sub == "status":
                self._send_json(200, self.fake.update_status(obj))
            else:
                self._send_json(200, self.fake.update(obj))
        except ApiError as e:
            self._send_json(e.code, _status_body(e.code, e.reason, e.message))

    def do_PATCH(self):
        try:
            api_version, kind, ns, name, _sub, _q = self._route()
            if self.headers.get("Content-Type") not in (
                "application/merge-patch+json", "application/json"
            ):
                raise ApiError(415, "UnsupportedMediaType",
                               "only merge-patch is supported")
            patch = self._read_body()
            self._send_json(
                200, self.fake.patch(api_version, kind, name, patch, ns)
            )
        except ApiError as e:
            self._send_json(e.code, _status_body(e.code, e.reason, e.message))

    def do_DELETE(self):
        try:
            api_version, kind, ns, name, _sub, _q = self._route()
            self.fake.delete(api_version, kind, name, ns)
            self._send_json(200, _status_body(200, "Success", "deleted"))
        except ApiError as e:
            self._send_json(e.code, _status_body(e.code, e.reason, e.message))

    # -- watch ---------------------------------------------------------

    def _stream_watch(self, api_version: str, kind: str,
                      ns: str | None) -> None:
        import time as _time

        stream = self.fake.watch(api_version, kind, ns)
        deadline = (_time.monotonic() + self.watch_timeout_seconds
                    if self.watch_timeout_seconds else None)
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        try:
            while True:
                wait = 1.0
                if deadline is not None:
                    wait = deadline - _time.monotonic()
                    if wait <= 0:
                        # Server-side stream timeout: drop the connection
                        # the way a real apiserver / LB idle-timeout would.
                        self.close_connection = True
                        return
                    wait = min(wait, 1.0)
                event = stream.next(timeout=wait)
                if event is None:
                    # Idle heartbeat: a bare newline chunk (iter_lines skips
                    # empty lines) so a disconnected client surfaces as a
                    # broken pipe and this thread exits.
                    payload = b"\n"
                else:
                    payload = json.dumps(
                        {"type": event.type, "object": event.object}
                    ).encode() + b"\n"
                self.wfile.write(f"{len(payload):x}\r\n".encode())
                self.wfile.write(payload + b"\r\n")
                self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            stream.stop()


def serve(fake: FakeApiServer, port: int = 0
          ) -> tuple[ThreadingHTTPServer, int]:
    """Serve ``fake`` on 127.0.0.1:<port> in a daemon thread; returns
    (httpd, bound_port). Callers stop with ``httpd.shutdown()``."""
    handler = type("BoundHandler", (_Handler,), {"fake": fake})
    httpd = ThreadingHTTPServer(("127.0.0.1", port), handler)
    # Watch handlers park in long-lived streaming loops; they must not
    # block interpreter exit or server_close.
    httpd.daemon_threads = True
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, httpd.server_address[1]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--port", type=int, default=8001)
    args = ap.parse_args(argv)
    fake = FakeApiServer()
    fake.ensure_namespace("default")
    fake.ensure_namespace("kubeflow")
    httpd, port = serve(fake, args.port)
    print(f"fake apiserver listening on http://127.0.0.1:{port}")
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        httpd.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
