"""Jupyter web app: `python -m kubeflow_tpu.webapps.jupyter`.

The jupyter-web-app CRUD surface (components/jupyter-web-app/default/
kubeflow/jupyterui/routes.py:33-168: post/add/delete/list notebook; PVC +
Notebook CR creation via baseui/api.py:32-141), TPU-flavored:

- ``GET    /api/namespaces/<ns>/notebooks``       list
- ``POST   /api/namespaces/<ns>/notebooks``       create (+ optional PVC)
- ``DELETE /api/namespaces/<ns>/notebooks/<name>`` delete
- ``GET    /``                                     HTML shell
- ``GET    /healthz``
"""

from __future__ import annotations

import argparse
import re
import sys
from http.server import ThreadingHTTPServer

from kubeflow_tpu.apis.notebooks import (
    NOTEBOOK_KIND,
    NOTEBOOKS_API_VERSION,
    notebook,
)
from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.k8s.client import ApiError, K8sClient
from kubeflow_tpu.runtime import add_client_args, client_from_args, strip_glog_args
from kubeflow_tpu.webapps import JsonHandler

_RE_LIST = re.compile(r"^/api/namespaces/([^/]+)/notebooks/?$")
_RE_ITEM = re.compile(r"^/api/namespaces/([^/]+)/notebooks/([^/]+)$")

_SHELL = """<!doctype html>
<html><head><title>kubeflow-tpu notebooks</title></head>
<body><h2>Notebooks</h2>
<p>JSON API: GET/POST /api/namespaces/&lt;ns&gt;/notebooks,
DELETE /api/namespaces/&lt;ns&gt;/notebooks/&lt;name&gt;</p>
</body></html>
"""


class JupyterApp:
    def __init__(self, client: K8sClient, default_image: str):
        self.client = client
        self.default_image = default_image

    # -- operations (routes.py:33-168 surface) --------------------------

    def list_notebooks(self, namespace: str) -> list[dict]:
        items = self.client.list(NOTEBOOKS_API_VERSION, NOTEBOOK_KIND,
                                 namespace)
        return [
            {
                "name": nb["metadata"]["name"],
                "namespace": nb["metadata"]["namespace"],
                "image": self._image_of(nb),
                "tpuChips": nb["spec"].get("tpu", {}).get("chips", 0),
                "state": nb.get("status", {}).get("state", "Unknown"),
                "url": f"/notebook/{namespace}/{nb['metadata']['name']}/",
            }
            for nb in items
        ]

    @staticmethod
    def _image_of(nb: dict) -> str:
        containers = (
            nb["spec"].get("template", {}).get("spec", {})
            .get("containers", [])
        )
        return containers[0].get("image", "") if containers else ""

    def create_notebook(self, namespace: str, body: dict) -> dict:
        name = body.get("name")
        if not name or not re.fullmatch(r"[a-z0-9]([-a-z0-9]*[a-z0-9])?",
                                        name):
            raise ValueError("invalid notebook name")
        workspace_pvc = None
        ws = body.get("workspace") or {}
        if ws.get("size"):
            workspace_pvc = f"{name}-workspace"
            self.client.apply(k8s.pvc(
                workspace_pvc, namespace, ws["size"],
                storage_class=ws.get("storageClass"),
            ))
        nb = notebook(
            name,
            namespace,
            image=body.get("image") or self.default_image,
            tpu_chips=int(body.get("tpuChips", 0)),
            cpu=str(body.get("cpu", "1")),
            memory=str(body.get("memory", "2Gi")),
            workspace_pvc=workspace_pvc,
        )
        return self.client.create(nb)

    def delete_notebook(self, namespace: str, name: str) -> None:
        self.client.delete(NOTEBOOKS_API_VERSION, NOTEBOOK_KIND, name,
                           namespace)


def make_server(app: JupyterApp, port: int) -> ThreadingHTTPServer:
    class Handler(JsonHandler):
        def do_GET(self):
            if self.path in ("/healthz", "/readyz"):
                self.send_json(200, {"status": "ok"})
                return
            m = _RE_LIST.match(self.path)
            if m:
                try:
                    self.send_json(
                        200, {"notebooks": app.list_notebooks(m.group(1))}
                    )
                except ApiError as e:
                    self.send_json(e.code, {"error": str(e)})
                return
            if self.path == "/":
                self.send_html(200, _SHELL)
                return
            self.send_json(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            m = _RE_LIST.match(self.path)
            if not m:
                self.send_json(404, {"error": f"no route {self.path}"})
                return
            try:
                created = app.create_notebook(m.group(1), self.read_json())
                self.send_json(201, {"name": created["metadata"]["name"]})
            except ValueError as e:
                self.send_json(400, {"error": str(e)})
            except ApiError as e:
                self.send_json(e.code, {"error": str(e)})

        def do_DELETE(self):
            m = _RE_ITEM.match(self.path)
            if not m:
                self.send_json(404, {"error": f"no route {self.path}"})
                return
            try:
                app.delete_notebook(m.group(1), m.group(2))
                self.send_json(200, {"deleted": m.group(2)})
            except ApiError as e:
                self.send_json(e.code, {"error": str(e)})

    return ThreadingHTTPServer(("0.0.0.0", port), Handler)


def main(argv=None) -> int:
    argv = strip_glog_args(list(sys.argv[1:] if argv is None else argv))
    p = argparse.ArgumentParser(description="jupyter web app")
    add_client_args(p)
    p.add_argument("--port", type=int, default=5000)
    p.add_argument("--default-image", required=True)
    args = p.parse_args(argv)

    app = JupyterApp(client_from_args(args), args.default_image)
    httpd = make_server(app, args.port)
    print(f"jupyter web app on :{args.port}")
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
