"""Study web app: `python -m kubeflow_tpu.webapps.study`.

The Katib UI analogue (kubeflow/katib vizier UI surface): list studies with
trial progress and best objective, inspect one study's trials, create/delete
studies.

- ``GET    /api/namespaces/<ns>/studies``          list with summary
- ``POST   /api/namespaces/<ns>/studies``          create a StudyJob CR
- ``GET    /api/namespaces/<ns>/studies/<name>``   detail incl. trials
- ``DELETE /api/namespaces/<ns>/studies/<name>``   delete
- ``GET    /healthz``
"""

from __future__ import annotations

import argparse
import re
import sys
from http.server import ThreadingHTTPServer

from kubeflow_tpu.apis.tuning import STUDY_JOB_KIND, TUNING_API_VERSION
from kubeflow_tpu.k8s.client import ApiError, K8sClient
from kubeflow_tpu.runtime import add_client_args, client_from_args, strip_glog_args
from kubeflow_tpu.webapps import JsonHandler

_RE_LIST = re.compile(r"^/api/namespaces/([^/]+)/studies/?$")
_RE_ITEM = re.compile(r"^/api/namespaces/([^/]+)/studies/([^/]+)$")


class StudyApp:
    def __init__(self, client: K8sClient):
        self.client = client

    def list_studies(self, namespace: str) -> list[dict]:
        return [self._summary(s) for s in self.client.list(
            TUNING_API_VERSION, STUDY_JOB_KIND, namespace)]

    @staticmethod
    def _summary(study: dict) -> dict:
        status = study.get("status", {})
        return {
            "name": study["metadata"]["name"],
            "namespace": study["metadata"]["namespace"],
            "algorithm": study["spec"].get("algorithm", "random"),
            "state": status.get("state", "Unknown"),
            "trials": len(status.get("trials", [])),
            "bestObjective": status.get("bestObjective"),
            "bestAssignments": status.get("bestAssignments"),
        }

    def get_study(self, namespace: str, name: str) -> dict:
        study = self.client.get(TUNING_API_VERSION, STUDY_JOB_KIND, name,
                                namespace)
        detail = self._summary(study)
        detail["parameters"] = study["spec"].get("parameters", [])
        detail["trialList"] = study.get("status", {}).get("trials", [])
        return detail

    def create_study(self, namespace: str, body: dict) -> dict:
        name = body.get("name") or body.get("metadata", {}).get("name")
        if not name:
            raise ValueError("study needs a name")
        spec = body.get("spec") or {
            k: v for k, v in body.items() if k != "name"
        }
        if "parameters" not in spec or "trialTemplate" not in spec:
            raise ValueError("spec needs 'parameters' and 'trialTemplate'")
        return self.client.create({
            "apiVersion": TUNING_API_VERSION,
            "kind": STUDY_JOB_KIND,
            "metadata": {"name": name, "namespace": namespace},
            "spec": spec,
        })

    def delete_study(self, namespace: str, name: str) -> None:
        self.client.delete(TUNING_API_VERSION, STUDY_JOB_KIND, name,
                           namespace)


def make_server(app: StudyApp, port: int) -> ThreadingHTTPServer:
    class Handler(JsonHandler):
        def do_GET(self):
            if self.path in ("/healthz", "/readyz"):
                self.send_json(200, {"status": "ok"})
                return
            m = _RE_ITEM.match(self.path)
            if m:
                try:
                    self.send_json(200, app.get_study(m.group(1),
                                                      m.group(2)))
                except ApiError as e:
                    self.send_json(e.code, {"error": str(e)})
                return
            m = _RE_LIST.match(self.path)
            if m:
                try:
                    self.send_json(200,
                                   {"studies": app.list_studies(m.group(1))})
                except ApiError as e:
                    self.send_json(e.code, {"error": str(e)})
                return
            self.send_json(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            m = _RE_LIST.match(self.path)
            if not m:
                self.send_json(404, {"error": f"no route {self.path}"})
                return
            try:
                created = app.create_study(m.group(1), self.read_json())
                self.send_json(201, {"name": created["metadata"]["name"]})
            except ValueError as e:
                self.send_json(400, {"error": str(e)})
            except ApiError as e:
                self.send_json(e.code, {"error": str(e)})

        def do_DELETE(self):
            m = _RE_ITEM.match(self.path)
            if not m:
                self.send_json(404, {"error": f"no route {self.path}"})
                return
            try:
                app.delete_study(m.group(1), m.group(2))
                self.send_json(200, {"deleted": m.group(2)})
            except ApiError as e:
                self.send_json(e.code, {"error": str(e)})

    return ThreadingHTTPServer(("0.0.0.0", port), Handler)


def main(argv=None) -> int:
    argv = strip_glog_args(list(sys.argv[1:] if argv is None else argv))
    p = argparse.ArgumentParser(description="study web app")
    add_client_args(p)
    p.add_argument("--port", type=int, default=8089)
    args = p.parse_args(argv)

    httpd = make_server(StudyApp(client_from_args(args)), args.port)
    print(f"study web app on :{args.port}")
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
