"""Web apps: CRUD UIs over the platform CRDs (jupyter-web-app and the study
UI — components/jupyter-web-app/default/routes.py:33-168,
kubeflow/katib UI analogues). Served from the same http.server runtime as the
rest of the platform; each app exposes a JSON API plus a minimal HTML shell.
"""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler


class JsonHandler(BaseHTTPRequestHandler):
    """Shared helpers for JSON web-app handlers."""

    def log_message(self, *a):
        pass

    def send_json(self, code: int, payload) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def send_html(self, code: int, html: str) -> None:
        body = html.encode()
        self.send_response(code)
        self.send_header("Content-Type", "text/html; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def read_json(self) -> dict:
        length = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(length) or b"{}")
