"""RLJob CRD API — the train↔serve RL workload (Podracer-style).

ONE object declares both halves of an on-policy RL loop and the pipe
between them ("Podracer architectures for scalable RL", PAPERS.md):

- a **learner** gang: worker pods running the minimal RL learner loop
  (:mod:`kubeflow_tpu.train.rl`) — consumes actor rollouts through the
  PR-5 prefetcher and pushes fresh weights fleet-wide every K optimizer
  steps over the live weight-push path
  (:meth:`~kubeflow_tpu.serving.continuous.ContinuousDecoder.update_weights`);
- an **actor pool**: continuous-decoder replicas generating rollouts,
  elastic and PREEMPTIBLE by definition — losing an actor costs some
  rollout throughput, never correctness (the learner's stream is the
  actors' output, and the next weight push re-converges stragglers).

The RLJob operator (:mod:`kubeflow_tpu.operators.rl`) lowers the CR
into two scheduler-managed JaxJobs at different priorities, so the
PR-10 gang scheduler places the learner as an all-or-nothing gang and
treats the actor pool as elastic capacity it may shrink (PR-14) or
preempt before ever touching the learner.
"""

from __future__ import annotations

from typing import Mapping

from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.version import API_GROUP

RL_KIND = "RLJob"
RL_PLURAL = "rljobs"
RL_API_VERSION = f"{API_GROUP}/v1"

# Defaults the operator and validation share. The priority GAP is the
# contract: the learner outranks its own actors, so a squeezed cluster
# shrinks/preempts rollout capacity before it ever stalls learning.
DEFAULT_LEARNER_PRIORITY = 100
DEFAULT_ACTOR_PRIORITY = 0
DEFAULT_PUSH_EVERY_STEPS = 2
DEFAULT_WEIGHTS_MAX_LAG = 1


def rl_job_schema() -> dict:
    learner_schema = {
        "type": "object",
        "properties": {
            "replicas": {"type": "integer", "minimum": 1},
            "tpuChipsPerReplica": {"type": "integer", "minimum": 0},
            "priority": {"type": "integer"},
            "queue": {"type": "string"},
            "steps": {"type": "integer", "minimum": 1},
            "batchSize": {"type": "integer", "minimum": 1},
            "pushEverySteps": {"type": "integer", "minimum": 1},
            "optimizer": {"type": "object",
                          "x-kubernetes-preserve-unknown-fields": True},
        },
    }
    actors_schema = {
        "type": "object",
        "properties": {
            "replicas": {"type": "integer", "minimum": 1},
            "minReplicas": {"type": "integer", "minimum": 1},
            "maxReplicas": {"type": "integer", "minimum": 1},
            "tpuChipsPerReplica": {"type": "integer", "minimum": 0},
            "priority": {"type": "integer"},
            "queue": {"type": "string"},
            # tpu-serving engine knobs passed to each actor's model
            # server verbatim (kv_layout, speculative_k, tp_shards...).
            "engine": {"type": "object",
                       "x-kubernetes-preserve-unknown-fields": True},
        },
    }
    rollout_schema = {
        "type": "object",
        "properties": {
            "promptLen": {"type": "integer", "minimum": 1},
            "maxNewTokens": {"type": "integer", "minimum": 1},
        },
    }
    weights_schema = {
        "type": "object",
        "properties": {
            # Bounded version skew: actors lagging the fleet's weights
            # epoch by more than maxLag pushes leave the rollout
            # routing set until a later push lands on them.
            "maxLag": {"type": "integer", "minimum": 0},
            "chunkBytes": {"type": "integer", "minimum": 1},
        },
    }
    return {
        "type": "object",
        "properties": {
            "spec": {
                "type": "object",
                "required": ["model"],
                "properties": {
                    "model": {"type": "string"},
                    "image": {"type": "string"},
                    "tpu": {
                        "type": "object",
                        "properties": {
                            "accelerator": {"type": "string"},
                            "topology": {"type": "string"},
                        },
                    },
                    "learner": learner_schema,
                    "actors": actors_schema,
                    "rollout": rollout_schema,
                    "weights": weights_schema,
                },
            },
            "status": {"type": "object",
                       "x-kubernetes-preserve-unknown-fields": True},
        },
    }


def rl_job_crd() -> dict:
    return k8s.crd(
        group=API_GROUP,
        kind=RL_KIND,
        plural=RL_PLURAL,
        short_names=["rlj"],
        categories=["all", "kubeflow-tpu"],
        versions=[
            k8s.crd_version(
                "v1",
                schema=rl_job_schema(),
                storage=True,
                printer_columns=[
                    k8s.printer_column("Model", ".spec.model"),
                    k8s.printer_column("Phase", ".status.phase"),
                    k8s.printer_column("Weights",
                                       ".status.weightsVersion",
                                       "integer"),
                    k8s.printer_column("Age",
                                       ".metadata.creationTimestamp",
                                       "date"),
                ],
            )
        ],
    )


def rl_job(
    name: str,
    namespace: str,
    model: str,
    *,
    image: str = "",
    learner: dict | None = None,
    actors: dict | None = None,
    rollout: dict | None = None,
    weights: dict | None = None,
    tpu: dict | None = None,
) -> dict:
    """Build an RLJob CR. ``learner``/``actors``/``rollout``/``weights``
    override the schema blocks above; omitted fields take the operator
    defaults (1 learner at priority 100, 2 preemptible actors at
    priority 0, push every 2 steps, max weight lag 1)."""
    spec: dict = {"model": model}
    if image:
        spec["image"] = image
    if tpu:
        spec["tpu"] = dict(tpu)
    if learner:
        spec["learner"] = dict(learner)
    if actors:
        spec["actors"] = dict(actors)
    if rollout:
        spec["rollout"] = dict(rollout)
    if weights:
        spec["weights"] = dict(weights)
    return {
        "apiVersion": RL_API_VERSION,
        "kind": RL_KIND,
        "metadata": k8s.metadata(name, namespace, {"app": name}),
        "spec": spec,
    }


class RLJobValidationError(ValueError):
    pass


def validate_rl_job(job: Mapping) -> None:
    spec = job.get("spec", {})
    name = job.get("metadata", {}).get("name", "<unnamed>")
    if not spec.get("model"):
        raise RLJobValidationError(f"RLJob {name}: spec.model is required")
    learner = spec.get("learner") or {}
    actors = spec.get("actors") or {}
    lp = int(learner.get("priority", DEFAULT_LEARNER_PRIORITY))
    ap = int(actors.get("priority", DEFAULT_ACTOR_PRIORITY))
    if lp <= ap:
        # The whole design rests on this gap: actors must be the
        # capacity the scheduler reclaims FIRST.
        raise RLJobValidationError(
            f"RLJob {name}: learner priority {lp} must exceed actor "
            f"priority {ap} (actors are preemptible by definition)")
    reps = int(actors.get("replicas", 2))
    lo = int(actors.get("minReplicas", reps))
    hi = int(actors.get("maxReplicas", max(reps, lo)))
    if not 1 <= lo <= hi:
        raise RLJobValidationError(
            f"RLJob {name}: actor elastic range [{lo}, {hi}] invalid")
    if not lo <= reps <= hi:
        raise RLJobValidationError(
            f"RLJob {name}: actors.replicas {reps} outside "
            f"[{lo}, {hi}]")
    push_every = int(learner.get("pushEverySteps",
                                 DEFAULT_PUSH_EVERY_STEPS))
    if push_every < 1:
        raise RLJobValidationError(
            f"RLJob {name}: pushEverySteps must be >= 1")
    max_lag = int((spec.get("weights") or {}).get(
        "maxLag", DEFAULT_WEIGHTS_MAX_LAG))
    if max_lag < 0:
        raise RLJobValidationError(
            f"RLJob {name}: weights.maxLag must be >= 0")
