"""StudyJob CRD API — hyperparameter tuning.

Analogue of Katib's StudyJob CRD (kubeflow/katib/studyjobcontroller.libsonnet:14-38;
worker/metricsCollector templates :115-147, :351-400). A StudyJob declares an
objective, a parameter space, a suggestion algorithm, and a trial template
(a JaxJob); the study controller spawns trial jobs, collects metrics from
their status, and feeds results back to the suggestion service.
"""

from __future__ import annotations

from typing import Any, Mapping

from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.version import API_GROUP

STUDY_JOB_KIND = "StudyJob"
STUDY_JOB_PLURAL = "studyjobs"
TUNING_API_VERSION = f"{API_GROUP}/v1"

# Suggestion algorithms — parity with suggestion.libsonnet:3-10 (random, grid,
# hyperband, bayesianoptimization).
ALGORITHMS = ("random", "grid", "hyperband", "bayesianoptimization")

PARAM_TYPES = ("double", "int", "categorical", "discrete")

OPTIMIZATION_TYPES = ("maximize", "minimize")


def study_job_crd() -> dict:
    schema = {
        "type": "object",
        "properties": {
            "spec": {
                "type": "object",
                "properties": {
                    "objective": {
                        "type": "object",
                        "properties": {
                            "type": {"type": "string", "enum": list(OPTIMIZATION_TYPES)},
                            "objectiveMetricName": {"type": "string"},
                            "goal": {"type": "number"},
                        },
                    },
                    "algorithm": {"type": "string", "enum": list(ALGORITHMS)},
                    "parallelTrialCount": {"type": "integer", "minimum": 1},
                    "maxTrialCount": {"type": "integer", "minimum": 1},
                    "maxFailedTrialCount": {"type": "integer", "minimum": 0},
                    "parameters": {
                        "type": "array",
                        "items": {
                            "type": "object",
                            "properties": {
                                "name": {"type": "string"},
                                "parameterType": {"type": "string", "enum": list(PARAM_TYPES)},
                                "feasibleSpace": {
                                    "type": "object",
                                    "x-kubernetes-preserve-unknown-fields": True,
                                },
                            },
                        },
                    },
                    "trialTemplate": {
                        "type": "object",
                        "x-kubernetes-preserve-unknown-fields": True,
                    },
                },
            },
            "status": {"type": "object", "x-kubernetes-preserve-unknown-fields": True},
        },
    }
    return k8s.crd(
        group=API_GROUP,
        kind=STUDY_JOB_KIND,
        plural=STUDY_JOB_PLURAL,
        short_names=["study"],
        categories=["all", "kubeflow-tpu"],
        versions=[
            k8s.crd_version(
                "v1",
                schema=schema,
                storage=True,
                printer_columns=[
                    k8s.printer_column("State", ".status.state"),
                    k8s.printer_column("Best", ".status.bestObjectiveValue"),
                    k8s.printer_column("Trials", ".status.completedTrialCount", "integer"),
                ],
            )
        ],
    )


def study_job(
    name: str,
    namespace: str,
    objective_metric: str,
    parameters: list[dict],
    trial_template: Mapping[str, Any],
    algorithm: str = "random",
    optimization_type: str = "maximize",
    goal: float | None = None,
    parallel_trials: int = 2,
    max_trials: int = 10,
    max_failed_trials: int = 3,
) -> dict:
    objective: dict = {
        "type": optimization_type,
        "objectiveMetricName": objective_metric,
    }
    if goal is not None:
        objective["goal"] = goal
    return {
        "apiVersion": TUNING_API_VERSION,
        "kind": STUDY_JOB_KIND,
        "metadata": k8s.metadata(name, namespace),
        "spec": {
            "objective": objective,
            "algorithm": algorithm,
            "parallelTrialCount": parallel_trials,
            "maxTrialCount": max_trials,
            "maxFailedTrialCount": max_failed_trials,
            "parameters": list(parameters),
            "trialTemplate": dict(trial_template),
        },
    }


def double_param(name: str, min_val: float, max_val: float, log_scale: bool = False) -> dict:
    return {
        "name": name,
        "parameterType": "double",
        "feasibleSpace": {"min": min_val, "max": max_val, "logScale": log_scale},
    }


def int_param(name: str, min_val: int, max_val: int) -> dict:
    return {
        "name": name,
        "parameterType": "int",
        "feasibleSpace": {"min": min_val, "max": max_val},
    }


def categorical_param(name: str, choices: list) -> dict:
    return {
        "name": name,
        "parameterType": "categorical",
        "feasibleSpace": {"list": list(choices)},
    }
