"""Profile CRD API — multi-tenancy.

Analogue of the reference's Profile CRD
(components/profile-controller/pkg/apis/kubeflow/v1alpha1, reconciled at
profile_controller.go:108-206): a cluster-scoped CR per user that the
controller expands into a namespace + namespaced-admin Role + RoleBinding for
the owner.
"""

from __future__ import annotations

from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.version import API_GROUP

PROFILE_KIND = "Profile"
PROFILE_PLURAL = "profiles"
PROFILES_API_VERSION = f"{API_GROUP}/v1"


def profile_crd() -> dict:
    schema = {
        "type": "object",
        "properties": {
            "spec": {
                "type": "object",
                "properties": {
                    "owner": {
                        "type": "object",
                        "properties": {
                            "kind": {"type": "string"},
                            "name": {"type": "string"},
                        },
                    },
                    "resourceQuota": {
                        "type": "object",
                        "x-kubernetes-preserve-unknown-fields": True,
                    },
                },
            },
            "status": {"type": "object", "x-kubernetes-preserve-unknown-fields": True},
        },
    }
    return k8s.crd(
        group=API_GROUP,
        kind=PROFILE_KIND,
        plural=PROFILE_PLURAL,
        scope="Cluster",
        categories=["kubeflow-tpu"],
        versions=[
            k8s.crd_version(
                "v1",
                schema=schema,
                storage=True,
                printer_columns=[k8s.printer_column("State", ".status.state")],
            )
        ],
    )


def profile(name: str, owner_name: str, owner_kind: str = "User",
            quota: dict | None = None) -> dict:
    spec: dict = {"owner": {"kind": owner_kind, "name": owner_name}}
    if quota:
        spec["resourceQuota"] = quota
    return {
        "apiVersion": PROFILES_API_VERSION,
        "kind": PROFILE_KIND,
        "metadata": k8s.metadata(name),
        "spec": spec,
    }
