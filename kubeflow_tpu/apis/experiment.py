"""Experiment CRD API — closed-loop knob search against serving SLOs.

Where a StudyJob (apis/tuning.py) tunes an arbitrary trial template, an
Experiment is specialised for the serving engine: it names a registered
bench_serving scenario (serving/scenarios.py), a knob space drawn from
the engine's KNOB_CATALOG, and a search algorithm; the controller runs
measured trials, reads objectives from the histogram exposition via the
autoscaler's scrape_signals path, and ships the winner through the
rollout controller as a candidate version.

Analogue of Katib's Experiment layered over kubebench-style measured
runs (kubeflow/katib studyjobcontroller.libsonnet + kubebench job
templates) — here both halves are one CRD.
"""

from __future__ import annotations

from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.version import API_GROUP

EXPERIMENT_KIND = "Experiment"
EXPERIMENT_PLURAL = "experiments"
EXPERIMENT_API_VERSION = f"{API_GROUP}/v1"

# Superset of the StudyJob algorithms: tpe and the median early-stop
# policy were added for experiments (tuning/suggestions.py).
ALGORITHMS = ("random", "grid", "hyperband", "bayesianoptimization", "tpe")

OPTIMIZATION_TYPES = ("maximize", "minimize")

# Objective metrics every trial reports — the scrape_signals vector plus
# throughput and KV footprint (serving/scenarios.py trial_objectives).
OBJECTIVE_METRICS = (
    "tokens_per_sec",
    "ttft_p99_s",
    "inter_token_p99_s",
    "queue_wait_p99_s",
    "kv_utilization",
    "kv_bytes_peak",
)

TRIAL_MODES = ("inprocess", "job")

# Engine knob catalog: the tunable constants the serving stack exposes,
# with safe ranges. Experiments validate their parameter space against
# this; docs/tuning.md renders it. Ranges are conservative — a knob can
# be legal outside its safe range, but an Experiment won't propose it.
KNOB_CATALOG: dict[str, dict] = {
    "slots": {
        "type": "int", "min": 1, "max": 64,
        "description": "continuous-batching slot count (decode width)",
    },
    "kv_block_size": {
        "type": "int", "min": 4, "max": 128,
        "description": "paged-KV block size in tokens; must divide the "
                       "virtual row width (prefill_len + max_new_tokens)",
    },
    "prefill_len_buckets": {
        "type": "int", "min": 0, "max": 8,
        "description": "number of padded prefill length buckets "
                       "(0 = single worst-case width)",
    },
    "speculative_k": {
        "type": "int", "min": 0, "max": 8,
        "description": "draft tokens per speculative step (0 = off)",
    },
    "prefill_chunk_tokens": {
        "type": "int", "min": 64, "max": 4096,
        "description": "chunked-prefill slice width interleaved with decode",
    },
    "prefix_cache_slots": {
        "type": "int", "min": 0, "max": 256,
        "description": "prefix-cache capacity in cached prefixes",
    },
    "kv_import_crossover_tokens": {
        "type": "int", "min": 16, "max": 8192,
        "description": "prefix length above which importing peer KV beats "
                       "recomputing prefill",
    },
    "queue_depth_target": {
        "type": "double", "min": 0.5, "max": 32.0,
        "description": "autoscaler queued-requests-per-replica target",
    },
}


def validate_knobs(parameters: list[dict]) -> list[dict]:
    """Check a katib-style parameter list against the knob catalog.

    Unknown knobs are allowed (scenarios may expose scenario-local
    parameters), but a knob present in the catalog must stay inside its
    safe range.
    """
    for p in parameters:
        entry = KNOB_CATALOG.get(p.get("name", ""))
        if entry is None:
            continue
        space = p.get("feasibleSpace", {})
        lo, hi = space.get("min"), space.get("max")
        if lo is not None and float(lo) < float(entry["min"]):
            raise ValueError(
                f"knob {p['name']!r} min {lo} below safe range "
                f">= {entry['min']}")
        if hi is not None and float(hi) > float(entry["max"]):
            raise ValueError(
                f"knob {p['name']!r} max {hi} above safe range "
                f"<= {entry['max']}")
    return parameters


def experiment_crd() -> dict:
    schema = {
        "type": "object",
        "properties": {
            "spec": {
                "type": "object",
                "required": ["scenario"],
                "properties": {
                    "scenario": {"type": "string"},
                    "objective": {
                        "type": "object",
                        "properties": {
                            "type": {
                                "type": "string",
                                "enum": list(OPTIMIZATION_TYPES),
                            },
                            "objectiveMetricName": {
                                "type": "string",
                                "enum": list(OBJECTIVE_METRICS),
                            },
                            "goal": {"type": "number"},
                        },
                    },
                    "algorithm": {
                        "type": "string", "enum": list(ALGORITHMS),
                    },
                    "parameters": {
                        "type": "array",
                        "items": {
                            "type": "object",
                            "properties": {
                                "name": {"type": "string"},
                                "parameterType": {"type": "string"},
                                "feasibleSpace": {
                                    "type": "object",
                                    "x-kubernetes-preserve-unknown-fields":
                                        True,
                                },
                            },
                        },
                    },
                    "parallelTrialCount": {"type": "integer", "minimum": 1},
                    "maxTrialCount": {"type": "integer", "minimum": 1},
                    "maxFailedTrialCount": {"type": "integer", "minimum": 0},
                    "seed": {"type": "integer", "minimum": 0},
                    "trialMode": {
                        "type": "string", "enum": list(TRIAL_MODES),
                    },
                    "earlyStop": {
                        "type": "object",
                        "properties": {
                            "policy": {
                                "type": "string", "enum": ["median"],
                            },
                            "minTrials": {"type": "integer", "minimum": 1},
                        },
                    },
                    "promotion": {
                        "type": "object",
                        "properties": {
                            "target": {"type": "string"},
                            "minImprovementPercent": {"type": "number"},
                        },
                    },
                },
            },
            "status": {
                "type": "object",
                "x-kubernetes-preserve-unknown-fields": True,
            },
        },
    }
    return k8s.crd(
        group=API_GROUP,
        kind=EXPERIMENT_KIND,
        plural=EXPERIMENT_PLURAL,
        short_names=["exp"],
        categories=["all", "kubeflow-tpu"],
        versions=[
            k8s.crd_version(
                "v1",
                schema=schema,
                storage=True,
                printer_columns=[
                    k8s.printer_column("State", ".status.state"),
                    k8s.printer_column("Scenario", ".spec.scenario"),
                    k8s.printer_column("Best", ".status.bestObjectiveValue"),
                    k8s.printer_column(
                        "Trials", ".status.completedTrialCount", "integer"),
                ],
            )
        ],
    )


def experiment(
    name: str,
    namespace: str,
    scenario: str,
    *,
    parameters: list[dict] | None = None,
    objective_metric: str = "tokens_per_sec",
    optimization_type: str = "maximize",
    goal: float | None = None,
    algorithm: str = "tpe",
    parallel_trials: int = 2,
    max_trials: int = 12,
    max_failed_trials: int = 3,
    seed: int = 0,
    trial_mode: str = "inprocess",
    early_stop: dict | None = None,
    promotion: dict | None = None,
) -> dict:
    if algorithm not in ALGORITHMS:
        raise ValueError(
            f"unknown algorithm {algorithm!r}; available {ALGORITHMS}")
    if objective_metric not in OBJECTIVE_METRICS:
        raise ValueError(
            f"unknown objective metric {objective_metric!r}; "
            f"available {OBJECTIVE_METRICS}")
    if trial_mode not in TRIAL_MODES:
        raise ValueError(
            f"unknown trial mode {trial_mode!r}; available {TRIAL_MODES}")
    objective: dict = {
        "type": optimization_type,
        "objectiveMetricName": objective_metric,
    }
    if goal is not None:
        objective["goal"] = goal
    spec: dict = {
        "scenario": scenario,
        "objective": objective,
        "algorithm": algorithm,
        "parallelTrialCount": parallel_trials,
        "maxTrialCount": max_trials,
        "maxFailedTrialCount": max_failed_trials,
        "seed": seed,
        "trialMode": trial_mode,
    }
    if parameters is not None:
        spec["parameters"] = validate_knobs(list(parameters))
    if early_stop is not None:
        spec["earlyStop"] = dict(early_stop)
    if promotion is not None:
        spec["promotion"] = dict(promotion)
    return {
        "apiVersion": EXPERIMENT_API_VERSION,
        "kind": EXPERIMENT_KIND,
        "metadata": k8s.metadata(name, namespace),
        "spec": spec,
    }
