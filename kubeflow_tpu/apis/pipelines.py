"""Pipeline/workflow and Application CRD API types.

The workflow layer the reference gets from Argo + KFP (kubeflow/argo/
argo.libsonnet:89-165; kubeflow/pipeline/*.libsonnet) recast as one
TPU-native CRD: a ``Workflow`` is a DAG of tasks, each task creating one
Kubernetes object (typically a training-job CR or a serving Deployment) once
its dependencies have succeeded. The ``Application`` CR is the deployed-
platform aggregation object (kubeflow/application/application.libsonnet:
14-60): a label selector plus component-kind list whose status mirrors the
readiness of everything it matches.
"""

from __future__ import annotations

from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.version import API_GROUP

PIPELINES_API_VERSION = f"{API_GROUP}/v1"

WORKFLOW_KIND = "Workflow"
WORKFLOW_PLURAL = "workflows"
SCHEDULED_WORKFLOW_KIND = "ScheduledWorkflow"
SCHEDULED_WORKFLOW_PLURAL = "scheduledworkflows"
APPLICATION_KIND = "Application"
APPLICATION_PLURAL = "applications"

# Workflow/task phases (argo's workflow phase surface).
PHASE_PENDING = "Pending"
PHASE_RUNNING = "Running"
PHASE_SUCCEEDED = "Succeeded"
PHASE_FAILED = "Failed"


def workflow_schema() -> dict:
    task = {
        "type": "object",
        "required": ["name", "resource"],
        "properties": {
            "name": {"type": "string", "minLength": 1},
            "dependencies": {
                "type": "array", "items": {"type": "string"},
            },
            # Failed task resources are deleted and recreated up to this
            # many times with exponential backoff (the argo per-step
            # retryStrategy surface, argo.libsonnet workflow-controller).
            "retries": {"type": "integer", "minimum": 0},
            "retryBackoffSeconds": {"type": "number", "minimum": 0},
            # Declared outputs: files/directories the task writes under
            # its injected KUBEFLOW_ARTIFACT_DIR. On success the
            # controller indexes each into the durable run record as an
            # artifact://ns/workflow/task/name URI; a missing declared
            # output fails the task (the KFP output-artifact contract,
            # minio.libsonnet + pipeline-persistenceagent.libsonnet).
            "outputs": {
                "type": "array",
                "items": {
                    "type": "object",
                    "required": ["name"],
                    "properties": {
                        "name": {"type": "string", "minLength": 1},
                        # Path relative to KUBEFLOW_ARTIFACT_DIR;
                        # defaults to the output name.
                        "path": {"type": "string"},
                    },
                },
            },
            # The object this task creates, verbatim (a job CR, a
            # Deployment, ...). Ownership and completion tracking are the
            # controller's job; kind/apiVersion are required here so a
            # malformed resource is rejected at admission, not discovered
            # as a wedged Running workflow.
            "resource": {
                "type": "object",
                "required": ["apiVersion", "kind"],
                "properties": {
                    "apiVersion": {"type": "string", "minLength": 1},
                    "kind": {"type": "string", "minLength": 1},
                },
                "x-kubernetes-preserve-unknown-fields": True,
            },
        },
    }
    return {
        "type": "object",
        "properties": {
            "spec": {
                "type": "object",
                "required": ["tasks"],
                "properties": {
                    "tasks": {"type": "array", "items": task, "minItems": 1},
                },
            },
            "status": {
                "type": "object",
                "x-kubernetes-preserve-unknown-fields": True,
            },
        },
    }


def workflow_crd() -> dict:
    return k8s.crd(
        group=API_GROUP,
        kind=WORKFLOW_KIND,
        plural=WORKFLOW_PLURAL,
        short_names=["wf"],
        categories=["all", "kubeflow-tpu"],
        versions=[
            k8s.crd_version(
                "v1",
                schema=workflow_schema(),
                served=True,
                storage=True,
                printer_columns=[
                    k8s.printer_column("Phase", ".status.phase"),
                    k8s.printer_column(
                        "Age", ".metadata.creationTimestamp", "date"
                    ),
                ],
            )
        ],
    )


def scheduled_workflow_schema() -> dict:
    return {
        "type": "object",
        "properties": {
            "spec": {
                "type": "object",
                "required": ["schedule", "workflowSpec"],
                "properties": {
                    # Standard 5-field cron, evaluated in UTC.
                    "schedule": {"type": "string", "minLength": 1},
                    "suspend": {"type": "boolean"},
                    # Runs in flight at once; further fire times are
                    # skipped (not queued) while at the limit.
                    "maxConcurrency": {"type": "integer", "minimum": 1},
                    # Completed stamped Workflows retained per schedule;
                    # run *records* (ConfigMap store) are pruned to this
                    # count too. 0 = keep everything.
                    "historyLimit": {"type": "integer", "minimum": 0},
                    "workflowSpec": workflow_schema()["properties"]["spec"],
                },
            },
            "status": {
                "type": "object",
                "x-kubernetes-preserve-unknown-fields": True,
            },
        },
    }


def scheduled_workflow_crd() -> dict:
    return k8s.crd(
        group=API_GROUP,
        kind=SCHEDULED_WORKFLOW_KIND,
        plural=SCHEDULED_WORKFLOW_PLURAL,
        short_names=["swf"],
        categories=["all", "kubeflow-tpu"],
        versions=[
            k8s.crd_version(
                "v1",
                schema=scheduled_workflow_schema(),
                served=True,
                storage=True,
                printer_columns=[
                    k8s.printer_column("Schedule", ".spec.schedule"),
                    k8s.printer_column(
                        "LastRun", ".status.lastScheduleTime"
                    ),
                    k8s.printer_column("Runs", ".status.runsStarted"),
                ],
            )
        ],
    )


def application_schema() -> dict:
    return {
        "type": "object",
        "properties": {
            "spec": {
                "type": "object",
                "properties": {
                    "selector": {
                        "type": "object",
                        "properties": {
                            "matchLabels": {
                                "type": "object",
                                "x-kubernetes-preserve-unknown-fields": True,
                            },
                        },
                    },
                    "componentKinds": {
                        "type": "array",
                        "items": {
                            "type": "object",
                            "required": ["kind"],
                            "properties": {
                                "group": {"type": "string"},
                                "kind": {"type": "string"},
                            },
                        },
                    },
                    "descriptor": {
                        "type": "object",
                        "x-kubernetes-preserve-unknown-fields": True,
                    },
                },
            },
            "status": {
                "type": "object",
                "x-kubernetes-preserve-unknown-fields": True,
            },
        },
    }


def application_crd() -> dict:
    return k8s.crd(
        group=API_GROUP,
        kind=APPLICATION_KIND,
        plural=APPLICATION_PLURAL,
        short_names=["app"],
        categories=["all", "kubeflow-tpu"],
        versions=[
            k8s.crd_version(
                "v1",
                schema=application_schema(),
                served=True,
                storage=True,
                printer_columns=[
                    k8s.printer_column(
                        "Assembly", ".status.assemblyPhase"
                    ),
                    k8s.printer_column("Ready", ".status.componentsReady"),
                ],
            )
        ],
    )


# ---------------------------------------------------------------------------
# Workflow DAG validation
# ---------------------------------------------------------------------------


def toposort_tasks(tasks: list[dict]) -> list[str]:
    """Task names in dependency order. Raises ValueError on duplicate names,
    unknown dependencies, or cycles — checked at admission and again by the
    controller (the CRD schema can't express graph invariants)."""
    names = [t["name"] for t in tasks]
    if len(set(names)) != len(names):
        dupes = sorted({n for n in names if names.count(n) > 1})
        raise ValueError(f"duplicate task names: {dupes}")
    deps = {t["name"]: list(t.get("dependencies", [])) for t in tasks}
    for name, ds in deps.items():
        unknown = [d for d in ds if d not in deps]
        if unknown:
            raise ValueError(f"task {name!r} depends on unknown {unknown}")
    order: list[str] = []
    state: dict[str, int] = {}  # 0 visiting, 1 done

    def visit(name: str, chain: tuple) -> None:
        if state.get(name) == 1:
            return
        if state.get(name) == 0:
            cycle = chain[chain.index(name):] + (name,)
            raise ValueError(f"dependency cycle: {' -> '.join(cycle)}")
        state[name] = 0
        for d in deps[name]:
            visit(d, chain + (name,))
        state[name] = 1
        order.append(name)

    for name in deps:
        visit(name, ())
    return order
