"""Training-job CRD API types.

The platform's job API family — the TPU-native equivalents of the reference's
five training CRDs (SURVEY.md §2.2):

- ``JaxJob``  — the native kind: SPMD JAX workers gang-scheduled onto a TPU
  slice, rendezvous via a JAX coordinator (replaces TFJob's PS/Worker +
  TF_CONFIG model, kubeflow/tf-training/tf-job-operator.libsonnet:10-96).
- ``TFJob``, ``PyTorchJob``, ``MXNetJob``, ``ChainerJob``, ``MPIJob`` —
  compatibility kinds with the reference's replica-type surfaces, lowered by
  their controllers onto the same gang-scheduling core.

All kinds share the replicaSpecs/runPolicy/status-conditions shape the
reference operators converged on, with a ``tpu`` block replacing
nvidia.com/gpu counts (e.g. pytorch-job.jsonnet:26-32).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Mapping, Sequence

from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.version import API_GROUP, DEFAULT_NAMESPACE

JOBS_API_VERSION = f"{API_GROUP}/v1"
# Deprecated-but-served compatibility version: replicaSpecs is a LIST of
# {replicaType, ...} entries (the reference's earlier training API shape).
# Storage stays at v1; the apiserver converts on read/write both ways
# (tf-job-operator.libsonnet:52-97's store-one/serve-both model).
JOBS_API_V1BETA1 = f"{API_GROUP}/v1beta1"

# ---------------------------------------------------------------------------
# Replica types per job kind (reference CRD validation properties, e.g.
# tf-job-operator.libsonnet:61-96 restricts PS/Worker/Chief/Master/Eval)
# ---------------------------------------------------------------------------

JAX_JOB_KIND = "JaxJob"
TF_JOB_KIND = "TFJob"
PYTORCH_JOB_KIND = "PyTorchJob"
MXNET_JOB_KIND = "MXNetJob"
CHAINER_JOB_KIND = "ChainerJob"
MPI_JOB_KIND = "MPIJob"

REPLICA_TYPES: dict[str, tuple[str, ...]] = {
    JAX_JOB_KIND: ("Worker",),
    TF_JOB_KIND: ("Chief", "PS", "Worker", "Evaluator"),
    PYTORCH_JOB_KIND: ("Master", "Worker"),
    MXNET_JOB_KIND: ("Scheduler", "Server", "Worker"),
    CHAINER_JOB_KIND: ("Master", "Worker"),
    MPI_JOB_KIND: ("Launcher", "Worker"),
}

# Replica types limited to at most one replica (Chief max 1:
# tf-job-operator.libsonnet:66-70).
SINGLETON_REPLICA_TYPES = {"Chief", "Master", "Scheduler", "Launcher"}

PLURALS: dict[str, str] = {
    JAX_JOB_KIND: "jaxjobs",
    TF_JOB_KIND: "tfjobs",
    PYTORCH_JOB_KIND: "pytorchjobs",
    MXNET_JOB_KIND: "mxnetjobs",
    CHAINER_JOB_KIND: "chainerjobs",
    MPI_JOB_KIND: "mpijobs",
}

ALL_JOB_KINDS = tuple(PLURALS)

# Condition types (mirrors the operator status contract asserted by
# testing/tf_job_simple_test.py:91 and printed via the CRD printer column
# tf-job-operator.libsonnet:70-81).
COND_CREATED = "Created"
COND_RUNNING = "Running"
COND_RESTARTING = "Restarting"
COND_SUCCEEDED = "Succeeded"
COND_FAILED = "Failed"

RESTART_POLICIES = ("Always", "OnFailure", "Never", "ExitCode")
CLEAN_POD_POLICIES = ("Running", "All", "None")

# Env vars the controller injects into every worker pod — the TF_CONFIG
# analogue (launcher.py:69-81) recast for `jax.distributed.initialize`.
ENV_COORDINATOR_ADDRESS = "JAX_COORDINATOR_ADDRESS"
ENV_NUM_PROCESSES = "JAX_NUM_PROCESSES"
ENV_PROCESS_ID = "JAX_PROCESS_ID"
ENV_SLICE_ID = "MEGASCALE_SLICE_ID"
ENV_NUM_SLICES = "MEGASCALE_NUM_SLICES"
ENV_COORDINATOR_PORT = "JAX_COORDINATOR_PORT"
ENV_TPU_TOPOLOGY = "TPU_TOPOLOGY"
ENV_TPU_ACCELERATOR = "TPU_ACCELERATOR_TYPE"
ENV_TPU_WORKER_HOSTNAMES = "TPU_WORKER_HOSTNAMES"

DEFAULT_COORDINATOR_PORT = 8476

# Identity of the owning job, injected into every worker so the training
# loop can publish final metrics to the job status (the path the study/
# benchmark controllers read — the metricsCollector-CronJob analogue,
# kubeflow/katib/studyjobcontroller.libsonnet:115-147).
ENV_JOB_NAME = "KUBEFLOW_TPU_JOB_NAME"
ENV_JOB_NAMESPACE = "KUBEFLOW_TPU_JOB_NAMESPACE"
ENV_JOB_KIND = "KUBEFLOW_TPU_JOB_KIND"

TPU_RESOURCE = "google.com/tpu"


def tpu_resources(chips: int) -> dict | None:
    """Pod resources block requesting TPU chips; None when chips == 0 (CPU).

    The analogue of the reference's `numGpus` → nvidia.com/gpu limits
    expansion (kubeflow/pytorch-job/prototypes/pytorch-job.jsonnet:26-32)."""
    if not chips:
        return None
    return {
        "limits": {TPU_RESOURCE: chips},
        "requests": {TPU_RESOURCE: chips},
    }


@dataclass(frozen=True)
class Condition:
    type: str
    status: str  # "True" | "False" | "Unknown"
    reason: str = ""
    message: str = ""
    last_transition_time: str = ""

    def to_dict(self) -> dict:
        return {
            "type": self.type,
            "status": self.status,
            "reason": self.reason,
            "message": self.message,
            "lastTransitionTime": self.last_transition_time,
        }


# ---------------------------------------------------------------------------
# Validation schema shared by all job kinds
# ---------------------------------------------------------------------------


def _replica_spec_schema(replica_types: Sequence[str]) -> dict:
    props = {}
    for rt in replica_types:
        max_replicas = 1 if rt in SINGLETON_REPLICA_TYPES else None
        replicas: dict = {"type": "integer", "minimum": 0}
        if max_replicas is not None:
            replicas["maximum"] = max_replicas
        props[rt] = {
            "type": "object",
            "properties": {
                "replicas": replicas,
                "restartPolicy": {"type": "string", "enum": list(RESTART_POLICIES)},
                "template": {"type": "object", "x-kubernetes-preserve-unknown-fields": True},
            },
        }
    return {"type": "object", "properties": props}


def _replica_list_schema(replica_types: Sequence[str]) -> dict:
    """The v1beta1 shape: replicaSpecs as a LIST of entries carrying
    ``replicaType`` — the reference's early training API
    (tf-job-operator.libsonnet:52-97 serves the old list shape alongside
    the newer map while storing one of them)."""
    return {
        "type": "array",
        "items": {
            "type": "object",
            "required": ["replicaType"],
            "properties": {
                "replicaType": {"type": "string",
                                "enum": list(replica_types)},
                "replicas": {"type": "integer", "minimum": 0},
                "restartPolicy": {"type": "string",
                                  "enum": list(RESTART_POLICIES)},
                "template": {"type": "object",
                             "x-kubernetes-preserve-unknown-fields": True},
            },
        },
    }


def job_schema(kind: str, *, api_version: str | None = None) -> dict:
    list_shape = api_version == JOBS_API_V1BETA1
    return {
        "type": "object",
        "properties": {
            "spec": {
                "type": "object",
                "properties": {
                    "replicaSpecs": (
                        _replica_list_schema(REPLICA_TYPES[kind])
                        if list_shape
                        else _replica_spec_schema(REPLICA_TYPES[kind])),
                    "tpu": {
                        "type": "object",
                        "properties": {
                            "accelerator": {"type": "string"},
                            "topology": {"type": "string"},
                            "numSlices": {"type": "integer", "minimum": 1},
                        },
                    },
                    "runPolicy": {
                        "type": "object",
                        "properties": {
                            "cleanPodPolicy": {
                                "type": "string",
                                "enum": list(CLEAN_POD_POLICIES),
                            },
                            "backoffLimit": {"type": "integer", "minimum": 0},
                            "activeDeadlineSeconds": {"type": "integer", "minimum": 1},
                            "ttlSecondsAfterFinished": {"type": "integer", "minimum": 0},
                        },
                    },
                    # Cluster-scheduler fields (apis/scheduling.py): a
                    # priority or queue opts the job into scheduler-managed
                    # gang placement; profile names a measured-throughput
                    # entry for heterogeneity-aware pool choice.
                    "priority": {"type": "integer"},
                    "queue": {"type": "string"},
                    "profile": {"type": "string"},
                    "preemptible": {"type": "boolean"},
                    # Elastic host range (scheduler-managed jobs): the
                    # grant may move inside [minReplicas, maxReplicas]
                    # while the job runs — the scheduler shrinks it to
                    # seat a queued gang (instead of evicting) and grows
                    # it into idle capacity; workers reshard live at the
                    # next step boundary (train/elastic.py). minReplicas
                    # must cover the gang's pod count: processes are
                    # fixed for the job's life, only the accelerator
                    # grant above them is elastic.
                    "elastic": {
                        "type": "object",
                        "required": ["minReplicas", "maxReplicas"],
                        "properties": {
                            "minReplicas": {"type": "integer",
                                            "minimum": 1},
                            "maxReplicas": {"type": "integer",
                                            "minimum": 1},
                        },
                    },
                },
                "x-kubernetes-preserve-unknown-fields": True,
            },
            "status": {"type": "object", "x-kubernetes-preserve-unknown-fields": True},
        },
    }


def job_crd(kind: str, *, conversion_namespace: str = DEFAULT_NAMESPACE,
            conversion_ca_bundle: str = "") -> dict:
    """CRD for one job kind, with the reference's printer-column surface
    (tf-job-operator.libsonnet:70-81: State + Age columns) and its
    multi-version story (ibid:52-97): ``v1`` is served AND stored;
    ``v1beta1`` (the list-shaped replicaSpecs of the earlier API) stays
    served-but-deprecated so existing clients keep working while the
    platform evolves the schema."""
    def printer_columns() -> list[dict]:
        # Fresh dicts per version — shared objects render as YAML
        # anchors/aliases in the deployable manifest.
        return [
            k8s.printer_column("State", ".status.state"),
            k8s.printer_column("Age", ".metadata.creationTimestamp",
                               "date"),
        ]

    v1beta1 = k8s.crd_version(
        "v1beta1",
        schema=job_schema(kind, api_version=JOBS_API_V1BETA1),
        served=True,
        storage=False,
        printer_columns=printer_columns(),
    )
    v1beta1["deprecated"] = True
    v1beta1["deprecationWarning"] = (
        f"{API_GROUP}/v1beta1 {kind} is deprecated; use {JOBS_API_VERSION}"
    )
    return k8s.crd(
        group=API_GROUP,
        kind=kind,
        plural=PLURALS[kind],
        short_names=[kind.lower().replace("job", "j")],
        categories=["all", "kubeflow-tpu"],
        versions=[
            k8s.crd_version(
                "v1",
                schema=job_schema(kind),
                served=True,
                storage=True,
                printer_columns=printer_columns(),
            ),
            v1beta1,
        ],
        # A real apiserver needs the webhook to convert between the two
        # shapes; the platform's webhook serves /convert with the same
        # convert_job registered below (the fake converts in-process).
        # ``conversion_ca_bundle`` carries the trust root for the
        # webhook's serving cert — deployments render it from the
        # platform Issuer's status.caCertificate (the Certificate CR
        # issues the webhook cert); empty is only valid for the
        # in-process fake, which never dials the webhook.
        conversion=k8s.crd_conversion_webhook(
            "admission-webhook", conversion_namespace,
            ca_bundle=conversion_ca_bundle),
    )


def all_job_crds(*, conversion_namespace: str = DEFAULT_NAMESPACE,
                 conversion_ca_bundle: str = "") -> list[dict]:
    return [job_crd(kind, conversion_namespace=conversion_namespace,
                    conversion_ca_bundle=conversion_ca_bundle)
            for kind in ALL_JOB_KINDS]


# ---------------------------------------------------------------------------
# Version conversion (the apiserver's store-v1/serve-both machinery)
# ---------------------------------------------------------------------------


def convert_job(job: dict, to_api_version: str) -> dict:
    """Convert a job between ``v1`` (replicaSpecs as a map keyed by
    replica type) and ``v1beta1`` (a list of entries carrying
    ``replicaType``). Lossless both ways; every other field — tpu,
    runPolicy, status — passes through unchanged."""
    import copy

    if job.get("apiVersion") == to_api_version:
        return job
    out = copy.deepcopy(job)
    out["apiVersion"] = to_api_version
    spec = out.get("spec")
    if not isinstance(spec, dict):
        return out
    rs = spec.get("replicaSpecs")
    if to_api_version == JOBS_API_VERSION and isinstance(rs, list):
        bad = [e for e in rs
               if not (isinstance(e, dict) and "replicaType" in e)]
        if bad:
            # Dropping a malformed entry would store less than the
            # client wrote — fail the conversion loudly, like the
            # duplicate check below.
            from kubeflow_tpu.k8s.client import ApiError

            raise ApiError.invalid(
                f"{job.get('kind')}: replicaSpecs entries must be "
                f"objects with a replicaType")
        entries = rs
        types = [e["replicaType"] for e in entries]
        if len(set(types)) != len(types):
            # Silently keeping the last duplicate would store something
            # the client never wrote — fail the conversion loudly.
            from kubeflow_tpu.k8s.client import ApiError

            raise ApiError.invalid(
                f"{job.get('kind')}: duplicate replicaType entries "
                f"{sorted(t for t in types if types.count(t) > 1)}")
        spec["replicaSpecs"] = {
            e["replicaType"]: {k: v for k, v in e.items()
                               if k != "replicaType"}
            for e in entries
        }
    elif to_api_version == JOBS_API_V1BETA1 and isinstance(rs, dict):
        spec["replicaSpecs"] = [
            {"replicaType": rt, **r} for rt, r in sorted(rs.items())
        ]
    return out


# Self-register with the client layer so any apiserver (fake or HTTP
# frontend) that sees these kinds converts with the real schema mapping.
from kubeflow_tpu.k8s.client import register_converter as _register  # noqa: E402

for _kind in ALL_JOB_KINDS:
    _register(_kind, convert_job)


# ---------------------------------------------------------------------------
# Spec validation used by controllers and the webhook
# ---------------------------------------------------------------------------


class JobValidationError(ValueError):
    pass


def validate_job(job: Mapping) -> None:
    kind = job.get("kind", "")
    if kind not in REPLICA_TYPES:
        raise JobValidationError(f"unknown job kind {kind!r}")
    spec = job.get("spec", {})
    replica_specs = spec.get("replicaSpecs", {})
    if not replica_specs:
        raise JobValidationError(
            f"{kind} {job['metadata'].get('name')}: spec.replicaSpecs is empty"
        )
    allowed = REPLICA_TYPES[kind]
    for rt, rspec in replica_specs.items():
        if rt not in allowed:
            raise JobValidationError(
                f"{kind}: replica type {rt!r} not in {allowed}"
            )
        replicas = rspec.get("replicas", 1)
        if not isinstance(replicas, int) or replicas < 0:
            raise JobValidationError(f"{kind}/{rt}: invalid replicas {replicas!r}")
        if rt in SINGLETON_REPLICA_TYPES and replicas > 1:
            raise JobValidationError(f"{kind}/{rt}: at most 1 replica allowed")
        rp = rspec.get("restartPolicy")
        if rp is not None and rp not in RESTART_POLICIES:
            raise JobValidationError(f"{kind}/{rt}: invalid restartPolicy {rp!r}")
        tmpl = rspec.get("template", {})
        if not tmpl.get("spec", {}).get("containers"):
            raise JobValidationError(f"{kind}/{rt}: template has no containers")
    rp = spec.get("runPolicy", {})
    cpp = rp.get("cleanPodPolicy")
    if cpp is not None and cpp not in CLEAN_POD_POLICIES:
        raise JobValidationError(f"{kind}: invalid cleanPodPolicy {cpp!r}")
    priority = spec.get("priority")
    if priority is not None and not isinstance(priority, int):
        raise JobValidationError(f"{kind}: priority must be an integer")
    queue = spec.get("queue")
    if queue is not None and not isinstance(queue, str):
        raise JobValidationError(f"{kind}: queue must be a string")
    elastic = spec.get("elastic")
    if elastic is not None:
        if not isinstance(elastic, Mapping):
            raise JobValidationError(f"{kind}: elastic must be an object")
        try:
            lo = int(elastic["minReplicas"])
            hi = int(elastic["maxReplicas"])
        except (KeyError, TypeError, ValueError):
            raise JobValidationError(
                f"{kind}: elastic needs integer minReplicas/maxReplicas")
        if lo < 1 or hi < lo:
            raise JobValidationError(
                f"{kind}: elastic range [{lo}, {hi}] invalid "
                "(1 <= min <= max)")
        pods = sum(rs.get("replicas", 1) for rs in replica_specs.values())
        if lo < pods:
            # The grant can never drop below the process count — worker
            # processes are fixed; only chips above them are elastic.
            raise JobValidationError(
                f"{kind}: elastic minReplicas {lo} below the gang's "
                f"{pods} pod(s); the host grant cannot drop under the "
                "process count")
