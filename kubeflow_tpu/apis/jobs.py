"""Training-job CRD API types.

The platform's job API family — the TPU-native equivalents of the reference's
five training CRDs (SURVEY.md §2.2):

- ``JaxJob``  — the native kind: SPMD JAX workers gang-scheduled onto a TPU
  slice, rendezvous via a JAX coordinator (replaces TFJob's PS/Worker +
  TF_CONFIG model, kubeflow/tf-training/tf-job-operator.libsonnet:10-96).
- ``TFJob``, ``PyTorchJob``, ``MXNetJob``, ``ChainerJob``, ``MPIJob`` —
  compatibility kinds with the reference's replica-type surfaces, lowered by
  their controllers onto the same gang-scheduling core.

All kinds share the replicaSpecs/runPolicy/status-conditions shape the
reference operators converged on, with a ``tpu`` block replacing
nvidia.com/gpu counts (e.g. pytorch-job.jsonnet:26-32).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Mapping, Sequence

from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.version import API_GROUP

JOBS_API_VERSION = f"{API_GROUP}/v1"

# ---------------------------------------------------------------------------
# Replica types per job kind (reference CRD validation properties, e.g.
# tf-job-operator.libsonnet:61-96 restricts PS/Worker/Chief/Master/Eval)
# ---------------------------------------------------------------------------

JAX_JOB_KIND = "JaxJob"
TF_JOB_KIND = "TFJob"
PYTORCH_JOB_KIND = "PyTorchJob"
MXNET_JOB_KIND = "MXNetJob"
CHAINER_JOB_KIND = "ChainerJob"
MPI_JOB_KIND = "MPIJob"

REPLICA_TYPES: dict[str, tuple[str, ...]] = {
    JAX_JOB_KIND: ("Worker",),
    TF_JOB_KIND: ("Chief", "PS", "Worker", "Evaluator"),
    PYTORCH_JOB_KIND: ("Master", "Worker"),
    MXNET_JOB_KIND: ("Scheduler", "Server", "Worker"),
    CHAINER_JOB_KIND: ("Master", "Worker"),
    MPI_JOB_KIND: ("Launcher", "Worker"),
}

# Replica types limited to at most one replica (Chief max 1:
# tf-job-operator.libsonnet:66-70).
SINGLETON_REPLICA_TYPES = {"Chief", "Master", "Scheduler", "Launcher"}

PLURALS: dict[str, str] = {
    JAX_JOB_KIND: "jaxjobs",
    TF_JOB_KIND: "tfjobs",
    PYTORCH_JOB_KIND: "pytorchjobs",
    MXNET_JOB_KIND: "mxnetjobs",
    CHAINER_JOB_KIND: "chainerjobs",
    MPI_JOB_KIND: "mpijobs",
}

ALL_JOB_KINDS = tuple(PLURALS)

# Condition types (mirrors the operator status contract asserted by
# testing/tf_job_simple_test.py:91 and printed via the CRD printer column
# tf-job-operator.libsonnet:70-81).
COND_CREATED = "Created"
COND_RUNNING = "Running"
COND_RESTARTING = "Restarting"
COND_SUCCEEDED = "Succeeded"
COND_FAILED = "Failed"

RESTART_POLICIES = ("Always", "OnFailure", "Never", "ExitCode")
CLEAN_POD_POLICIES = ("Running", "All", "None")

# Env vars the controller injects into every worker pod — the TF_CONFIG
# analogue (launcher.py:69-81) recast for `jax.distributed.initialize`.
ENV_COORDINATOR_ADDRESS = "JAX_COORDINATOR_ADDRESS"
ENV_NUM_PROCESSES = "JAX_NUM_PROCESSES"
ENV_PROCESS_ID = "JAX_PROCESS_ID"
ENV_SLICE_ID = "MEGASCALE_SLICE_ID"
ENV_NUM_SLICES = "MEGASCALE_NUM_SLICES"
ENV_COORDINATOR_PORT = "JAX_COORDINATOR_PORT"
ENV_TPU_TOPOLOGY = "TPU_TOPOLOGY"
ENV_TPU_ACCELERATOR = "TPU_ACCELERATOR_TYPE"
ENV_TPU_WORKER_HOSTNAMES = "TPU_WORKER_HOSTNAMES"

DEFAULT_COORDINATOR_PORT = 8476

# Identity of the owning job, injected into every worker so the training
# loop can publish final metrics to the job status (the path the study/
# benchmark controllers read — the metricsCollector-CronJob analogue,
# kubeflow/katib/studyjobcontroller.libsonnet:115-147).
ENV_JOB_NAME = "KUBEFLOW_TPU_JOB_NAME"
ENV_JOB_NAMESPACE = "KUBEFLOW_TPU_JOB_NAMESPACE"
ENV_JOB_KIND = "KUBEFLOW_TPU_JOB_KIND"

TPU_RESOURCE = "google.com/tpu"


def tpu_resources(chips: int) -> dict | None:
    """Pod resources block requesting TPU chips; None when chips == 0 (CPU).

    The analogue of the reference's `numGpus` → nvidia.com/gpu limits
    expansion (kubeflow/pytorch-job/prototypes/pytorch-job.jsonnet:26-32)."""
    if not chips:
        return None
    return {
        "limits": {TPU_RESOURCE: chips},
        "requests": {TPU_RESOURCE: chips},
    }


@dataclass(frozen=True)
class Condition:
    type: str
    status: str  # "True" | "False" | "Unknown"
    reason: str = ""
    message: str = ""
    last_transition_time: str = ""

    def to_dict(self) -> dict:
        return {
            "type": self.type,
            "status": self.status,
            "reason": self.reason,
            "message": self.message,
            "lastTransitionTime": self.last_transition_time,
        }


# ---------------------------------------------------------------------------
# Validation schema shared by all job kinds
# ---------------------------------------------------------------------------


def _replica_spec_schema(replica_types: Sequence[str]) -> dict:
    props = {}
    for rt in replica_types:
        max_replicas = 1 if rt in SINGLETON_REPLICA_TYPES else None
        replicas: dict = {"type": "integer", "minimum": 0}
        if max_replicas is not None:
            replicas["maximum"] = max_replicas
        props[rt] = {
            "type": "object",
            "properties": {
                "replicas": replicas,
                "restartPolicy": {"type": "string", "enum": list(RESTART_POLICIES)},
                "template": {"type": "object", "x-kubernetes-preserve-unknown-fields": True},
            },
        }
    return {"type": "object", "properties": props}


def job_schema(kind: str) -> dict:
    return {
        "type": "object",
        "properties": {
            "spec": {
                "type": "object",
                "properties": {
                    "replicaSpecs": _replica_spec_schema(REPLICA_TYPES[kind]),
                    "tpu": {
                        "type": "object",
                        "properties": {
                            "accelerator": {"type": "string"},
                            "topology": {"type": "string"},
                            "numSlices": {"type": "integer", "minimum": 1},
                        },
                    },
                    "runPolicy": {
                        "type": "object",
                        "properties": {
                            "cleanPodPolicy": {
                                "type": "string",
                                "enum": list(CLEAN_POD_POLICIES),
                            },
                            "backoffLimit": {"type": "integer", "minimum": 0},
                            "activeDeadlineSeconds": {"type": "integer", "minimum": 1},
                            "ttlSecondsAfterFinished": {"type": "integer", "minimum": 0},
                        },
                    },
                },
                "x-kubernetes-preserve-unknown-fields": True,
            },
            "status": {"type": "object", "x-kubernetes-preserve-unknown-fields": True},
        },
    }


def job_crd(kind: str) -> dict:
    """CRD for one job kind, with the reference's printer-column surface
    (tf-job-operator.libsonnet:70-81: State + Age columns)."""
    return k8s.crd(
        group=API_GROUP,
        kind=kind,
        plural=PLURALS[kind],
        short_names=[kind.lower().replace("job", "j")],
        categories=["all", "kubeflow-tpu"],
        versions=[
            k8s.crd_version(
                "v1",
                schema=job_schema(kind),
                served=True,
                storage=True,
                printer_columns=[
                    k8s.printer_column("State", ".status.state"),
                    k8s.printer_column("Age", ".metadata.creationTimestamp", "date"),
                ],
            )
        ],
    )


def all_job_crds() -> list[dict]:
    return [job_crd(kind) for kind in ALL_JOB_KINDS]


# ---------------------------------------------------------------------------
# Spec validation used by controllers and the webhook
# ---------------------------------------------------------------------------


class JobValidationError(ValueError):
    pass


def validate_job(job: Mapping) -> None:
    kind = job.get("kind", "")
    if kind not in REPLICA_TYPES:
        raise JobValidationError(f"unknown job kind {kind!r}")
    spec = job.get("spec", {})
    replica_specs = spec.get("replicaSpecs", {})
    if not replica_specs:
        raise JobValidationError(
            f"{kind} {job['metadata'].get('name')}: spec.replicaSpecs is empty"
        )
    allowed = REPLICA_TYPES[kind]
    for rt, rspec in replica_specs.items():
        if rt not in allowed:
            raise JobValidationError(
                f"{kind}: replica type {rt!r} not in {allowed}"
            )
        replicas = rspec.get("replicas", 1)
        if not isinstance(replicas, int) or replicas < 0:
            raise JobValidationError(f"{kind}/{rt}: invalid replicas {replicas!r}")
        if rt in SINGLETON_REPLICA_TYPES and replicas > 1:
            raise JobValidationError(f"{kind}/{rt}: at most 1 replica allowed")
        rp = rspec.get("restartPolicy")
        if rp is not None and rp not in RESTART_POLICIES:
            raise JobValidationError(f"{kind}/{rt}: invalid restartPolicy {rp!r}")
        tmpl = rspec.get("template", {})
        if not tmpl.get("spec", {}).get("containers"):
            raise JobValidationError(f"{kind}/{rt}: template has no containers")
    rp = spec.get("runPolicy", {})
    cpp = rp.get("cleanPodPolicy")
    if cpp is not None and cpp not in CLEAN_POD_POLICIES:
        raise JobValidationError(f"{kind}: invalid cleanPodPolicy {cpp!r}")
