"""BenchmarkJob CRD API.

Analogue of kubebench (kubeflow/kubebench/prototypes/kubebench-job.jsonnet:6-23,
kubebench-operator.jsonnet): a BenchmarkJob wraps a training job template with
a benchmark config, runs it, scrapes the reported metrics, and records results
(reporter-csv equivalent) in its status.
"""

from __future__ import annotations

from typing import Any, Mapping

from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.version import API_GROUP

BENCHMARK_JOB_KIND = "BenchmarkJob"
BENCHMARK_JOB_PLURAL = "benchmarkjobs"
BENCHMARK_API_VERSION = f"{API_GROUP}/v1"


def benchmark_job_crd() -> dict:
    schema = {
        "type": "object",
        "properties": {
            "spec": {
                "type": "object",
                "properties": {
                    "jobTemplate": {
                        "type": "object",
                        "x-kubernetes-preserve-unknown-fields": True,
                    },
                    "metrics": {"type": "array", "items": {"type": "string"}},
                    "warmupSteps": {"type": "integer", "minimum": 0},
                    "measureSteps": {"type": "integer", "minimum": 1},
                    "repetitions": {"type": "integer", "minimum": 1},
                },
            },
            "status": {"type": "object", "x-kubernetes-preserve-unknown-fields": True},
        },
    }
    return k8s.crd(
        group=API_GROUP,
        kind=BENCHMARK_JOB_KIND,
        plural=BENCHMARK_JOB_PLURAL,
        short_names=["bench"],
        categories=["all", "kubeflow-tpu"],
        versions=[
            k8s.crd_version(
                "v1",
                schema=schema,
                storage=True,
                printer_columns=[
                    k8s.printer_column("State", ".status.state"),
                    k8s.printer_column("Result", ".status.results"),
                ],
            )
        ],
    )


def benchmark_job(
    name: str,
    namespace: str,
    job_template: Mapping[str, Any],
    metrics: list[str] | None = None,
    warmup_steps: int = 10,
    measure_steps: int = 50,
    repetitions: int = 1,
) -> dict:
    return {
        "apiVersion": BENCHMARK_API_VERSION,
        "kind": BENCHMARK_JOB_KIND,
        "metadata": k8s.metadata(name, namespace),
        "spec": {
            "jobTemplate": dict(job_template),
            "metrics": list(metrics or ["samples_per_sec"]),
            "warmupSteps": warmup_steps,
            "measureSteps": measure_steps,
            "repetitions": repetitions,
        },
    }
