"""Certificate / Issuer / Endpoint CRD APIs — the secure-entrypoint types.

Analogues of the reference's cert-manager + cloud-endpoints surface:

- Issuer — the root of trust a Certificate references. ``selfSigned``
  issuers hold a platform-generated CA (the in-cluster analogue of
  cert-manager's selfSigned/CA issuers); the ``acme`` stanza mirrors the
  reference's letsencrypt issuer param
  (/root/reference/kubeflow/gcp/prototypes/cert-manager.jsonnet:8
  ``acmeUrl https://acme-v02.api.letsencrypt.org/directory``) and drives
  the order state machine in the controller.
- Certificate — dnsNames + issuerRef + secretName + duration/renewBefore;
  the controller issues into the Secret and rotates before expiry
  (iap.libsonnet wires the equivalent secret into the ESP/envoy ingress,
  /root/reference/kubeflow/gcp/iap.libsonnet:1-1041).
- Endpoint — hostname → target service record, the cloud-endpoints
  analogue (/root/reference/kubeflow/gcp/prototypes/cloud-endpoints.jsonnet:1-11
  maintains Cloud DNS records for <name>.endpoints.<project>.cloud.goog);
  here records land in the platform's zone ConfigMap, which in-cluster
  resolvers and the deploy UI read.
"""

from __future__ import annotations

from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.version import API_GROUP

CERT_API_GROUP = f"cert.{API_GROUP}"
CERTS_API_VERSION = f"{CERT_API_GROUP}/v1"
ISSUER_KIND = "Issuer"
ISSUER_PLURAL = "issuers"
CERTIFICATE_KIND = "Certificate"
CERTIFICATE_PLURAL = "certificates"
ENDPOINT_KIND = "Endpoint"
ENDPOINT_PLURAL = "endpoints"

# The DNS-zone record store the Endpoint controller maintains.
DNS_ZONE_CONFIGMAP = "kubeflow-dns-zone"

COND_READY = "Ready"

# ACME-style order states (the issuance state machine).
ORDER_PENDING = "Pending"
ORDER_VALIDATED = "Validated"
ORDER_ISSUED = "Issued"


def issuer_crd() -> dict:
    schema = {
        "type": "object",
        "properties": {
            "spec": {
                "type": "object",
                "properties": {
                    "selfSigned": {
                        "type": "object",
                        "properties": {
                            "commonName": {"type": "string"},
                        },
                    },
                    "acme": {
                        "type": "object",
                        "properties": {
                            "url": {"type": "string"},
                            "email": {"type": "string"},
                        },
                    },
                },
                "x-kubernetes-preserve-unknown-fields": True,
            },
            "status": {"type": "object",
                       "x-kubernetes-preserve-unknown-fields": True},
        },
    }
    return k8s.crd(
        group=CERT_API_GROUP,
        kind=ISSUER_KIND,
        plural=ISSUER_PLURAL,
        categories=["kubeflow-tpu"],
        versions=[k8s.crd_version(
            "v1", schema=schema, storage=True,
            printer_columns=[
                k8s.printer_column("Ready", ".status.ready"),
                k8s.printer_column("Age", ".metadata.creationTimestamp",
                                   "date"),
            ],
        )],
    )


def certificate_crd() -> dict:
    schema = {
        "type": "object",
        "properties": {
            "spec": {
                "type": "object",
                "required": ["secretName", "dnsNames", "issuerRef"],
                "properties": {
                    "secretName": {"type": "string"},
                    "dnsNames": {
                        "type": "array",
                        "items": {"type": "string"},
                        "minItems": 1,
                    },
                    "issuerRef": {
                        "type": "object",
                        "required": ["name"],
                        "properties": {"name": {"type": "string"}},
                    },
                    "durationSeconds": {"type": "integer", "minimum": 1},
                    "renewBeforeSeconds": {"type": "integer", "minimum": 0},
                },
            },
            "status": {"type": "object",
                       "x-kubernetes-preserve-unknown-fields": True},
        },
    }
    return k8s.crd(
        group=CERT_API_GROUP,
        kind=CERTIFICATE_KIND,
        plural=CERTIFICATE_PLURAL,
        short_names=["cert"],
        categories=["kubeflow-tpu"],
        versions=[k8s.crd_version(
            "v1", schema=schema, storage=True,
            printer_columns=[
                k8s.printer_column("Ready", ".status.ready"),
                k8s.printer_column("NotAfter", ".status.notAfter"),
                k8s.printer_column("Revision", ".status.revision"),
            ],
        )],
    )


def endpoint_crd() -> dict:
    schema = {
        "type": "object",
        "properties": {
            "spec": {
                "type": "object",
                "required": ["hostname", "target"],
                "properties": {
                    "hostname": {"type": "string"},
                    "target": {"type": "string"},
                },
            },
            "status": {"type": "object",
                       "x-kubernetes-preserve-unknown-fields": True},
        },
    }
    return k8s.crd(
        group=CERT_API_GROUP,
        kind=ENDPOINT_KIND,
        plural=ENDPOINT_PLURAL,
        categories=["kubeflow-tpu"],
        versions=[k8s.crd_version(
            "v1", schema=schema, storage=True,
            printer_columns=[
                k8s.printer_column("Hostname", ".spec.hostname"),
                k8s.printer_column("Target", ".spec.target"),
                k8s.printer_column("Ready", ".status.ready"),
            ],
        )],
    )


def all_cert_crds() -> list[dict]:
    return [issuer_crd(), certificate_crd(), endpoint_crd()]
