"""InferenceService CRD API — the fleet-serving control surface.

The reference serves models as a hand-sized tf-serving Deployment behind
an http-proxy (kubeflow/tf-serving/tf-serving-template.libsonnet:29-49);
this CRD is that stack at production shape: ONE object declares a model,
a replica range, the engine knobs, and the autoscaling targets, and the
InferenceService operator (operators/inference.py) reconciles N
model-server replicas, a prefix-affine gateway route over them, and a
metric-driven autoscaler consuming the PR-7 latency histograms.
"""

from __future__ import annotations

from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.version import API_GROUP

INFERENCE_KIND = "InferenceService"
INFERENCE_PLURAL = "inferenceservices"
INFERENCE_API_VERSION = f"{API_GROUP}/v1"

# Autoscale policy defaults: targets are BREACH thresholds (p99s over the
# PR-7 histograms, KV fill over the real-byte gauges); scale-down needs
# every signal under target * scale_down_ratio (hysteresis band) AND
# cooldown_seconds since the last scale event (flap damping). In a
# role-split service each pool is judged ONLY on the signals that bind
# it: prefill on queue-wait/TTFT p99, decode on KV-byte fill and
# inter-token p99.
DEFAULT_AUTOSCALE = {
    "queueWaitP99Ms": 500.0,
    "ttftP99Ms": 2000.0,
    "interTokenP99Ms": 500.0,
    "kvBytesUtilization": 0.85,
    "scaleDownRatio": 0.5,
    "cooldownSeconds": 60.0,
    "scrapePeriodSeconds": 10.0,
    # How long a replica's last-good scrape may stand in for a failed
    # one. Within the window the operator HOLDS (no scale, no rollout
    # gate verdict on substituted data); past it the replica counts as
    # unobservable.
    "signalStalenessSeconds": 30.0,
    # Predictive scale-up (flash-crowd elasticity): fit the per-pool
    # queue-wait/TTFT trend over the kept scrape rounds and scale when
    # the projection at now + horizonSeconds breaches — ahead of the
    # breach itself — jumping straight to the projected replica count
    # (capped at maxStepUp added per round) instead of +1-per-period.
    # Off by default: reactive-only behavior is unchanged.
    "predictive": False,
    "horizonSeconds": 30.0,
    "maxStepUp": 4,
}

# Newborn warm-up defaults (spec.warmup): peer weight birth off, no
# shared compile-cache volume, and a zero ramp window — each knob is
# opt-in so an unconfigured service keeps the checkpoint-boot behavior.
# rampSeconds additionally bounds how long the autoscaler treats a
# just-born (possibly unscrapeable) replica as warming: such replicas
# neither anchor the scale-down cooldown nor count as calm signals.
DEFAULT_WARMUP = {
    "peerWeights": False,
    "compileCacheDir": "",
    "rampSeconds": 0.0,
}

# Roles a disaggregated InferenceService splits its replicas into.
INFERENCE_ROLES = ("prefill", "decode")

# Rollout policy defaults: the canary walk schedule (percent of traffic
# at each step), the dwell per step, and the SLO gates. ``gateRatio``
# bounds the candidate's TTFT/inter-token p99 at a multiple of the
# incumbent's; ``errorRateRatio`` does the same for the error rate (with
# an absolute floor so a 0-error incumbent doesn't make any candidate
# error infinite); ``quorum`` is the fraction of canary replicas that
# must stay scrapeable — losing it is a rollback, not a wait.
DEFAULT_ROLLOUT = {
    "steps": [1, 10, 50, 100],
    "stepSeconds": 60.0,
    "gateRatio": 1.5,
    "errorRateRatio": 2.0,
    "errorRateFloor": 0.01,
    "shadowFraction": 0.1,
    "shadowSeconds": 30.0,
    "quorum": 0.5,
}


def validate_versions(versions: list[dict]) -> list[dict]:
    """Validate a ``spec.versions`` list: unique names, every entry a
    ``{name, weightsRef, traffic}``, traffic weights summing to 100.
    Returns a normalized copy (ints/floats coerced) or raises
    ValueError — shared by the builder, the CRD tests, and the rollout
    controller's admission path."""
    if not versions:
        raise ValueError("spec.versions must be a non-empty list")
    seen: set[str] = set()
    out: list[dict] = []
    total = 0.0
    for v in versions:
        name = str(v.get("name", "")).strip()
        if not name:
            raise ValueError("spec.versions entry missing name")
        if name in seen:
            raise ValueError(f"duplicate version name {name!r}")
        seen.add(name)
        if not str(v.get("weightsRef", "")).strip():
            raise ValueError(f"version {name!r} missing weightsRef")
        traffic = float(v.get("traffic", 0))
        if traffic < 0 or traffic > 100:
            raise ValueError(
                f"version {name!r} traffic {traffic} outside [0, 100]")
        total += traffic
        entry = {"name": name, "weightsRef": str(v["weightsRef"]),
                 "traffic": traffic}
        # Optional per-version engine knob overrides (an Experiment's
        # winning config rides its candidate version through the walk).
        engine = v.get("engine")
        if engine is not None:
            if not isinstance(engine, dict):
                raise ValueError(
                    f"version {name!r} engine must be an object")
            entry["engine"] = dict(engine)
        out.append(entry)
    if abs(total - 100.0) > 1e-6:
        raise ValueError(
            f"spec.versions traffic weights sum to {total}, want 100")
    return out


def inference_service_crd() -> dict:
    autoscale_props = {
        "queueWaitP99Ms": {"type": "number", "minimum": 0},
        "ttftP99Ms": {"type": "number", "minimum": 0},
        "interTokenP99Ms": {"type": "number", "minimum": 0},
        "kvBytesUtilization": {"type": "number", "minimum": 0,
                               "maximum": 1},
        "scaleDownRatio": {"type": "number", "minimum": 0, "maximum": 1},
        "cooldownSeconds": {"type": "number", "minimum": 0},
        "scrapePeriodSeconds": {"type": "number", "minimum": 0},
        "signalStalenessSeconds": {"type": "number", "minimum": 0},
        "predictive": {"type": "boolean"},
        "horizonSeconds": {"type": "number", "minimum": 0},
        "maxStepUp": {"type": "integer", "minimum": 1},
    }
    # Newborn warm-up: peer weight birth, the shared compile-cache
    # volume, and the ramp window the autoscaler/gateway honor.
    warmup_schema = {
        "type": "object",
        "properties": {
            "peerWeights": {"type": "boolean"},
            "compileCacheDir": {"type": "string"},
            "rampSeconds": {"type": "number", "minimum": 0},
        },
    }
    # Engine knobs pass through to the model-server args verbatim, but
    # tpShards is declared explicitly: the operator reads it to size
    # each replica's chip request (a tp=4 replica is a 4-chip pod), and
    # a role-level override lets a disaggregated service run a big
    # prefill mesh next to small decode meshes.
    engine_schema = {
        "type": "object",
        "properties": {
            "tpShards": {"type": "integer", "minimum": 1},
            # Long-context knobs, declared explicitly: cpShards and
            # ppStages multiply into the replica chip request
            # (tp*cp*pp chips per pod), and role-level overrides let a
            # disaggregated service run a wide-cp prefill pool feeding
            # tp-only decode pools over the existing handoff.
            "cpShards": {"type": "integer", "minimum": 1},
            "ppStages": {"type": "integer", "minimum": 1},
            "prefillChunkTokens": {"type": "integer", "minimum": 0},
            "maxPromptLen": {"type": "integer", "minimum": 0},
            # Host-RAM KV tier budget (bytes): declared explicitly so
            # operators sizing pod memory see it in the schema — the
            # tier's bytes come out of the pod's RAM, not HBM.
            "hostKvBytes": {"type": "integer", "minimum": 0},
            # Fleet KV economy: the prefix->holder directory's key
            # capacity (0 = economy off), the shared cold store ref
            # ("mem://<name>[?bytes=n]"), and the recompute-vs-import
            # crossover threshold in prefill tokens. Declared so the
            # operator can validate them and so colocated replicas of
            # one service share the same cold store name by default.
            "kvDirectorySize": {"type": "integer", "minimum": 0},
            "coldStoreRef": {"type": "string"},
            "importCrossoverTokens": {"type": "integer", "minimum": 0},
        },
        "x-kubernetes-preserve-unknown-fields": True,
    }
    # Multi-tenant QoS: per-tenant weights/rates threaded to the model
    # server's fair-share pop loop AND to the gateway route's shedding
    # buckets.
    tenant_schema = {
        "type": "object",
        "properties": {
            "weight": {"type": "number", "minimum": 0},
            "rate": {"type": "number", "minimum": 0},
            "burst": {"type": "number", "minimum": 0},
            "priority": {"type": "integer"},
        },
    }
    qos_schema = {
        "type": "object",
        "properties": {
            "agingSeconds": {"type": "number", "minimum": 0},
            "tenants": {"type": "object",
                        "additionalProperties": tenant_schema},
            "default": tenant_schema,
        },
    }
    # Per-role pool overrides for disaggregated prefill/decode serving:
    # each role gets its own replica range and engine overrides (merged
    # over the top-level engine; the operator pins serving_role and the
    # paged KV layout the handoff needs).
    role_props = {
        "replicas": {"type": "integer", "minimum": 0},
        "minReplicas": {"type": "integer", "minimum": 1},
        "maxReplicas": {"type": "integer", "minimum": 1},
        "engine": engine_schema,
    }
    schema = {
        "type": "object",
        "properties": {
            "spec": {
                "type": "object",
                "required": ["model"],
                "properties": {
                    "model": {"type": "string"},
                    "modelPath": {"type": "string"},
                    "image": {"type": "string"},
                    "replicas": {"type": "integer", "minimum": 0},
                    "minReplicas": {"type": "integer", "minimum": 1},
                    "maxReplicas": {"type": "integer", "minimum": 1},
                    "tpuChipsPerReplica": {"type": "integer",
                                           "minimum": 0},
                    # Engine knobs passed verbatim to the model-server
                    # args (the tpu-serving param surface); tpShards
                    # additionally sizes the replica's chip request.
                    "engine": engine_schema,
                    "router": {
                        "type": "object",
                        "properties": {
                            "affinityTokens": {"type": "integer",
                                               "minimum": 1},
                            "pressure": {"type": "integer",
                                         "minimum": 0},
                            "kvPressure": {"type": "number",
                                           "minimum": 0, "maximum": 1},
                        },
                    },
                    "roles": {
                        "type": "object",
                        "properties": {
                            role: {"type": "object",
                                   "properties": role_props}
                            for role in INFERENCE_ROLES
                        },
                    },
                    "qos": qos_schema,
                    "autoscale": {"type": "object",
                                  "properties": autoscale_props},
                    "warmup": warmup_schema,
                    # Progressive delivery: the declared model versions
                    # (traffic is the steady-state split the rollout
                    # walks toward) and the canary policy knobs.
                    "versions": {
                        "type": "array",
                        "items": {
                            "type": "object",
                            "required": ["name", "weightsRef"],
                            "properties": {
                                "name": {"type": "string"},
                                "weightsRef": {"type": "string"},
                                "traffic": {"type": "number",
                                            "minimum": 0,
                                            "maximum": 100},
                                # Engine knob overrides the candidate
                                # carries (Experiment promotion).
                                "engine": {
                                    "type": "object",
                                    "x-kubernetes-preserve-unknown-fields":
                                        True,
                                },
                            },
                        },
                    },
                    "rollout": {
                        "type": "object",
                        "properties": {
                            "steps": {
                                "type": "array",
                                "items": {"type": "number",
                                          "minimum": 0,
                                          "maximum": 100},
                            },
                            "stepSeconds": {"type": "number",
                                            "minimum": 0},
                            "gateRatio": {"type": "number",
                                          "minimum": 1},
                            "errorRateRatio": {"type": "number",
                                               "minimum": 1},
                            "errorRateFloor": {"type": "number",
                                               "minimum": 0},
                            "shadowFraction": {"type": "number",
                                               "minimum": 0,
                                               "maximum": 1},
                            "shadowSeconds": {"type": "number",
                                              "minimum": 0},
                            "quorum": {"type": "number",
                                       "minimum": 0, "maximum": 1},
                        },
                    },
                },
            },
            "status": {"type": "object",
                       "x-kubernetes-preserve-unknown-fields": True},
        },
    }
    return k8s.crd(
        group=API_GROUP,
        kind=INFERENCE_KIND,
        plural=INFERENCE_PLURAL,
        short_names=["isvc"],
        categories=["all", "kubeflow-tpu"],
        versions=[
            k8s.crd_version(
                "v1",
                schema=schema,
                storage=True,
                printer_columns=[
                    k8s.printer_column("Model", ".spec.model"),
                    k8s.printer_column("Replicas", ".status.replicas",
                                       "integer"),
                    k8s.printer_column("Ready", ".status.readyReplicas",
                                       "integer"),
                    k8s.printer_column("Phase", ".status.phase"),
                    k8s.printer_column("Age", ".metadata.creationTimestamp",
                                       "date"),
                ],
            )
        ],
    )


def inference_service(
    name: str,
    namespace: str,
    model: str,
    *,
    model_path: str = "",
    image: str = "",
    replicas: int = 1,
    min_replicas: int = 1,
    max_replicas: int = 4,
    tpu_chips_per_replica: int = 0,
    engine: dict | None = None,
    affinity_tokens: int = 32,
    pressure: int = 8,
    kv_pressure: float = 0.0,
    roles: dict | None = None,
    qos: dict | None = None,
    autoscale: dict | None = None,
    warmup: dict | None = None,
    versions: list[dict] | None = None,
    rollout: dict | None = None,
) -> dict:
    """Build an InferenceService CR. ``engine`` maps tpu-serving param
    names (batch_size, kv_layout, ...) to values; ``autoscale`` overrides
    DEFAULT_AUTOSCALE keys. ``roles`` splits the service into
    disaggregated prefill/decode pools: ``{"prefill": {"replicas": 2,
    "engine": {...}}, "decode": {...}}`` — each pool autoscaled on the
    signal that binds it. ``kv_pressure`` (0 disables) lets the gateway
    spill affine picks off a backend whose KV pool fill crosses it.
    ``qos`` ({tenants: {name: {weight, rate, burst, priority}},
    agingSeconds, default}) turns on multi-tenant fair-share admission
    in every replica and 429 shedding at the gateway route.

    ``versions`` ([{name, weightsRef, traffic}, ...], weights summing
    to 100) declares the model versions the service serves; when more
    than one is present the RolloutController canaries the newest in
    via the walk declared by ``rollout`` (DEFAULT_ROLLOUT overridden
    key-wise). Single-version specs (the default) are unchanged —
    omitting ``versions`` produces the exact legacy manifest."""
    if roles:
        bad = set(roles) - set(INFERENCE_ROLES)
        if bad:
            raise ValueError(f"unknown inference roles {sorted(bad)}")
    if versions is not None:
        versions = validate_versions(versions)
        if roles:
            # Scope bound: a versioned rollout pushes one param tree
            # into one homogeneous pool; disaggregated prefill/decode
            # pools version independently is future work.
            raise ValueError(
                "spec.versions is not supported on a role-split "
                "(disaggregated) service")
    if rollout is not None:
        bad = set(rollout) - set(DEFAULT_ROLLOUT)
        if bad:
            raise ValueError(f"unknown rollout keys {sorted(bad)}")
    if warmup is not None:
        bad = set(warmup) - set(DEFAULT_WARMUP)
        if bad:
            raise ValueError(f"unknown warmup keys {sorted(bad)}")
    router: dict = {"affinityTokens": int(affinity_tokens),
                    "pressure": int(pressure)}
    if kv_pressure:
        router["kvPressure"] = float(kv_pressure)
    spec: dict = {
        "model": model,
        "replicas": int(replicas),
        "minReplicas": int(min_replicas),
        "maxReplicas": int(max_replicas),
        "router": router,
        "autoscale": {**DEFAULT_AUTOSCALE, **(autoscale or {})},
    }
    if roles:
        spec["roles"] = {r: dict(v) for r, v in roles.items()}
    if qos:
        spec["qos"] = dict(qos)
    if warmup is not None:
        # Present only when asked for: an unconfigured service renders
        # the exact legacy manifest (no spec.warmup key at all).
        spec["warmup"] = {**DEFAULT_WARMUP, **warmup}
    if model_path:
        spec["modelPath"] = model_path
    if image:
        spec["image"] = image
    if tpu_chips_per_replica:
        spec["tpuChipsPerReplica"] = int(tpu_chips_per_replica)
    if engine:
        spec["engine"] = dict(engine)
    if versions is not None:
        spec["versions"] = versions
        spec["rollout"] = {**DEFAULT_ROLLOUT, **(rollout or {})}
    return {
        "apiVersion": INFERENCE_API_VERSION,
        "kind": INFERENCE_KIND,
        "metadata": k8s.metadata(name, namespace, {"app": name}),
        "spec": spec,
    }
