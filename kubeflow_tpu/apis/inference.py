"""InferenceService CRD API — the fleet-serving control surface.

The reference serves models as a hand-sized tf-serving Deployment behind
an http-proxy (kubeflow/tf-serving/tf-serving-template.libsonnet:29-49);
this CRD is that stack at production shape: ONE object declares a model,
a replica range, the engine knobs, and the autoscaling targets, and the
InferenceService operator (operators/inference.py) reconciles N
model-server replicas, a prefix-affine gateway route over them, and a
metric-driven autoscaler consuming the PR-7 latency histograms.
"""

from __future__ import annotations

from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.version import API_GROUP

INFERENCE_KIND = "InferenceService"
INFERENCE_PLURAL = "inferenceservices"
INFERENCE_API_VERSION = f"{API_GROUP}/v1"

# Autoscale policy defaults: targets are BREACH thresholds (p99s over the
# PR-7 histograms, KV fill over the real-byte gauges); scale-down needs
# every signal under target * scale_down_ratio (hysteresis band) AND
# cooldown_seconds since the last scale event (flap damping).
DEFAULT_AUTOSCALE = {
    "queueWaitP99Ms": 500.0,
    "ttftP99Ms": 2000.0,
    "kvBytesUtilization": 0.85,
    "scaleDownRatio": 0.5,
    "cooldownSeconds": 60.0,
    "scrapePeriodSeconds": 10.0,
}


def inference_service_crd() -> dict:
    autoscale_props = {
        "queueWaitP99Ms": {"type": "number", "minimum": 0},
        "ttftP99Ms": {"type": "number", "minimum": 0},
        "kvBytesUtilization": {"type": "number", "minimum": 0,
                               "maximum": 1},
        "scaleDownRatio": {"type": "number", "minimum": 0, "maximum": 1},
        "cooldownSeconds": {"type": "number", "minimum": 0},
        "scrapePeriodSeconds": {"type": "number", "minimum": 0},
    }
    schema = {
        "type": "object",
        "properties": {
            "spec": {
                "type": "object",
                "required": ["model"],
                "properties": {
                    "model": {"type": "string"},
                    "modelPath": {"type": "string"},
                    "image": {"type": "string"},
                    "replicas": {"type": "integer", "minimum": 0},
                    "minReplicas": {"type": "integer", "minimum": 1},
                    "maxReplicas": {"type": "integer", "minimum": 1},
                    "tpuChipsPerReplica": {"type": "integer",
                                           "minimum": 0},
                    # Engine knobs passed verbatim to the model-server
                    # args (the tpu-serving param surface).
                    "engine": {
                        "type": "object",
                        "x-kubernetes-preserve-unknown-fields": True,
                    },
                    "router": {
                        "type": "object",
                        "properties": {
                            "affinityTokens": {"type": "integer",
                                               "minimum": 1},
                            "pressure": {"type": "integer",
                                         "minimum": 0},
                        },
                    },
                    "autoscale": {"type": "object",
                                  "properties": autoscale_props},
                },
            },
            "status": {"type": "object",
                       "x-kubernetes-preserve-unknown-fields": True},
        },
    }
    return k8s.crd(
        group=API_GROUP,
        kind=INFERENCE_KIND,
        plural=INFERENCE_PLURAL,
        short_names=["isvc"],
        categories=["all", "kubeflow-tpu"],
        versions=[
            k8s.crd_version(
                "v1",
                schema=schema,
                storage=True,
                printer_columns=[
                    k8s.printer_column("Model", ".spec.model"),
                    k8s.printer_column("Replicas", ".status.replicas",
                                       "integer"),
                    k8s.printer_column("Ready", ".status.readyReplicas",
                                       "integer"),
                    k8s.printer_column("Phase", ".status.phase"),
                    k8s.printer_column("Age", ".metadata.creationTimestamp",
                                       "date"),
                ],
            )
        ],
    )


def inference_service(
    name: str,
    namespace: str,
    model: str,
    *,
    model_path: str = "",
    image: str = "",
    replicas: int = 1,
    min_replicas: int = 1,
    max_replicas: int = 4,
    tpu_chips_per_replica: int = 0,
    engine: dict | None = None,
    affinity_tokens: int = 32,
    pressure: int = 8,
    autoscale: dict | None = None,
) -> dict:
    """Build an InferenceService CR. ``engine`` maps tpu-serving param
    names (batch_size, kv_layout, ...) to values; ``autoscale`` overrides
    DEFAULT_AUTOSCALE keys."""
    spec: dict = {
        "model": model,
        "replicas": int(replicas),
        "minReplicas": int(min_replicas),
        "maxReplicas": int(max_replicas),
        "router": {"affinityTokens": int(affinity_tokens),
                   "pressure": int(pressure)},
        "autoscale": {**DEFAULT_AUTOSCALE, **(autoscale or {})},
    }
    if model_path:
        spec["modelPath"] = model_path
    if image:
        spec["image"] = image
    if tpu_chips_per_replica:
        spec["tpuChipsPerReplica"] = int(tpu_chips_per_replica)
    if engine:
        spec["engine"] = dict(engine)
    return {
        "apiVersion": INFERENCE_API_VERSION,
        "kind": INFERENCE_KIND,
        "metadata": k8s.metadata(name, namespace, {"app": name}),
        "spec": spec,
    }
