from kubeflow_tpu.apis import jobs

__all__ = ["jobs"]
