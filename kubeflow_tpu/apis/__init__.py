"""CRD type surfaces (group/version/kind + object builders) for the platform's APIs."""
from kubeflow_tpu.apis import jobs

__all__ = ["jobs"]
