"""Cluster-scheduler API types: SchedulingPolicy CRD + placement contract.

The scheduler (:mod:`kubeflow_tpu.scheduler`) owns placement for every
training-job kind. Its API surface is deliberately small:

- a ``SchedulingPolicy`` CR carrying the cluster-wide knobs (scheduling
  period, starvation aging, preemption policy, queue weights, throughput
  profiles) — the scheduler reconciles this object, and every job/pod/node
  event requeues it, so one reconcile == one scheduling round;
- job ``spec.priority`` / ``spec.queue`` / ``spec.profile`` /
  ``spec.preemptible`` fields (schema added in :mod:`~kubeflow_tpu.apis.jobs`)
  that opt a job into scheduler-managed placement;
- annotations that carry decisions between the scheduler and the job
  controller: the gang's reservation lands as ONE ``placement`` annotation
  on the job (all-or-nothing by construction — there is no per-replica
  placement write to half-apply), and preemption marks victims with
  ``preempted-by`` on the job and its pods.

Placement annotation value (JSON)::

    {"pool": "v5e", "topology": "2x4", "slice": "v5e-0",
     "nodes": ["node-a", "node-b"], "decidedAt": "..."}

``nodes`` has exactly one entry per gang pod; the job controller maps pod
*i* of the gang onto ``nodes[i]`` (`spec.nodeName`), replacing the bare GKE
nodeSelector path for managed jobs.
"""

from __future__ import annotations

import json
from typing import Mapping

from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.version import API_GROUP, DEFAULT_NAMESPACE

SCHEDULING_API_VERSION = f"{API_GROUP}/v1"
SCHEDULING_POLICY_KIND = "SchedulingPolicy"
SCHEDULING_POLICY_PLURAL = "schedulingpolicies"

# Node labels the capacity model reads. Accelerator/topology are the GKE
# TPU labels the job controller already targets; the slice label groups
# hosts into one contiguous slice (a gang must land wholly inside one).
NODE_ACCEL_LABEL = "cloud.google.com/gke-tpu-accelerator"
NODE_TOPO_LABEL = "cloud.google.com/gke-tpu-topology"
NODE_SLICE_LABEL = f"{API_GROUP}/slice"

# Decision-carrying annotations (job + pod metadata).
ANN_PLACEMENT = f"{API_GROUP}/placement"
ANN_PREEMPTED_BY = f"{API_GROUP}/preempted-by"
ANN_POOL = f"{API_GROUP}/pool"
ANN_SLICE = f"{API_GROUP}/slice"

# Scheduler-owned job condition types (the job controller's lifecycle
# conditions — Created/Running/… — stay owned by the job controller).
COND_QUEUED = "Queued"
COND_UNSCHEDULABLE = "Unschedulable"

# status.scheduling.state values.
STATE_QUEUED = "Queued"
STATE_ADMITTED = "Admitted"
STATE_PREEMPTED = "Preempted"
STATE_UNSCHEDULABLE = "Unschedulable"

DEFAULT_SCHEDULING_PERIOD_SECONDS = 5.0
DEFAULT_AGING_SECONDS = 300.0
DEFAULT_REQUEUE_BACKOFF_SECONDS = 10.0
DEFAULT_QUEUE = "default"
DEFAULT_QUEUE_WEIGHT = 1.0


def is_managed(job: Mapping) -> bool:
    """A job is scheduler-managed iff it asks for queueing: an explicit
    priority or queue opts in. Unmanaged jobs keep the legacy first-come
    path (bare GKE nodeSelectors), so existing workloads are untouched."""
    spec = job.get("spec", {})
    return spec.get("priority") is not None or bool(spec.get("queue"))


def job_priority(job: Mapping) -> int:
    p = job.get("spec", {}).get("priority")
    return int(p) if p is not None else 0


def job_queue(job: Mapping) -> str:
    return job.get("spec", {}).get("queue") or DEFAULT_QUEUE


def is_preemptible(job: Mapping) -> bool:
    return bool(job.get("spec", {}).get("preemptible", True))


def elastic_spec(job: Mapping) -> dict | None:
    """The job's elastic range — ``{"min": minReplicas, "max":
    maxReplicas}`` in hosts — or None for a fixed-size gang. Declaring
    the range is the job's consent to live resizing: the scheduler may
    grant anywhere inside it and move the grant while the job runs (the
    train loop reshards at the next step boundary). Malformed blocks
    read as non-elastic so the scheduler never resizes on garbage."""
    raw = job.get("spec", {}).get("elastic")
    if not isinstance(raw, Mapping):
        return None
    try:
        lo = int(raw.get("minReplicas", 1))
        hi = int(raw.get("maxReplicas", lo))
    except (TypeError, ValueError):
        return None
    if lo < 1 or hi < lo:
        return None
    return {"min": lo, "max": hi}


def placement(job: Mapping) -> dict | None:
    """Parse the job's placement annotation; None when unplaced (or the
    annotation is malformed — treated as unplaced so the scheduler
    re-decides rather than the job controller acting on garbage)."""
    raw = job.get("metadata", {}).get("annotations", {}).get(ANN_PLACEMENT)
    if not raw:
        return None
    try:
        decided = json.loads(raw)
    except (TypeError, ValueError):
        return None
    if not isinstance(decided, dict) or not decided.get("nodes"):
        return None
    return decided


def encode_placement(pool: str, topology: str, slice_id: str,
                     nodes: list[str], decided_at: str,
                     elastic: Mapping | None = None) -> str:
    """``elastic`` (written for elastic jobs only) carries
    ``{"granted": n, "min": m, "max": M}`` so the training loop can map
    its host grant onto a device count without a second API read: target
    devices = visible devices × granted / max (the pod is provisioned
    for the max grant; parallel/reshard.scaled_mesh_config does the
    axis math)."""
    decided = {
        "pool": pool, "topology": topology, "slice": slice_id,
        "nodes": list(nodes), "decidedAt": decided_at,
    }
    if elastic is not None:
        decided["elastic"] = dict(elastic)
    return json.dumps(decided, sort_keys=True)


def placement_grant(job: Mapping) -> tuple[int, int] | None:
    """(granted, max) hosts from an elastic placement; None when the job
    is unplaced or not elastic. The ratio is the elastic train loop's
    resize signal (train/elastic.py)."""
    decided = placement(job)
    if decided is None:
        return None
    elastic = decided.get("elastic")
    if not isinstance(elastic, Mapping):
        return None
    try:
        granted = int(elastic.get("granted", len(decided["nodes"])))
        cap = int(elastic["max"])
    except (KeyError, TypeError, ValueError):
        return None
    if granted < 1 or cap < granted:
        return None
    return granted, cap


# ---------------------------------------------------------------------------
# SchedulingPolicy CRD
# ---------------------------------------------------------------------------


def scheduling_policy_schema() -> dict:
    return {
        "type": "object",
        "properties": {
            "spec": {
                "type": "object",
                "properties": {
                    "schedulingPeriodSeconds": {
                        "type": "number", "minimum": 0.01},
                    "agingSeconds": {
                        # Seconds of queue wait worth one priority point
                        # (starvation aging); 0 disables aging.
                        "type": "number", "minimum": 0},
                    "preemption": {
                        "type": "object",
                        "properties": {
                            "enabled": {"type": "boolean"},
                            "minPriorityGap": {
                                # A preemptor must outrank its victim by
                                # strictly more than this many points.
                                "type": "integer", "minimum": 0},
                            "requeueBackoffSeconds": {
                                "type": "number", "minimum": 0},
                            "gracePeriodSeconds": {
                                "type": "number", "minimum": 0},
                        },
                    },
                    "elastic": {
                        # Live-resize policy for jobs declaring
                        # spec.elastic: shrink a running elastic victim
                        # (placement rewrite → step-boundary reshard)
                        # before falling back to preemption-by-kill, and
                        # opportunistically grow elastic jobs into idle
                        # capacity left after the queue pass.
                        "type": "object",
                        "properties": {
                            "shrinkBeforePreempt": {"type": "boolean"},
                            "growEnabled": {"type": "boolean"},
                            "growDelaySeconds": {
                                # Quiet period after a shrink before the
                                # same job may grow back (anti-thrash).
                                "type": "number", "minimum": 0},
                        },
                    },
                    "queues": {
                        "type": "array",
                        "items": {
                            "type": "object",
                            "required": ["name"],
                            "properties": {
                                "name": {"type": "string"},
                                "weight": {"type": "number",
                                           "exclusiveMinimum": 0},
                            },
                        },
                    },
                    "profiles": {
                        # profile -> accelerator -> measured throughput
                        # (tokens/s/chip, BENCH_*.json numbers): the
                        # Gavel-style heterogeneity signal.
                        "type": "object",
                        "x-kubernetes-preserve-unknown-fields": True,
                    },
                },
            },
            "status": {"type": "object",
                       "x-kubernetes-preserve-unknown-fields": True},
        },
    }


def scheduling_policy_crd() -> dict:
    return k8s.crd(
        group=API_GROUP,
        kind=SCHEDULING_POLICY_KIND,
        plural=SCHEDULING_POLICY_PLURAL,
        short_names=["schedpol"],
        categories=["all", "kubeflow-tpu"],
        versions=[
            k8s.crd_version(
                "v1",
                schema=scheduling_policy_schema(),
                served=True,
                storage=True,
                printer_columns=[
                    k8s.printer_column("Queued", ".status.queueDepth"),
                    k8s.printer_column("Age", ".metadata.creationTimestamp",
                                       "date"),
                ],
            ),
        ],
    )


def scheduling_policy(name: str = "default",
                      namespace: str = DEFAULT_NAMESPACE,
                      **spec) -> dict:
    return {
        "apiVersion": SCHEDULING_API_VERSION,
        "kind": SCHEDULING_POLICY_KIND,
        "metadata": k8s.metadata(name, namespace),
        "spec": spec,
    }


def policy_knobs(policy: Mapping) -> dict:
    """Resolve a policy spec into a flat knob dict with defaults."""
    spec = policy.get("spec", {}) if policy else {}
    preemption = spec.get("preemption", {}) or {}
    elastic = spec.get("elastic", {}) or {}
    weights = {DEFAULT_QUEUE: DEFAULT_QUEUE_WEIGHT}
    for q in spec.get("queues", []) or []:
        if isinstance(q, Mapping) and q.get("name"):
            weights[q["name"]] = float(q.get("weight",
                                             DEFAULT_QUEUE_WEIGHT))
    return {
        "period": float(spec.get("schedulingPeriodSeconds",
                                 DEFAULT_SCHEDULING_PERIOD_SECONDS)),
        "aging_seconds": float(spec.get("agingSeconds",
                                        DEFAULT_AGING_SECONDS)),
        "preemption_enabled": bool(preemption.get("enabled", True)),
        "min_priority_gap": int(preemption.get("minPriorityGap", 0)),
        "requeue_backoff": float(preemption.get(
            "requeueBackoffSeconds", DEFAULT_REQUEUE_BACKOFF_SECONDS)),
        "grace_seconds": float(preemption.get("gracePeriodSeconds", 30.0)),
        "shrink_enabled": bool(elastic.get("shrinkBeforePreempt", True)),
        "grow_enabled": bool(elastic.get("growEnabled", True)),
        "grow_delay": float(elastic.get("growDelaySeconds", 0.0)),
        "queue_weights": weights,
        "profiles": dict(spec.get("profiles", {}) or {}),
    }
