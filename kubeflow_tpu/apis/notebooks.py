"""Notebook CRD API.

The analogue of the reference's Notebook CRD, defined both at
components/notebook-controller/pkg/apis/notebook/v1alpha1/notebook_types.go:28-80
and kubeflow/jupyter/notebooks.libsonnet:11-20. A Notebook CR describes one
user notebook server; the controller materialises it as a StatefulSet +
Service with a gateway route, status mirrored from the pod container state
(notebook_controller.go:148-263).
"""

from __future__ import annotations

from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.version import API_GROUP

NOTEBOOK_KIND = "Notebook"
NOTEBOOK_PLURAL = "notebooks"
NOTEBOOKS_API_VERSION = f"{API_GROUP}/v1"
NOTEBOOK_PORT = 8888


def notebook_crd() -> dict:
    schema = {
        "type": "object",
        "properties": {
            "spec": {
                "type": "object",
                "properties": {
                    "template": {
                        "type": "object",
                        "x-kubernetes-preserve-unknown-fields": True,
                    },
                    "tpu": {
                        "type": "object",
                        "properties": {
                            "accelerator": {"type": "string"},
                            "chips": {"type": "integer", "minimum": 0},
                        },
                    },
                },
                "x-kubernetes-preserve-unknown-fields": True,
            },
            "status": {"type": "object", "x-kubernetes-preserve-unknown-fields": True},
        },
    }
    return k8s.crd(
        group=API_GROUP,
        kind=NOTEBOOK_KIND,
        plural=NOTEBOOK_PLURAL,
        short_names=["nb"],
        categories=["all", "kubeflow-tpu"],
        versions=[
            k8s.crd_version(
                "v1",
                schema=schema,
                storage=True,
                printer_columns=[
                    k8s.printer_column("State", ".status.state"),
                    k8s.printer_column("Age", ".metadata.creationTimestamp", "date"),
                ],
            )
        ],
    )


def notebook(
    name: str,
    namespace: str,
    image: str,
    tpu_chips: int = 0,
    cpu: str = "1",
    memory: str = "2Gi",
    workspace_pvc: str | None = None,
) -> dict:
    """Build a Notebook CR (what jupyter-web-app POSTs,
    components/jupyter-web-app/default/routes.py:33-111)."""
    resources: dict = {"requests": {"cpu": cpu, "memory": memory}}
    if tpu_chips:
        resources["limits"] = {"google.com/tpu": tpu_chips}
    volumes = []
    mounts = []
    if workspace_pvc:
        volumes.append(k8s.pvc_volume("workspace", workspace_pvc))
        mounts.append(k8s.volume_mount("workspace", "/home/jovyan"))
    return {
        "apiVersion": NOTEBOOKS_API_VERSION,
        "kind": NOTEBOOK_KIND,
        "metadata": k8s.metadata(name, namespace, {"app": name}),
        "spec": {
            "template": {
                "spec": k8s.pod_spec(
                    [
                        k8s.container(
                            "notebook",
                            image,
                            resources=resources,
                            ports={"notebook": 8888},
                            volume_mounts=mounts or None,
                            env={"JUPYTER_ENABLE_LAB": "true"},
                        )
                    ],
                    volumes=volumes or None,
                )
            },
            "tpu": {"chips": tpu_chips},
        },
    }
