// Token-store: memory-mapped token-corpus reader for the input pipeline.
//
// The hot half of the data loader, in C++ (the role the reference gives its
// native components; its data path is S3 sidecar downloads,
// components/openmpi-controller/controller/controller.py:105-116 — here the
// corpus is one mmapped binary file and batch assembly is memcpy-speed,
// zero Python per row). Exposed to Python over a C ABI via ctypes
// (kubeflow_tpu/train/tokenstore.py), with a pure-numpy fallback that
// implements the identical sampling arithmetic, so the two paths are
// interchangeable and cross-checked in tests.
//
// File format (little-endian):
//   magic  u32  = 0x4b545055 ("KTPU")
//   version u32 = 1
//   dtype  u32  = 4  (int32 tokens)
//   pad    u32
//   n_tokens u64
//   tokens  int32[n_tokens]
//
// Sampling: row r of (batch, seq+1) at step s starts at
//   splitmix64(seed ^ (s*batch + r)) % (n_tokens - seq - 1)
// — stateless, deterministic, seekable from any step (resume-friendly).
// Sequential mode reads contiguous windows strided across processes for
// epoch-style coverage.

#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0x4b545055u;

struct Store {
  int fd = -1;
  const uint8_t* map = nullptr;
  size_t map_len = 0;
  const int32_t* tokens = nullptr;
  uint64_t n_tokens = 0;
};

uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ull;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
  return x ^ (x >> 31);
}

}  // namespace

extern "C" {

// Returns an opaque handle (heap pointer) or null on failure.
void* ts_open(const char* path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || static_cast<size_t>(st.st_size) < 24) {
    ::close(fd);
    return nullptr;
  }
  void* map = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (map == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  const uint8_t* bytes = static_cast<const uint8_t*>(map);
  uint32_t magic, version, dtype;
  uint64_t n_tokens;
  std::memcpy(&magic, bytes, 4);
  std::memcpy(&version, bytes + 4, 4);
  std::memcpy(&dtype, bytes + 8, 4);
  std::memcpy(&n_tokens, bytes + 16, 8);
  // Divide, don't multiply: `24 + n_tokens * 4` wraps for crafted headers
  // (n_tokens >= 2^62) and would admit a file whose reads run off the map.
  if (magic != kMagic || version != 1 || dtype != 4 ||
      n_tokens > (static_cast<uint64_t>(st.st_size) - 24) / 4) {
    munmap(map, st.st_size);
    ::close(fd);
    return nullptr;
  }
  Store* s = new Store;
  s->fd = fd;
  s->map = bytes;
  s->map_len = st.st_size;
  s->tokens = reinterpret_cast<const int32_t*>(bytes + 24);
  s->n_tokens = n_tokens;
  return s;
}

uint64_t ts_n_tokens(void* handle) {
  return handle ? static_cast<Store*>(handle)->n_tokens : 0;
}

void ts_close(void* handle) {
  if (!handle) return;
  Store* s = static_cast<Store*>(handle);
  munmap(const_cast<uint8_t*>(s->map), s->map_len);
  ::close(s->fd);
  delete s;
}

// Fill out[batch][width] with shuffled windows for (seed, step).
// Returns 0 on success, -1 if the corpus is shorter than width.
int ts_fill_shuffled(void* handle, int32_t* out, uint64_t batch,
                     uint64_t width, uint64_t seed, uint64_t step) {
  Store* s = static_cast<Store*>(handle);
  if (!s || s->n_tokens < width) return -1;
  const uint64_t span = s->n_tokens - width + 1;
  for (uint64_t r = 0; r < batch; ++r) {
    const uint64_t off = splitmix64(seed ^ (step * batch + r)) % span;
    std::memcpy(out + r * width, s->tokens + off, width * 4);
  }
  return 0;
}

// Fill out[batch][width] with contiguous windows for epoch-style reads:
// window w = global_row (wrapping), rows strided by num_shards so shard
// p reads rows p, p+num_shards, ... Returns 0, or -1 on bad args.
int ts_fill_sequential(void* handle, int32_t* out, uint64_t batch,
                       uint64_t width, uint64_t start_row, uint64_t shard,
                       uint64_t num_shards) {
  Store* s = static_cast<Store*>(handle);
  if (!s || s->n_tokens < width || num_shards == 0) return -1;
  const uint64_t n_windows = s->n_tokens / width;
  if (n_windows == 0) return -1;
  for (uint64_t r = 0; r < batch; ++r) {
    const uint64_t row = (start_row + r) * num_shards + shard;
    const uint64_t off = (row % n_windows) * width;
    std::memcpy(out + r * width, s->tokens + off, width * 4);
  }
  return 0;
}

}  // extern "C"
