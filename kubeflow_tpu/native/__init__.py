"""Native (C++) runtime components; built by the Makefile here."""
