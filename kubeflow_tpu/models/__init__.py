"""Model zoo.

The reference trains models only through container images it doesn't own
(tf_cnn_benchmarks — tf-controller-examples/tf-cnn/launcher.py; TF-Serving
model binaries). Here the workloads are first-class: pure-functional JAX
models with explicit param pytrees so the parallel library's path-rule
sharding (kubeflow_tpu/parallel/sharding.py) applies uniformly.

- :mod:`~kubeflow_tpu.models.transformer` — decoder-only LM (Llama-3-style:
  RMSNorm, RoPE, GQA, SwiGLU), the flagship training/serving workload.
- :mod:`~kubeflow_tpu.models.bert` — BERT encoder (baseline config #2).
- :mod:`~kubeflow_tpu.models.resnet` — ResNet CNN (the tf_cnn_benchmarks
  analogue, baseline config #1).
- :mod:`~kubeflow_tpu.models.registry` — name → (config, init, apply) lookup
  used by jobs, serving, and the benchmark harness.
"""

from kubeflow_tpu.models import registry
from kubeflow_tpu.models.registry import get_model, list_models

__all__ = ["registry", "get_model", "list_models"]
