"""BERT encoder (baseline config #2: BERT-base multi-worker training).

Same TPU-first structure as the flagship LM — stacked layers under
``lax.scan``, bf16 compute/f32 accumulation, path-rule sharding — with the
BERT specifics: learned position embeddings, post-norm residuals (original
architecture), GELU MLP, bidirectional flash attention, MLM + NSP heads.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, replace

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from kubeflow_tpu.ops import flash_attention, layer_norm, softmax_cross_entropy
from kubeflow_tpu.parallel.mesh import AXIS_DATA, AXIS_FSDP, AXIS_TENSOR
from kubeflow_tpu.parallel.sharding import PartitionRule


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30_522
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    max_seq_len: int = 512
    type_vocab_size: int = 2
    norm_eps: float = 1e-12
    dtype: jnp.dtype = jnp.bfloat16
    remat: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


PRESETS: dict[str, BertConfig] = {
    "bert-base": BertConfig(),
    "bert-large": BertConfig(d_model=1024, n_layers=24, n_heads=16, d_ff=4096),
    "bert-test-tiny": BertConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, d_ff=128,
        max_seq_len=128,
    ),
}


def config(name: str, **overrides) -> BertConfig:
    return replace(PRESETS[name], **overrides)


def init(key, cfg: BertConfig):
    d, f = cfg.d_model, cfg.d_ff
    keys = jax.random.split(key, 12)

    def dense(k, shape, fan_in):
        return jax.random.normal(k, shape, jnp.float32) * (fan_in**-0.5)

    def stack(k, shape, fan_in):
        return dense(k, (cfg.n_layers, *shape), fan_in)

    def ln(shape=(cfg.n_layers, d)):
        return {"scale": jnp.ones(shape, jnp.float32),
                "bias": jnp.zeros(shape, jnp.float32)}

    return {
        "embed": {
            "word": dense(keys[0], (cfg.vocab_size, d), d),
            "position": dense(keys[1], (cfg.max_seq_len, d), d),
            "type": dense(keys[2], (cfg.type_vocab_size, d), d),
            "ln": {"scale": jnp.ones((d,)), "bias": jnp.zeros((d,))},
        },
        "layers": {
            "attn": {
                "wq": stack(keys[3], (d, d), d),
                "wk": stack(keys[4], (d, d), d),
                "wv": stack(keys[5], (d, d), d),
                "wo": stack(keys[6], (d, d), d),
                "bq": jnp.zeros((cfg.n_layers, d)),
                "bk": jnp.zeros((cfg.n_layers, d)),
                "bv": jnp.zeros((cfg.n_layers, d)),
                "bo": jnp.zeros((cfg.n_layers, d)),
            },
            "mlp": {
                "wi": stack(keys[7], (d, f), d),
                "bi": jnp.zeros((cfg.n_layers, f)),
                "wo": stack(keys[8], (f, d), f),
                "bo2": jnp.zeros((cfg.n_layers, d)),
            },
            "ln_attn": ln(),
            "ln_mlp": ln(),
        },
        "pooler": {"kernel": dense(keys[9], (d, d), d), "bias": jnp.zeros((d,))},
        "mlm": {
            "transform": dense(keys[10], (d, d), d),
            "transform_bias": jnp.zeros((d,)),
            "ln": {"scale": jnp.ones((d,)), "bias": jnp.zeros((d,))},
            "output_bias": jnp.zeros((cfg.vocab_size,)),
        },
        "nsp": {"kernel": dense(keys[11], (d, 2), d), "bias": jnp.zeros((2,))},
    }


def partition_rules(cfg: BertConfig) -> list[PartitionRule]:
    return [
        PartitionRule(r"embed/word", P(AXIS_TENSOR, AXIS_FSDP)),
        PartitionRule(r"attn/w[qkv]", P(None, AXIS_FSDP, AXIS_TENSOR)),
        PartitionRule(r"attn/wo", P(None, AXIS_TENSOR, AXIS_FSDP)),
        PartitionRule(r"mlp/wi", P(None, AXIS_FSDP, AXIS_TENSOR)),
        PartitionRule(r"mlp/wo", P(None, AXIS_TENSOR, AXIS_FSDP)),
    ]


def batch_partition_spec(cfg: BertConfig) -> P:
    return P((AXIS_DATA, AXIS_FSDP), None)


def _layer_fn(cfg: BertConfig, mesh, carry, layer):
    x, pad_mask = carry
    b, t, d = x.shape
    a = layer["attn"]
    q = (x @ a["wq"].astype(cfg.dtype) + a["bq"].astype(cfg.dtype)).reshape(
        b, t, cfg.n_heads, cfg.head_dim
    )
    k = (x @ a["wk"].astype(cfg.dtype) + a["bk"].astype(cfg.dtype)).reshape(
        b, t, cfg.n_heads, cfg.head_dim
    )
    v = (x @ a["wv"].astype(cfg.dtype) + a["bv"].astype(cfg.dtype)).reshape(
        b, t, cfg.n_heads, cfg.head_dim
    )
    attn = flash_attention(q, k, v, causal=False,
                           kv_mask=pad_mask).reshape(b, t, d)
    attn = attn @ a["wo"].astype(cfg.dtype) + a["bo"].astype(cfg.dtype)
    x = layer_norm(x + attn, layer["ln_attn"]["scale"],
                   layer["ln_attn"]["bias"], eps=cfg.norm_eps)

    m = layer["mlp"]
    h = jax.nn.gelu(x @ m["wi"].astype(cfg.dtype) + m["bi"].astype(cfg.dtype))
    h = h @ m["wo"].astype(cfg.dtype) + m["bo2"].astype(cfg.dtype)
    x = layer_norm(x + h, layer["ln_mlp"]["scale"], layer["ln_mlp"]["bias"],
                   eps=cfg.norm_eps)
    if mesh is not None:
        x = lax.with_sharding_constraint(
            x, jax.NamedSharding(mesh, P((AXIS_DATA, AXIS_FSDP), None, None))
        )
    return (x, pad_mask), None


def apply(params, tokens, cfg: BertConfig, *, type_ids=None, pad_mask=None,
          mesh=None):
    """tokens [B, T] → (sequence_output [B, T, D], pooled [B, D])."""
    b, t = tokens.shape
    if pad_mask is None:
        pad_mask = jnp.ones((b, t), jnp.float32)
    if type_ids is None:
        type_ids = jnp.zeros((b, t), jnp.int32)
    e = params["embed"]
    x = (
        e["word"][tokens] + e["position"][:t][None] + e["type"][type_ids]
    )
    x = layer_norm(x, e["ln"]["scale"], e["ln"]["bias"], eps=cfg.norm_eps)
    x = x.astype(cfg.dtype)

    layer_fn = functools.partial(_layer_fn, cfg, mesh)
    if cfg.remat:
        layer_fn = jax.checkpoint(layer_fn)
    (x, _), _ = lax.scan(layer_fn, (x, pad_mask), params["layers"])

    pooled = jnp.tanh(
        x[:, 0].astype(jnp.float32) @ params["pooler"]["kernel"]
        + params["pooler"]["bias"]
    )
    return x, pooled


def mlm_logits(params, sequence_output, cfg: BertConfig):
    h = sequence_output.astype(jnp.float32) @ params["mlm"]["transform"]
    h = jax.nn.gelu(h + params["mlm"]["transform_bias"])
    h = layer_norm(h, params["mlm"]["ln"]["scale"], params["mlm"]["ln"]["bias"],
                   eps=cfg.norm_eps)
    return h @ params["embed"]["word"].T + params["mlm"]["output_bias"]


def loss_fn(params, batch, cfg: BertConfig, *, mesh=None):
    """Masked-LM pretraining loss. batch: tokens [B,T], mlm_labels [B,T]
    (negative = unmasked position), optional pad_mask."""
    seq, _ = apply(params, batch["tokens"], cfg,
                   pad_mask=batch.get("pad_mask"), mesh=mesh)
    logits = mlm_logits(params, seq, cfg)
    return softmax_cross_entropy(logits, batch["mlm_labels"])
