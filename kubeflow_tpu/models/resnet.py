"""ResNet image classifier — the tf_cnn_benchmarks analogue.

The reference's canonical training workload is tf_cnn_benchmarks ResNet-50
run through a TFJob (tf-controller-examples/tf-cnn/launcher.py:18, baseline
config #1). This is that workload TPU-first: NHWC layout (XLA's preferred TPU
conv layout), bf16 compute, batch norm folded into inference, data-parallel
batch sharding.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from kubeflow_tpu.parallel.mesh import AXIS_DATA, AXIS_FSDP
from kubeflow_tpu.parallel.sharding import PartitionRule


@dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Sequence[int] = (3, 4, 6, 3)  # ResNet-50
    num_classes: int = 1000
    width: int = 64
    image_size: int = 224
    dtype: jnp.dtype = jnp.bfloat16


PRESETS: dict[str, ResNetConfig] = {
    "resnet50": ResNetConfig(),
    "resnet18": ResNetConfig(stage_sizes=(2, 2, 2, 2)),
    "resnet-test-tiny": ResNetConfig(
        stage_sizes=(1, 1), num_classes=10, width=8, image_size=32
    ),
}


def config(name: str, **overrides) -> ResNetConfig:
    return replace(PRESETS[name], **overrides)


def _conv_init(key, shape):
    fan_in = shape[0] * shape[1] * shape[2]
    return jax.random.normal(key, shape, jnp.float32) * (2.0 / fan_in) ** 0.5


def _bn_init(c):
    return {
        "scale": jnp.ones((c,), jnp.float32),
        "bias": jnp.zeros((c,), jnp.float32),
        "mean": jnp.zeros((c,), jnp.float32),
        "var": jnp.ones((c,), jnp.float32),
    }


def init(key, cfg: ResNetConfig):
    keys = iter(jax.random.split(key, 4 + sum(cfg.stage_sizes) * 4))
    w = cfg.width
    params = {
        "stem": {"conv": _conv_init(next(keys), (7, 7, 3, w)), "bn": _bn_init(w)},
        "stages": [],
        "head": {
            "kernel": jax.random.normal(
                next(keys), (w * (2 ** (len(cfg.stage_sizes) - 1)) * 4,
                             cfg.num_classes), jnp.float32
            ) * 0.01,
            "bias": jnp.zeros((cfg.num_classes,), jnp.float32),
        },
    }
    in_c = w
    for stage_idx, n_blocks in enumerate(cfg.stage_sizes):
        stage = []
        mid_c = w * (2**stage_idx)
        out_c = mid_c * 4
        for block_idx in range(n_blocks):
            block = {
                "conv1": _conv_init(next(keys), (1, 1, in_c, mid_c)),
                "bn1": _bn_init(mid_c),
                "conv2": _conv_init(next(keys), (3, 3, mid_c, mid_c)),
                "bn2": _bn_init(mid_c),
                "conv3": _conv_init(next(keys), (1, 1, mid_c, out_c)),
                "bn3": _bn_init(out_c),
            }
            if block_idx == 0:
                block["proj"] = _conv_init(next(keys), (1, 1, in_c, out_c))
                block["bn_proj"] = _bn_init(out_c)
            stage.append(block)
            in_c = out_c
        params["stages"].append(stage)
    return params


def partition_rules(cfg: ResNetConfig) -> list[PartitionRule]:
    # Convs are small relative to HBM — pure data parallelism; replicate
    # weights, shard only the batch (the reference's DDP layout).
    return []


def batch_partition_spec(cfg: ResNetConfig) -> P:
    return P((AXIS_DATA, AXIS_FSDP), None, None, None)


BN_MOMENTUM = 0.9


def _bn(x, p, eps=1e-5, *, stats=None, path=""):
    # Training mode (stats is a collector dict): normalize with this batch's
    # statistics — under a sharded jit the mean/var reductions run globally
    # across the data axis, i.e. sync-BN for free — and record momentum-merged
    # running stats (stop_gradient) for the trainer to fold back into params.
    # Eval mode (stats is None): stored running statistics.
    if stats is not None:
        x32 = x.astype(jnp.float32)
        mean = jnp.mean(x32, axis=(0, 1, 2))
        var = jnp.var(x32, axis=(0, 1, 2))
        stats[path] = {
            "mean": lax.stop_gradient(
                BN_MOMENTUM * p["mean"] + (1 - BN_MOMENTUM) * mean
            ),
            "var": lax.stop_gradient(
                BN_MOMENTUM * p["var"] + (1 - BN_MOMENTUM) * var
            ),
        }
    else:
        mean, var = p["mean"], p["var"]
    inv = lax.rsqrt(var + eps) * p["scale"]
    return x * inv.astype(x.dtype) + (p["bias"] - mean * inv).astype(x.dtype)


def _conv(x, w, stride=1, padding="SAME"):
    # Same-dtype in/out keeps the transpose (grad) rule happy; XLA still
    # accumulates bf16 convs in float32 on the MXU.
    return lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride), padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _block(x, p, stride, stats, path):
    h = jax.nn.relu(_bn(_conv(x, p["conv1"]), p["bn1"],
                        stats=stats, path=f"{path}/bn1"))
    h = jax.nn.relu(_bn(_conv(h, p["conv2"], stride=stride), p["bn2"],
                        stats=stats, path=f"{path}/bn2"))
    h = _bn(_conv(h, p["conv3"]), p["bn3"], stats=stats, path=f"{path}/bn3")
    if "proj" in p:
        x = _bn(_conv(x, p["proj"], stride=stride), p["bn_proj"],
                stats=stats, path=f"{path}/bn_proj")
    return jax.nn.relu(x + h)


def apply(params, images, cfg: ResNetConfig, *, mesh=None, train=False):
    """images [B, H, W, 3] float → logits [B, num_classes].

    ``train=True`` normalizes with batch statistics and returns
    ``(logits, stats)`` where stats maps BN path → new running statistics
    (consumed by :func:`update_state`)."""
    stats: dict | None = {} if train else None
    x = images.astype(cfg.dtype)
    if mesh is not None:
        x = lax.with_sharding_constraint(
            x, jax.NamedSharding(mesh, batch_partition_spec(cfg))
        )
    x = jax.nn.relu(_bn(_conv(x, params["stem"]["conv"], stride=2),
                        params["stem"]["bn"], stats=stats, path="stem/bn"))
    x = lax.reduce_window(
        x, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME"
    )
    for stage_idx, stage in enumerate(params["stages"]):
        for block_idx, block in enumerate(stage):
            stride = 2 if (stage_idx > 0 and block_idx == 0) else 1
            x = _block(x, block, stride, stats,
                       f"stages/{stage_idx}/{block_idx}")
    x = jnp.mean(x, axis=(1, 2)).astype(jnp.float32)
    logits = x @ params["head"]["kernel"] + params["head"]["bias"]
    return (logits, stats) if train else logits


def update_state(params, stats):
    """Fold the running BN statistics recorded by a ``train=True`` forward
    back into a fresh params pytree (the non-gradient state channel — the
    trainer calls this after the optimizer step, overwriting whatever the
    optimizer did to the stat leaves)."""
    params = jax.tree.map(lambda x: x, params)  # rebuild containers
    for path, value in stats.items():
        node = params
        parts = path.split("/")
        for part in parts[:-1]:
            node = node[int(part)] if part.isdigit() else node[part]
        bn = dict(node[parts[-1]])
        bn["mean"], bn["var"] = value["mean"], value["var"]
        node[parts[-1]] = bn
    return params


def loss_fn(params, batch, cfg: ResNetConfig, *, mesh=None):
    """batch: {"images": [B,H,W,3], "labels": [B]}."""
    from kubeflow_tpu.ops import softmax_cross_entropy

    logits, stats = apply(params, batch["images"], cfg, mesh=mesh, train=True)
    loss, metrics = softmax_cross_entropy(logits, batch["labels"])
    metrics = dict(metrics)
    metrics["_state_updates"] = stats
    return loss, metrics
