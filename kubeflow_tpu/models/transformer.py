"""Decoder-only transformer LM (the flagship workload).

Llama-3-family architecture — RMSNorm pre-norm, rotary positions, grouped-
query flash attention, SwiGLU MLP — written TPU-first:

- Layers are *stacked* (one leading L dim per weight) and iterated with
  ``lax.scan`` (compile time O(1) in depth, FSDP shards every layer
  identically) or, for shallow models, an unrolled Python loop
  (``cfg.scan_layers=False`` — avoids the scan's saved-activation
  stacking, measured ~27% of step time at 3 layers).
- All matmuls run in bfloat16 against float32 master weights held by the
  optimizer; contractions request float32 accumulation on the MXU.
- Sharding is declared as path rules (DP×FSDP×TP out of the box); activations
  get explicit constraints at layer boundaries so GSPMD's decisions stay
  pinned under compiler drift.
- Optional context parallelism routes attention through the ring kernel over
  the ``sequence`` mesh axis (long-context mode, SURVEY.md §5.7).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, replace

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from kubeflow_tpu.ops import flash_attention, rms_norm
from kubeflow_tpu.ops.rotary import apply_rotary, rotary_frequencies
from kubeflow_tpu.parallel.mesh import (
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_PIPELINE,
    AXIS_SEQUENCE,
    AXIS_TENSOR,
)
from kubeflow_tpu.parallel.ring_attention import ring_attention
from kubeflow_tpu.parallel.sharding import PartitionRule


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32_000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14_336
    max_seq_len: int = 8192
    rope_theta: float = 500_000.0
    norm_eps: float = 1e-5
    dtype: jnp.dtype = jnp.bfloat16
    tie_embeddings: bool = False
    # Attention runs through the sequence-axis ring kernel when True.
    context_parallel: bool = False
    remat: bool = True
    # Mixture-of-Experts FFN (0 = dense). GShard-style top-k routing with a
    # static capacity per expert (dropped tokens ride the residual), expert
    # weights sharded over the mesh's `expert` axis — GSPMD inserts the
    # dispatch/combine all-to-alls from the einsum shardings.
    n_experts: int = 0
    expert_top_k: int = 2
    expert_capacity_factor: float = 1.25
    router_aux_loss: float = 0.01
    # Pipeline parallelism (0 = off): layers split into this many stages
    # over the mesh's `pipeline` axis, GPipe-scheduled with
    # pipeline_microbatches microbatches (parallel/pipeline.py).
    pipeline_stages: int = 0
    pipeline_microbatches: int = 4
    # Attention implementation: None (auto = blockwise flash), "plain",
    # "xla" (kubeflow_tpu.ops.flash_attention's implementation arg) and the
    # kv block width — None picks the per-path measured-best (2048 on the
    # XLA scan, where block_k == seq_len collapses it to one fused block,
    # +14% step throughput on v5e; 1024 tiles on the TPU kernels).
    attn_impl: str | None = None
    attn_block_k: int | None = None
    # jax.checkpoint policy when remat=True: "dots" saves matmul outputs
    # (recompute only elementwise), "none" saves nothing (full recompute,
    # minimum HBM traffic), "dots_batched" additionally saves batched dots,
    # "llm" saves exactly the tensors a decoder block's backward reuses
    # most per byte (gate/up projections + pre-wo attention context) and
    # recomputes the cheap rest — measured the best time×memory point for
    # deep models on one chip.
    remat_policy: str = "dots"
    # Iterate layers with lax.scan (O(1) compile in depth) or a Python
    # loop. Scan stacks every saved activation through dynamic-update-
    # slices — measured ~27% of step time at 3 layers — so shallow models
    # should unroll; deep ones need scan for compile time.
    scan_layers: bool = True
    # Compute the LM head + cross entropy in this many row chunks under
    # jax.checkpoint (0 = unchunked): the full [tokens, vocab] fp32 logits
    # (>1GB at 8k tokens × 32k vocab) never materialize — backward
    # recomputes each chunk's logits. Training-loss path only; apply()
    # still returns full logits for serving.
    loss_chunks: int = 0
    # Chunked layer iteration: scan over n_layers/scan_group_size groups,
    # unrolling the layers inside each group. The remat boundary moves to
    # the group, so the only activations the scan stacks are the group
    # inputs ([G, B, T, D]) instead of every per-layer saved dot —
    # compile stays O(G) while the dynamic-update-slice stacking cost
    # drops by the group factor. 1 = plain per-layer scan.
    scan_group_size: int = 1

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


# Named presets; sizes per the public Llama-3/TinyLlama shapes.
PRESETS: dict[str, TransformerConfig] = {
    "llama3-8b": TransformerConfig(
        vocab_size=128_256, d_model=4096, n_layers=32, n_heads=32,
        n_kv_heads=8, d_ff=14_336, rope_theta=500_000.0,
    ),
    "llama-1b": TransformerConfig(
        vocab_size=32_000, d_model=2048, n_layers=16, n_heads=16,
        n_kv_heads=8, d_ff=5632,
    ),
    "lm-test-tiny": TransformerConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, remat=False,
    ),
    # Single-chip flagship bench config: llama-style blocks at d=4096 with
    # a 5×d FFN and llama-3.2-style GQA (32 query / 4 kv heads), 3 layers /
    # 32k vocab — 1.13B params, the widest matmuls that fit 16GB HBM with
    # adafactor. MXU efficiency rises with contraction width (measured
    # v5e: 72 TF/s at K=2048, 107 at K=4096, 162 at K=8192), and at 3
    # layers the activations fit without remat while the unrolled layer
    # loop avoids the scan's saved-activation stacking (~27% of step
    # time). Ladder measured: L4/ff14336/kv8 scan+remat 53.4% MFU →
    # L3/ff20480/kv4 60.4% → unrolled no-remat 69.9% → splash attention
    # kernel (r4) 77.7% (BENCH_r04).
    "flagship-1b": TransformerConfig(
        vocab_size=32_000, d_model=4096, n_layers=3, n_heads=32,
        n_kv_heads=4, d_ff=20_480, max_seq_len=2048, remat=False,
        scan_layers=False, attn_impl="splash", attn_block_k=1024,
    ),
    # Realistic-depth flagship: 16 llama-style layers (VERDICT r2 #1 —
    # the depth class of BERT/Llama users actually bring), 1.53B params,
    # the widest 16-layer geometry that keeps ~2GB HBM headroom on a
    # 16GB v5e (configs within ~300MB of the HBM limit measurably thrash:
    # same geometry drops from 46% to 32-38% MFU). The deep recipe vs the
    # shallow flagship: unrolled layers + the "llm" named-save remat
    # policy (save gate/up/attn-context, recompute the cheap rest) and
    # bf16 gradients (OptimizerConfig.grad_dtype) — each buys HBM that
    # goes straight into width. Round 4: the GQA-native splash attention
    # kernel (fused bwd + causal block skipping) replaced the single-block
    # XLA path and the unchunked LM loss replaced loss_chunks=8 (the
    # splash memory savings make the full logits fit; the chunked head's
    # extra forward cost ~1.2 MFU pts). Measured ladder at 16L, 8192
    # tok/step: r3 XLA 61.3/57.2/48.0/38.1 at seq256/512/1024/2048 →
    # splash 62.6/62.5/60.5/57.6 (BENCH_r04).
    "flagship-deep": TransformerConfig(
        vocab_size=32_000, d_model=3072, n_layers=16, n_heads=24,
        n_kv_heads=4, d_ff=6656, max_seq_len=2048, remat=True,
        remat_policy="llm", scan_layers=False, loss_chunks=0,
        attn_impl="splash", attn_block_k=1024,
    ),
    # Mixtral-family shape at reduced depth (8 experts, top-2).
    "moe-1b": TransformerConfig(
        vocab_size=32_000, d_model=1024, n_layers=8, n_heads=16,
        n_kv_heads=4, d_ff=3584, n_experts=8, expert_top_k=2,
    ),
    "moe-test-tiny": TransformerConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, remat=False, n_experts=4,
        expert_top_k=2,
    ),
}


def config(name: str, **overrides) -> TransformerConfig:
    return replace(PRESETS[name], **overrides)


# ---------------------------------------------------------------------------
# Parameters
# ---------------------------------------------------------------------------


def init(key, cfg: TransformerConfig):
    """Parameter pytree; weights float32 (cast to cfg.dtype at apply time)."""
    d, f = cfg.d_model, cfg.d_ff
    hd = cfg.head_dim
    # NOTE: split count must stay 8 — changing it would silently reshuffle
    # every existing model's init for a given seed (threefry pairs counters
    # with the split width). Extra keys come from fold_in, like lm_head.
    keys = jax.random.split(key, 8)

    def dense(k, shape, fan_in):
        return jax.random.normal(k, shape, jnp.float32) * (fan_in**-0.5)

    def stack(k, shape, fan_in):
        return dense(k, (cfg.n_layers, *shape), fan_in)

    if cfg.n_experts:
        e = cfg.n_experts
        mlp = {
            "router": stack(jax.random.fold_in(key, 98), (d, e), d),
            "gate": stack(keys[5], (e, d, f), d),
            "up": stack(keys[6], (e, d, f), d),
            "down": stack(keys[7], (e, f, d), f),
        }
    else:
        mlp = {
            "gate": stack(keys[5], (d, f), d),
            "up": stack(keys[6], (d, f), d),
            "down": stack(keys[7], (f, d), f),
        }
    params = {
        "embed": {"kernel": dense(keys[0], (cfg.vocab_size, d), d)},
        "layers": {
            "attn": {
                "wq": stack(keys[1], (d, cfg.n_heads * hd), d),
                "wk": stack(keys[2], (d, cfg.n_kv_heads * hd), d),
                "wv": stack(keys[3], (d, cfg.n_kv_heads * hd), d),
                "wo": stack(keys[4], (cfg.n_heads * hd, d), cfg.n_heads * hd),
            },
            "mlp": mlp,
            "ln_attn": jnp.ones((cfg.n_layers, d), jnp.float32),
            "ln_mlp": jnp.ones((cfg.n_layers, d), jnp.float32),
        },
        "final_norm": jnp.ones((d,), jnp.float32),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = {
            "kernel": dense(jax.random.fold_in(key, 99), (d, cfg.vocab_size), d)
        }
    return params


def partition_rules(cfg: TransformerConfig) -> list[PartitionRule]:
    """DP×FSDP×TP(×EP) layout. Stacked layer weights carry a leading L dim
    (never sharded). Megatron pairing: column-parallel in (wq/wk/wv/gate/up),
    row-parallel out (wo/down) so each block needs one reduce per residual
    add. MoE expert weights [L, E, ...] shard E over the expert axis."""
    # Stacked layer weights' leading L dim maps onto pipeline stages when
    # pipeline parallelism is on (each stage holds its contiguous slice).
    ldim = AXIS_PIPELINE if cfg.pipeline_stages > 1 else None
    rules = [
        PartitionRule(r"embed/kernel", P(AXIS_TENSOR, AXIS_FSDP)),
        PartitionRule(r"attn/w[qkv]", P(ldim, AXIS_FSDP, AXIS_TENSOR)),
        PartitionRule(r"attn/wo", P(ldim, AXIS_TENSOR, AXIS_FSDP)),
    ]
    if cfg.pipeline_stages > 1:
        rules.append(PartitionRule(r"layers/ln_", P(AXIS_PIPELINE)))
    if cfg.n_experts:
        rules += [
            PartitionRule(r"mlp/router", P(ldim, AXIS_FSDP, None)),
            PartitionRule(
                r"mlp/(gate|up)",
                P(ldim, AXIS_EXPERT, AXIS_FSDP, AXIS_TENSOR),
            ),
            PartitionRule(
                r"mlp/down", P(ldim, AXIS_EXPERT, AXIS_TENSOR, AXIS_FSDP)
            ),
        ]
    else:
        rules += [
            PartitionRule(r"mlp/(gate|up)", P(ldim, AXIS_FSDP, AXIS_TENSOR)),
            PartitionRule(r"mlp/down", P(ldim, AXIS_TENSOR, AXIS_FSDP)),
        ]
    rules.append(PartitionRule(r"lm_head/kernel", P(AXIS_FSDP, AXIS_TENSOR)))
    # norms replicated (fall through to default P()).
    return rules


def batch_partition_spec(cfg: TransformerConfig) -> P:
    if cfg.context_parallel:
        return P((AXIS_DATA, AXIS_FSDP), AXIS_SEQUENCE)
    return P((AXIS_DATA, AXIS_FSDP), None)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _constrain(x, mesh, spec):
    if mesh is not None:
        x = lax.with_sharding_constraint(x, jax.NamedSharding(mesh, spec))
    return x


def _attention(x, layer, cfg: TransformerConfig, rope, mesh):
    b, t, d = x.shape
    hd = cfg.head_dim
    cos, sin = rope
    q = (x @ layer["wq"].astype(cfg.dtype)).reshape(b, t, cfg.n_heads, hd)
    k = (x @ layer["wk"].astype(cfg.dtype)).reshape(b, t, cfg.n_kv_heads, hd)
    v = (x @ layer["wv"].astype(cfg.dtype)).reshape(b, t, cfg.n_kv_heads, hd)
    # Inert unless the policy names them ("llm_qkv"): saving post-rope
    # q/k/v spares the backward from re-running rms_norm + the three
    # projections + rope just to rebuild the flash kernel's residuals.
    q = checkpoint_name(apply_rotary(q, cos, sin), "attn_q")
    k = checkpoint_name(apply_rotary(k, cos, sin), "attn_k")
    v = checkpoint_name(v, "attn_v")
    if cfg.context_parallel:
        # Ring over the sequence axis; GQA folded by repeating KV heads
        # (ring kernel is MHA). [B,T,H,D] -> [B,H,T,D].
        reps = cfg.n_heads // cfg.n_kv_heads
        k = jnp.repeat(k, reps, axis=2)
        v = jnp.repeat(v, reps, axis=2)
        out = ring_attention(
            q.transpose(0, 2, 1, 3),
            k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3),
            mesh,
            causal=True,
        ).transpose(0, 2, 1, 3)
    else:
        out = flash_attention(
            q, k, v, causal=True,
            implementation=cfg.attn_impl,
            block_k=cfg.attn_block_k,
        )
    out = out.reshape(b, t, cfg.n_heads * hd)
    # Inert without the "llm" policy: wo's backward reuses its input, so
    # saving it here spares recomputing the whole attention block.
    out = checkpoint_name(out, "attn_ctx")
    return out @ layer["wo"].astype(cfg.dtype)


def _mlp(x, layer, cfg: TransformerConfig):
    gate = checkpoint_name(x @ layer["gate"].astype(cfg.dtype), "mlp_gate")
    up = checkpoint_name(x @ layer["up"].astype(cfg.dtype), "mlp_up")
    return (jax.nn.silu(gate) * up) @ layer["down"].astype(cfg.dtype)


def moe_ffn(x, mlp, cfg: TransformerConfig, token_valid=None):
    """GShard-style MoE FFN: top-k routing with static per-expert capacity.

    Everything is fixed-shape einsums (no gather/scatter, no dynamic
    shapes): tokens are dispatched into [E, C, D] expert buffers via a
    one-hot dispatch tensor, each expert runs a batched SwiGLU (weights
    stacked on a leading E dim, sharded over the `expert` mesh axis —
    GSPMD turns the dispatch/combine einsums into all-to-alls over ICI),
    and outputs combine back weighted by the normalized gate. Tokens past
    an expert's capacity are dropped and ride the residual connection.

    x: [B, T, D] → (y [B, T, D], aux_loss scalar) — aux is the
    load-balancing loss (Switch/GShard: E · Σ_e fraction_e · mean_prob_e).

    ``token_valid`` ([B, T] bool): padding tokens claim no expert capacity
    and are excluded from the aux statistics — without this, a ragged
    serving batch's pad slots would evict real tokens from their experts.
    """
    b, t, d = x.shape
    e = cfg.n_experts
    k = min(cfg.expert_top_k, e)
    n = b * t
    capacity = max(int(n * k / e * cfg.expert_capacity_factor), k)
    xf = x.reshape(n, d)

    logits = (xf.astype(jnp.float32)
              @ mlp["router"].astype(jnp.float32))  # router in fp32
    probs = jax.nn.softmax(logits, axis=-1)  # [n, e]
    gate_vals, expert_idx = lax.top_k(probs, k)  # [n, k]
    gate_vals = gate_vals / jnp.maximum(
        jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9
    )

    oh_e = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)  # [n, k, e]
    n_valid = jnp.float32(n)
    if token_valid is not None:
        tv = token_valid.reshape(n).astype(jnp.float32)
        oh_e = oh_e * tv[:, None, None]
        n_valid = jnp.maximum(jnp.sum(tv), 1.0)
    # Position of each (token, slot) within its expert, priority-major:
    # all first choices are placed before any second choice (GShard order).
    flat = oh_e.transpose(1, 0, 2).reshape(k * n, e)
    pos = (jnp.cumsum(flat, axis=0) - flat).reshape(k, n, e).transpose(
        1, 0, 2
    )
    slot_pos = jnp.sum(pos * oh_e, axis=-1)  # [n, k]
    keep = slot_pos < capacity
    oh_c = jax.nn.one_hot(
        jnp.where(keep, slot_pos, 0), capacity, dtype=jnp.float32
    ) * keep[..., None]  # [n, k, c]

    dispatch = jnp.einsum("nke,nkc->nec", oh_e, oh_c)
    combine = jnp.einsum(
        "nke,nkc,nk->nec", oh_e, oh_c, gate_vals
    ).astype(cfg.dtype)

    expert_in = jnp.einsum(
        "nd,nec->ecd", xf, dispatch.astype(cfg.dtype)
    )  # [e, c, d]
    g = jnp.einsum("ecd,edf->ecf", expert_in, mlp["gate"].astype(cfg.dtype))
    u = jnp.einsum("ecd,edf->ecf", expert_in, mlp["up"].astype(cfg.dtype))
    out = jnp.einsum(
        "ecf,efd->ecd", jax.nn.silu(g) * u, mlp["down"].astype(cfg.dtype)
    )
    y = jnp.einsum("ecd,nec->nd", out, combine)

    # Load-balance aux: fraction of top-1 tokens per expert × mean router
    # prob per expert (differentiable through probs only; valid tokens only).
    top1_frac = jnp.sum(oh_e[:, 0, :], axis=0) / n_valid
    if token_valid is not None:
        mean_prob = jnp.sum(
            probs * token_valid.reshape(n, 1), axis=0
        ) / n_valid
    else:
        mean_prob = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(top1_frac * mean_prob)
    return y.reshape(b, t, d).astype(x.dtype), aux


def _layer_fn(cfg: TransformerConfig, mesh, rope, carry, layer):
    x, aux = carry
    act_spec = batch_partition_spec(cfg) + (None,)
    h = rms_norm(x, layer["ln_attn"], eps=cfg.norm_eps)
    x = x + _attention(h, layer["attn"], cfg, rope, mesh)
    x = _constrain(x, mesh, P(*act_spec))
    h = rms_norm(x, layer["ln_mlp"], eps=cfg.norm_eps)
    if cfg.n_experts:
        y, layer_aux = moe_ffn(h, layer["mlp"], cfg)
        x = x + y
        aux = aux + layer_aux
    else:
        x = x + _mlp(h, layer["mlp"], cfg)
    x = _constrain(x, mesh, P(*act_spec))
    return (x, aux), None


def _layer_fn_attn_saved(cfg: TransformerConfig, mesh, rope, mlp_policy,
                         carry, layer):
    """The "llm_attn" remat layout: the attention half runs OUTSIDE any
    checkpoint region — its backward consumes the kernel's own residuals
    (q/k/v/out/logsumexp) instead of re-running rms_norm + the three
    projections + rope + the flash forward — while the FFN half (the bulk
    of saved-activation memory) stays under ``jax.checkpoint`` saving only
    the gate/up projections. At long sequence the attention-rebuild
    recompute is the dominant remat bill; this trades ~120MB/layer of
    residuals for all of it."""
    x, aux = carry
    act_spec = batch_partition_spec(cfg) + (None,)
    h = rms_norm(x, layer["ln_attn"], eps=cfg.norm_eps)
    x = x + _attention(h, layer["attn"], cfg, rope, mesh)
    x = _constrain(x, mesh, P(*act_spec))

    @functools.partial(jax.checkpoint, policy=mlp_policy)
    def mlp_part(x, ln, mlp):
        h = rms_norm(x, ln, eps=cfg.norm_eps)
        return x + _mlp(h, mlp, cfg)

    x = mlp_part(x, layer["ln_mlp"], layer["mlp"])
    x = _constrain(x, mesh, P(*act_spec))
    return (x, aux), None


def _embed_lookup(kernel, tokens, cfg: TransformerConfig, mesh):
    """Token embedding. Under a tensor-parallel mesh the lookup runs as a
    one-hot matmul: GSPMD partitions matmuls cleanly (contraction over the
    tensor-sharded vocab dim → one reduce), where a gather from a sharded
    table triggers involuntary full rematerialization (spmd_partitioner
    replicate-then-reshard, observed on the dryrun tp path); the backward
    scatter-add becomes a matmul too. Plain gather elsewhere — one-hot costs
    O(B·T·V) flops it only earns back when it buys clean partitioning."""
    if mesh is not None and mesh.shape.get(AXIS_TENSOR, 1) > 1:
        one_hot = jax.nn.one_hot(tokens, cfg.vocab_size, dtype=kernel.dtype)
        return one_hot @ kernel
    return kernel[tokens]


def hidden_states(params, tokens, cfg: TransformerConfig, *, mesh=None):
    """tokens [B, T] → (final-norm hidden [B, T, D] in cfg.dtype, MoE aux
    loss). The trunk of :func:`apply` without the LM head — the chunked
    training-loss path applies the head inside the loss instead."""
    t = tokens.shape[1]
    rope = rotary_frequencies(cfg.head_dim, t, theta=cfg.rope_theta)
    x = _embed_lookup(
        params["embed"]["kernel"].astype(cfg.dtype), tokens, cfg, mesh
    )
    x = _constrain(x, mesh, P(*(batch_partition_spec(cfg) + (None,))))

    policy = {
        "dots": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        "dots_batched": jax.checkpoint_policies.dots_saveable,
        "llm": jax.checkpoint_policies.save_only_these_names(
            "attn_ctx", "mlp_gate", "mlp_up"
        ),
        # "llm" + post-rope q/k/v: the flash backward's residual rebuild
        # starts from the saved projections instead of re-running
        # rms_norm/wq/wk/wv/rope. Costs ~(1+2/group)·B·T·D bf16 per layer;
        # buys back the projection recompute — the right trade at long
        # sequence where attention dominates the remat bill.
        "llm_qkv": jax.checkpoint_policies.save_only_these_names(
            "attn_ctx", "mlp_gate", "mlp_up", "attn_q", "attn_k", "attn_v"
        ),
        # Attention outside the remat region entirely (its kernel
        # residuals are saved; only the FFN half is checkpointed) —
        # handled structurally below, not by a save filter.
        "llm_attn": jax.checkpoint_policies.save_only_these_names(
            "mlp_gate", "mlp_up"
        ),
        # "llm" + the splash kernel's own residuals (o/logsumexp, named
        # "attn_res" via residual_checkpoint_name): the backward skips the
        # forward-kernel rerun. Only meaningful with attn_impl="splash".
        "llm_res": jax.checkpoint_policies.save_only_these_names(
            "attn_ctx", "mlp_gate", "mlp_up", "attn_res"
        ),
        "none": None,
    }[cfg.remat_policy]
    attn_saved = cfg.remat and cfg.remat_policy == "llm_attn"

    if cfg.pipeline_stages > 1 and mesh is not None:
        if cfg.n_experts or cfg.context_parallel:
            raise ValueError(
                "pipeline_stages composes with dp/fsdp/tp, not (yet) with "
                "MoE or context parallelism"
            )
        if cfg.n_layers % cfg.pipeline_stages:
            raise ValueError(
                f"n_layers {cfg.n_layers} not divisible by "
                f"pipeline_stages {cfg.pipeline_stages}"
            )
        from kubeflow_tpu.parallel.pipeline import pipeline_apply

        if attn_saved:
            raise ValueError(
                "remat_policy='llm_attn' is incompatible with "
                "pipeline_stages>1 (stages checkpoint whole layers); "
                "use 'llm'"
            )

        def one_layer(layer, h):
            h2 = rms_norm(h, layer["ln_attn"], eps=cfg.norm_eps)
            h = h + _attention(h2, layer["attn"], cfg, rope, None)
            h2 = rms_norm(h, layer["ln_mlp"], eps=cfg.norm_eps)
            return h + _mlp(h2, layer["mlp"], cfg)

        if cfg.remat:
            one_layer = jax.checkpoint(one_layer, policy=policy)
        x = pipeline_apply(one_layer, params["layers"], x, mesh,
                           n_micro=cfg.pipeline_microbatches)
        aux = jnp.zeros((), jnp.float32)
    else:
        if attn_saved:
            if cfg.n_experts:
                raise ValueError(
                    "remat_policy='llm_attn' applies to dense FFN layers; "
                    "MoE models should use 'llm' or 'dots'"
                )
            if cfg.scan_group_size > 1:
                # The grouped scan wraps whole groups in jax.checkpoint,
                # which would discard the attention residuals this policy
                # exists to keep — refuse rather than silently degrade
                # below "llm".
                raise ValueError(
                    "remat_policy='llm_attn' is incompatible with "
                    "scan_group_size>1; use 'llm'"
                )
            layer_fn = functools.partial(
                _layer_fn_attn_saved, cfg, mesh, rope, policy
            )
        else:
            layer_fn = functools.partial(_layer_fn, cfg, mesh, rope)
        carry = (x, jnp.zeros((), jnp.float32))
        if cfg.scan_group_size > 1 and not cfg.scan_layers:
            raise ValueError(
                "scan_group_size applies to the lax.scan representation; "
                "set scan_layers=True (or drop scan_group_size)"
            )
        if cfg.scan_layers and cfg.scan_group_size > 1:
            group = cfg.scan_group_size
            if cfg.n_layers % group:
                raise ValueError(
                    f"n_layers {cfg.n_layers} not divisible by "
                    f"scan_group_size {group}"
                )

            def group_fn(c, layers):
                for i in range(group):
                    layer = jax.tree.map(lambda w: w[i], layers)
                    c, _ = layer_fn(c, layer)
                return c, None

            if cfg.remat:
                group_fn = jax.checkpoint(group_fn, policy=policy)
            grouped = jax.tree.map(
                lambda w: w.reshape(
                    cfg.n_layers // group, group, *w.shape[1:]
                ),
                params["layers"],
            )
            carry, _ = lax.scan(group_fn, carry, grouped)
        else:
            if cfg.remat and not attn_saved:
                # llm_attn checkpoints inside the layer fn (FFN half only).
                layer_fn = jax.checkpoint(layer_fn, policy=policy)
            if cfg.scan_layers:
                carry, _ = lax.scan(layer_fn, carry, params["layers"])
            else:
                for i in range(cfg.n_layers):
                    layer = jax.tree.map(lambda w: w[i], params["layers"])
                    carry, _ = layer_fn(carry, layer)
        x, aux = carry

    x = rms_norm(x, params["final_norm"], eps=cfg.norm_eps)
    return x, aux


def _head_kernel(params, cfg: TransformerConfig):
    if cfg.tie_embeddings:
        return params["embed"]["kernel"].T
    return params["lm_head"]["kernel"]


def apply(params, tokens, cfg: TransformerConfig, *, mesh=None,
          return_aux: bool = False):
    """tokens [B, T] int32 → logits [B, T, V] (cfg.dtype).

    ``return_aux=True`` additionally returns the summed MoE router
    load-balance loss (0.0 for dense models)."""
    x, aux = hidden_states(params, tokens, cfg, mesh=mesh)
    logits = x @ _head_kernel(params, cfg).astype(cfg.dtype)
    if return_aux:
        return logits, aux
    return logits


def loss_fn(params, batch, cfg: TransformerConfig, *, mesh=None):
    """Next-token LM loss. batch: {"tokens": [B, T+1] int32} (or separate
    "inputs"/"targets"); negative targets are ignored."""
    from kubeflow_tpu.ops import softmax_cross_entropy
    from kubeflow_tpu.ops.losses import chunked_lm_head_loss

    if "inputs" in batch:
        inputs, targets = batch["inputs"], batch["targets"]
    else:
        inputs, targets = batch["tokens"][:, :-1], batch["tokens"][:, 1:]
    if cfg.loss_chunks:
        x, aux = hidden_states(params, inputs, cfg, mesh=mesh)
        b, t, d = x.shape
        loss, metrics = chunked_lm_head_loss(
            x.reshape(b * t, d),
            _head_kernel(params, cfg).astype(cfg.dtype),
            targets.reshape(b * t),
            z_loss=1e-4, n_chunks=cfg.loss_chunks,
        )
    else:
        logits, aux = apply(params, inputs, cfg, mesh=mesh, return_aux=True)
        loss, metrics = softmax_cross_entropy(logits, targets, z_loss=1e-4)
    if cfg.n_experts and cfg.router_aux_loss:
        aux_loss = cfg.router_aux_loss * aux
        metrics["router_aux_loss"] = aux_loss
        loss = loss + aux_loss
    return loss, metrics
