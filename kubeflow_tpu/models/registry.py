"""Model registry: name → ModelSpec.

The lookup layer jobs, serving, and the benchmark harness share — the
analogue of the reference's prototype `@param model name` indirection
(e.g. kubeflow/examples/prototypes/tf-job-simple.jsonnet), but typed.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

from kubeflow_tpu.models import bert, resnet, transformer


@dataclass(frozen=True)
class ModelSpec:
    name: str
    family: str
    config: Any
    init: Callable          # (key, cfg) -> params
    apply: Callable         # (params, inputs, cfg, *, mesh=None) -> outputs
    loss_fn: Callable       # (params, batch, cfg, *, mesh=None) -> (loss, metrics)
    partition_rules: Callable
    batch_partition_spec: Callable
    # Optional non-gradient state channel: (params, metrics["_state_updates"])
    # -> params, applied by the trainer after the optimizer step (BN running
    # stats and the like).
    update_state: Callable | None = None


def _spec(name, family, module, cfg) -> ModelSpec:
    return ModelSpec(
        name=name,
        family=family,
        config=cfg,
        init=module.init,
        apply=module.apply,
        loss_fn=module.loss_fn,
        partition_rules=module.partition_rules,
        batch_partition_spec=module.batch_partition_spec,
        update_state=getattr(module, "update_state", None),
    )


def get_model(name: str, **overrides) -> ModelSpec:
    for family, module in (
        ("transformer", transformer),
        ("bert", bert),
        ("resnet", resnet),
    ):
        if name in module.PRESETS:
            return _spec(name, family, module, module.config(name, **overrides))
    raise KeyError(
        f"unknown model {name!r}; available: {sorted(list_models())}"
    )


def list_models() -> list[str]:
    return [
        *transformer.PRESETS,
        *bert.PRESETS,
        *resnet.PRESETS,
    ]
