"""Autoregressive decoding with a KV cache for the transformer family.

TPU-first incremental decoding: one prefill pass fills the cache for the
whole (right-padded) prompt batch, then ``lax.scan`` decodes in lockstep —
every step is a fixed-shape single-token forward against the cache, so the
whole generate call is ONE compiled executable (no per-token dispatch, no
shape churn). Ragged prompts are handled with a per-row validity mask and
per-row RoPE positions: row ``b``'s token at decode step ``t`` carries true
position ``length[b] + t`` even though it lives at cache slot ``T0 + t``.

The reference serves generation through TF-Serving's black-box ModelServer;
this is the equivalent capability for the platform's own engine
(kubeflow/tf-serving/tf-serving-template.libsonnet:29-49 surface).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from kubeflow_tpu.ops import rms_norm
from kubeflow_tpu.ops.rotary import rotary_frequencies
from kubeflow_tpu.models.transformer import TransformerConfig, moe_ffn

_NEG_INF = -1e30


def init_cache(cfg: TransformerConfig, batch: int, total_len: int):
    """Per-layer K/V cache, stacked on a leading layer dim like the params."""
    shape = (cfg.n_layers, batch, total_len, cfg.n_kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


def _gqa_attention(q, k_cache, v_cache, mask, cfg):
    """Grouped-query attention over a KV cache, GQA-native: the query-
    head group rides its own einsum axis, so K/V are read at kv-head
    width — never repeated to H_q width (a 2-8x cut in decode cache
    traffic, the decode-step bandwidth bill). q: [B, S, H, hd]; cache:
    [B, T, Hkv, hd]; mask broadcastable to [B, Hkv, G, S, T]. Returns
    [B, S, H*hd]."""
    b, s, _h, hd = q.shape
    group = cfg.n_heads // cfg.n_kv_heads
    qg = q.reshape(b, s, cfg.n_kv_heads, group, hd)
    scores = jnp.einsum(
        "bskgd,btkd->bkgst", qg.astype(jnp.float32),
        k_cache.astype(jnp.float32)
    ) * (hd ** -0.5)
    scores = jnp.where(mask, scores, _NEG_INF)
    p = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
    return jnp.einsum("bkgst,btkd->bskgd", p, v_cache).reshape(
        b, s, cfg.n_heads * hd)


def _cached_attention(x, layer, cfg, rope_bt, k_cache, v_cache, pos, valid):
    """x: [B, S, D] at cache slots pos..pos+S; attends over the full cache
    masked by ``valid`` [B, total]. Returns (out, k_cache, v_cache)."""
    b, s, _d = x.shape
    hd = cfg.head_dim
    cos, sin = rope_bt  # [B, S, hd//2] gathered per row by the caller
    q = (x @ layer["wq"].astype(cfg.dtype)).reshape(b, s, cfg.n_heads, hd)
    k = (x @ layer["wk"].astype(cfg.dtype)).reshape(b, s, cfg.n_kv_heads, hd)
    v = (x @ layer["wv"].astype(cfg.dtype)).reshape(b, s, cfg.n_kv_heads, hd)
    q = _rope(q, cos, sin)
    k = _rope(k, cos, sin)
    k_cache = lax.dynamic_update_slice(k_cache, k, (0, pos, 0, 0))
    v_cache = lax.dynamic_update_slice(v_cache, v, (0, pos, 0, 0))

    total = k_cache.shape[1]
    # Causality within the new block: query at slot pos+i sees key slot j
    # iff j <= pos+i; prompt padding and unwritten slots are masked by
    # ``valid`` (which already includes slots pos..pos+S for this block).
    j_idx = jnp.arange(total)[None, None, :]
    i_idx = pos + jnp.arange(s)[None, :, None]
    mask = (j_idx <= i_idx) & valid[:, None, :]
    out = _gqa_attention(q, k_cache, v_cache, mask[:, None, None], cfg)
    return out @ layer["wo"].astype(cfg.dtype), k_cache, v_cache


def _rope(x, cos, sin):
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    c, s = cos[:, :, None, :], sin[:, :, None, :]
    return jnp.concatenate(
        [x1 * c - x2 * s, x1 * s + x2 * c], axis=-1
    ).astype(x.dtype)


def forward_cached(params, tokens, cfg: TransformerConfig, cache, pos,
                   positions, valid, token_valid=None):
    """tokens [B, S] at cache slots pos..pos+S with true sequence positions
    ``positions`` [B, S] → (logits [B, S, V], new cache). ``token_valid``
    ([B, S]) marks real (non-pad) tokens in THIS block — MoE routing must
    not let ragged-prefill padding claim expert capacity."""
    cos_t, sin_t = rotary_frequencies(cfg.head_dim, cache["k"].shape[2],
                                      theta=cfg.rope_theta)
    rope_bt = (cos_t[positions], sin_t[positions])
    x = params["embed"]["kernel"].astype(cfg.dtype)[tokens]

    def layer_fn(x, layer_and_cache):
        layer, k_cache, v_cache = layer_and_cache
        h = rms_norm(x, layer["ln_attn"], eps=cfg.norm_eps)
        attn, k_cache, v_cache = _cached_attention(
            h, layer["attn"], cfg, rope_bt, k_cache, v_cache, pos, valid
        )
        x = x + attn
        h = rms_norm(x, layer["ln_mlp"], eps=cfg.norm_eps)
        if cfg.n_experts:
            y, _aux = moe_ffn(h, layer["mlp"], cfg, token_valid=token_valid)
            x = x + y
        else:
            gate = h @ layer["mlp"]["gate"].astype(cfg.dtype)
            up = h @ layer["mlp"]["up"].astype(cfg.dtype)
            x = x + (jax.nn.silu(gate) * up) @ layer["mlp"]["down"].astype(
                cfg.dtype
            )
        return x, (k_cache, v_cache)

    x, (k_new, v_new) = lax.scan(
        layer_fn, x, (params["layers"], cache["k"], cache["v"])
    )
    x = rms_norm(x, params["final_norm"], eps=cfg.norm_eps)
    head = (params["embed"]["kernel"].T if cfg.tie_embeddings
            else params["lm_head"]["kernel"])
    logits = x @ head.astype(cfg.dtype)
    return logits.astype(jnp.float32), {"k": k_new, "v": v_new}


def sample_token(logits, key, temperature, top_k: int = 0):
    """logits [B, V], temperature [B] (<=0 → greedy), static top_k."""
    greedy = jnp.argmax(logits, axis=-1)
    if top_k and top_k < logits.shape[-1]:
        kth = lax.top_k(logits, top_k)[0][:, -1:]
        logits = jnp.where(logits < kth, _NEG_INF, logits)
    temp = jnp.maximum(temperature, 1e-6)[:, None]
    sampled = jax.random.categorical(key, logits / temp, axis=-1)
    return jnp.where(temperature <= 0.0, greedy, sampled).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("cfg", "max_new_tokens",
                                             "top_k"))
def generate(params, prompt_tokens, prompt_lengths, cfg: TransformerConfig,
             *, max_new_tokens: int, key, temperature, top_k: int = 0,
             row_valid=None):
    """prompt_tokens [B, T0] right-padded, prompt_lengths [B] →
    (generated [B, max_new_tokens], prefill_logits [B, V]).

    ``temperature`` [B]: <=0 rows decode greedily. ``row_valid`` [B] marks
    real instances in a server-padded batch — pad rows must not claim MoE
    expert capacity during decode and evict real tokens' expert choices.
    One compiled call: prefill + a scanned decode loop over the KV cache.
    """
    b, t0 = prompt_tokens.shape
    total = t0 + max_new_tokens
    cache = init_cache(cfg, b, total)
    # A zero-length row would wrap the last-logit gather to index -1 (the
    # last prefill slot) and seed generation from garbage; clamp to 1 so
    # the behavior is defined even if callers skip engine validation.
    prompt_lengths = jnp.maximum(prompt_lengths, 1)
    if row_valid is None:
        row_valid = jnp.ones((b,), bool)

    slot = jnp.arange(total)[None, :]
    valid = slot < prompt_lengths[:, None]  # prompt slots only
    positions = jnp.broadcast_to(jnp.arange(t0)[None], (b, t0))
    logits, cache = forward_cached(
        params, prompt_tokens, cfg, cache, 0, positions, valid,
        token_valid=(jnp.arange(t0)[None] < prompt_lengths[:, None])
        & row_valid[:, None],
    )
    last = jnp.take_along_axis(
        logits, (prompt_lengths - 1)[:, None, None], axis=1
    )[:, 0]

    def step(carry, i):
        cache, valid, tok, logits_prev, key = carry
        key, sub = jax.random.split(key)
        tok = sample_token(logits_prev, sub, temperature, top_k)
        slot_i = t0 + i
        valid = valid.at[:, slot_i].set(True)
        pos_i = (prompt_lengths + i)[:, None]  # true position per row
        logits, cache = forward_cached(
            params, tok[:, None], cfg, cache, slot_i, pos_i, valid,
            token_valid=row_valid[:, None],
        )
        return (cache, valid, tok, logits[:, 0], key), tok

    (_, _, _, _, _), toks = lax.scan(
        step, (cache, valid, jnp.zeros((b,), jnp.int32), last, key),
        jnp.arange(max_new_tokens),
    )
    return toks.T, last  # [B, max_new], [B, V]


# ---------------------------------------------------------------------------
# Continuous-batching primitives (serving/continuous.py drives these)
# ---------------------------------------------------------------------------
#
# The lockstep ``generate`` above compiles prefill+decode into one call — the
# right shape for offline batches, the wrong one for a server: every request
# waits for the slowest peer. The continuous path splits the work into
# fixed-shape executables so the scheduler can retire/admit rows between
# steps: ``admit_rows_and_step`` (prefill a round's admissions, scatter them
# into the persistent state, and take one decode step — one dispatch) and
# ``decode_step``/``decode_chunk`` (one token / K fused tokens for ALL
# slots). ``prefill`` + ``insert_row`` remain as the unfused admission
# pieces (callers that need the row cache itself). Unlike ``generate``'s
# shared scalar ``pos``, rows here sit at *different* sequence positions,
# so the cache write and attention mask are per-row.


def _ragged_attention(x, layer, cfg, rope_bt, k_cache, v_cache, pos_b, valid):
    """Single-token attention where row ``b`` writes cache slot ``pos_b[b]``
    — the continuous-batching variant of :func:`_cached_attention` (rows at
    heterogeneous positions). x: [B, 1, D]; pos_b: [B]; valid: [B, total]."""
    b, s, _d = x.shape
    hd = cfg.head_dim
    cos, sin = rope_bt
    q = (x @ layer["wq"].astype(cfg.dtype)).reshape(b, s, cfg.n_heads, hd)
    k = (x @ layer["wk"].astype(cfg.dtype)).reshape(b, s, cfg.n_kv_heads, hd)
    v = (x @ layer["wv"].astype(cfg.dtype)).reshape(b, s, cfg.n_kv_heads, hd)
    q = _rope(q, cos, sin)
    k = _rope(k, cos, sin)
    rows = jnp.arange(b)
    # Out-of-bounds pos_b (a retired row parked at total) is dropped by
    # scatter semantics — retired rows write nowhere.
    k_cache = k_cache.at[rows, pos_b].set(k[:, 0])
    v_cache = v_cache.at[rows, pos_b].set(v[:, 0])
    out = _gqa_attention(q, k_cache, v_cache,
                         valid[:, None, None, None, :], cfg)
    return out @ layer["wo"].astype(cfg.dtype), k_cache, v_cache


@functools.partial(jax.jit, static_argnames=("cfg", "total_len"))
def prefill(params, prompt_tokens, prompt_lengths, cfg: TransformerConfig, *,
            total_len: int):
    """One request's prompt pass: tokens [B, T0] right-padded → (cache with
    ``total_len`` slots, last-position logits [B, V]). Slots beyond the true
    length hold pad junk; decode overwrites them before the mask admits them.
    """
    b, t0 = prompt_tokens.shape
    cache = init_cache(cfg, b, total_len)
    prompt_lengths = jnp.maximum(prompt_lengths, 1)
    valid = jnp.arange(total_len)[None, :] < prompt_lengths[:, None]
    positions = jnp.broadcast_to(jnp.arange(t0)[None], (b, t0))
    logits, cache = forward_cached(
        params, prompt_tokens, cfg, cache, 0, positions, valid,
        token_valid=positions < prompt_lengths[:, None],
    )
    last = jnp.take_along_axis(
        logits, (prompt_lengths - 1)[:, None, None], axis=1
    )[:, 0]
    return cache, last


def init_decode_state(cfg: TransformerConfig, slots: int, total_len: int,
                      seed: int = 0):
    """Persistent server decode state: ``slots`` in-flight rows over a shared
    fixed-shape KV cache. ``length`` is each row's next write slot (== tokens
    held so far); inactive rows are parked with ``active`` False."""
    return {
        "cache": init_cache(cfg, slots, total_len),
        "length": jnp.zeros((slots,), jnp.int32),
        "remaining": jnp.zeros((slots,), jnp.int32),
        "active": jnp.zeros((slots,), bool),
        "temperature": jnp.zeros((slots,), jnp.float32),
        "last_logits": jnp.zeros((slots, cfg.vocab_size), jnp.float32),
        "key": jax.random.PRNGKey(seed),
    }


@functools.partial(jax.jit, donate_argnames=("state",))
def insert_row(state, slot, row_cache, last_logits, length, remaining,
               temperature):
    """Copy a prefilled request (batch-1 ``prefill`` outputs) into row
    ``slot`` of the persistent state. ``slot`` is traced — one executable
    serves every slot index."""
    k = lax.dynamic_update_slice(
        state["cache"]["k"], row_cache["k"], (0, slot, 0, 0, 0)
    )
    v = lax.dynamic_update_slice(
        state["cache"]["v"], row_cache["v"], (0, slot, 0, 0, 0)
    )
    return {
        "cache": {"k": k, "v": v},
        "length": state["length"].at[slot].set(length),
        "remaining": state["remaining"].at[slot].set(remaining),
        "active": state["active"].at[slot].set(remaining > 0),
        "temperature": state["temperature"].at[slot].set(temperature),
        "last_logits": state["last_logits"].at[slot].set(last_logits[0]),
        "key": state["key"],
    }


def _admit_rows_body(state, params, cfg: TransformerConfig, slots,
                     prompt_tokens, prompt_lengths, remaining, temperature):
    total_len = state["cache"]["k"].shape[2]
    b, t0 = prompt_tokens.shape
    cache = init_cache(cfg, b, total_len)
    prompt_lengths = jnp.maximum(prompt_lengths, 1)
    valid = jnp.arange(total_len)[None, :] < prompt_lengths[:, None]
    positions = jnp.broadcast_to(jnp.arange(t0)[None], (b, t0))
    logits, cache = forward_cached(
        params, prompt_tokens, cfg, cache, 0, positions, valid,
        token_valid=positions < prompt_lengths[:, None],
    )
    last = jnp.take_along_axis(
        logits, (prompt_lengths - 1)[:, None, None], axis=1
    )[:, 0]
    return {
        "cache": {
            "k": state["cache"]["k"].at[:, slots].set(cache["k"]),
            "v": state["cache"]["v"].at[:, slots].set(cache["v"]),
        },
        "length": state["length"].at[slots].set(prompt_lengths),
        "remaining": state["remaining"].at[slots].set(remaining),
        "active": state["active"].at[slots].set(remaining > 0),
        "temperature": state["temperature"].at[slots].set(temperature),
        "last_logits": state["last_logits"].at[slots].set(last),
        "key": state["key"],
    }, last


@functools.partial(jax.jit, static_argnames=("cfg", "top_k", "eos_id"),
                   donate_argnames=("state",))
def admit_rows_and_step(state, params, cfg: TransformerConfig, slots,
                        prompt_tokens, prompt_lengths, remaining,
                        temperature, top_k: int = 0,
                        eos_id: int | None = None):
    """Fused admission: prefill ``[K, T0]`` prompts, scatter them into
    rows ``slots`` of the persistent state, AND run one decode step for
    every active row — a single dispatch, so the new requests' first
    token ships on the admission round-trip itself (2 RTTs prompt→token
    where a prefill/insert/step pipeline pays 4), and peer rows advance
    exactly as a separate ramp step would have advanced them. ``slots``
    may repeat indices only as bucket padding that duplicates a real
    admission verbatim (identical data per duplicate index keeps the
    scatter deterministic). Returns (state, prefill last-logits [K, V],
    sampled token [slots], emitted mask [slots])."""
    state, last = _admit_rows_body(state, params, cfg, slots,
                                   prompt_tokens, prompt_lengths,
                                   remaining, temperature)
    state, tok, emit = _decode_step_body(state, params, cfg, top_k, eos_id)
    return state, last, tok, emit


# ---------------------------------------------------------------------------
# Prefix KV pool (serving/prefix_cache.py holds the host-side trie)
# ---------------------------------------------------------------------------
#
# Most production prompts share a long common prefix (system prompt,
# few-shot template); causality makes its K/V rows depend only on the
# prefix tokens themselves, so they can be computed once, parked in a
# fixed-capacity device pool, and gathered into a new request's row at
# admission — the request then prefills ONLY its suffix. The pool is
# deliberately functional (no donation): a store never invalidates the
# array an in-flight admission already captured, so host-side pinning is
# a logical-consistency guard, not a memory-safety one.


def init_prefix_pool(cfg: TransformerConfig, pool_slots: int,
                     max_prefix_len: int):
    """Device prefix pool: ``pool_slots`` rows of per-layer K/V for up to
    ``max_prefix_len`` positions, laid out like the decode cache (layer
    dim leading) so row gather/scatter is a contiguous copy."""
    shape = (cfg.n_layers, pool_slots, max_prefix_len, cfg.n_kv_heads,
             cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


@jax.jit
def store_prefix_row(pool, pool_slot, state, row):
    """Publish decode-state row ``row``'s first ``max_prefix_len`` cache
    positions into pool row ``pool_slot`` (the publish-on-finish path:
    the prompt region of a finished request's row is its prefix). Both
    indices are traced — one executable serves every (row, slot) pair."""
    plen = pool["k"].shape[2]
    return {
        "k": pool["k"].at[:, pool_slot].set(state["cache"]["k"][:, row,
                                                                :plen]),
        "v": pool["v"].at[:, pool_slot].set(state["cache"]["v"][:, row,
                                                                :plen]),
    }


@jax.jit
def store_prefix_cache(pool, pool_slot, cache):
    """Publish a batch-1 :func:`prefill` cache into pool row ``pool_slot``
    (the prime path: preload a shared system prompt without touching the
    decode state or its RNG)."""
    plen = pool["k"].shape[2]
    return {
        "k": pool["k"].at[:, pool_slot].set(cache["k"][:, 0, :plen]),
        "v": pool["v"].at[:, pool_slot].set(cache["v"][:, 0, :plen]),
    }


def _admit_prefix_body(state, params, cfg: TransformerConfig, slot, pool,
                       pool_slot, prefix_len, suffix_tokens, prompt_len,
                       remaining, temperature):
    total_len = state["cache"]["k"].shape[2]
    _b, s = suffix_tokens.shape  # batch 1, suffix padded to a length bucket
    cache = init_cache(cfg, 1, total_len)
    # Lay the reused prefix rows into cache positions 0..max_prefix_len.
    # Rows past prefix_len hold the donor's unrelated continuation — the
    # suffix forward overwrites positions prefix_len..prefix_len+s, and
    # ``valid`` masks everything beyond prompt_len until decode writes it.
    k0 = lax.dynamic_update_slice(
        cache["k"], pool["k"][:, pool_slot][:, None], (0, 0, 0, 0, 0))
    v0 = lax.dynamic_update_slice(
        cache["v"], pool["v"][:, pool_slot][:, None], (0, 0, 0, 0, 0))
    suffix_len = jnp.maximum(prompt_len - prefix_len, 1)
    positions = prefix_len + jnp.arange(s)[None, :]
    valid = jnp.arange(total_len)[None, :] < prompt_len
    logits, cache = forward_cached(
        params, suffix_tokens, cfg, {"k": k0, "v": v0}, prefix_len,
        positions, valid,
        token_valid=jnp.arange(s)[None, :] < suffix_len,
    )
    last = jnp.take_along_axis(
        logits, jnp.reshape(suffix_len - 1, (1, 1, 1)), axis=1
    )[:, 0]
    return {
        "cache": {
            "k": state["cache"]["k"].at[:, slot].set(cache["k"][:, 0]),
            "v": state["cache"]["v"].at[:, slot].set(cache["v"][:, 0]),
        },
        "length": state["length"].at[slot].set(prompt_len),
        "remaining": state["remaining"].at[slot].set(remaining),
        "active": state["active"].at[slot].set(remaining > 0),
        "temperature": state["temperature"].at[slot].set(temperature),
        "last_logits": state["last_logits"].at[slot].set(last[0]),
        "key": state["key"],
    }, last


@functools.partial(jax.jit, static_argnames=("cfg", "top_k", "eos_id"),
                   donate_argnames=("state",))
def admit_prefix_and_step(state, params, cfg: TransformerConfig, slot, pool,
                          pool_slot, prefix_len, suffix_tokens, prompt_len,
                          remaining, temperature, top_k: int = 0,
                          eos_id: int | None = None):
    """Prefix-hit admission: gather pool row ``pool_slot``'s first
    ``prefix_len`` K/V positions into decode-state row ``slot``, prefill
    ONLY the suffix (``suffix_tokens`` [1, S], padded to a length
    bucket), and run one fused decode step — the prefix-reuse twin of
    :func:`admit_rows_and_step`, still a single dispatch. ``prefix_len``
    and ``prompt_len`` are traced, so one executable per suffix bucket
    serves every cached prefix length. Returns (state, prefill
    last-logits [1, V], sampled token [slots], emitted mask [slots])."""
    state, last = _admit_prefix_body(state, params, cfg, slot, pool,
                                     pool_slot, prefix_len, suffix_tokens,
                                     prompt_len, remaining, temperature)
    state, tok, emit = _decode_step_body(state, params, cfg, top_k, eos_id)
    return state, last, tok, emit


@functools.partial(jax.jit, donate_argnames=("state",))
def retire_row(state, slot):
    """Host-initiated early stop (EOS): clear ``active`` and park the row's
    write position at ``total`` so the next ``decode_step`` neither samples
    for it nor lands its cache scatter (out-of-bounds scatter updates are
    dropped). ``insert_row`` resets ``length`` on readmission."""
    total = state["cache"]["k"].shape[2]
    return {**state,
            "active": state["active"].at[slot].set(False),
            "length": state["length"].at[slot].set(total)}


def _decode_step_body(state, params, cfg: TransformerConfig, top_k: int,
                      eos_id: int | None):
    """One decode step (traceable body shared by :func:`decode_step` and
    :func:`decode_chunk`). With ``eos_id`` set, a row that samples it is
    parked ON DEVICE (active cleared, write position parked at ``total``
    like :func:`retire_row`) so a fused multi-step loop needs no host
    round-trip per token to stop at EOS."""
    total = state["cache"]["k"].shape[2]
    emit = state["active"]
    key, sub = jax.random.split(state["key"])
    tok = sample_token(state["last_logits"], sub, state["temperature"], top_k)
    p_b = state["length"]
    cos_t, sin_t = rotary_frequencies(cfg.head_dim, total,
                                      theta=cfg.rope_theta)
    rope_bt = (cos_t[p_b[:, None]], sin_t[p_b[:, None]])
    x = params["embed"]["kernel"].astype(cfg.dtype)[tok][:, None]
    valid = jnp.arange(total)[None, :] <= p_b[:, None]

    def layer_fn(x, layer_and_cache):
        layer, k_cache, v_cache = layer_and_cache
        h = rms_norm(x, layer["ln_attn"], eps=cfg.norm_eps)
        attn, k_cache, v_cache = _ragged_attention(
            h, layer["attn"], cfg, rope_bt, k_cache, v_cache, p_b, valid
        )
        x = x + attn
        h = rms_norm(x, layer["ln_mlp"], eps=cfg.norm_eps)
        if cfg.n_experts:
            y, _aux = moe_ffn(h, layer["mlp"], cfg, token_valid=emit[:, None])
            x = x + y
        else:
            gate = h @ layer["mlp"]["gate"].astype(cfg.dtype)
            up = h @ layer["mlp"]["up"].astype(cfg.dtype)
            x = x + (jax.nn.silu(gate) * up) @ layer["mlp"]["down"].astype(
                cfg.dtype
            )
        return x, (k_cache, v_cache)

    x, (k_new, v_new) = lax.scan(
        layer_fn, x, (params["layers"], state["cache"]["k"],
                      state["cache"]["v"])
    )
    x = rms_norm(x, params["final_norm"], eps=cfg.norm_eps)
    head = (params["embed"]["kernel"].T if cfg.tie_embeddings
            else params["lm_head"]["kernel"])
    logits = (x @ head.astype(cfg.dtype)).astype(jnp.float32)[:, 0]
    step_inc = emit.astype(jnp.int32)
    length = p_b + step_inc
    remaining = state["remaining"] - step_inc
    active = emit & (remaining > 0) & (length < total)
    if eos_id is not None:
        hit_eos = emit & (tok == eos_id)
        active = active & ~hit_eos
        # Park like retire_row: an out-of-bounds write position drops the
        # row's cache scatter on subsequent fused steps.
        length = jnp.where(hit_eos, total, length)
    new_state = {
        "cache": {"k": k_new, "v": v_new},
        "length": length,
        "remaining": remaining,
        "active": active,
        "temperature": state["temperature"],
        "last_logits": jnp.where(emit[:, None], logits,
                                 state["last_logits"]),
        "key": key,
    }
    return new_state, tok, emit


@functools.partial(jax.jit, static_argnames=("cfg", "top_k", "eos_id"),
                   donate_argnames=("state",))
def decode_step(state, params, cfg: TransformerConfig, top_k: int = 0,
                eos_id: int | None = None):
    """One token for every active row: sample from each row's last logits,
    run the [slots, 1] forward at per-row positions, refresh the state.
    Returns (state, sampled token [slots], emitted mask [slots]) — the host
    dispatches ``token[i]`` to request ``i`` wherever ``emitted[i]``."""
    return _decode_step_body(state, params, cfg, top_k, eos_id)


@functools.partial(jax.jit,
                   static_argnames=("cfg", "steps", "top_k", "eos_id"),
                   donate_argnames=("state",))
def decode_chunk(state, params, cfg: TransformerConfig, steps: int,
                 top_k: int = 0, eos_id: int | None = None):
    """``steps`` decode steps fused into ONE device dispatch via
    ``lax.scan`` — the high-RTT-link decode path (VERDICT r3 #5: a
    per-token dispatch costs ~2 tunnel round-trips here, so 32 tokens
    paid ~64 RTTs; a K-step chunk pays 2 RTTs per K tokens). EOS and
    row-exhaustion are handled inside the loop on device (rows park
    exactly as :func:`retire_row` would). Returns
    (state, tokens [steps, slots], emitted [steps, slots]); the host
    flushes each request's stream once per chunk."""

    def body(s, _):
        s, tok, emit = _decode_step_body(s, params, cfg, top_k, eos_id)
        return s, (tok, emit)

    state, (toks, emits) = lax.scan(body, state, None, length=steps)
    return state, toks, emits
