"""Autoregressive decoding with a KV cache for the transformer family.

TPU-first incremental decoding: one prefill pass fills the cache for the
whole (right-padded) prompt batch, then ``lax.scan`` decodes in lockstep —
every step is a fixed-shape single-token forward against the cache, so the
whole generate call is ONE compiled executable (no per-token dispatch, no
shape churn). Ragged prompts are handled with a per-row validity mask and
per-row RoPE positions: row ``b``'s token at decode step ``t`` carries true
position ``length[b] + t`` even though it lives at cache slot ``T0 + t``.

The reference serves generation through TF-Serving's black-box ModelServer;
this is the equivalent capability for the platform's own engine
(kubeflow/tf-serving/tf-serving-template.libsonnet:29-49 surface).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from kubeflow_tpu.ops import rms_norm
from kubeflow_tpu.ops.attention import (
    paged_decode_attention,
    paged_span_attention,
    ring_span_attention,
)
from kubeflow_tpu.ops.rotary import rotary_frequencies
from kubeflow_tpu.models.transformer import TransformerConfig, moe_ffn

_NEG_INF = -1e30


def init_cache(cfg: TransformerConfig, batch: int, total_len: int):
    """Per-layer K/V cache, stacked on a leading layer dim like the params."""
    shape = (cfg.n_layers, batch, total_len, cfg.n_kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


def _gqa_attention(q, k_cache, v_cache, mask, cfg):
    """Grouped-query attention over a KV cache, GQA-native: the query-
    head group rides its own einsum axis, so K/V are read at kv-head
    width — never repeated to H_q width (a 2-8x cut in decode cache
    traffic, the decode-step bandwidth bill). q: [B, S, H, hd]; cache:
    [B, T, Hkv, hd]; mask broadcastable to [B, Hkv, G, S, T]. Returns
    [B, S, H*hd]."""
    b, s, _h, hd = q.shape
    group = cfg.n_heads // cfg.n_kv_heads
    qg = q.reshape(b, s, cfg.n_kv_heads, group, hd)
    scores = jnp.einsum(
        "bskgd,btkd->bkgst", qg.astype(jnp.float32),
        k_cache.astype(jnp.float32)
    ) * (hd ** -0.5)
    scores = jnp.where(mask, scores, _NEG_INF)
    p = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
    return jnp.einsum("bkgst,btkd->bskgd", p, v_cache).reshape(
        b, s, cfg.n_heads * hd)


def _cached_attention(x, layer, cfg, rope_bt, k_cache, v_cache, pos, valid):
    """x: [B, S, D] at cache slots pos..pos+S; attends over the full cache
    masked by ``valid`` [B, total]. Returns (out, k_cache, v_cache)."""
    b, s, _d = x.shape
    hd = cfg.head_dim
    cos, sin = rope_bt  # [B, S, hd//2] gathered per row by the caller
    q = (x @ layer["wq"].astype(cfg.dtype)).reshape(b, s, cfg.n_heads, hd)
    k = (x @ layer["wk"].astype(cfg.dtype)).reshape(b, s, cfg.n_kv_heads, hd)
    v = (x @ layer["wv"].astype(cfg.dtype)).reshape(b, s, cfg.n_kv_heads, hd)
    q = _rope(q, cos, sin)
    k = _rope(k, cos, sin)
    k_cache = lax.dynamic_update_slice(k_cache, k, (0, pos, 0, 0))
    v_cache = lax.dynamic_update_slice(v_cache, v, (0, pos, 0, 0))

    total = k_cache.shape[1]
    # Causality within the new block: query at slot pos+i sees key slot j
    # iff j <= pos+i; prompt padding and unwritten slots are masked by
    # ``valid`` (which already includes slots pos..pos+S for this block).
    j_idx = jnp.arange(total)[None, None, :]
    i_idx = pos + jnp.arange(s)[None, :, None]
    mask = (j_idx <= i_idx) & valid[:, None, :]
    out = _gqa_attention(q, k_cache, v_cache, mask[:, None, None], cfg)
    return out @ layer["wo"].astype(cfg.dtype), k_cache, v_cache


def _rope(x, cos, sin):
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    c, s = cos[:, :, None, :], sin[:, :, None, :]
    return jnp.concatenate(
        [x1 * c - x2 * s, x1 * s + x2 * c], axis=-1
    ).astype(x.dtype)


def forward_cached(params, tokens, cfg: TransformerConfig, cache, pos,
                   positions, valid, token_valid=None):
    """tokens [B, S] at cache slots pos..pos+S with true sequence positions
    ``positions`` [B, S] → (logits [B, S, V], new cache). ``token_valid``
    ([B, S]) marks real (non-pad) tokens in THIS block — MoE routing must
    not let ragged-prefill padding claim expert capacity."""
    cos_t, sin_t = rotary_frequencies(cfg.head_dim, cache["k"].shape[2],
                                      theta=cfg.rope_theta)
    rope_bt = (cos_t[positions], sin_t[positions])
    x = params["embed"]["kernel"].astype(cfg.dtype)[tokens]

    def layer_fn(x, layer_and_cache):
        layer, k_cache, v_cache = layer_and_cache
        h = rms_norm(x, layer["ln_attn"], eps=cfg.norm_eps)
        attn, k_cache, v_cache = _cached_attention(
            h, layer["attn"], cfg, rope_bt, k_cache, v_cache, pos, valid
        )
        x = x + attn
        h = rms_norm(x, layer["ln_mlp"], eps=cfg.norm_eps)
        if cfg.n_experts:
            y, _aux = moe_ffn(h, layer["mlp"], cfg, token_valid=token_valid)
            x = x + y
        else:
            gate = h @ layer["mlp"]["gate"].astype(cfg.dtype)
            up = h @ layer["mlp"]["up"].astype(cfg.dtype)
            x = x + (jax.nn.silu(gate) * up) @ layer["mlp"]["down"].astype(
                cfg.dtype
            )
        return x, (k_cache, v_cache)

    x, (k_new, v_new) = lax.scan(
        layer_fn, x, (params["layers"], cache["k"], cache["v"])
    )
    x = rms_norm(x, params["final_norm"], eps=cfg.norm_eps)
    head = (params["embed"]["kernel"].T if cfg.tie_embeddings
            else params["lm_head"]["kernel"])
    logits = x @ head.astype(cfg.dtype)
    return logits.astype(jnp.float32), {"k": k_new, "v": v_new}


def _top_k_mask(logits, top_k: int):
    """Mask everything below the k-th logit to -inf (no-op for top_k=0)."""
    if top_k and top_k < logits.shape[-1]:
        kth = lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, _NEG_INF, logits)
    return logits


def sample_token(logits, key, temperature, top_k: int = 0):
    """logits [B, V], temperature [B] (<=0 → greedy), static top_k."""
    greedy = jnp.argmax(logits, axis=-1)
    logits = _top_k_mask(logits, top_k)
    temp = jnp.maximum(temperature, 1e-6)[:, None]
    sampled = jax.random.categorical(key, logits / temp, axis=-1)
    return jnp.where(temperature <= 0.0, greedy, sampled).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("cfg", "max_new_tokens",
                                             "top_k"))
def generate(params, prompt_tokens, prompt_lengths, cfg: TransformerConfig,
             *, max_new_tokens: int, key, temperature, top_k: int = 0,
             row_valid=None):
    """prompt_tokens [B, T0] right-padded, prompt_lengths [B] →
    (generated [B, max_new_tokens], prefill_logits [B, V]).

    ``temperature`` [B]: <=0 rows decode greedily. ``row_valid`` [B] marks
    real instances in a server-padded batch — pad rows must not claim MoE
    expert capacity during decode and evict real tokens' expert choices.
    One compiled call: prefill + a scanned decode loop over the KV cache.
    """
    b, t0 = prompt_tokens.shape
    total = t0 + max_new_tokens
    cache = init_cache(cfg, b, total)
    # A zero-length row would wrap the last-logit gather to index -1 (the
    # last prefill slot) and seed generation from garbage; clamp to 1 so
    # the behavior is defined even if callers skip engine validation.
    prompt_lengths = jnp.maximum(prompt_lengths, 1)
    if row_valid is None:
        row_valid = jnp.ones((b,), bool)

    slot = jnp.arange(total)[None, :]
    valid = slot < prompt_lengths[:, None]  # prompt slots only
    positions = jnp.broadcast_to(jnp.arange(t0)[None], (b, t0))
    logits, cache = forward_cached(
        params, prompt_tokens, cfg, cache, 0, positions, valid,
        token_valid=(jnp.arange(t0)[None] < prompt_lengths[:, None])
        & row_valid[:, None],
    )
    last = jnp.take_along_axis(
        logits, (prompt_lengths - 1)[:, None, None], axis=1
    )[:, 0]

    def step(carry, i):
        cache, valid, tok, logits_prev, key = carry
        key, sub = jax.random.split(key)
        tok = sample_token(logits_prev, sub, temperature, top_k)
        slot_i = t0 + i
        valid = valid.at[:, slot_i].set(True)
        pos_i = (prompt_lengths + i)[:, None]  # true position per row
        logits, cache = forward_cached(
            params, tok[:, None], cfg, cache, slot_i, pos_i, valid,
            token_valid=row_valid[:, None],
        )
        return (cache, valid, tok, logits[:, 0], key), tok

    (_, _, _, _, _), toks = lax.scan(
        step, (cache, valid, jnp.zeros((b,), jnp.int32), last, key),
        jnp.arange(max_new_tokens),
    )
    return toks.T, last  # [B, max_new], [B, V]


# ---------------------------------------------------------------------------
# Continuous-batching primitives (serving/continuous.py drives these)
# ---------------------------------------------------------------------------
#
# The lockstep ``generate`` above compiles prefill+decode into one call — the
# right shape for offline batches, the wrong one for a server: every request
# waits for the slowest peer. The continuous path splits the work into
# fixed-shape executables so the scheduler can retire/admit rows between
# steps: ``admit_rows_and_step`` (prefill a round's admissions, scatter them
# into the persistent state, and take one decode step — one dispatch) and
# ``decode_step``/``decode_chunk`` (one token / K fused tokens for ALL
# slots). ``prefill`` + ``insert_row`` remain as the unfused admission
# pieces (callers that need the row cache itself). Unlike ``generate``'s
# shared scalar ``pos``, rows here sit at *different* sequence positions,
# so the cache write and attention mask are per-row.


def _kv_arr(pool):
    """Payload array of a KV block pool — the int8 codes when the pool
    is quantized (``{"q", "scale"}``), the pool itself otherwise. Shape
    queries (block size, layer count) go through this so every caller
    is layout- AND precision-agnostic."""
    return pool["q"] if isinstance(pool, dict) else pool


def _quantize_kv(vals):
    """Abs-max int8 quantization of K/V values ``[..., H, hd]`` with one
    f32 scale per (position, head): ``{"q": int8, "scale": [..., H]}``.
    All-zero vectors (freshly admitted padding) map to scale 0 → exact
    zeros on dequant."""
    v32 = vals.astype(jnp.float32)
    scale = jnp.max(jnp.abs(v32), axis=-1) / 127.0
    safe = jnp.where(scale > 0.0, scale, 1.0)
    q = jnp.clip(jnp.round(v32 / safe[..., None]), -127, 127)
    return {"q": q.astype(jnp.int8), "scale": scale}


def _pool_gather(pool, table):
    """Read a layer's block pool ``[N, Bs, H, hd]`` through block table
    ``[B, MB]`` into virtual rows ``[B, MB*Bs, H, hd]`` — virtual position
    ``p`` of row ``b`` lives at block ``table[b, p // Bs]``, offset
    ``p % Bs``. Sentinel entries (``>= N``, the unallocated marker) clamp
    to the last block; the junk they surface sits in positions the
    validity mask already excludes, so it contributes exact zeros.
    Quantized pools dequantize after the gather (this materialized path
    is the reference; the fused kernel dequantizes in-register)."""
    if isinstance(pool, dict):
        b = table.shape[0]
        h = pool["scale"].shape[2]
        hd = pool["q"].shape[3]
        q = pool["q"][table].reshape(b, -1, h, hd).astype(jnp.float32)
        s = pool["scale"][table].reshape(b, -1, h)
        return q * s[..., None]
    _n, _bs, h, hd = pool.shape
    return pool[table].reshape(table.shape[0], -1, h, hd)


def _pool_write(pool, table, cols, vals):
    """Scatter ``vals`` [B, S, H, hd] at per-row virtual positions
    ``cols`` [B, S] through the block table. Out-of-range cols (rows
    parked at ``total``) and sentinel table entries resolve to a
    physical index past the pool, which scatter semantics drop — the
    paged twin of the dense path's parked-row no-op write. Quantized
    pools abs-max-quantize at scatter time: each written position's int8
    codes and per-head scale land together, so a block's payload and its
    scales can never drift apart."""
    arr = _kv_arr(pool)
    n, bs = arr.shape[0], arr.shape[1]
    mb = table.shape[1]
    blk = jnp.take_along_axis(table, jnp.clip(cols // bs, 0, mb - 1), axis=1)
    blk = jnp.where((cols >= 0) & (cols < mb * bs), blk, n)
    if isinstance(pool, dict):
        qd = _quantize_kv(vals)
        return {"q": pool["q"].at[blk, cols % bs].set(qd["q"]),
                "scale": pool["scale"].at[blk, cols % bs].set(qd["scale"])}
    return pool.at[blk, cols % bs].set(vals)


def _ragged_attention(x, layer, cfg, rope_bt, k_cache, v_cache, pos_b, valid,
                      table=None, fused=False, mesh=None):
    """Single-token attention where row ``b`` writes cache slot ``pos_b[b]``
    — the continuous-batching variant of :func:`_cached_attention` (rows at
    heterogeneous positions). x: [B, 1, D]; pos_b: [B]; valid: [B, total].

    With ``table`` ([B, max_blocks]) the caches are a paged block pool
    ``[N, Bs, H, hd]``: the write scatters through the table and the
    attention reads the row gathered at block granularity — same math,
    same mask, so outputs are byte-identical to the dense layout. With
    ``fused`` the gather never happens: the block-table attention kernel
    (ops/attention.py:paged_decode_attention) walks the table with an
    online softmax, so the dense ``[B, total]`` view of the cache is
    never materialized (its numerics are f32-equivalent, not bitwise —
    the gather path stays the pinned-parity reference). ``mesh`` (a
    tensor-parallel serving mesh) routes the fused read through the
    kernel's shard_map twin: each shard walks the same table over its
    local KV heads."""
    b, s, _d = x.shape
    hd = cfg.head_dim
    cos, sin = rope_bt
    q = (x @ layer["wq"].astype(cfg.dtype)).reshape(b, s, cfg.n_heads, hd)
    k = (x @ layer["wk"].astype(cfg.dtype)).reshape(b, s, cfg.n_kv_heads, hd)
    v = (x @ layer["wv"].astype(cfg.dtype)).reshape(b, s, cfg.n_kv_heads, hd)
    q = _rope(q, cos, sin)
    k = _rope(k, cos, sin)
    rows = jnp.arange(b)
    # Out-of-bounds pos_b (a retired row parked at total) is dropped by
    # scatter semantics — retired rows write nowhere.
    if table is None:
        k_cache = k_cache.at[rows, pos_b].set(k[:, 0])
        v_cache = v_cache.at[rows, pos_b].set(v[:, 0])
        k_read, v_read = k_cache, v_cache
    else:
        k_cache = _pool_write(k_cache, table, pos_b[:, None], k)
        v_cache = _pool_write(v_cache, table, pos_b[:, None], v)
        if fused:
            # The decode step's validity mask is exactly "positions
            # <= pos_b" (the just-written token included), which is the
            # fused kernel's span contract.
            out = paged_decode_attention(
                q[:, 0], k_cache, v_cache, table, pos_b,
                n_kv_heads=cfg.n_kv_heads, mesh=mesh,
            ).reshape(b, s, cfg.n_heads * hd).astype(cfg.dtype)
            return out @ layer["wo"].astype(cfg.dtype), k_cache, v_cache
        k_read = _pool_gather(k_cache, table)
        v_read = _pool_gather(v_cache, table)
    out = _gqa_attention(q, k_read, v_read,
                         valid[:, None, None, None, :], cfg)
    # Quantized pools dequantize to f32; fold back to the compute dtype
    # (identity for fp pools) so the residual stream's dtype is stable.
    return (out.astype(cfg.dtype) @ layer["wo"].astype(cfg.dtype),
            k_cache, v_cache)


@functools.partial(jax.jit, static_argnames=("cfg", "total_len"))
def prefill(params, prompt_tokens, prompt_lengths, cfg: TransformerConfig, *,
            total_len: int):
    """One request's prompt pass: tokens [B, T0] right-padded → (cache with
    ``total_len`` slots, last-position logits [B, V]). Slots beyond the true
    length hold pad junk; decode overwrites them before the mask admits them.
    """
    b, t0 = prompt_tokens.shape
    cache = init_cache(cfg, b, total_len)
    prompt_lengths = jnp.maximum(prompt_lengths, 1)
    valid = jnp.arange(total_len)[None, :] < prompt_lengths[:, None]
    positions = jnp.broadcast_to(jnp.arange(t0)[None], (b, t0))
    logits, cache = forward_cached(
        params, prompt_tokens, cfg, cache, 0, positions, valid,
        token_valid=positions < prompt_lengths[:, None],
    )
    last = jnp.take_along_axis(
        logits, (prompt_lengths - 1)[:, None, None], axis=1
    )[:, 0]
    return cache, last


def init_decode_state(cfg: TransformerConfig, slots: int, total_len: int,
                      seed: int = 0):
    """Persistent server decode state: ``slots`` in-flight rows over a shared
    fixed-shape KV cache. ``length`` is each row's next write slot (== tokens
    held so far); inactive rows are parked with ``active`` False."""
    return {
        "cache": init_cache(cfg, slots, total_len),
        "length": jnp.zeros((slots,), jnp.int32),
        "remaining": jnp.zeros((slots,), jnp.int32),
        "active": jnp.zeros((slots,), bool),
        "temperature": jnp.zeros((slots,), jnp.float32),
        "last_logits": jnp.zeros((slots, cfg.vocab_size), jnp.float32),
        "key": jax.random.PRNGKey(seed),
    }


@functools.partial(jax.jit, donate_argnames=("state",))
def insert_row(state, slot, row_cache, last_logits, length, remaining,
               temperature):
    """Copy a prefilled request (batch-1 ``prefill`` outputs) into row
    ``slot`` of the persistent state. ``slot`` is traced — one executable
    serves every slot index."""
    k = lax.dynamic_update_slice(
        state["cache"]["k"], row_cache["k"], (0, slot, 0, 0, 0)
    )
    v = lax.dynamic_update_slice(
        state["cache"]["v"], row_cache["v"], (0, slot, 0, 0, 0)
    )
    return {
        "cache": {"k": k, "v": v},
        "length": state["length"].at[slot].set(length),
        "remaining": state["remaining"].at[slot].set(remaining),
        "active": state["active"].at[slot].set(remaining > 0),
        "temperature": state["temperature"].at[slot].set(temperature),
        "last_logits": state["last_logits"].at[slot].set(last_logits[0]),
        "key": state["key"],
    }


def _admit_rows_body(state, params, cfg: TransformerConfig, slots,
                     prompt_tokens, prompt_lengths, remaining, temperature):
    total_len = state["cache"]["k"].shape[2]
    b, t0 = prompt_tokens.shape
    cache = init_cache(cfg, b, total_len)
    prompt_lengths = jnp.maximum(prompt_lengths, 1)
    valid = jnp.arange(total_len)[None, :] < prompt_lengths[:, None]
    positions = jnp.broadcast_to(jnp.arange(t0)[None], (b, t0))
    logits, cache = forward_cached(
        params, prompt_tokens, cfg, cache, 0, positions, valid,
        token_valid=positions < prompt_lengths[:, None],
    )
    last = jnp.take_along_axis(
        logits, (prompt_lengths - 1)[:, None, None], axis=1
    )[:, 0]
    return {
        "cache": {
            "k": state["cache"]["k"].at[:, slots].set(cache["k"]),
            "v": state["cache"]["v"].at[:, slots].set(cache["v"]),
        },
        "length": state["length"].at[slots].set(prompt_lengths),
        "remaining": state["remaining"].at[slots].set(remaining),
        "active": state["active"].at[slots].set(remaining > 0),
        "temperature": state["temperature"].at[slots].set(temperature),
        "last_logits": state["last_logits"].at[slots].set(last),
        "key": state["key"],
    }, last


@functools.partial(jax.jit, static_argnames=("cfg", "top_k", "eos_id"),
                   donate_argnames=("state",))
def admit_rows_and_step(state, params, cfg: TransformerConfig, slots,
                        prompt_tokens, prompt_lengths, remaining,
                        temperature, top_k: int = 0,
                        eos_id: int | None = None):
    """Fused admission: prefill ``[K, T0]`` prompts, scatter them into
    rows ``slots`` of the persistent state, AND run one decode step for
    every active row — a single dispatch, so the new requests' first
    token ships on the admission round-trip itself (2 RTTs prompt→token
    where a prefill/insert/step pipeline pays 4), and peer rows advance
    exactly as a separate ramp step would have advanced them. ``slots``
    may repeat indices only as bucket padding that duplicates a real
    admission verbatim (identical data per duplicate index keeps the
    scatter deterministic). Returns (state, prefill last-logits [K, V],
    sampled token [slots], emitted mask [slots])."""
    state, last = _admit_rows_body(state, params, cfg, slots,
                                   prompt_tokens, prompt_lengths,
                                   remaining, temperature)
    state, tok, emit = _decode_step_body(state, params, cfg, top_k, eos_id)
    return state, last, tok, emit


# ---------------------------------------------------------------------------
# Prefix KV pool (serving/prefix_cache.py holds the host-side trie)
# ---------------------------------------------------------------------------
#
# Most production prompts share a long common prefix (system prompt,
# few-shot template); causality makes its K/V rows depend only on the
# prefix tokens themselves, so they can be computed once, parked in a
# fixed-capacity device pool, and gathered into a new request's row at
# admission — the request then prefills ONLY its suffix. The pool is
# deliberately functional (no donation): a store never invalidates the
# array an in-flight admission already captured, so host-side pinning is
# a logical-consistency guard, not a memory-safety one.


def init_prefix_pool(cfg: TransformerConfig, pool_slots: int,
                     max_prefix_len: int):
    """Device prefix pool: ``pool_slots`` rows of per-layer K/V for up to
    ``max_prefix_len`` positions, laid out like the decode cache (layer
    dim leading) so row gather/scatter is a contiguous copy."""
    shape = (cfg.n_layers, pool_slots, max_prefix_len, cfg.n_kv_heads,
             cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


@jax.jit
def store_prefix_row(pool, pool_slot, state, row):
    """Publish decode-state row ``row``'s first ``max_prefix_len`` cache
    positions into pool row ``pool_slot`` (the publish-on-finish path:
    the prompt region of a finished request's row is its prefix). Both
    indices are traced — one executable serves every (row, slot) pair."""
    plen = pool["k"].shape[2]
    return {
        "k": pool["k"].at[:, pool_slot].set(state["cache"]["k"][:, row,
                                                                :plen]),
        "v": pool["v"].at[:, pool_slot].set(state["cache"]["v"][:, row,
                                                                :plen]),
    }


@jax.jit
def store_prefix_cache(pool, pool_slot, cache):
    """Publish a batch-1 :func:`prefill` cache into pool row ``pool_slot``
    (the prime path: preload a shared system prompt without touching the
    decode state or its RNG)."""
    plen = pool["k"].shape[2]
    return {
        "k": pool["k"].at[:, pool_slot].set(cache["k"][:, 0, :plen]),
        "v": pool["v"].at[:, pool_slot].set(cache["v"][:, 0, :plen]),
    }


def _admit_prefix_body(state, params, cfg: TransformerConfig, slot, pool,
                       pool_slot, prefix_len, suffix_tokens, prompt_len,
                       remaining, temperature):
    total_len = state["cache"]["k"].shape[2]
    _b, s = suffix_tokens.shape  # batch 1, suffix padded to a length bucket
    cache = init_cache(cfg, 1, total_len)
    # Lay the reused prefix rows into cache positions 0..max_prefix_len.
    # Rows past prefix_len hold the donor's unrelated continuation — the
    # suffix forward overwrites positions prefix_len..prefix_len+s, and
    # ``valid`` masks everything beyond prompt_len until decode writes it.
    k0 = lax.dynamic_update_slice(
        cache["k"], pool["k"][:, pool_slot][:, None], (0, 0, 0, 0, 0))
    v0 = lax.dynamic_update_slice(
        cache["v"], pool["v"][:, pool_slot][:, None], (0, 0, 0, 0, 0))
    suffix_len = jnp.maximum(prompt_len - prefix_len, 1)
    positions = prefix_len + jnp.arange(s)[None, :]
    valid = jnp.arange(total_len)[None, :] < prompt_len
    logits, cache = forward_cached(
        params, suffix_tokens, cfg, {"k": k0, "v": v0}, prefix_len,
        positions, valid,
        token_valid=jnp.arange(s)[None, :] < suffix_len,
    )
    last = jnp.take_along_axis(
        logits, jnp.reshape(suffix_len - 1, (1, 1, 1)), axis=1
    )[:, 0]
    return {
        "cache": {
            "k": state["cache"]["k"].at[:, slot].set(cache["k"][:, 0]),
            "v": state["cache"]["v"].at[:, slot].set(cache["v"][:, 0]),
        },
        "length": state["length"].at[slot].set(prompt_len),
        "remaining": state["remaining"].at[slot].set(remaining),
        "active": state["active"].at[slot].set(remaining > 0),
        "temperature": state["temperature"].at[slot].set(temperature),
        "last_logits": state["last_logits"].at[slot].set(last[0]),
        "key": state["key"],
    }, last


@functools.partial(jax.jit, static_argnames=("cfg", "top_k", "eos_id"),
                   donate_argnames=("state",))
def admit_prefix_and_step(state, params, cfg: TransformerConfig, slot, pool,
                          pool_slot, prefix_len, suffix_tokens, prompt_len,
                          remaining, temperature, top_k: int = 0,
                          eos_id: int | None = None):
    """Prefix-hit admission: gather pool row ``pool_slot``'s first
    ``prefix_len`` K/V positions into decode-state row ``slot``, prefill
    ONLY the suffix (``suffix_tokens`` [1, S], padded to a length
    bucket), and run one fused decode step — the prefix-reuse twin of
    :func:`admit_rows_and_step`, still a single dispatch. ``prefix_len``
    and ``prompt_len`` are traced, so one executable per suffix bucket
    serves every cached prefix length. Returns (state, prefill
    last-logits [1, V], sampled token [slots], emitted mask [slots])."""
    state, last = _admit_prefix_body(state, params, cfg, slot, pool,
                                     pool_slot, prefix_len, suffix_tokens,
                                     prompt_len, remaining, temperature)
    state, tok, emit = _decode_step_body(state, params, cfg, top_k, eos_id)
    return state, last, tok, emit


@functools.partial(jax.jit, donate_argnames=("state",))
def retire_row(state, slot):
    """Host-initiated early stop (EOS, or a QoS suspension): clear
    ``active`` and park the row's write position at ``total`` so the next
    ``decode_step`` neither samples for it nor lands its cache scatter
    (out-of-bounds scatter updates are dropped — same parking the fused
    EOS path uses on device). Works on either KV layout via
    :func:`_state_kv`; ``insert_row``/admission resets ``length`` on
    readmission."""
    total = _state_kv(state)[3]
    return {**state,
            "active": state["active"].at[slot].set(False),
            "length": state["length"].at[slot].set(total)}


def _state_kv(state):
    """Layout-agnostic view of a decode state's KV storage: returns
    ``(k, v, table, total)``. Dense states carry ``[L, slots, total, H,
    hd]`` caches (table None); paged states carry the block pool
    ``[L, N, Bs, H, hd]`` plus the ``[slots, max_blocks]`` block table
    (virtual ``total = max_blocks * Bs``)."""
    if "pool" in state:
        k = state["pool"]["k"]
        table = state["block_table"]
        return (k, state["pool"]["v"], table,
                table.shape[1] * _kv_arr(k).shape[2])
    k = state["cache"]["k"]
    return k, state["cache"]["v"], None, k.shape[2]


def _with_kv(state, k, v):
    """Refresh a state's KV storage under whichever layout it carries."""
    if "pool" in state:
        return {**state, "pool": {"k": k, "v": v}}
    return {**state, "cache": {"k": k, "v": v}}


def _single_token_forward(params, cfg: TransformerConfig, k_cache0, v_cache0,
                          tok, pos_b, token_valid, table=None, fused=False,
                          mesh=None):
    """One [B, 1] forward at per-row cache positions ``pos_b`` against the
    persistent caches (the layer loop shared by :func:`_decode_step_body`
    and the verify commit pass). With ``table`` the caches are the paged
    block pool read/written through the block table (``fused`` swaps the
    gathered read for the block-walking attention kernel). Returns
    (logits [B, V], k, v)."""
    total = (k_cache0.shape[2] if table is None
             else table.shape[1] * _kv_arr(k_cache0).shape[2])
    cos_t, sin_t = rotary_frequencies(cfg.head_dim, total,
                                      theta=cfg.rope_theta)
    rope_bt = (cos_t[pos_b[:, None]], sin_t[pos_b[:, None]])
    x = params["embed"]["kernel"].astype(cfg.dtype)[tok][:, None]
    valid = jnp.arange(total)[None, :] <= pos_b[:, None]

    def layer_fn(x, layer_and_cache):
        layer, k_cache, v_cache = layer_and_cache
        h = rms_norm(x, layer["ln_attn"], eps=cfg.norm_eps)
        attn, k_cache, v_cache = _ragged_attention(
            h, layer["attn"], cfg, rope_bt, k_cache, v_cache, pos_b, valid,
            table=table, fused=fused, mesh=mesh,
        )
        x = x + attn
        h = rms_norm(x, layer["ln_mlp"], eps=cfg.norm_eps)
        if cfg.n_experts:
            y, _aux = moe_ffn(h, layer["mlp"], cfg,
                              token_valid=token_valid[:, None])
            x = x + y
        else:
            gate = h @ layer["mlp"]["gate"].astype(cfg.dtype)
            up = h @ layer["mlp"]["up"].astype(cfg.dtype)
            x = x + (jax.nn.silu(gate) * up) @ layer["mlp"]["down"].astype(
                cfg.dtype
            )
        return x, (k_cache, v_cache)

    x, (k_new, v_new) = lax.scan(
        layer_fn, x, (params["layers"], k_cache0, v_cache0)
    )
    x = rms_norm(x, params["final_norm"], eps=cfg.norm_eps)
    head = (params["embed"]["kernel"].T if cfg.tie_embeddings
            else params["lm_head"]["kernel"])
    logits = (x @ head.astype(cfg.dtype)).astype(jnp.float32)[:, 0]
    return logits, k_new, v_new


def _decode_step_body(state, params, cfg: TransformerConfig, top_k: int,
                      eos_id: int | None, fused: bool = False, mesh=None):
    """One decode step (traceable body shared by :func:`decode_step` and
    :func:`decode_chunk`). With ``eos_id`` set, a row that samples it is
    parked ON DEVICE (active cleared, write position parked at ``total``
    like :func:`retire_row`) so a fused multi-step loop needs no host
    round-trip per token to stop at EOS. Works on either KV layout
    (:func:`_state_kv`): dense per-slot rows or the paged block pool
    (``fused`` swaps the paged read for the block-table kernel)."""
    k0, v0, table, total = _state_kv(state)
    emit = state["active"]
    key, sub = jax.random.split(state["key"])
    tok = sample_token(state["last_logits"], sub, state["temperature"], top_k)
    p_b = state["length"]
    logits, k_new, v_new = _single_token_forward(
        params, cfg, k0, v0, tok, p_b, emit, table=table, fused=fused,
        mesh=mesh,
    )
    step_inc = emit.astype(jnp.int32)
    length = p_b + step_inc
    remaining = state["remaining"] - step_inc
    active = emit & (remaining > 0) & (length < total)
    if eos_id is not None:
        hit_eos = emit & (tok == eos_id)
        active = active & ~hit_eos
        # Park like retire_row: an out-of-bounds write position drops the
        # row's cache scatter on subsequent fused steps.
        length = jnp.where(hit_eos, total, length)
    new_state = {
        **state,
        "length": length,
        "remaining": remaining,
        "active": active,
        "last_logits": jnp.where(emit[:, None], logits,
                                 state["last_logits"]),
        "key": key,
    }
    return _with_kv(new_state, k_new, v_new), tok, emit


@functools.partial(jax.jit,
                   static_argnames=("cfg", "top_k", "eos_id", "kv_fused",
                                    "mesh"),
                   donate_argnames=("state",))
def decode_step(state, params, cfg: TransformerConfig, top_k: int = 0,
                eos_id: int | None = None, kv_fused: bool = False,
                mesh=None):
    """One token for every active row: sample from each row's last logits,
    run the [slots, 1] forward at per-row positions, refresh the state.
    Returns (state, sampled token [slots], emitted mask [slots]) — the host
    dispatches ``token[i]`` to request ``i`` wherever ``emitted[i]``.
    ``kv_fused`` (paged states only) reads the cache through the
    block-table attention kernel instead of the gathered dense view;
    ``mesh`` (static, a tensor-parallel serving mesh) routes that fused
    read through the kernel's shard_map mesh twin."""
    return _decode_step_body(state, params, cfg, top_k, eos_id, kv_fused,
                             mesh)


@functools.partial(jax.jit,
                   static_argnames=("cfg", "steps", "top_k", "eos_id",
                                    "kv_fused", "mesh"),
                   donate_argnames=("state",))
def decode_chunk(state, params, cfg: TransformerConfig, steps: int,
                 top_k: int = 0, eos_id: int | None = None,
                 kv_fused: bool = False, mesh=None):
    """``steps`` decode steps fused into ONE device dispatch via
    ``lax.scan`` — the high-RTT-link decode path (VERDICT r3 #5: a
    per-token dispatch costs ~2 tunnel round-trips here, so 32 tokens
    paid ~64 RTTs; a K-step chunk pays 2 RTTs per K tokens). EOS and
    row-exhaustion are handled inside the loop on device (rows park
    exactly as :func:`retire_row` would). Returns
    (state, tokens [steps, slots], emitted [steps, slots]); the host
    flushes each request's stream once per chunk."""

    def body(s, _):
        s, tok, emit = _decode_step_body(s, params, cfg, top_k, eos_id,
                                         kv_fused, mesh)
        return s, (tok, emit)

    state, (toks, emits) = lax.scan(body, state, None, length=steps)
    return state, toks, emits


# ---------------------------------------------------------------------------
# Speculative decoding (serving/speculative.py holds the host-side proposers)
# ---------------------------------------------------------------------------
#
# Decode is memory-bandwidth-bound: every step reads the whole KV cache to
# produce ONE token. Verifying K cheap draft tokens in a single [slots, K]
# forward reads the cache once for up to K+1 tokens of progress — the
# verify is compute the prefill path already knows how to do. Greedy
# outputs are byte-identical to plain decode by construction (a draft
# token is only kept when it equals the argmax the target would have
# produced); temperature>0 rows use rejection-resampling against the
# deterministic draft proposal, which leaves the sampled distribution
# exactly the target's. A verify step is two forwards fused into ONE
# dispatch: the K-wide scoring pass plus a single-token commit pass that
# writes the first non-draft token's K/V, so the decode-state invariant
# (``length`` K/V rows live, ``last_logits`` predicts position
# ``length``) holds on exit and verify composes freely with
# ``decode_step``/``decode_chunk``/``retire_row``. Rejected draft tails
# need no explicit rollback: validity is derived from ``length`` every
# step, so not advancing past the accepted region IS the rollback.


def _span_attention(x, layer, cfg, rope_bt, k_cache, v_cache, pos_b,
                    table=None, fused=False, mesh=None, ring=None):
    """Block attention where row ``b``'s ``S`` tokens occupy cache slots
    ``pos_b[b]..pos_b[b]+S-1`` — the S-wide sibling of
    :func:`_ragged_attention` (rows at heterogeneous positions). Block
    token ``s`` attends every cache slot ``<= pos_b + s`` (its own K/V
    was just written), so causality holds within the block and over the
    row's history. Out-of-bounds writes (parked rows, cache-tail spill)
    are dropped by scatter semantics. With ``table`` the caches are the
    paged block pool, written/read through the block table; ``fused``
    swaps the gathered read for the span block-walk
    (ops/attention.py:paged_span_attention) so the dense
    ``[B, MB*Bs]`` view is never materialized — the same contract (and
    the same f32-equivalent-not-bitwise caveat) as the fused decode
    read. ``ring`` (a serving mesh with a ``sequence`` axis) routes the
    gathered span read through the context-parallel ring
    (ops/attention.py:ring_span_attention) — chunked-prefill's long-
    prompt path, same f32-equivalence caveat."""
    b, s, _d = x.shape
    hd = cfg.head_dim
    cos, sin = rope_bt
    q = (x @ layer["wq"].astype(cfg.dtype)).reshape(b, s, cfg.n_heads, hd)
    k = (x @ layer["wk"].astype(cfg.dtype)).reshape(b, s, cfg.n_kv_heads, hd)
    v = (x @ layer["wv"].astype(cfg.dtype)).reshape(b, s, cfg.n_kv_heads, hd)
    q = _rope(q, cos, sin)
    k = _rope(k, cos, sin)
    cols = pos_b[:, None] + jnp.arange(s)[None, :]
    if table is None:
        rows = jnp.arange(b)[:, None]
        k_cache = k_cache.at[rows, cols].set(k)
        v_cache = v_cache.at[rows, cols].set(v)
        k_read, v_read = k_cache, v_cache
        total = k_cache.shape[1]
    else:
        k_cache = _pool_write(k_cache, table, cols, k)
        v_cache = _pool_write(v_cache, table, cols, v)
        if fused:
            # Span contract: token ``s`` attends positions <= pos_b + s
            # — exactly the mask below, walked block-by-block instead
            # of gathered dense.
            out = paged_span_attention(
                q, k_cache, v_cache, table, pos_b,
                n_kv_heads=cfg.n_kv_heads, mesh=mesh,
            ).reshape(b, s, cfg.n_heads * hd).astype(cfg.dtype)
            return out @ layer["wo"].astype(cfg.dtype), k_cache, v_cache
        k_read = _pool_gather(k_cache, table)
        v_read = _pool_gather(v_cache, table)
        total = table.shape[1] * _kv_arr(k_cache).shape[1]
        if ring is not None:
            out = ring_span_attention(
                q, k_read, v_read, pos_b, n_kv_heads=cfg.n_kv_heads,
                mesh=ring,
            ).astype(cfg.dtype)
            return (out.reshape(b, s, cfg.n_heads * hd)
                    @ layer["wo"].astype(cfg.dtype), k_cache, v_cache)
    mask = jnp.arange(total)[None, None, :] <= cols[:, :, None]
    out = _gqa_attention(q, k_read, v_read, mask[:, None, None], cfg)
    return (out.astype(cfg.dtype) @ layer["wo"].astype(cfg.dtype),
            k_cache, v_cache)


def _block_forward(params, cfg: TransformerConfig, k_cache0, v_cache0,
                   tokens, pos_b, token_valid, table=None, fused=False,
                   mesh=None, ring=None):
    """[B, S] forward writing K/V at per-row start positions ``pos_b`` →
    (logits [B, S, V], k, v). The verify scoring pass, the paged
    suffix-only prefill, and the draft model's catch-up feed all ride
    this; ``fused`` routes the paged span read through the block-walk
    instead of the dense gather."""
    total = (k_cache0.shape[2] if table is None
             else table.shape[1] * _kv_arr(k_cache0).shape[2])
    _b, s = tokens.shape
    cos_t, sin_t = rotary_frequencies(cfg.head_dim, total,
                                      theta=cfg.rope_theta)
    pos = pos_b[:, None] + jnp.arange(s)[None, :]
    rope_bt = (cos_t[pos], sin_t[pos])
    x = params["embed"]["kernel"].astype(cfg.dtype)[tokens]

    def layer_fn(x, layer_and_cache):
        layer, k_cache, v_cache = layer_and_cache
        h = rms_norm(x, layer["ln_attn"], eps=cfg.norm_eps)
        attn, k_cache, v_cache = _span_attention(
            h, layer["attn"], cfg, rope_bt, k_cache, v_cache, pos_b,
            table=table, fused=fused, mesh=mesh, ring=ring,
        )
        x = x + attn
        h = rms_norm(x, layer["ln_mlp"], eps=cfg.norm_eps)
        if cfg.n_experts:
            y, _aux = moe_ffn(h, layer["mlp"], cfg, token_valid=token_valid)
            x = x + y
        else:
            gate = h @ layer["mlp"]["gate"].astype(cfg.dtype)
            up = h @ layer["mlp"]["up"].astype(cfg.dtype)
            x = x + (jax.nn.silu(gate) * up) @ layer["mlp"]["down"].astype(
                cfg.dtype
            )
        return x, (k_cache, v_cache)

    x, (k_new, v_new) = lax.scan(
        layer_fn, x, (params["layers"], k_cache0, v_cache0)
    )
    x = rms_norm(x, params["final_norm"], eps=cfg.norm_eps)
    head = (params["embed"]["kernel"].T if cfg.tie_embeddings
            else params["lm_head"]["kernel"])
    return (x @ head.astype(cfg.dtype)).astype(jnp.float32), k_new, v_new


def _target_probs(logits, temperature, top_k: int):
    """Processed target distribution (top-k mask + temperature floor) for
    speculative accept/resample — must match :func:`sample_token`'s
    sampling branch exactly or acceptance would test a different
    distribution than the one decode samples from. logits [..., V],
    temperature broadcastable to logits[..., 0]."""
    logits = _top_k_mask(logits, top_k)
    temp = jnp.maximum(temperature, 1e-6)[..., None]
    return jax.nn.softmax(logits / temp, axis=-1)


def _verify_step_body(state, params, cfg: TransformerConfig, draft,
                      draft_len, top_k: int, eos_id: int | None,
                      fused: bool = False, mesh=None):
    """One speculative verify: score ``draft`` [slots, K] against the
    decode state, accept each row's longest matching prefix, commit the
    first non-draft token. Returns (state, tokens [slots, K+1],
    emitted [slots, K+1]) — ``emitted`` is a per-row prefix mask over
    the emitted tokens (1..K+1 of them for active rows)."""
    k0, v0, table, total = _state_kv(state)
    slots, k_w = draft.shape
    emit0 = state["active"]
    p_b = state["length"]
    temp = state["temperature"]
    key, k_acc, k_res = jax.random.split(state["key"], 3)

    # Pass 1: ONE [slots, K] forward scores every draft position (and
    # writes the draft K/V — accepted rows keep it, rejected tails stay
    # masked out by ``length`` until overwritten). ``fused`` walks the
    # span read through the block table instead of gathering the dense
    # view — the K-wide twin of the fused decode read.
    in_draft = jnp.arange(k_w)[None, :] < draft_len[:, None]
    block_logits, k1, v1 = _block_forward(
        params, cfg, k0, v0, draft, p_b,
        token_valid=emit0[:, None] & in_draft, table=table, fused=fused,
        mesh=mesh,
    )
    # prev_logits[:, i] predicts draft position i: last_logits for i=0,
    # the scoring pass's own outputs shifted by one after that.
    prev_logits = jnp.concatenate(
        [state["last_logits"][:, None], block_logits[:, : k_w - 1]], axis=1
    )
    greedy_ok = draft == jnp.argmax(prev_logits, axis=-1)
    probs = _target_probs(prev_logits, temp[:, None], top_k)
    p_draft = jnp.take_along_axis(probs, draft[..., None], axis=-1)[..., 0]
    # Deterministic proposer => q is a point mass: accept w.p. p(d).
    sampled_ok = jax.random.uniform(k_acc, (slots, k_w)) < p_draft
    ok = jnp.where((temp <= 0.0)[:, None], greedy_ok, sampled_ok) & in_draft
    acc = jnp.cumprod(ok.astype(jnp.int32), axis=1)
    n = acc.sum(axis=1)
    # Emission is n accepted drafts + 1 committed token, capped so the row
    # never overruns its budget or its cache (the cap only ever DROPS
    # accepted drafts — the capped position was accepted, so emitting the
    # draft token there stays distribution-exact).
    n_eff = jnp.minimum(n, jnp.maximum(state["remaining"] - 1, 0))
    n_eff = jnp.minimum(n_eff, jnp.maximum(total - 1 - p_b, 0))

    # Commit token: target sample at position n_eff. On a true rejection
    # the rejected draft id is masked out first — rejection-resampling
    # from the residual of a point-mass proposal, which keeps the overall
    # per-position distribution exactly the target's.
    all_logits = jnp.concatenate(
        [prev_logits, block_logits[:, k_w - 1:]], axis=1
    )
    commit_logits = jnp.take_along_axis(
        all_logits, n_eff[:, None, None], axis=1
    )[:, 0]
    d_at = jnp.take_along_axis(
        draft, jnp.minimum(n_eff, k_w - 1)[:, None], axis=1
    )[:, 0]
    rejected = (n_eff == n) & (n_eff < draft_len)
    # Top-k BEFORE the rejection mask: the residual must stay inside the
    # target's top-k support (masking first and re-thresholding after
    # would let the k+1-th token leak into the resample).
    res_logits = jnp.where(
        rejected[:, None]
        & (jnp.arange(cfg.vocab_size)[None, :] == d_at[:, None]),
        _NEG_INF, _top_k_mask(commit_logits, top_k),
    )
    commit = sample_token(res_logits, k_res, temp, top_k=0)
    commit = jnp.where(n_eff < n, d_at, commit)

    idx = jnp.arange(k_w + 1)[None, :]
    draft_pad = jnp.concatenate(
        [draft, jnp.zeros((slots, 1), jnp.int32)], axis=1
    )
    out = jnp.where(idx < n_eff[:, None], draft_pad, commit[:, None])
    emitted = emit0[:, None] & (idx <= n_eff[:, None])
    hit_eos = jnp.zeros((slots,), bool)
    if eos_id is not None:
        is_eos = (out == eos_id) & emitted
        eos_before = jnp.cumsum(is_eos.astype(jnp.int32), axis=1) \
            - is_eos.astype(jnp.int32)
        emitted = emitted & (eos_before == 0)  # keep the EOS, drop its tail
        hit_eos = ((out == eos_id) & emitted).any(axis=1)
    m = emitted.sum(axis=1).astype(jnp.int32)

    # Pass 2 (same dispatch): write the commit token's K/V at its row
    # position and refresh last_logits — restores the decode invariant so
    # the next step (plain or verify) continues seamlessly. Rows parked
    # by EOS above still run the pass; their write lands inside the row
    # but the row's length is parked at ``total`` so it is never read.
    commit_pos = p_b + n_eff
    logits2, k2, v2 = _single_token_forward(
        params, cfg, k1, v1, commit, commit_pos, emit0, table=table,
        fused=fused, mesh=mesh,
    )

    length = p_b + m
    remaining = state["remaining"] - m
    active = emit0 & (remaining > 0) & (length < total) & ~hit_eos
    length = jnp.where(hit_eos, total, length)
    new_state = {
        **state,
        "length": length,
        "remaining": remaining,
        "active": active,
        "last_logits": jnp.where(emit0[:, None], logits2,
                                 state["last_logits"]),
        "key": key,
    }
    return _with_kv(new_state, k2, v2), out, emitted


@functools.partial(jax.jit,
                   static_argnames=("cfg", "top_k", "eos_id", "kv_fused",
                                    "mesh"),
                   donate_argnames=("state",))
def verify_step(state, params, cfg: TransformerConfig, draft, draft_len,
                top_k: int = 0, eos_id: int | None = None,
                kv_fused: bool = False, mesh=None):
    """Score ``draft`` [slots, K] tokens against the decode-state KV cache
    in ONE fused dispatch and emit each row's longest accepted prefix plus
    one committed target token (1..K+1 tokens of progress per row).
    Greedy rows are byte-identical to plain :func:`decode_step` chains;
    temperature>0 rows rejection-resample so the sampled distribution is
    unchanged. EOS parks rows on device exactly like
    :func:`_decode_step_body`. Returns (state, tokens [slots, K+1],
    emitted [slots, K+1])."""
    return _verify_step_body(state, params, cfg, draft, draft_len, top_k,
                             eos_id, kv_fused, mesh)


@functools.partial(jax.jit,
                   static_argnames=("cfg", "top_k", "eos_id", "kv_fused",
                                    "mesh"),
                   donate_argnames=("state",))
def verify_chunk(state, params, cfg: TransformerConfig, drafts, draft_lens,
                 top_k: int = 0, eos_id: int | None = None,
                 kv_fused: bool = False, mesh=None):
    """``steps`` verify steps fused into ONE dispatch via ``lax.scan`` —
    the speculative twin of :func:`decode_chunk`, so a chunk of K-token
    verifies still pays ~2 RTTs on a high-RTT link. ``drafts``
    [steps, slots, K] holds each step's proposals (later slices are
    chain continuations that simply fail verification after an early
    rejection — correctness never depends on the proposer being right).
    Returns (state, tokens [steps, slots, K+1], emitted likewise)."""

    def body(s, xs):
        draft, dlen = xs
        s, out, emitted = _verify_step_body(s, params, cfg, draft, dlen,
                                            top_k, eos_id, kv_fused, mesh)
        return s, (out, emitted)

    state, (outs, emits) = lax.scan(body, state, (drafts, draft_lens))
    return state, outs, emits


@functools.partial(jax.jit, static_argnames=("cfg", "steps"),
                   donate_argnames=("state",))
def extend_and_propose(state, params, cfg: TransformerConfig, feed,
                       feed_pos, feed_len, steps: int):
    """Draft-model helper: force-feed each row's newly committed target
    tokens (``feed`` [slots, S], ``feed_len`` real, starting at cache
    position ``feed_pos``) into the DRAFT decode state, then greedily
    decode ``steps`` proposal tokens per row — one dispatch total. The
    proposal steps advance the draft state past the confirmed region;
    the next call's feed (at host-tracked confirmed positions) overwrites
    whatever the target rejected, so no rollback pass is needed. Rows
    with ``feed_pos`` at the cache end are parked (their writes drop).
    Returns (state, proposals [slots, steps])."""
    in_feed = jnp.arange(feed.shape[1])[None, :] < feed_len[:, None]
    block_logits, k1, v1 = _block_forward(
        params, cfg, state["cache"]["k"], state["cache"]["v"], feed,
        feed_pos, token_valid=in_feed,
    )
    last = jnp.take_along_axis(
        block_logits, jnp.maximum(feed_len - 1, 0)[:, None, None], axis=1
    )[:, 0]
    live = feed_len > 0
    state = {
        "cache": {"k": k1, "v": v1},
        "length": feed_pos + feed_len,
        # Proposal budget only — the draft state's remaining/active are
        # reset from the host's feed every round.
        "remaining": jnp.where(live, steps + 1, 0).astype(jnp.int32),
        "active": live,
        "temperature": jnp.zeros_like(state["temperature"]),
        "last_logits": jnp.where(live[:, None], last,
                                 state["last_logits"]),
        "key": state["key"],
    }

    def body(s, _):
        s, tok, _emit = _decode_step_body(s, params, cfg, 0, None)
        return s, tok

    state, toks = lax.scan(body, state, None, length=steps)
    return state, toks.T  # [slots, steps]


# ---------------------------------------------------------------------------
# Paged KV cache (serving/kv_allocator.py holds the host-side allocator)
# ---------------------------------------------------------------------------
#
# The dense layout above reserves ``total_len`` K/V positions per decode
# slot — every admitted request pays worst-case HBM no matter its actual
# prompt or budget. The paged layout stores K/V in a pool of fixed-size
# blocks and maps each slot's virtual positions through a per-slot block
# table: slot ``b``'s position ``p`` lives at block
# ``table[b, p // Bs]``, offset ``p % Bs``. Concurrency is then bounded
# by TOKENS RESIDENT (blocks in use), not by ``slots * total_len``, and
# a prefix-cache hit shares the donor's full blocks by reference
# (refcounts in the host allocator) with zero device copies — only a
# partially-filled tail block is copy-on-write'd.
#
# Attention reads gather the row at block granularity and the math,
# masks, and widths are kept identical to the dense path (masked junk
# contributes exact zeros), so greedy outputs are byte-identical between
# layouts; ``decode_step`` / ``decode_chunk`` / ``verify_step`` /
# ``verify_chunk`` accept either state via :func:`_state_kv`. Table
# entries are initialised to ``num_blocks`` (an out-of-range sentinel):
# writes through unallocated entries are dropped by scatter semantics
# and gathers clamp into junk the validity mask already excludes.


def init_paged_state(cfg: TransformerConfig, slots: int, num_blocks: int,
                     block_size: int, max_blocks_per_seq: int, seed: int = 0,
                     kv_dtype: str = "fp"):
    """Paged server decode state: a device block pool
    ``[L, num_blocks, block_size, Hkv, hd]`` shared by all slots plus a
    per-slot block table. Virtual row width is
    ``max_blocks_per_seq * block_size`` (the dense ``total_len``).

    ``kv_dtype="int8"`` stores the pool quantized: int8 payload plus one
    f32 abs-max scale per (layer, position, kv head) riding a parallel
    scale pool indexed by the SAME block ids — so the host allocator's
    share/refcount/CoW bookkeeping covers payload and scales in one
    move, and resident K/V costs ~``head_dim + 4`` bytes per head
    instead of ``head_dim * fp_bytes``."""
    shape = (cfg.n_layers, num_blocks, block_size, cfg.n_kv_heads,
             cfg.head_dim)
    if kv_dtype == "int8":
        def _pool():
            return {"q": jnp.zeros(shape, jnp.int8),
                    "scale": jnp.zeros(shape[:-1], jnp.float32)}
        pool = {"k": _pool(), "v": _pool()}
    elif kv_dtype in ("", "fp"):
        pool = {"k": jnp.zeros(shape, cfg.dtype),
                "v": jnp.zeros(shape, cfg.dtype)}
    else:
        raise ValueError(f"unknown kv_dtype {kv_dtype!r}")
    return {
        "pool": pool,
        "block_table": jnp.full((slots, max_blocks_per_seq), num_blocks,
                                jnp.int32),
        "length": jnp.zeros((slots,), jnp.int32),
        "remaining": jnp.zeros((slots,), jnp.int32),
        "active": jnp.zeros((slots,), bool),
        "temperature": jnp.zeros((slots,), jnp.float32),
        "last_logits": jnp.zeros((slots, cfg.vocab_size), jnp.float32),
        "key": jax.random.PRNGKey(seed),
    }


def _paged_admit_rows_body(state, params, cfg: TransformerConfig, slots,
                           prompt_tokens, prompt_lengths, remaining,
                           temperature):
    """Prefill a round's admissions into a scratch dense cache (the exact
    dense-path math, so logits are byte-identical), then scatter each
    row's K/V into the pool blocks the host allocated for its slot
    (``state["block_table"][slots]``; sentinel entries drop their
    writes)."""
    pool_k, pool_v = state["pool"]["k"], state["pool"]["v"]
    bs = _kv_arr(pool_k).shape[2]
    mb = state["block_table"].shape[1]
    total = mb * bs
    b, t0 = prompt_tokens.shape
    cache = init_cache(cfg, b, total)
    prompt_lengths = jnp.maximum(prompt_lengths, 1)
    valid = jnp.arange(total)[None, :] < prompt_lengths[:, None]
    positions = jnp.broadcast_to(jnp.arange(t0)[None], (b, t0))
    logits, cache = forward_cached(
        params, prompt_tokens, cfg, cache, 0, positions, valid,
        token_valid=positions < prompt_lengths[:, None],
    )
    last = jnp.take_along_axis(
        logits, (prompt_lengths - 1)[:, None, None], axis=1
    )[:, 0]
    rows_tbl = state["block_table"][slots]  # [b, mb]
    upd_k = cache["k"].reshape(cfg.n_layers, b, mb, bs, cfg.n_kv_heads,
                               cfg.head_dim)
    upd_v = cache["v"].reshape(cfg.n_layers, b, mb, bs, cfg.n_kv_heads,
                               cfg.head_dim)

    def _scatter(pool, upd):
        # Quantized pools quantize at this scatter, exactly like the
        # per-token decode write — payload and scales land together.
        if isinstance(pool, dict):
            qd = _quantize_kv(upd)
            return {"q": pool["q"].at[:, rows_tbl].set(qd["q"]),
                    "scale": pool["scale"].at[:, rows_tbl].set(qd["scale"])}
        return pool.at[:, rows_tbl].set(upd)

    return {
        **state,
        "pool": {"k": _scatter(pool_k, upd_k),
                 "v": _scatter(pool_v, upd_v)},
        "length": state["length"].at[slots].set(prompt_lengths),
        "remaining": state["remaining"].at[slots].set(remaining),
        "active": state["active"].at[slots].set(remaining > 0),
        "temperature": state["temperature"].at[slots].set(temperature),
        "last_logits": state["last_logits"].at[slots].set(last),
    }, last


@functools.partial(jax.jit,
                   static_argnames=("cfg", "top_k", "eos_id", "kv_fused",
                                    "mesh"),
                   donate_argnames=("state",))
def paged_admit_rows_and_step(state, params, cfg: TransformerConfig, slots,
                              prompt_tokens, prompt_lengths, remaining,
                              temperature, top_k: int = 0,
                              eos_id: int | None = None,
                              kv_fused: bool = False, mesh=None):
    """Paged twin of :func:`admit_rows_and_step`: prefill ``[K, T0]``
    prompts, scatter them into the slots' allocated pool blocks, AND run
    one fused decode step — still a single dispatch. The host must have
    written each admitted slot's block table row before the call."""
    state, last = _paged_admit_rows_body(state, params, cfg, slots,
                                         prompt_tokens, prompt_lengths,
                                         remaining, temperature)
    state, tok, emit = _decode_step_body(state, params, cfg, top_k, eos_id,
                                         kv_fused, mesh)
    return state, last, tok, emit


def _paged_admit_prefix_body(state, params, cfg: TransformerConfig, slot,
                             prefix_len, suffix_tokens, prompt_len,
                             remaining, temperature, fused=False,
                             mesh=None, ring=None):
    """Suffix-only prefill through the slot's block table: the leading
    ``prefix_len`` positions are already backed by shared (and possibly
    one CoW'd) blocks, so the forward reads them in place — ZERO
    device-side copies of the reused prefix — and writes only the
    suffix K/V into the slot's owned blocks. ``fused`` block-walks the
    span read too, so a fused deployment never materializes the dense
    row even at admission."""
    table_row = state["block_table"][slot][None]  # [1, mb]
    _b, s = suffix_tokens.shape
    suffix_len = jnp.maximum(prompt_len - prefix_len, 1)
    logits, pool_k, pool_v = _block_forward(
        params, cfg, state["pool"]["k"], state["pool"]["v"], suffix_tokens,
        jnp.reshape(prefix_len, (1,)),
        token_valid=jnp.arange(s)[None, :] < suffix_len, table=table_row,
        fused=fused, mesh=mesh, ring=ring,
    )
    last = jnp.take_along_axis(
        logits, jnp.reshape(suffix_len - 1, (1, 1, 1)), axis=1
    )[:, 0]
    return {
        **state,
        "pool": {"k": pool_k, "v": pool_v},
        "length": state["length"].at[slot].set(prompt_len),
        "remaining": state["remaining"].at[slot].set(remaining),
        "active": state["active"].at[slot].set(remaining > 0),
        "temperature": state["temperature"].at[slot].set(temperature),
        "last_logits": state["last_logits"].at[slot].set(last[0]),
    }, last


@functools.partial(jax.jit,
                   static_argnames=("cfg", "top_k", "eos_id", "kv_fused",
                                    "mesh", "ring"),
                   donate_argnames=("state",))
def paged_admit_prefix_and_step(state, params, cfg: TransformerConfig, slot,
                                prefix_len, suffix_tokens, prompt_len,
                                remaining, temperature, top_k: int = 0,
                                eos_id: int | None = None,
                                kv_fused: bool = False, mesh=None,
                                ring=None):
    """Paged twin of :func:`admit_prefix_and_step` — except the reused
    prefix is never gathered or copied: the host mapped the donor's full
    blocks into ``slot``'s table (refcount-shared) and CoW'd at most the
    one partially-filled tail block, so this dispatch only prefills the
    suffix and takes the fused decode step. ``ring`` routes the span
    read through the context-parallel ring — the final chunk of a
    chunked long admission rides this so its attention over the whole
    already-scattered prompt is sequence-sharded too."""
    state, last = _paged_admit_prefix_body(state, params, cfg, slot,
                                           prefix_len, suffix_tokens,
                                           prompt_len, remaining,
                                           temperature, kv_fused, mesh,
                                           ring)
    state, tok, emit = _decode_step_body(state, params, cfg, top_k, eos_id,
                                         kv_fused, mesh)
    return state, last, tok, emit


@functools.partial(jax.jit,
                   static_argnames=("cfg", "kv_fused", "mesh", "ring"),
                   donate_argnames=("state",))
def paged_prefill_chunk(state, params, cfg: TransformerConfig, slot, pos,
                        chunk_tokens, chunk_len, kv_fused: bool = False,
                        mesh=None, ring=None):
    """One bounded chunk of a long admission: forward ``chunk_tokens``
    ([1, S], right-padded to ``chunk_len`` real tokens) at virtual
    positions ``pos..pos+chunk_len-1`` of ``slot``'s row, writing K/V
    through the slot's block table. Each chunk's attention spans every
    previously-scattered position (the span mask admits ``<= pos + s``),
    so a chain of chunks reproduces the monolithic prefill's K/V
    byte-for-byte — chunking changes the dispatch schedule, not the
    math. The row is left PARKED (``length`` at the table horizon,
    ``active`` False): interleaved decode dispatches between chunks see
    an out-of-range position, so their unconditional scatters drop and
    their masks never admit the half-built row (the same discipline as
    :func:`retire_row`). The FINAL chunk must go through
    :func:`paged_admit_prefix_and_step` with ``prefix_len`` = tokens
    already chunked in — that activates the row, sets
    length/remaining/last_logits, and takes the fused first decode step.
    Consumes no RNG, so chunked sampling streams match monolithic ones.
    Pad positions beyond ``chunk_len`` write junk K/V exactly like the
    admit paths' padded suffixes — the next chunk (or decode) overwrites
    them before any mask admits them. ``ring`` sequence-shards the span
    read (context-parallel chunk prefill)."""
    table_row = state["block_table"][slot][None]  # [1, mb]
    _b, s = chunk_tokens.shape
    _logits, pool_k, pool_v = _block_forward(
        params, cfg, state["pool"]["k"], state["pool"]["v"], chunk_tokens,
        jnp.reshape(pos, (1,)),
        token_valid=jnp.arange(s)[None, :] < chunk_len, table=table_row,
        fused=kv_fused, mesh=mesh, ring=ring,
    )
    total = state["block_table"].shape[1] * _kv_arr(pool_k).shape[2]
    return {
        **state,
        "pool": {"k": pool_k, "v": pool_v},
        "length": state["length"].at[slot].set(total),
        "active": state["active"].at[slot].set(False),
    }


@functools.partial(jax.jit, donate_argnames=("pool",))
def store_blocks(pool, block_ids, cache):
    """Scatter a batch-1 :func:`prefill` cache into pool blocks
    ``block_ids`` ([nblk]; sentinel entries drop) — the paged prime path
    (preload a shared system prompt without touching the decode RNG).
    Quantized pools quantize here, so primed blocks carry their scales."""
    arr = _kv_arr(pool["k"])
    n_layers, bs = arr.shape[0], arr.shape[2]
    nblk = block_ids.shape[0]
    tail = arr.shape[3:]

    def _store(dst, vals):
        vals = vals[:, 0, : nblk * bs].reshape(n_layers, nblk, bs, *tail)
        if isinstance(dst, dict):
            qd = _quantize_kv(vals)
            return {"q": dst["q"].at[:, block_ids].set(qd["q"]),
                    "scale": dst["scale"].at[:, block_ids].set(qd["scale"])}
        return dst.at[:, block_ids].set(vals)

    return {"k": _store(pool["k"], cache["k"]),
            "v": _store(pool["v"], cache["v"])}


@jax.jit
def export_blocks(pool, block_ids):
    """Gather pool blocks ``block_ids`` ([nblk]) into a standalone
    payload — the device half of the prefill→decode KV handoff. Fp
    pools yield ``{"k": [L, nblk, Bs, H, hd], "v": ...}``; quantized
    pools yield the int8 codes AND the per-(position, head) scales
    (``{"q", "scale"}`` per side), so the payload is the pool content
    verbatim: an importer lands bit-identical values without ever
    re-quantizing. Pure gather — the donor pool is untouched, so an
    export never invalidates blocks in-flight readers share."""
    def _take(kv):
        if isinstance(kv, dict):
            return {"q": kv["q"][:, block_ids],
                    "scale": kv["scale"][:, block_ids]}
        return kv[:, block_ids]

    return {"k": _take(pool["k"]), "v": _take(pool["v"])}


@functools.partial(jax.jit, donate_argnames=("pool",))
def import_blocks(pool, block_ids, payload):
    """Scatter an :func:`export_blocks` payload into pool blocks
    ``block_ids`` — the receiving half of the KV handoff, the
    cross-replica twin of :func:`store_blocks` (which quantizes a fresh
    fp prefill; this path copies codes + scales verbatim, so a
    quantized handoff is exact by construction, never a second
    quantization). Layouts must match: an fp payload into an fp pool,
    a quantized payload into a quantized pool."""
    def _put(dst, vals):
        if isinstance(dst, dict):
            return {"q": dst["q"].at[:, block_ids].set(vals["q"]),
                    "scale": dst["scale"].at[:, block_ids].set(
                        vals["scale"])}
        return dst.at[:, block_ids].set(vals)

    return {"k": _put(pool["k"], payload["k"]),
            "v": _put(pool["v"], payload["v"])}


@functools.partial(jax.jit, donate_argnames=("pool",))
def copy_block(pool, dst, src):
    """Copy one block's K/V across the pool — the copy-on-write for a
    partially-filled shared tail block (the ONLY device copy a prefix
    hit ever pays). ``dst``/``src`` are traced, one executable serves
    every pair. Quantized pools copy payload AND scales in the same
    dispatch — a CoW'd block is exact, not re-quantized."""
    def _copy(kv):
        if isinstance(kv, dict):
            return {"q": kv["q"].at[:, dst].set(kv["q"][:, src]),
                    "scale": kv["scale"].at[:, dst].set(kv["scale"][:, src])}
        return kv.at[:, dst].set(kv[:, src])

    return {"k": _copy(pool["k"]), "v": _copy(pool["v"])}


# ---------------------------------------------------------------------------
# Tensor-parallel serving layout (serving/continuous.py's tp_shards knob)
# ---------------------------------------------------------------------------
#
# A tp-sharded decoder runs every executable above over a tensor mesh:
# weights carry the Megatron column/row split from
# models/transformer.py:partition_rules, and the KV storage — dense rows
# or the paged block pool, fp or quantized — is sharded over the KV-HEAD
# axis. Block ids index the (unsharded) block dim, so they stay
# host-global: the allocator, the prefix trie, refcount/CoW, and the
# export/import handoff never see the split. Per-head attention math is
# fully local to a shard; the only cross-shard reductions are the
# row-parallel output projections (wo, mlp down), which GSPMD inserts
# from the weight shardings.


def _kv_side_spec(side, axis: str, pp_axis: str | None = None):
    """Spec for one side (k or v) of a KV store whose head dim is the
    second-to-last payload dim — covers the dense [L, slots, T, Hkv, hd]
    cache, the paged [L, N, Bs, Hkv, hd] pool, and the quantized
    ``{"q", "scale"}`` pair (scales drop the trailing hd). ``pp_axis``
    additionally shards the leading LAYER dim — the pipeline-parallel
    serving layout, where each stage holds the KV for its own layer
    range. Block ids index dims the split never touches, so the
    allocator/trie/handoff host code is pp-blind exactly as it is
    tp-blind."""
    from jax.sharding import PartitionSpec as P

    def _spec(arr):
        return P(pp_axis, *([None] * (arr.ndim - 3)), axis, None)

    if isinstance(side, dict):
        return {"q": _spec(side["q"]),
                "scale": P(pp_axis, *([None] * (side["scale"].ndim - 2)),
                           axis)}
    return _spec(side)


def decode_state_specs(state, axis: str = "tensor",
                       pp_axis: str | None = None):
    """PartitionSpec pytree for a decode state on a tensor-parallel
    serving mesh: KV payload sharded over the KV-head axis (and, with
    ``pp_axis``, over the layer dim), every other leaf (tables, lengths,
    logits, RNG key) replicated."""
    from jax.sharding import PartitionSpec as P

    def _replicate(tree):
        return jax.tree.map(lambda _: P(), tree)

    specs = {}
    for name, sub in state.items():
        if name in ("pool", "cache"):
            specs[name] = {s: _kv_side_spec(sub[s], axis, pp_axis)
                           for s in sub}
        else:
            specs[name] = _replicate(sub)
    return specs


def shard_decode_state(state, mesh, axis: str = "tensor",
                       pp_axis: str | None = None):
    """Place a decode state (or a dense prefix pool — any {"k","v"}
    tree) onto ``mesh`` with the KV-head split of
    :func:`decode_state_specs`."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    if set(state) == {"k", "v"}:
        specs = {s: _kv_side_spec(state[s], axis, pp_axis) for s in state}
    else:
        specs = decode_state_specs(state, axis, pp_axis)
    shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P))
    return jax.device_put(state, shardings)
