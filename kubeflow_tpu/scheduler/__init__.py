"""Cluster scheduler: heterogeneity-aware gang placement with priority
preemption (ROADMAP item 2, in the spirit of Gavel — PAPERS.md).

- :mod:`~kubeflow_tpu.scheduler.capacity` — pools of contiguous TPU
  slices from Node objects + measured-throughput profiles.
- :mod:`~kubeflow_tpu.scheduler.queue` — weighted-fair priority queue
  with starvation aging.
- :mod:`~kubeflow_tpu.scheduler.controller` — the policy loop as a
  controller over SchedulingPolicy: all-or-nothing gang admission,
  priority preemption riding the gang-coordinated SIGTERM checkpoint.
"""

from kubeflow_tpu.scheduler.capacity import (
    ClusterCapacity,
    Slice,
    ThroughputBook,
)
from kubeflow_tpu.scheduler.controller import SchedulerController
from kubeflow_tpu.scheduler.queue import QueueEntry, order_queue

__all__ = [
    "ClusterCapacity",
    "Slice",
    "ThroughputBook",
    "SchedulerController",
    "QueueEntry",
    "order_queue",
]
