"""Cluster scheduler: heterogeneity-aware gang placement with priority
preemption (ROADMAP item 2, in the spirit of Gavel — PAPERS.md).

- :mod:`~kubeflow_tpu.scheduler.capacity` — pools of contiguous TPU
  slices from Node objects + measured-throughput profiles.
- :mod:`~kubeflow_tpu.scheduler.queue` — weighted-fair priority queue
  with starvation aging.
- :mod:`~kubeflow_tpu.scheduler.controller` — the policy loop as a
  controller over SchedulingPolicy: all-or-nothing gang admission,
  priority preemption riding the gang-coordinated SIGTERM checkpoint.
"""

__all__ = [
    "ClusterCapacity",
    "Slice",
    "ThroughputBook",
    "SchedulerController",
    "QueueEntry",
    "order_queue",
]

# Lazy attribute resolution (PEP 562): the serving QoS layer imports
# kubeflow_tpu.scheduler.queue for the shared fair-share/aging policy,
# and that import must not drag the controller's k8s/operator stack
# into the model-server process.
_HOMES = {
    "ClusterCapacity": "capacity", "Slice": "capacity",
    "ThroughputBook": "capacity",
    "SchedulerController": "controller",
    "QueueEntry": "queue", "order_queue": "queue",
}


def __getattr__(name: str):
    if name in _HOMES:
        import importlib

        mod = importlib.import_module(
            f"kubeflow_tpu.scheduler.{_HOMES[name]}")
        return getattr(mod, name)
    raise AttributeError(name)
