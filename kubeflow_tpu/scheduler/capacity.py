"""Cluster capacity model: heterogeneous TPU slice pools built from Nodes.

The model the Gavel-style policy loop places against
(:mod:`kubeflow_tpu.scheduler.controller`): nodes labeled with a GKE TPU
accelerator type form *pools*; within a pool, nodes sharing a slice label
form one contiguous *slice* (the unit a gang must land wholly inside —
the ICI domain). Hosts are the placement grain: one gang pod occupies one
host, matching the one-pod-per-TPU-VM-host layout the job controller
renders.

Occupancy is derived, never stored: a host is busy iff a live placement
annotation (or a still-running pod of a revoked placement) claims it, so
the model is rebuilt from the apiserver every round and survives scheduler
restarts with zero recovery code — the same level-triggered contract as
the controllers.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Mapping

from kubeflow_tpu.apis import scheduling as api


@dataclass
class Slice:
    """One contiguous slice: an ordered set of schedulable hosts."""

    pool: str            # accelerator type, e.g. "v5e"
    slice_id: str
    topology: str = ""
    nodes: list[str] = field(default_factory=list)
    chips_per_host: int = 0

    @property
    def size(self) -> int:
        return len(self.nodes)


class ClusterCapacity:
    """Pools/slices from Node objects + a within-round reservation view.

    ``reserve``/``occupy`` mutate only this in-memory view: one scheduling
    round works against one consistent snapshot, so two gangs admitted in
    the same round can never be handed overlapping hosts — the other half
    of the all-or-nothing guarantee (the first half being the single
    placement annotation per gang).
    """

    def __init__(self, slices: Iterable[Slice]):
        self.slices: list[Slice] = list(slices)
        self._busy: dict[str, str] = {}  # node name -> holder key

    # -- construction --------------------------------------------------

    @classmethod
    def from_nodes(cls, nodes: Iterable[Mapping]) -> "ClusterCapacity":
        by_slice: dict[tuple[str, str], Slice] = {}
        for node in nodes:
            meta = node.get("metadata", {})
            labels = meta.get("labels", {}) or {}
            accel = labels.get(api.NODE_ACCEL_LABEL)
            if not accel:
                continue  # not a TPU host
            if node.get("spec", {}).get("unschedulable"):
                continue  # cordoned / draining
            if _not_ready(node):
                continue  # node-kill churn: a dead kubelet is not capacity
            slice_id = labels.get(api.NODE_SLICE_LABEL,
                                  f"{accel}-{meta.get('name', '')}")
            key = (accel, slice_id)
            sl = by_slice.get(key)
            if sl is None:
                sl = by_slice[key] = Slice(
                    pool=accel, slice_id=slice_id,
                    topology=labels.get(api.NODE_TOPO_LABEL, ""),
                )
            sl.nodes.append(meta.get("name", ""))
            chips = (node.get("status", {}).get("capacity", {})
                     or {}).get("google.com/tpu", 0)
            try:
                sl.chips_per_host = max(sl.chips_per_host, int(chips))
            except (TypeError, ValueError):
                pass
        for sl in by_slice.values():
            sl.nodes.sort()  # deterministic host order
        return cls(sorted(by_slice.values(),
                          key=lambda s: (s.pool, s.slice_id)))

    # -- inspection ----------------------------------------------------

    @property
    def node_names(self) -> set[str]:
        return {n for sl in self.slices for n in sl.nodes}

    def pools(self) -> dict[str, list[Slice]]:
        out: dict[str, list[Slice]] = {}
        for sl in self.slices:
            out.setdefault(sl.pool, []).append(sl)
        return out

    def largest_slice(self, accelerator: str | None = None) -> int:
        sizes = [sl.size for sl in self.slices
                 if accelerator in (None, sl.pool)]
        return max(sizes, default=0)

    def free_hosts(self, sl: Slice) -> list[str]:
        return [n for n in sl.nodes if n not in self._busy]

    def holder(self, node: str) -> str | None:
        return self._busy.get(node)

    # -- reservation view ----------------------------------------------

    def occupy(self, nodes: Iterable[str], holder: str) -> None:
        """Mark hosts busy (existing placements / still-running pods).
        First holder wins: a stale pod of a revoked placement keeps the
        host busy until it actually exits."""
        for node in nodes:
            self._busy.setdefault(node, holder)

    def release(self, holder: str) -> None:
        self._busy = {n: h for n, h in self._busy.items() if h != holder}

    def vacate(self, nodes: Iterable[str]) -> None:
        """Free specific hosts (an elastic shrink returns the tail of a
        grant while the holder keeps the rest)."""
        for node in nodes:
            self._busy.pop(node, None)

    def feasible(self, n_hosts: int,
                 accelerator: str | None = None) -> list[Slice]:
        """Slices with >= n_hosts free right now (accelerator-filtered)."""
        return [sl for sl in self.slices
                if accelerator in (None, sl.pool)
                and len(self.free_hosts(sl)) >= n_hosts]

    def ever_fits(self, n_hosts: int,
                  accelerator: str | None = None) -> bool:
        """Could the request fit an EMPTY cluster? False means the job is
        structurally unschedulable (requests > largest matching slice),
        not merely waiting for capacity."""
        return n_hosts <= self.largest_slice(accelerator)

    def reserve(self, sl: Slice, n_hosts: int, holder: str) -> list[str]:
        """Atomically claim n_hosts on one slice — all or nothing."""
        free = self.free_hosts(sl)
        if len(free) < n_hosts:
            raise ValueError(
                f"slice {sl.slice_id}: {len(free)} free < {n_hosts}")
        nodes = free[:n_hosts]
        for node in nodes:
            self._busy[node] = holder
        return nodes


def _not_ready(node: Mapping) -> bool:
    for cond in node.get("status", {}).get("conditions", []) or []:
        if cond.get("type") == "Ready":
            return cond.get("status") != "True"
    return False  # no conditions reported — assume schedulable (fake nodes)


# ---------------------------------------------------------------------------
# Throughput profiles (the heterogeneity signal)
# ---------------------------------------------------------------------------

# Default normalized throughput book, seeded from the repo's BENCH_*.json
# measurements (tokens/s/chip on the flagship train config) scaled by the
# pools' relative peak: jobs without a measured profile fall back to
# "default". A SchedulingPolicy's spec.profiles overrides/extends this.
DEFAULT_PROFILES: dict[str, dict[str, float]] = {
    "default": {"v5e": 1.0, "v5p": 2.3},
    # BENCH_r05: flagship-1b 22325 tok/s/chip on the v5e-class config;
    # v5p-class peak ratio from the accelerator peak-flops ratio.
    "flagship-1b": {"v5e": 22325.0, "v5p": 51348.0},
}


class ThroughputBook:
    """(profile, accelerator) -> measured throughput. Scores placements
    Gavel-style: normalized throughput, so a job runs where it is
    *measured* fastest rather than wherever arrived first."""

    def __init__(self, profiles: Mapping[str, Mapping[str, float]]
                 | None = None):
        merged: dict[str, dict[str, float]] = {
            k: dict(v) for k, v in DEFAULT_PROFILES.items()}
        for name, table in (profiles or {}).items():
            if isinstance(table, Mapping):
                merged.setdefault(name, {}).update(
                    {a: float(t) for a, t in table.items()})
        self._profiles = merged

    @classmethod
    def from_bench_files(cls, files: Mapping[str, str],
                         extra: Mapping[str, Mapping[str, float]]
                         | None = None) -> "ThroughputBook":
        """Build profiles from the repo's BENCH_*.json measurement files:
        ``files`` maps accelerator type -> path measured on it. Each file
        contributes its config's leading token (e.g. ``flagship-1b``) as
        the profile name with ``tokens_per_sec_per_chip`` as the
        throughput (plus the deep-model twin when present)."""
        import json as _json

        profiles: dict[str, dict[str, float]] = {}
        for accel, path in files.items():
            try:
                with open(path) as f:
                    data = _json.load(f)
            except (OSError, ValueError):
                continue  # a missing/garbled bench file is not capacity
            rec = data.get("parsed", data)
            if not isinstance(rec, Mapping):
                continue
            for cfg_key, tps_key in (
                    ("config", "tokens_per_sec_per_chip"),
                    ("deep_config", "deep_tokens_per_sec_per_chip")):
                cfg, tps = rec.get(cfg_key), rec.get(tps_key)
                if not cfg or not isinstance(tps, (int, float)):
                    continue
                profile = str(cfg).split()[0]
                profiles.setdefault(profile, {})[accel] = float(tps)
        for name, table in (extra or {}).items():
            profiles.setdefault(name, {}).update(table)
        return cls(profiles)

    def throughput(self, profile: str | None, accelerator: str) -> float:
        table = self._profiles.get(profile or "default") \
            or self._profiles["default"]
        if accelerator in table:
            return float(table[accelerator])
        # Unknown accelerator: neutral 1.0 so it is placeable, not favored.
        return 1.0

    def score(self, profile: str | None, accelerator: str) -> float:
        """Normalized throughput in (0, 1]: 1.0 on the job's best pool."""
        table = self._profiles.get(profile or "default") \
            or self._profiles["default"]
        best = max(table.values(), default=1.0)
        return self.throughput(profile, accelerator) / max(best, 1e-9)
