"""Priority/fairness queue ordering for the cluster scheduler.

Pure functions over queue entries so the policy is unit-testable without
an apiserver. Ordering implements three forces, strongest first:

1. **Weighted fair sharing** across queues: the queue with the lowest
   used-share/weight ratio goes first (Gavel's fairness round), so one
   tenant cannot monopolize the cluster just by submitting first.
2. **Effective priority** within a queue: ``spec.priority`` plus
   starvation aging — every ``aging_seconds`` of queue wait is worth one
   priority point, so a low-priority gang behind a stream of
   high-priority arrivals is *eventually* first in line.
3. FIFO (queuedAt) as the tie-break.
"""

from __future__ import annotations

import datetime
from dataclasses import dataclass, field
from typing import Mapping


def parse_time(ts: str) -> datetime.datetime:
    return datetime.datetime.fromisoformat(ts.replace("Z", "+00:00"))


def aged_priority(priority: float, waited_seconds: float,
                  aging_seconds: float) -> float:
    """Effective priority after starvation aging: every
    ``aging_seconds`` of wait is worth one priority point, so a
    low-priority entry behind a stream of high-priority arrivals is
    eventually first in line. ``aging_seconds <= 0`` disables aging.

    Pure float math (no datetimes, no k8s imports) so the SAME policy
    serves the cluster scheduler's gang queue and the serving QoS
    admission queue (serving/qos.py) — one aging rule, two consumers.
    """
    if aging_seconds <= 0:
        return float(priority)
    return float(priority) + max(waited_seconds, 0.0) / aging_seconds


def fairness_ratio(used_share: float, weight: float) -> float:
    """Weighted-fair ordering key: the queue/tenant with the LOWEST
    used-share/weight ratio goes first (Gavel's fairness round), so
    service converges to the configured weights under backlog."""
    return float(used_share) / max(float(weight), 1e-9)


@dataclass
class QueueEntry:
    """One queued (unplaced) gang."""

    key: tuple[str, str, str]  # (kind, namespace, name)
    priority: int
    queue: str
    hosts: int                 # hosts needed to admit (elastic: the floor)
    queued_at: datetime.datetime
    eligible_at: datetime.datetime | None = None  # preemption backoff
    accelerator: str | None = None
    profile: str | None = None
    preemptible: bool = True
    # Elastic range {"min", "max"} in hosts (apis/scheduling.elastic_spec):
    # admission reserves `hosts` (the floor) and opportunistically extends
    # toward max in the same round; None = fixed-size gang.
    elastic: dict | None = None
    job: dict = field(default_factory=dict, repr=False)

    def effective_priority(self, now: datetime.datetime,
                           aging_seconds: float) -> float:
        waited = (now - self.queued_at).total_seconds()
        return aged_priority(self.priority, waited, aging_seconds)


def order_queue(entries: list[QueueEntry], now: datetime.datetime, *,
                aging_seconds: float,
                queue_weights: Mapping[str, float],
                used_share: Mapping[str, float]) -> list[QueueEntry]:
    """Admission order for one scheduling round.

    ``used_share`` is each queue's currently-running share (hosts, or any
    consistent unit); entries still inside a preemption backoff window are
    pushed behind everything eligible (but kept — a round with spare
    capacity may still reach them once eligible)."""

    def fairness(entry: QueueEntry) -> float:
        return fairness_ratio(used_share.get(entry.queue, 0.0),
                              queue_weights.get(entry.queue, 1.0))

    def sort_key(entry: QueueEntry):
        backoff = (entry.eligible_at is not None
                   and entry.eligible_at > now)
        return (
            backoff,                                       # eligible first
            fairness(entry),                               # fair share
            -entry.effective_priority(now, aging_seconds),  # priority
            entry.queued_at,                               # FIFO
            entry.key,                                     # determinism
        )

    return sorted(entries, key=sort_key)
