"""Scheduler manager: ``python -m kubeflow_tpu.scheduler``.

The binary the scheduler Deployment runs — one SchedulerController
(leader-elected when replicated) against the in-cluster apiserver. The
training-operator manager also embeds the controller
(:mod:`kubeflow_tpu.operators.__main__`) for single-manager deployments;
this entrypoint is the split-out deployment the scheduler manifest
renders, so placement policy can roll independently of the operators.
"""

from __future__ import annotations

from kubeflow_tpu.runtime import controller_main


def make_controllers(client):
    from kubeflow_tpu.scheduler.controller import SchedulerController

    return [SchedulerController(client)]


def main(argv=None) -> int:
    return controller_main(
        argv, make_controllers,
        "kubeflow-tpu cluster scheduler (gang placement + preemption)",
    )


if __name__ == "__main__":
    raise SystemExit(main())
