"""Heterogeneity-aware gang scheduler (the Gavel-style policy loop).

``SchedulerController`` owns placement for every training-job kind. It
reconciles the cluster's ``SchedulingPolicy`` object; every job, pod and
node event requeues that one key, so **one reconcile == one scheduling
round** over a consistent snapshot:

1. rebuild the capacity model from Nodes (pools of contiguous slices;
   dead/cordoned nodes are simply not capacity — node-kill churn needs no
   special path);
2. derive occupancy from live placements and still-running pods (never
   stored — a scheduler restart recovers by reading the world);
3. order the queue: weighted fair share across queues, then
   priority + starvation aging, then FIFO;
4. admit gangs **all-or-nothing**: a gang gets one placement annotation
   naming a host per pod on ONE slice, or stays queued. Partial placement
   is structurally impossible — there is no per-replica write to
   half-apply, and one round reserves against one in-memory view;
5. preempt when a higher-priority gang cannot fit: victims get the
   ``preempted-by`` mark on the job and each pod, then the evictor
   delivers the kubelet's SIGTERM→grace→SIGKILL sequence — riding the
   gang-coordinated checkpoint path, so preempt→requeue→resume is
   data-exact (the input stream is stateless in ``(seed, step)``).

Decisions are exported through the shared operator MetricRegistry:
queue depth and wait by queue, placement latency, preemptions and
requeues by reason.
"""

from __future__ import annotations

import datetime
import logging
import time
from typing import Callable, Mapping

from kubeflow_tpu.apis import jobs as jobs_api
from kubeflow_tpu.apis import scheduling as api
from kubeflow_tpu.k8s.client import K8sClient, retry_on_conflict
from kubeflow_tpu.operators.base import OPERATOR_METRICS, Controller
from kubeflow_tpu.scheduler.capacity import ClusterCapacity, ThroughputBook
from kubeflow_tpu.scheduler.queue import QueueEntry, order_queue, parse_time

log = logging.getLogger(__name__)

POD_API = "v1"

# Scheduler decision metrics, in the shared operator registry so ONE
# scrape of the manager's /metrics sees queue health next to the runtime
# signals (and the single-renderer invariant holds).
M_QUEUE_DEPTH = OPERATOR_METRICS.gauge(
    "scheduler_queue_depth",
    "Gangs queued (unplaced), by queue", labels=("queue",))
M_QUEUE_WAIT = OPERATOR_METRICS.histogram(
    "scheduler_queue_wait_seconds",
    "Queue wait from first sight to admission, by queue",
    labels=("queue",))
M_PLACEMENT = OPERATOR_METRICS.histogram(
    "scheduler_placement_seconds",
    "Latency of one placement decision (snapshot to annotation write)")
M_ADMISSIONS = OPERATOR_METRICS.counter(
    "scheduler_admissions_total",
    "Gangs admitted, by pool", labels=("pool",))
M_PREEMPTIONS = OPERATOR_METRICS.counter(
    "scheduler_preemptions_total",
    "Gangs preempted, by reason", labels=("reason",))
M_REQUEUES = OPERATOR_METRICS.counter(
    "scheduler_requeues_total",
    "Placed gangs sent back to the queue, by reason", labels=("reason",))
M_UNSCHEDULABLE = OPERATOR_METRICS.gauge(
    "scheduler_unschedulable_jobs",
    "Jobs whose request can never fit the current pools")
M_SHRINKS = OPERATOR_METRICS.counter(
    "scheduler_shrinks_total",
    "Elastic jobs shrunk (placement rewritten) to seat a queued gang")
M_GROWS = OPERATOR_METRICS.counter(
    "scheduler_grows_total",
    "Elastic jobs grown into idle capacity")


def _now_dt() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc)


def _iso(dt: datetime.datetime) -> str:
    return dt.replace(microsecond=0).isoformat().replace("+00:00", "Z")


def _job_key(job: Mapping) -> tuple[str, str, str]:
    m = job["metadata"]
    return (job["kind"], m.get("namespace", ""), m["name"])


def _key_str(key: tuple[str, str, str]) -> str:
    return "/".join(key)


def _gang_hosts(job: Mapping) -> int:
    """Gang size in hosts: one pod per host (the TPU-VM layout)."""
    return sum(rs.get("replicas", 1)
               for rs in job.get("spec", {}).get("replicaSpecs", {}).values())


class SchedulerController(Controller):
    """Cluster scheduler as a controller over SchedulingPolicy."""

    api_version = api.SCHEDULING_API_VERSION
    kind = api.SCHEDULING_POLICY_KIND
    resync_seconds = 5.0

    def __init__(self, client: K8sClient, *,
                 evict: Callable[[dict, float], bool] | None = None):
        super().__init__(client)
        # Pluggable eviction: tests wire FakeKubelet.evict (real SIGTERM +
        # grace); the default mirrors what an eviction looks like from the
        # apiserver — pod Failed, reason Preempted, DisruptionTarget.
        self._evict = evict or self._default_evict
        self._policy_keys: set[tuple[str, str]] = set()

    def watched_kinds(self):
        return ([(POD_API, "Pod"), (POD_API, "Node")]
                + [(jobs_api.JOBS_API_VERSION, k)
                   for k in jobs_api.ALL_JOB_KINDS])

    def _handle_event(self, event) -> None:
        obj = event.object
        if obj.get("kind") == self.kind:
            super()._handle_event(event)
            return
        # Any job/pod/node event triggers a scheduling round: requeue
        # every policy (there is normally exactly one).
        keys = self._policy_keys or {
            self._key(p) for p in self._list_policies()}
        for key in keys:
            self._enqueue(key)

    def _list_policies(self) -> list[dict]:
        try:
            return self.client.list(self.api_version, self.kind)
        except Exception:
            return []

    # ------------------------------------------------------------------
    # one scheduling round
    # ------------------------------------------------------------------

    def reconcile(self, policy: dict) -> float | None:
        self._policy_keys = {self._key(policy)}
        knobs = api.policy_knobs(policy)
        now = _now_dt()

        capacity = ClusterCapacity.from_nodes(
            self.client.list(POD_API, "Node"))
        book = ThroughputBook(knobs["profiles"])
        jobs = self._managed_jobs()
        pods_by_job, pod_nodes = self._pod_occupancy()

        placed, queue, used_share = self._partition(
            jobs, capacity, pods_by_job, now, knobs)
        # Hosts held by pods that outlive their (revoked) placement keep
        # the hosts busy until the processes actually exit.
        for node, holder in pod_nodes.items():
            capacity.occupy([node], holder)

        # Preemptions in flight: a job marked preempted-by whose pods are
        # still alive. Its preemptor must not evict MORE victims, and the
        # eviction itself is retried level-triggered — a transiently
        # failed SIGTERM delivery last round must not leave the victim
        # running forever on revoked hosts.
        pending_preemptors = set()
        for job in jobs:
            preemptor = job["metadata"].get("annotations", {}).get(
                api.ANN_PREEMPTED_BY)
            alive = pods_by_job.get(_job_key(job))
            if not preemptor or not alive:
                continue
            pending_preemptors.add(preemptor)
            if api.placement(job) is None:
                self._evict_pods(job, alive, preemptor, knobs)

        depth: dict[str, int] = {}
        unschedulable = 0
        waiting = 0         # fits-someday gangs still queued this round
        resized: set[str] = set()  # jobs shrunk this round: never ALSO
        #                            evicted, and never regrown, in it
        for entry in order_queue(queue, now,
                                 aging_seconds=knobs["aging_seconds"],
                                 queue_weights=knobs["queue_weights"],
                                 used_share=used_share):
            depth[entry.queue] = depth.get(entry.queue, 0) + 1
            t0 = time.perf_counter()
            if not capacity.ever_fits(entry.hosts, entry.accelerator):
                unschedulable += 1
                self._mark_unschedulable(entry, capacity)
                continue
            feasible = capacity.feasible(entry.hosts, entry.accelerator)
            if feasible:
                self._admit(entry, feasible, capacity, book, now)
                M_PLACEMENT.observe(time.perf_counter() - t0)
                depth[entry.queue] -= 1
                continue
            in_backoff = bool(entry.eligible_at and entry.eligible_at > now)
            is_pending = _key_str(entry.key) in pending_preemptors
            # The cheaper move first: reclaim grant above an elastic
            # victim's floor (a placement rewrite the victim absorbs at a
            # step boundary) before any SIGTERM flies.
            if (knobs["shrink_enabled"] and not in_backoff
                    and not is_pending
                    and self._try_shrink(entry, placed, capacity, book,
                                         knobs, now, resized)):
                M_PLACEMENT.observe(time.perf_counter() - t0)
                depth[entry.queue] -= 1
                continue
            waiting += 1
            if (knobs["preemption_enabled"] and not in_backoff
                    and not is_pending):
                if self._try_preempt(entry, placed, capacity,
                                     pods_by_job, knobs, now,
                                     exclude=resized):
                    pending_preemptors.add(_key_str(entry.key))

        if knobs["grow_enabled"] and not waiting:
            # Only genuinely idle capacity: a queued gang that could fit
            # this pool someday has first claim on freed hosts.
            self._grow_pass(placed, capacity, knobs, now, resized)

        for q in set(depth) | set(knobs["queue_weights"]):
            M_QUEUE_DEPTH.labels(q).set(depth.get(q, 0))
        M_UNSCHEDULABLE.set(unschedulable)
        self._push_policy_status(policy, depth, now)
        return knobs["period"]

    # ------------------------------------------------------------------
    # snapshot helpers
    # ------------------------------------------------------------------

    def _managed_jobs(self) -> list[dict]:
        out = []
        for kind in jobs_api.ALL_JOB_KINDS:
            try:
                listed = self.client.list(jobs_api.JOBS_API_VERSION, kind)
            except Exception:
                continue  # kind not registered in this cluster
            out.extend(j for j in listed if api.is_managed(j))
        return out

    def _pod_occupancy(self):
        """(job key -> alive pod names, node -> holder) from live pods."""
        pods_by_job: dict[tuple[str, str, str], list[str]] = {}
        pod_nodes: dict[str, str] = {}
        for pod in self.client.list(POD_API, "Pod"):
            phase = pod.get("status", {}).get("phase", "Pending")
            if phase in ("Succeeded", "Failed"):
                continue
            meta = pod["metadata"]
            ann = meta.get("annotations", {}) or {}
            labels = meta.get("labels", {}) or {}
            kind = labels.get("kubeflow-tpu.org/job-kind")
            owner = labels.get("kubeflow-tpu.org/job-name")
            if kind and owner:
                pods_by_job.setdefault(
                    (kind, meta.get("namespace", ""), owner),
                    []).append(meta["name"])
            node = pod.get("spec", {}).get("nodeName")
            if node and api.ANN_POOL in ann:
                pod_nodes[node] = f"pod:{meta.get('namespace','')}/" \
                                  f"{meta['name']}"
        return pods_by_job, pod_nodes

    def _partition(self, jobs, capacity: ClusterCapacity,
                   pods_by_job, now, knobs):
        """Split managed jobs into placed (occupying) and queued; revoke
        placements whose hosts vanished (node kill)."""
        placed: list[dict] = []
        queue: list[QueueEntry] = []
        used_share: dict[str, float] = {}
        live_nodes = capacity.node_names
        for job in jobs:
            state = job.get("status", {}).get("state")
            if state in ("Succeeded", "Failed"):
                continue
            key = _job_key(job)
            decided = api.placement(job)
            if decided is not None:
                if not set(decided["nodes"]) <= live_nodes:
                    # A reserved host died: the whole gang must move
                    # (contiguous-slice invariant) — revoke and requeue.
                    self._revoke(job, reason="node-lost", now=now,
                                 backoff=knobs["requeue_backoff"])
                    M_REQUEUES.labels("node-lost").inc()
                else:
                    capacity.occupy(decided["nodes"], _key_str(key))
                    placed.append(job)
                    used_share[api.job_queue(job)] = (
                        used_share.get(api.job_queue(job), 0.0)
                        + len(decided["nodes"]))
                    continue
            queue.append(self._entry(job, now))
        return placed, queue, used_share

    def _entry(self, job: dict, now) -> QueueEntry:
        sched = job.get("status", {}).get("scheduling", {}) or {}
        queued_at = now
        if sched.get("queuedAt"):
            try:
                queued_at = parse_time(sched["queuedAt"])
            except ValueError:
                pass
        else:
            self._write_scheduling(job, {
                "state": api.STATE_QUEUED, "queuedAt": _iso(now),
                "queue": api.job_queue(job),
                "priority": api.job_priority(job),
            }, condition=(api.COND_QUEUED, "True", "AwaitingCapacity",
                          "gang queued by the cluster scheduler"))
        eligible_at = None
        if sched.get("requeueAfter"):
            try:
                eligible_at = parse_time(sched["requeueAfter"])
            except ValueError:
                pass
        tpu = job.get("spec", {}).get("tpu", {}) or {}
        pods = _gang_hosts(job)
        elastic = api.elastic_spec(job)
        if elastic and elastic["max"] < pods:
            # A range that cannot seat every process is malformed
            # (admission webhook validation rejects it; a scheduler must
            # not act on garbage): treat as a fixed-size gang.
            elastic = None
        # Elastic gangs admit at their floor (every process seated, at
        # least minReplicas hosts) and extend toward maxReplicas from
        # whatever the slice has free — degraded admission now beats
        # queued-at-full-size later; the grow pass recovers the rest.
        hosts = max(pods, elastic["min"]) if elastic else pods
        return QueueEntry(
            key=_job_key(job),
            priority=api.job_priority(job),
            queue=api.job_queue(job),
            hosts=hosts,
            queued_at=queued_at,
            eligible_at=eligible_at,
            accelerator=tpu.get("accelerator") or None,
            profile=job.get("spec", {}).get("profile"),
            preemptible=api.is_preemptible(job),
            elastic=elastic,
            job=job,
        )

    # ------------------------------------------------------------------
    # decisions
    # ------------------------------------------------------------------

    def _admit(self, entry: QueueEntry, feasible, capacity, book,
               now) -> None:
        """Reserve a full slice-contiguous host set and publish it as ONE
        placement annotation — the all-or-nothing write."""
        def rank(sl):
            # Highest measured throughput first (Gavel), then best-fit
            # (least leftover free hosts — keeps big slices whole), then
            # stable id for determinism.
            return (-book.score(entry.profile, sl.pool),
                    len(capacity.free_hosts(sl)) - entry.hosts,
                    sl.slice_id)

        sl = min(feasible, key=rank)
        want = entry.hosts
        if entry.elastic:
            # Opportunistic grow at admission: take whatever the chosen
            # slice has free up to maxReplicas — idle contiguous hosts
            # convert straight into data-parallel width.
            free = len(capacity.free_hosts(sl))
            want = min(max(entry.hosts, free), entry.elastic["max"])
        nodes = capacity.reserve(sl, want, _key_str(entry.key))
        kind, ns, name = entry.key
        elastic_grant = None
        if entry.elastic:
            elastic_grant = {"granted": len(nodes),
                             "min": entry.elastic["min"],
                             "max": entry.elastic["max"]}
        placement = api.encode_placement(sl.pool, sl.topology, sl.slice_id,
                                         nodes, _iso(now),
                                         elastic=elastic_grant)
        self.client.patch(
            jobs_api.JOBS_API_VERSION, kind, name,
            {"metadata": {"annotations": {
                api.ANN_PLACEMENT: placement,
                api.ANN_PREEMPTED_BY: None,  # cleared on re-admission
            }}},
            ns,
        )
        job = dict(entry.job)
        job["metadata"] = dict(job["metadata"])
        self._write_scheduling(job, {
            "state": api.STATE_ADMITTED,
            "pool": sl.pool, "slice": sl.slice_id,
            "nodes": nodes, "admittedAt": _iso(now),
            "granted": len(nodes) if entry.elastic else None,
            "requeueAfter": None, "preemptedBy": None,
        }, condition=(api.COND_QUEUED, "False", "Admitted",
                      f"placed on {sl.pool}/{sl.slice_id}"))
        M_ADMISSIONS.labels(sl.pool).inc()
        M_QUEUE_WAIT.labels(entry.queue).observe(
            max((now - entry.queued_at).total_seconds(), 0.0))
        log.info("admitted %s -> %s/%s %s", _key_str(entry.key),
                 sl.pool, sl.slice_id, nodes)

    def _floor(self, job: Mapping) -> int:
        """An elastic job's smallest legal grant: every pod seated and at
        least minReplicas hosts."""
        elastic = api.elastic_spec(job)
        if elastic is None:
            return _gang_hosts(job)
        return max(_gang_hosts(job), elastic["min"])

    def _try_shrink(self, entry: QueueEntry, placed, capacity, book,
                    knobs, now, resized: set[str]) -> bool:
        """Seat ``entry`` by shrinking elastic jobs toward their floors —
        a placement rewrite the victims absorb live (step-boundary
        reshard), no eviction, no lost step. Applies only when shrinking
        fully seats the entry on one slice; chooses the slice needing the
        fewest shrunk jobs. Declaring ``spec.elastic`` is consent to run
        anywhere inside the range whenever the cluster is contended, so
        (unlike eviction) no priority gap gates the reclaim — grant above
        the floor is borrowed capacity."""
        candidates = []
        for sl in capacity.slices:
            if entry.accelerator not in (None, sl.pool):
                continue
            if sl.size < entry.hosts:
                continue
            free = len(capacity.free_hosts(sl))
            shrinkable = []
            for job in placed:
                decided = api.placement(job)
                if not decided or decided.get("slice") != sl.slice_id:
                    continue
                if api.elastic_spec(job) is None:
                    continue
                reclaim = len(decided["nodes"]) - self._floor(job)
                if reclaim > 0:
                    shrinkable.append((job, reclaim))
            # Lowest priority loses width first; bigger reclaim breaks
            # ties (fewer jobs disturbed for the same freed capacity).
            shrinkable.sort(key=lambda jr: (api.job_priority(jr[0]),
                                            -jr[1]))
            chosen, freed = [], free
            for job, reclaim in shrinkable:
                if freed >= entry.hosts:
                    break
                take = min(reclaim, entry.hosts - freed)
                chosen.append((job, take))
                freed += take
            if freed >= entry.hosts and chosen:
                candidates.append((len(chosen), sl, chosen))
        if not candidates:
            return False
        _, sl, chosen = min(candidates,
                            key=lambda c: (c[0], c[1].slice_id))
        for job, take in chosen:
            self._shrink(job, take, capacity, now)
            resized.add(_key_str(_job_key(job)))
        self._admit(entry, [sl], capacity, book, now)
        return True

    def _shrink(self, job: dict, hosts: int, capacity, now) -> None:
        """Return the tail ``hosts`` of an elastic grant. Pods sit on the
        grant's PREFIX (operators/jobs.py maps pod i to nodes[i]), so a
        tail drop never unseats a process — the job's training loop sees
        the smaller grant at its next placement poll and reshards."""
        decided = api.placement(job)
        keep = decided["nodes"][:len(decided["nodes"]) - hosts]
        dropped = decided["nodes"][len(keep):]
        self._rewrite_grant(job, decided, keep, now)
        capacity.vacate(dropped)
        M_SHRINKS.inc()
        log.info("shrunk %s to %d host(s), released %s",
                 _key_str(_job_key(job)), len(keep), dropped)

    def _grow_pass(self, placed, capacity, knobs, now,
                   resized: set[str]) -> None:
        """Extend under-max elastic grants into hosts left free after the
        queue pass (idle → data-parallel width). A job resized this round
        never regrows in it, and ``growDelaySeconds`` keeps a quiet
        period after any resize (anti-thrash)."""
        for job in placed:
            key = _key_str(_job_key(job))
            if key in resized:
                continue
            elastic = api.elastic_spec(job)
            decided = api.placement(job)
            if elastic is None or decided is None:
                continue
            granted = len(decided["nodes"])
            if granted >= elastic["max"]:
                continue
            sched = job.get("status", {}).get("scheduling", {}) or {}
            if knobs["grow_delay"] > 0 and sched.get("resizedAt"):
                try:
                    since = (now - parse_time(
                        sched["resizedAt"])).total_seconds()
                    if since < knobs["grow_delay"]:
                        continue
                except ValueError:
                    pass
            sl = next((s for s in capacity.slices
                       if s.pool == decided.get("pool")
                       and s.slice_id == decided.get("slice")), None)
            if sl is None:
                continue
            extra = min(len(capacity.free_hosts(sl)),
                        elastic["max"] - granted)
            if extra <= 0:
                continue
            nodes = decided["nodes"] + capacity.reserve(sl, extra, key)
            self._rewrite_grant(job, decided, nodes, now)
            M_GROWS.inc()
            log.info("grew %s to %d host(s) on %s/%s", key, len(nodes),
                     sl.pool, sl.slice_id)

    def _rewrite_grant(self, job: dict, decided: Mapping,
                       nodes: list[str], now) -> None:
        """Publish a resized grant: the SAME all-or-nothing placement
        annotation with a new node set, granted count updated, state
        still Admitted. Also updates the in-memory job dict so later
        passes in this round see the new grant, not the snapshot's."""
        elastic = api.elastic_spec(job) or {}
        kind, ns, name = _job_key(job)
        placement = api.encode_placement(
            decided.get("pool", ""), decided.get("topology", ""),
            decided.get("slice", ""), nodes, _iso(now),
            elastic={"granted": len(nodes),
                     "min": elastic.get("min", 1),
                     "max": elastic.get("max", len(nodes))})
        self.client.patch(
            jobs_api.JOBS_API_VERSION, kind, name,
            {"metadata": {"annotations": {api.ANN_PLACEMENT: placement}}},
            ns,
        )
        job.setdefault("metadata", {}).setdefault(
            "annotations", {})[api.ANN_PLACEMENT] = placement
        self._write_scheduling(job, {
            "nodes": list(nodes), "granted": len(nodes),
            "resizedAt": _iso(now),
        })

    def _try_preempt(self, entry: QueueEntry, placed, capacity,
                     pods_by_job, knobs, now,
                     exclude: set[str] = frozenset()) -> bool:
        """Free one slice for ``entry`` by evicting strictly lower-priority
        gangs. Chooses the slice needing the fewest victims; victims are
        the lowest-priority, most-recently-admitted gangs there. Jobs in
        ``exclude`` (shrunk this round) are never also evicted — one
        round disturbs a victim at most once."""
        candidates = []
        for sl in capacity.slices:
            if entry.accelerator not in (None, sl.pool):
                continue
            if sl.size < entry.hosts:
                continue
            free = len(capacity.free_hosts(sl))
            victims = []
            for job in placed:
                decided = api.placement(job)
                if not decided or decided.get("slice") != sl.slice_id:
                    continue
                if _key_str(_job_key(job)) in exclude:
                    continue
                if not api.is_preemptible(job):
                    continue
                gap = knobs["min_priority_gap"]
                if api.job_priority(job) + gap >= entry.priority:
                    continue
                victims.append(job)
            # Lowest priority first; bigger gangs break ties (fewer
            # victims evicted for the same freed capacity).
            victims.sort(key=lambda j: (
                api.job_priority(j), -len(api.placement(j)["nodes"])))
            chosen, freed = [], free
            for victim in victims:
                if freed >= entry.hosts:
                    break
                chosen.append(victim)
                freed += len(api.placement(victim)["nodes"])
            if freed >= entry.hosts and chosen:
                candidates.append((len(chosen), sl, chosen))
        if not candidates:
            return False
        _, sl, chosen = min(candidates,
                            key=lambda c: (c[0], c[1].slice_id))
        for victim in chosen:
            self._preempt(victim, by=entry, knobs=knobs, now=now,
                          pods=pods_by_job.get(_job_key(victim), []))
        return True

    def _preempt(self, victim: dict, *, by: QueueEntry, knobs, now,
                 pods) -> None:
        kind, ns, name = _job_key(victim)
        preemptor = _key_str(by.key)
        log.info("preempting %s/%s for %s", kind, name, preemptor)
        # 1. Revoke the reservation and mark the victim, in one patch:
        # the placement annotation disappearing is what parks the job
        # controller's recreate path until re-admission.
        self.client.patch(
            jobs_api.JOBS_API_VERSION, kind, name,
            {"metadata": {"annotations": {
                api.ANN_PLACEMENT: None,
                api.ANN_PREEMPTED_BY: preemptor,
            }}},
            ns,
        )
        self._write_scheduling(victim, {
            "state": api.STATE_PREEMPTED,
            "preemptedBy": preemptor,
            "requeueAfter": _iso(
                now + datetime.timedelta(
                    seconds=knobs["requeue_backoff"])),
            "pool": None, "slice": None, "nodes": None,
        }, condition=(api.COND_QUEUED, "True", "Preempted",
                      f"preempted by higher-priority {preemptor}"))
        # 2. Mark each pod, then evict it (SIGTERM → grace → SIGKILL via
        # the kubelet). A transiently failed delivery is retried by the
        # next round's pending-preemption sweep.
        self._evict_pods(victim, pods, preemptor, knobs)
        M_PREEMPTIONS.labels("priority").inc()
        M_REQUEUES.labels("preempted").inc()

    def _evict_pods(self, victim: dict, pods, preemptor: str,
                    knobs) -> None:
        """Mark + evict a victim's pods. The preempted-by mark lands
        FIRST so the job controller's preemption accounting recognizes
        the eviction whatever the pod's final phase/reason looks like."""
        ns = victim["metadata"].get("namespace")
        for pod_name in pods:
            try:
                self.client.patch(
                    POD_API, "Pod", pod_name,
                    {"metadata": {"annotations": {
                        api.ANN_PREEMPTED_BY: preemptor}}},
                    ns)
                pod = self.client.get(POD_API, "Pod", pod_name, ns)
            except Exception:
                continue  # pod vanished (or a fault): retried next round
            try:
                self._evict(pod, knobs["grace_seconds"])
            except Exception:
                log.exception("evicting %s/%s failed", ns, pod_name)

    def _default_evict(self, pod: dict, grace_seconds: float) -> bool:
        """Apiserver-visible shape of a kubelet eviction: Failed phase,
        Preempted reason, DisruptionTarget condition."""
        name = pod["metadata"]["name"]
        ns = pod["metadata"].get("namespace")

        def _write(client: K8sClient):
            current = client.get_or_none(POD_API, "Pod", name, ns)
            if current is None:
                return None
            status = current.setdefault("status", {})
            status["phase"] = "Failed"
            status["reason"] = "Preempted"
            conds = [c for c in status.get("conditions", [])
                     if c.get("type") != "DisruptionTarget"]
            conds.append({"type": "DisruptionTarget", "status": "True",
                          "reason": "PreemptionByScheduler"})
            status["conditions"] = conds
            return client.update_status(current)

        return retry_on_conflict(self.client, _write) is not None

    def _revoke(self, job: dict, *, reason: str, now, backoff) -> None:
        kind, ns, name = _job_key(job)
        self.client.patch(
            jobs_api.JOBS_API_VERSION, kind, name,
            {"metadata": {"annotations": {api.ANN_PLACEMENT: None}}},
            ns,
        )
        self._write_scheduling(job, {
            "state": api.STATE_QUEUED,
            "requeueAfter": _iso(
                now + datetime.timedelta(seconds=backoff)),
            "pool": None, "slice": None, "nodes": None,
        }, condition=(api.COND_QUEUED, "True", "Requeued",
                      f"placement revoked: {reason}"))

    def _mark_unschedulable(self, entry: QueueEntry,
                            capacity: ClusterCapacity) -> None:
        biggest = capacity.largest_slice(entry.accelerator)
        cond = (api.COND_UNSCHEDULABLE, "True", "NoFittingPool",
                f"gang needs {entry.hosts} host(s) on one "
                f"{entry.accelerator or 'any'} slice; largest is {biggest}")
        sched = entry.job.get("status", {}).get("scheduling", {}) or {}
        if sched.get("state") == api.STATE_UNSCHEDULABLE:
            return  # already surfaced; don't churn status writes
        self._write_scheduling(entry.job, {
            "state": api.STATE_UNSCHEDULABLE,
        }, condition=cond)

    # ------------------------------------------------------------------
    # status plumbing
    # ------------------------------------------------------------------

    def _write_scheduling(self, job: dict, fields: Mapping,
                          condition: tuple[str, str, str, str]
                          | None = None) -> None:
        """Merge scheduler-owned fields into the job's status (refetch +
        reapply on conflict). Touches ONLY status.scheduling and the
        scheduler's own condition types — the job controller keeps
        ownership of state/replicaStatuses/its conditions."""
        kind, ns, name = _job_key(job)

        def _write(client: K8sClient):
            current = client.get_or_none(jobs_api.JOBS_API_VERSION, kind,
                                         name, ns)
            if current is None:
                return None
            status = current.setdefault("status", {})
            sched = dict(status.get("scheduling", {}) or {})
            before = (dict(sched),
                      [c for c in status.get("conditions", [])
                       if c.get("type") in (api.COND_QUEUED,
                                            api.COND_UNSCHEDULABLE)])
            for k, v in fields.items():
                if v is None:
                    sched.pop(k, None)
                else:
                    sched[k] = v
            status["scheduling"] = sched
            if condition is not None:
                ctype, cstatus, reason, message = condition
                conds = status.setdefault("conditions", [])
                existing = next(
                    (c for c in conds if c.get("type") == ctype), None)
                new = {"type": ctype, "status": cstatus, "reason": reason,
                       "message": message,
                       "lastTransitionTime": _iso(_now_dt())}
                if existing is None:
                    conds.append(new)
                elif (existing.get("status") != cstatus
                      or existing.get("reason") != reason):
                    conds[conds.index(existing)] = new
                # Queued and Unschedulable are mutually exclusive.
                other = (api.COND_UNSCHEDULABLE if ctype == api.COND_QUEUED
                         else api.COND_QUEUED)
                for c in conds:
                    if c.get("type") == other and c.get("status") == "True":
                        c["status"] = "False"
            after = (status.get("scheduling"),
                     [c for c in status.get("conditions", [])
                      if c.get("type") in (api.COND_QUEUED,
                                           api.COND_UNSCHEDULABLE)])
            if before == after:
                return current  # no-op: don't emit MODIFIED storms
            return client.update_status(current)

        try:
            retry_on_conflict(self.client, _write)
        except Exception:
            # Transient apiserver faults on a status mirror must not kill
            # the round: the next round reconverges (level-triggered).
            log.debug("scheduling status write failed for %s/%s",
                      kind, name, exc_info=True)

    def _push_policy_status(self, policy: dict, depth: Mapping[str, int],
                            now) -> None:
        # Content-stable: no per-round timestamp, so a quiescent cluster
        # writes nothing (_push_status no-ops on equal status) and the
        # policy's own MODIFIED events can't self-trigger rounds forever.
        status = dict(policy.get("status", {}) or {})
        status["queueDepth"] = sum(depth.values())
        status["queueDepthByQueue"] = dict(sorted(depth.items()))
        updated = dict(policy)
        updated["status"] = status
        try:
            self._push_status(updated)
        except Exception:
            log.debug("policy status write failed", exc_info=True)
