"""Canned workload entrypoints job prototypes run in worker containers —
the tf-controller-examples analogue (tf-controller-examples/tf-cnn/launcher.py).

Every workload reads the operator-injected rendezvous env, joins the
collective, runs, and exits 0 on success (job completion is pod exit status,
the contract the reference's operators share).
"""
