"""MPI worker lifecycle sidecar:
`python -m kubeflow_tpu.workloads.mpi_sidecar`.

The openmpi-controller analogue (components/openmpi-controller/controller/
controller.py:17-116): the reference runs this next to each MPI worker to
(a) wait for the GPU driver, (b) poll the master pod's phase via the k8s
API, and (c) tear the worker down when the master finishes, so workers
don't idle forever after mpirun exits. TPU-recast:

- the driver-wait becomes the slice health probe (devices visible);
- the master poll watches the job's Launcher pod through the apiserver;
- teardown is a clean exit (the pod's restartPolicy does the rest) —
  the file-signal protocol is unnecessary because workers here are plain
  processes the kubelet supervises, not sidecar-signaled containers.

Exit code mirrors the launcher: 0 when the Launcher pod Succeeded,
1 when it Failed or disappeared, so the worker pod's terminal state
follows the job outcome.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from kubeflow_tpu.apis.jobs import ENV_JOB_NAME, ENV_JOB_NAMESPACE
from kubeflow_tpu.runtime import add_client_args, client_from_args, \
    strip_glog_args

LABEL_JOB = "kubeflow-tpu.org/job-name"
LABEL_REPLICA_TYPE = "kubeflow-tpu.org/replica-type"


def wait_for_launcher(client, job_name: str, namespace: str, *,
                      poll_seconds: float = 5.0, timeout: float = 0.0,
                      grace_polls: int = 3, log=print,
                      sleep=time.sleep) -> int:
    """Poll the job's Launcher pod until it reaches a terminal phase.
    Returns its exit status (0 Succeeded / 1 Failed-or-gone). A missing
    launcher is tolerated for ``grace_polls`` polls (it may not be
    scheduled yet), then treated as failure."""
    deadline = time.monotonic() + timeout if timeout else None
    missing = 0
    while True:
        pods = client.list(
            "v1", "Pod", namespace,
            label_selector={LABEL_JOB: job_name,
                            LABEL_REPLICA_TYPE: "launcher"},
        )
        if not pods:
            missing += 1
            if missing > grace_polls:
                log(f"launcher pod for {job_name} gone; exiting")
                return 1
        else:
            missing = 0
            phase = pods[0].get("status", {}).get("phase", "Pending")
            if phase == "Succeeded":
                log("launcher succeeded; tearing down worker")
                return 0
            if phase == "Failed":
                log("launcher failed; tearing down worker")
                return 1
        if deadline and time.monotonic() > deadline:
            log("timed out waiting on launcher")
            return 1
        sleep(poll_seconds)


def main(argv=None) -> int:
    argv = strip_glog_args(list(sys.argv[1:] if argv is None else argv))
    p = argparse.ArgumentParser(
        description="MPI worker lifecycle sidecar (launcher-phase watcher)"
    )
    add_client_args(p)
    p.add_argument("--job-name", default=os.environ.get(ENV_JOB_NAME, ""))
    p.add_argument("--job-namespace",
                   default=os.environ.get(ENV_JOB_NAMESPACE, "default"))
    p.add_argument("--poll-seconds", type=float, default=5.0)
    p.add_argument("--timeout", type=float, default=0.0)
    args = p.parse_args(argv)
    if not args.job_name:
        p.error(f"--job-name or ${ENV_JOB_NAME} required")
    client = client_from_args(args)
    rc = wait_for_launcher(
        client, args.job_name, args.job_namespace,
        poll_seconds=args.poll_seconds, timeout=args.timeout,
        log=lambda m: print(json.dumps({"msg": m})),
    )
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
