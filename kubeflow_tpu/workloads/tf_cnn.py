"""ResNet training workload — the tf_cnn_benchmarks analogue
(tf-controller-examples/tf-cnn/launcher.py:18, BASELINE config #1).

The TFJob-kind default command. Where the reference's launcher parses
TF_CONFIG and execs tf_cnn_benchmarks into a gRPC PS cluster, this joins the
JAX collective (the controller injects both TF_CONFIG for compat and the JAX
coordinator env) and trains data-parallel ResNet on synthetic images.
"""

from __future__ import annotations

import argparse
import json
import sys

from kubeflow_tpu.runtime import strip_glog_args


def main(argv=None) -> int:
    argv = strip_glog_args(list(sys.argv[1:] if argv is None else argv))
    p = argparse.ArgumentParser(description="ResNet training workload")
    p.add_argument("--model", default="resnet50",
                   help="resnet50 | resnet18 | resnet-test-tiny")
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--data", type=int, default=-1,
                   help="data-parallel mesh size (-1 = all devices)")
    args = p.parse_args(argv)

    from kubeflow_tpu.parallel.mesh import MeshConfig
    from kubeflow_tpu.train.loop import RunConfig, run

    result = run(RunConfig(
        model=args.model,
        mesh=MeshConfig(data=args.data),
        batch_size=args.batch_size,
        steps=args.steps,
        log_every=args.log_every,
        checkpoint_dir=args.checkpoint_dir,
    ))
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
