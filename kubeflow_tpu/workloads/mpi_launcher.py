"""MPIJob launcher — the kubectl-delivery + entrypoint the Launcher replica
runs: `python -m kubeflow_tpu.workloads.mpi_launcher -- <command...>`.

The reference delivers the hostfile with a kubectl-delivery init image and
drives worker lifecycle with the openmpi sidecar's file-signal protocol
(kubeflow/mpi-job/mpi-operator.libsonnet:280,
components/openmpi-controller/controller/controller.py:17-116). Here the
controller ships the hostfile content in ``MPI_HOSTFILE_CONTENT`` and this
launcher completes the contract:

1. write the hostfile to ``OMPI_MCA_orte_default_hostfile``;
2. wait until every worker hostname resolves (pods Running behind the
   headless Service — the kubectl-delivery readiness wait);
3. exec ``mpirun --hostfile <f> -np <slots> <command>`` (or the command
   directly when mpirun is absent / no workers — single-process mode, so
   the same image works for smoke tests without an MPI runtime).
"""

from __future__ import annotations

import argparse
import os
import shutil
import socket
import subprocess
import sys
import time

from kubeflow_tpu.runtime import strip_glog_args

DEFAULT_HOSTFILE = "/etc/mpi/hostfile"
ENV_HOSTFILE = "OMPI_MCA_orte_default_hostfile"
ENV_HOSTFILE_CONTENT = "MPI_HOSTFILE_CONTENT"


def parse_hostfile(content: str) -> list[tuple[str, int]]:
    """[(host, slots)] from 'host slots=N' lines."""
    entries = []
    for line in content.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.split()
        slots = 1
        for p in parts[1:]:
            if p.startswith("slots="):
                slots = int(p.split("=", 1)[1])
        entries.append((parts[0], slots))
    return entries


def write_hostfile(content: str, path: str) -> list[tuple[str, int]]:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(content if content.endswith("\n") else content + "\n")
    return parse_hostfile(content)


def wait_for_workers(hosts: list[str], *, timeout: float = 300.0,
                     poll: float = 2.0, resolve=socket.gethostbyname,
                     log=print) -> None:
    """Block until every worker resolves (headless-Service DNS appears when
    its pod is Running) — the kubectl-delivery wait loop."""
    deadline = time.monotonic() + timeout
    pending = list(hosts)
    while pending:
        still = []
        for host in pending:
            try:
                resolve(host)
            except OSError:
                still.append(host)
        pending = still
        if not pending:
            return
        if time.monotonic() > deadline:
            raise TimeoutError(f"workers never became resolvable: {pending}")
        log(f"waiting for workers: {pending}")
        time.sleep(poll)


def build_command(command: list[str], hostfile: str,
                  entries: list[tuple[str, int]], *,
                  mpirun=None) -> list[str]:
    mpirun = shutil.which("mpirun") if mpirun is None else mpirun
    if not entries or not mpirun:
        return command  # single-process mode
    np = sum(slots for _h, slots in entries)
    return [
        mpirun, "--hostfile", hostfile, "-np", str(np),
        "--allow-run-as-root",
        # TPU pods: one worker process per host, env forwarded.
        "--map-by", "node", "--bind-to", "none",
        "-x", "PATH", "-x", "PYTHONPATH",
        *command,
    ]


def main(argv=None) -> int:
    argv = strip_glog_args(list(sys.argv[1:] if argv is None else argv))
    p = argparse.ArgumentParser(
        description="MPIJob launcher (hostfile + worker wait + mpirun)"
    )
    p.add_argument("--hostfile", default=os.environ.get(ENV_HOSTFILE,
                                                        DEFAULT_HOSTFILE))
    p.add_argument("--wait-timeout", type=float, default=300.0)
    p.add_argument("--dry-run", action="store_true",
                   help="print the command instead of executing")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="-- <program args...>")
    args = p.parse_args(argv)
    command = [c for c in args.command if c != "--"]
    if not command:
        p.error("no command given (use: mpi_launcher -- prog args)")

    content = os.environ.get(ENV_HOSTFILE_CONTENT, "")
    entries = write_hostfile(content, args.hostfile) if content else []
    if entries:
        wait_for_workers([h for h, _s in entries],
                         timeout=args.wait_timeout)
    full = build_command(command, args.hostfile, entries)
    if args.dry_run:
        print(" ".join(full))
        return 0
    return subprocess.call(full)


if __name__ == "__main__":
    raise SystemExit(main())
