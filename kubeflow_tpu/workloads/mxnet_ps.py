"""Parameter-server training workload speaking the DMLC wire format.

Proves the MXNetJob kind end-to-end: the THREE DMLC roles the reference's
mxnet-operator schedules (scheduler / server / worker, DMLC_* env contract
— kubeflow/mxnet-job surface) rendezvous using ONLY the operator-injected
environment and train a model through a real push/pull parameter-server
protocol. MXNet itself is not in the image (and would bring its own CUDA
assumptions); the PS architecture is implemented directly — length-prefixed
JSON over TCP, weights sharded across servers — which is exactly what the
env contract exists to bootstrap.

Roles:
- scheduler: rendezvous hub on DMLC_PS_ROOT_PORT; collects every node's
  (role, id, addr), broadcasts the server address table, then waits for
  worker FINALIZE messages before releasing the servers.
- server: holds a contiguous shard of the weight vector; PUSH applies an
  SGD update to the shard, PULL returns it.
- worker: synthetic linear-regression batches; each step pulls the full
  weight vector, computes the MSE gradient, pushes shard-wise.

Every role prints one JSON line; workers report first/final loss so the
E2E test can assert training actually converged.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import struct
import threading
import time

import numpy as np

_TIMEOUT = 120.0


def _send(sock: socket.socket, msg: dict) -> None:
    data = json.dumps(msg).encode()
    sock.sendall(struct.pack("<I", len(data)) + data)


def _recv(sock: socket.socket) -> dict:
    head = b""
    while len(head) < 4:
        chunk = sock.recv(4 - len(head))
        if not chunk:
            raise ConnectionError("peer closed")
        head += chunk
    (n,) = struct.unpack("<I", head)
    data = b""
    while len(data) < n:
        chunk = sock.recv(n - len(data))
        if not chunk:
            raise ConnectionError("peer closed")
        data += chunk
    return json.loads(data)


def _connect(addr: str, port: int) -> socket.socket:
    """Connect with retry — gang pods start in arbitrary order, so the
    peer may not be listening yet (the kubectl-delivery wait analogue)."""
    deadline = time.monotonic() + _TIMEOUT
    while True:
        try:
            sock = socket.create_connection((addr, port), timeout=_TIMEOUT)
            sock.settimeout(_TIMEOUT)
            return sock
        except OSError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.2)


# ---------------------------------------------------------------------------
# Scheduler
# ---------------------------------------------------------------------------


def _bind_listener(port: int, backlog: int) -> socket.socket:
    """Bind with retry: a restarted gang can race the previous incarnation
    still holding the fixed coordinator port."""
    deadline = time.monotonic() + _TIMEOUT
    while True:
        srv = socket.socket()
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            srv.bind(("0.0.0.0", port))
            break
        except OSError:
            srv.close()
            if time.monotonic() > deadline:
                raise
            time.sleep(0.5)
    srv.listen(backlog)
    srv.settimeout(_TIMEOUT)
    return srv


def run_scheduler(port: int, n_servers: int, n_workers: int) -> dict:
    srv = _bind_listener(port, n_servers + n_workers)

    conns: list[socket.socket] = []
    worker_conns: list[socket.socket] = []
    servers: dict[int, list] = {}
    while len(servers) < n_servers or len(worker_conns) < n_workers:
        sock, addr = srv.accept()
        sock.settimeout(_TIMEOUT)
        reg = _recv(sock)
        if reg["role"] == "server":
            servers[reg["id"]] = [addr[0], reg["port"]]
        else:
            worker_conns.append(sock)
        conns.append(sock)
    table = {"servers": [servers[i] for i in range(n_servers)]}
    for sock in conns:
        _send(sock, table)
    # Barrier: every worker reports FINALIZE when its steps are done, then
    # the servers are released (they block on a scheduler message).
    done = 0
    for sock in worker_conns:
        try:
            if _recv(sock).get("finalize"):
                done += 1
        except (ConnectionError, TimeoutError):
            pass
    for sock in conns:
        try:
            _send(sock, {"shutdown": True})
        except OSError:
            pass
        sock.close()
    srv.close()
    return {"role": "scheduler", "servers": n_servers,
            "workers_finalized": done}


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------


def run_server(root_uri: str, root_port: int, server_id: int,
               n_servers: int, dim: int, lr: float) -> dict:
    shard = np.zeros(_shard_slice(server_id, n_servers, dim).stop
                     - _shard_slice(server_id, n_servers, dim).start,
                     np.float64)
    lsock = socket.socket()
    lsock.bind(("0.0.0.0", 0))
    lsock.listen(16)
    lsock.settimeout(_TIMEOUT)
    lport = lsock.getsockname()[1]

    pushes = 0
    stop = threading.Event()
    # One thread serves each worker connection; pushes and pulls from
    # different workers interleave, so shard updates and snapshots share a
    # lock (without it a pull could read a half-applied update and the
    # pushes counter could drop increments).
    shard_lock = threading.Lock()

    def serve_conn(sock: socket.socket) -> None:
        nonlocal pushes, shard
        sock.settimeout(_TIMEOUT)
        try:
            while True:
                msg = _recv(sock)
                if msg["op"] == "pull":
                    with shard_lock:
                        snapshot = shard.tolist()
                    _send(sock, {"shard": snapshot})
                elif msg["op"] == "push":
                    grad = np.asarray(msg["grad"], np.float64)
                    with shard_lock:
                        shard -= lr * grad  # in-place SGD on the shard
                        pushes += 1
                    _send(sock, {"ok": True})
                elif msg["op"] == "done":
                    _send(sock, {"ok": True})
                    return
        except (ConnectionError, TimeoutError, OSError):
            return

    def acceptor() -> None:
        while not stop.is_set():
            try:
                sock, _ = lsock.accept()
            except (TimeoutError, OSError):
                return
            threading.Thread(target=serve_conn, args=(sock,),
                             daemon=True).start()

    threading.Thread(target=acceptor, daemon=True).start()

    sched = _connect(root_uri, root_port)
    _send(sched, {"role": "server", "id": server_id, "port": lport})
    _recv(sched)  # address table (servers don't need it)
    _recv(sched)  # blocks until the scheduler's shutdown broadcast
    stop.set()
    lsock.close()
    sched.close()
    return {"role": "server", "id": server_id, "pushes": pushes}


def _shard_slice(server_id: int, n_servers: int, dim: int) -> slice:
    per = dim // n_servers
    start = server_id * per
    stop = dim if server_id == n_servers - 1 else start + per
    return slice(start, stop)


# ---------------------------------------------------------------------------
# Worker
# ---------------------------------------------------------------------------


def run_worker(root_uri: str, root_port: int, worker_id: int,
               n_servers: int, dim: int, steps: int,
               batch: int) -> dict:
    sched = _connect(root_uri, root_port)
    _send(sched, {"role": "worker", "id": worker_id})
    table = _recv(sched)
    server_socks = [_connect(a, p) for a, p in table["servers"]]

    rng = np.random.default_rng(42 + worker_id)
    w_true = np.linspace(-1.0, 1.0, dim)
    losses = []
    for _ in range(steps):
        # Pull the sharded weight vector.
        w = np.empty(dim, np.float64)
        for sid, sock in enumerate(server_socks):
            _send(sock, {"op": "pull"})
            w[_shard_slice(sid, n_servers, dim)] = _recv(sock)["shard"]
        x = rng.standard_normal((batch, dim))
        y = x @ w_true
        err = x @ w - y
        losses.append(float(np.mean(err ** 2)))
        grad = 2.0 * x.T @ err / batch
        for sid, sock in enumerate(server_socks):
            _send(sock, {"op": "push",
                         "grad": grad[_shard_slice(sid, n_servers,
                                                   dim)].tolist()})
            _recv(sock)
    for sock in server_socks:
        _send(sock, {"op": "done"})
        _recv(sock)
        sock.close()
    _send(sched, {"finalize": True})
    sched.close()
    return {"role": "worker", "id": worker_id, "steps": steps,
            "first_loss": losses[0], "final_loss": losses[-1],
            "converged": losses[-1] < losses[0] * 0.5}


# ---------------------------------------------------------------------------


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=32)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.1)
    args = ap.parse_args(argv)

    role = os.environ["DMLC_ROLE"]
    root_uri = os.environ["DMLC_PS_ROOT_URI"]
    root_port = int(os.environ["DMLC_PS_ROOT_PORT"])
    n_servers = int(os.environ["DMLC_NUM_SERVER"])
    n_workers = int(os.environ["DMLC_NUM_WORKER"])

    if role == "scheduler":
        report = run_scheduler(root_port, n_servers, n_workers)
    elif role == "server":
        report = run_server(root_uri, root_port,
                            int(os.environ["DMLC_SERVER_ID"]),
                            n_servers, args.dim, args.lr)
    elif role == "worker":
        report = run_worker(root_uri, root_port,
                            int(os.environ["DMLC_WORKER_ID"]),
                            n_servers, args.dim, args.steps, args.batch)
    else:
        raise SystemExit(f"unknown DMLC_ROLE {role!r}")
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
