"""Slice health probe: `python -m kubeflow_tpu.workloads.slice_health`.

The TPU analogue of the reference's GPU driver-wait + availability prober
(openmpi sidecar driver poll, controller.py:74-90; metric-collector
kubeflow-readiness.py:21-37): verify the worker actually has its devices
and the collective actually works, exit 0/1. Used three ways — an init/
sidecar container gating workload start, a Job the operator can schedule as
a pre-flight on a fresh slice, and a liveness probe command.

Checks: local device count (> 0, and == --expect-local-devices when
given), global device count across the rendezvous (== --expect-devices
when given), and a timed psum over every device (the ICI path) against
--max-collective-ms.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from kubeflow_tpu.runtime import strip_glog_args


def main(argv=None) -> int:
    argv = strip_glog_args(list(sys.argv[1:] if argv is None else argv))
    p = argparse.ArgumentParser(description="TPU slice health probe")
    p.add_argument("--expect-devices", type=int, default=0,
                   help="required global device count (0 = any)")
    p.add_argument("--expect-local-devices", type=int, default=0)
    p.add_argument("--max-collective-ms", type=float, default=0.0,
                   help="fail if the psum probe exceeds this (0 = no limit)")
    p.add_argument("--skip-collective", action="store_true")
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.parallel.distributed import (
        initialize_from_env,
        shutdown,
    )

    report: dict = {"healthy": False}
    try:
        info = initialize_from_env()
        n_local = jax.local_device_count()
        n_global = jax.device_count()
        report.update(process_id=info.process_id,
                      local_devices=n_local, global_devices=n_global,
                      platform=jax.devices()[0].platform)
        if n_local < 1:
            raise RuntimeError("no local devices")
        if args.expect_local_devices and n_local != args.expect_local_devices:
            raise RuntimeError(
                f"local devices {n_local} != {args.expect_local_devices}"
            )
        if args.expect_devices and n_global != args.expect_devices:
            raise RuntimeError(
                f"global devices {n_global} != {args.expect_devices}"
            )
        if not args.skip_collective:
            probe = jax.pmap(lambda x: jax.lax.psum(x, "d"), axis_name="d")
            out = probe(jnp.ones((n_local,), jnp.float32))  # compile
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            out = probe(jnp.full((n_local,), 2.0, jnp.float32))
            got = float(out[0])  # fetch = real completion
            ms = (time.perf_counter() - t0) * 1e3
            report.update(psum=got, collective_ms=round(ms, 3))
            if got != 2.0 * n_global:
                raise RuntimeError(f"psum wrong: {got} != {2.0 * n_global}")
            if args.max_collective_ms and ms > args.max_collective_ms:
                raise RuntimeError(
                    f"collective {ms:.1f}ms > {args.max_collective_ms}ms"
                )
        report["healthy"] = True
        return 0
    except Exception as e:
        report["error"] = str(e)
        return 1
    finally:
        print(json.dumps(report))
        try:
            shutdown()
        except Exception:
            pass


if __name__ == "__main__":
    raise SystemExit(main())
