"""Allreduce smoke test — the distributed-rendezvous E2E workload.

Default command for the MXNet/Chainer compat job prototypes and the
fake-slice E2E test: join the collective via the operator-injected env, psum
a known value over every device, assert the result, exit 0. This is the
smallest job that proves rendezvous + collectives work end to end (the role
tf-job-simple plays in CI, testing/tf_job_simple_test.py).
"""

from __future__ import annotations

import argparse
import json
import sys

from kubeflow_tpu.runtime import strip_glog_args


def main(argv=None) -> int:
    argv = strip_glog_args(list(sys.argv[1:] if argv is None else argv))
    p = argparse.ArgumentParser(description="collective allreduce smoke test")
    p.add_argument("--value", type=float, default=1.0)
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.parallel.distributed import (
        initialize_from_env,
        shutdown,
    )

    info = initialize_from_env()
    n_local = jax.local_device_count()
    n_global = jax.device_count()

    allreduce = jax.pmap(lambda x: jax.lax.psum(x, "d"), axis_name="d")

    out = allreduce(jnp.full((n_local,), args.value, jnp.float32))
    got = float(out[0])
    want = args.value * n_global
    result = {
        "process_id": info.process_id,
        "num_processes": info.num_processes,
        "local_devices": n_local,
        "global_devices": n_global,
        "psum": got,
        "expected": want,
        "ok": abs(got - want) < 1e-4,
    }

    if info.is_multislice:
        # Multislice gang: the controller injected the MEGASCALE env;
        # prove the DCN-mapped mesh path end to end — hybrid placement
        # (slices span the data axis, parallel/mesh.py), a global array
        # sharded over it, and a cross-slice reduction.
        import os

        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P

        from kubeflow_tpu.parallel.mesh import MeshConfig, build_mesh

        mesh = build_mesh(MeshConfig(data=-1),
                          num_slices=info.num_slices)
        arr = jax.make_array_from_callback(
            (n_global,), NamedSharding(mesh, P("data")),
            lambda idx: np.full((1,), args.value, np.float32),
        )
        total = jax.jit(jnp.sum,
                        out_shardings=NamedSharding(mesh, P()))(arr)
        result.update({
            "num_slices": info.num_slices,
            "slice_id": info.slice_id,
            "megascale_coordinator":
                os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"),
            "hybrid_mesh_data_degree": mesh.shape["data"],
            "dcn_psum": float(total),
            "ok": result["ok"]
            and abs(float(total) - args.value * n_global) < 1e-4,
        })

    print(json.dumps(result))
    shutdown()
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
