"""Allreduce bandwidth benchmark over ICI — the MPIJob/Horovod-benchmark
analogue (BASELINE config #4; reference surface:
kubeflow/mpi-job/prototypes/mpi-job-custom.jsonnet:35-59).

Sweeps buffer sizes, psums each over every device, reports per-size wall
time and algorithmic bus bandwidth as JSON lines. The MPIJob prototype's
default command.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from kubeflow_tpu.runtime import strip_glog_args


def _bench_one(n_elems: int, iters: int) -> dict:
    import jax
    import jax.numpy as jnp

    n_local = jax.local_device_count()
    n_global = jax.device_count()
    allreduce = jax.pmap(lambda x: jax.lax.psum(x, "d"), axis_name="d")
    x = jnp.ones((n_local, n_elems), jnp.float32)
    allreduce(x)[0].block_until_ready()  # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = allreduce(x)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    bytes_ = n_elems * 4
    # Ring-allreduce algorithmic bandwidth: 2(n-1)/n × payload / time.
    algo_bw = (2 * (n_global - 1) / max(n_global, 1)) * bytes_ / dt
    return {
        "elements": n_elems,
        "bytes": bytes_,
        "devices": n_global,
        "seconds_per_allreduce": dt,
        "algo_bandwidth_gbps": algo_bw / 1e9,
    }


def main(argv=None) -> int:
    argv = strip_glog_args(list(sys.argv[1:] if argv is None else argv))
    p = argparse.ArgumentParser(description="allreduce bandwidth benchmark")
    p.add_argument("--min-elems", type=int, default=1 << 10)
    p.add_argument("--max-elems", type=int, default=1 << 24)
    p.add_argument("--iters", type=int, default=10)
    args = p.parse_args(argv)

    from kubeflow_tpu.parallel.distributed import (
        initialize_from_env,
        shutdown,
    )

    info = initialize_from_env()
    results = []
    n = args.min_elems
    while n <= args.max_elems:
        r = _bench_one(n, args.iters)
        results.append(r)
        if info.process_id == 0:
            print(json.dumps(r))
        n *= 4
    if info.process_id == 0:
        best = max(r["algo_bandwidth_gbps"] for r in results)
        summary = {"metric": "allreduce_peak_bandwidth", "value": best,
                   "unit": "GB/s", "devices": results[0]["devices"]}
        print(json.dumps(summary))
        from kubeflow_tpu.train.loop import publish_metrics

        publish_metrics({"allreduce_peak_gbps": best})
    shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
