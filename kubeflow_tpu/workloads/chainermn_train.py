"""Synchronous data-parallel training over the CHAINERMN env contract.

Proves the ChainerJob kind end-to-end: master + workers rendezvous using
ONLY the operator-injected CHAINERMN_MASTER_ADDR/PORT/NUM_PROCESSES/
PROCESS_ID environment (operators/jobs.py ChainerJob branch — the
chainer-operator's MPI-style contract) and run synchronous SGD with a
star allreduce: every process computes a local gradient on its own data
shard, the master averages and broadcasts, all ranks apply the same
update. Chainer itself is not in the image; the contract is exercised by
the training protocol it exists to bootstrap, same as
:mod:`kubeflow_tpu.workloads.mxnet_ps` for DMLC.

Every rank prints one JSON line with first/final loss; rank 0 also
reports the process count so the E2E test can assert the full gang
participated.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import struct
import time

import numpy as np

_TIMEOUT = 120.0


def _send(sock: socket.socket, arr: np.ndarray) -> None:
    data = arr.astype("<f8").tobytes()
    sock.sendall(struct.pack("<I", len(data)) + data)


def _recv(sock: socket.socket) -> np.ndarray:
    head = b""
    while len(head) < 4:
        chunk = sock.recv(4 - len(head))
        if not chunk:
            raise ConnectionError("peer closed")
        head += chunk
    (n,) = struct.unpack("<I", head)
    data = b""
    while len(data) < n:
        chunk = sock.recv(n - len(data))
        if not chunk:
            raise ConnectionError("peer closed")
        data += chunk
    return np.frombuffer(data, "<f8").copy()


def _star_allreduce_master(conns, local: np.ndarray) -> np.ndarray:
    total = local.copy()
    for sock in conns:
        total += _recv(sock)
    mean = total / (len(conns) + 1)
    for sock in conns:
        _send(sock, mean)
    return mean


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=32)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.1)
    args = ap.parse_args(argv)

    addr = os.environ["CHAINERMN_MASTER_ADDR"]
    port = int(os.environ["CHAINERMN_MASTER_PORT"])
    nproc = int(os.environ["CHAINERMN_NUM_PROCESSES"])
    rank = int(os.environ["CHAINERMN_PROCESS_ID"])

    conns: list[socket.socket] = []
    if rank == 0:
        from kubeflow_tpu.workloads.mxnet_ps import _bind_listener

        srv = _bind_listener(port, nproc)
        while len(conns) < nproc - 1:
            sock, _ = srv.accept()
            sock.settimeout(_TIMEOUT)
            conns.append(sock)
    else:
        # Retry: the gang's pods start in arbitrary order, so the master
        # may not be listening yet.
        deadline = time.monotonic() + _TIMEOUT
        while True:
            try:
                master = socket.create_connection((addr, port),
                                                  timeout=_TIMEOUT)
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.2)
        master.settimeout(_TIMEOUT)

    rng = np.random.default_rng(7 + rank)  # distinct shard per rank
    w_true = np.linspace(-1.0, 1.0, args.dim)
    w = np.zeros(args.dim)
    losses = []
    for _ in range(args.steps):
        x = rng.standard_normal((args.batch, args.dim))
        y = x @ w_true
        err = x @ w - y
        losses.append(float(np.mean(err ** 2)))
        grad = 2.0 * x.T @ err / args.batch
        if rank == 0:
            grad = _star_allreduce_master(conns, grad)
        else:
            _send(master, grad)
            grad = _recv(master)
        w -= args.lr * grad  # every rank applies the SAME averaged update

    if rank == 0:
        for sock in conns:
            sock.close()
    else:
        master.close()
    print(json.dumps({
        "rank": rank, "num_processes": nproc, "steps": args.steps,
        "first_loss": losses[0], "final_loss": losses[-1],
        "converged": losses[-1] < losses[0] * 0.5,
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
