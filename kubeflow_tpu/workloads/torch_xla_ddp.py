"""PyTorchJob workload: DDP-style training from the operator-injected env
(MASTER_ADDR/MASTER_PORT/WORLD_SIZE/RANK — the same contract the reference's
pytorch-operator injects, kubeflow/pytorch-job/prototypes/pytorch-job.jsonnet).

On TPU VMs with torch_xla installed this runs the torch-xla SPMD path; on
CPU-only images (and CI) it falls back to torch.distributed gloo DDP, so the
PyTorchJob kind is exercised end to end either way.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from kubeflow_tpu.runtime import strip_glog_args


def _train_torch(args) -> dict:
    import torch
    import torch.nn as nn

    world = int(os.environ.get("WORLD_SIZE", "1"))
    rank = int(os.environ.get("RANK", "0"))
    distributed = world > 1
    if distributed:
        import torch.distributed as dist

        dist.init_process_group(
            backend="gloo", init_method="env://",
            world_size=world, rank=rank,
        )

    try:
        import torch_xla.core.xla_model as xm  # type: ignore

        device = xm.xla_device()
    except Exception:
        device = torch.device("cpu")

    torch.manual_seed(args.seed + rank)
    model = nn.Sequential(
        nn.Linear(args.dim, args.hidden), nn.ReLU(),
        nn.Linear(args.hidden, 10),
    ).to(device)
    if distributed:
        from torch.nn.parallel import DistributedDataParallel

        model = DistributedDataParallel(model)
    opt = torch.optim.AdamW(model.parameters(), lr=1e-3)
    loss_fn = nn.CrossEntropyLoss()

    loss = None
    for step in range(args.steps):
        x = torch.randn(args.batch_size, args.dim, device=device)
        y = torch.randint(0, 10, (args.batch_size,), device=device)
        opt.zero_grad()
        loss = loss_fn(model(x), y)
        loss.backward()  # DDP allreduces grads here
        opt.step()
        if (step + 1) % args.log_every == 0 and rank == 0:
            print(f"step={step + 1} loss={loss.item():.4f}")

    if distributed:
        import torch.distributed as dist

        dist.barrier()
        dist.destroy_process_group()
    return {"rank": rank, "world_size": world, "steps": args.steps,
            "loss": float(loss.item()) if loss is not None else None}


def main(argv=None) -> int:
    argv = strip_glog_args(list(sys.argv[1:] if argv is None else argv))
    p = argparse.ArgumentParser(description="PyTorchJob DDP workload")
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--dim", type=int, default=64)
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    result = _train_torch(args)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
