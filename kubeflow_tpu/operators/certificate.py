"""Cert-manager entrypoint: `python -m kubeflow_tpu.operators.certificate`
(the cert-manager controller Deployment analogue,
/root/reference/kubeflow/gcp/prototypes/cert-manager.jsonnet:1-12) —
runs ONLY the certificate-lifecycle controllers, matching the
per-controller RBAC the cert-manager prototype grants."""

from __future__ import annotations

from kubeflow_tpu.runtime import controller_main


def main(argv=None) -> int:
    from kubeflow_tpu.operators.certificates import (
        CertificateController,
        EndpointController,
        IssuerController,
    )

    return controller_main(
        argv,
        lambda client: [IssuerController(client),
                        CertificateController(client),
                        EndpointController(client)],
        "kubeflow-tpu certificate (issuer/certificate/endpoint) controller",
    )


if __name__ == "__main__":
    raise SystemExit(main())
