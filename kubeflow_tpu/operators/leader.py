"""Lease-based leader election for replicated controller managers.

The controller-runtime capability the training-operator Deployment's
``replicas`` param promises ("leader-elected"): N manager pods run, one
holds a ``coordination.k8s.io/v1`` Lease and reconciles; the rest stand by
and take over when renewal lapses. Same semantics as client-go's
leaderelection package (acquire if unheld or expired, renew at
``renew_seconds`` intervals, lease valid ``lease_seconds``), built on the
platform's own client so it runs against the fake apiserver in tests.
"""

from __future__ import annotations

import datetime
import logging
import threading
import uuid

from kubeflow_tpu.k8s.client import ApiError, K8sClient

log = logging.getLogger(__name__)

LEASE_API_VERSION = "coordination.k8s.io/v1"


def _now() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc)


def _parse(ts: str) -> datetime.datetime:
    return datetime.datetime.fromisoformat(ts.replace("Z", "+00:00"))


class LeaderElector:
    def __init__(self, client: K8sClient, *, name: str,
                 namespace: str = "kubeflow",
                 identity: str | None = None,
                 lease_seconds: float = 15.0,
                 renew_seconds: float = 5.0):
        self.client = client
        self.name = name
        self.namespace = namespace
        self.identity = identity or f"{name}-{uuid.uuid4().hex[:8]}"
        self.lease_seconds = lease_seconds
        self.renew_seconds = renew_seconds
        self._stop = threading.Event()
        self._is_leader = threading.Event()

    # ------------------------------------------------------------------

    def _lease_body(self) -> dict:
        return {
            "apiVersion": LEASE_API_VERSION,
            "kind": "Lease",
            "metadata": {"name": self.name, "namespace": self.namespace},
            "spec": {
                "holderIdentity": self.identity,
                "leaseDurationSeconds": int(self.lease_seconds),
                # metav1.MicroTime requires fractional seconds; isoformat()
                # drops them when microsecond == 0 (client-go uses
                # RFC3339Micro for exactly this reason).
                "renewTime": _now().strftime("%Y-%m-%dT%H:%M:%S.%fZ"),
            },
        }

    def try_acquire(self) -> bool:
        """One acquire-or-renew attempt. Returns current leadership."""
        try:
            lease = self.client.get_or_none(
                LEASE_API_VERSION, "Lease", self.name, self.namespace
            )
            if lease is None:
                self.client.create(self._lease_body())
                log.info("%s: acquired new lease as %s", self.name,
                         self.identity)
                self._is_leader.set()
                return True
            spec = lease.get("spec", {})
            holder = spec.get("holderIdentity")
            renew = spec.get("renewTime")
            expired = True
            if renew:
                age = (_now() - _parse(renew)).total_seconds()
                expired = age > spec.get("leaseDurationSeconds",
                                         self.lease_seconds)
            if holder == self.identity or expired:
                lease["spec"] = self._lease_body()["spec"]
                self.client.update(lease)  # CAS via resourceVersion
                if not self._is_leader.is_set():
                    log.info("%s: %s lease as %s", self.name,
                             "took over expired" if holder != self.identity
                             else "renewed", self.identity)
                self._is_leader.set()
                return True
            self._is_leader.clear()
            return False
        except ApiError as e:
            # 409 = lost the update race to another candidate.
            if e.code != 409:
                log.warning("%s: lease attempt failed: %s", self.name, e)
            self._is_leader.clear()
            return False

    @property
    def is_leader(self) -> bool:
        return self._is_leader.is_set()

    def wait_for_leadership(self, timeout: float | None = None) -> bool:
        """Block (acquiring in a loop) until this candidate leads.
        ``timeout=0`` makes a single non-blocking attempt."""
        import time

        end = time.monotonic() + timeout if timeout is not None else None
        while not self._stop.is_set():
            if self.try_acquire():
                return True
            if end and time.monotonic() > end:
                return False
            self._stop.wait(self.renew_seconds)
        return False

    def run(self) -> None:
        """Acquire-then-renew loop (daemon thread); leadership state is
        exposed via :attr:`is_leader`."""
        while not self._stop.is_set():
            self.try_acquire()
            self._stop.wait(self.renew_seconds)

    def start(self) -> threading.Thread:
        self._thread = threading.Thread(
            target=self.run, name=f"lease-{self.name}", daemon=True
        )
        self._thread.start()
        return self._thread

    def release(self) -> None:
        """Drop the lease on clean shutdown so a standby takes over fast.
        Stops and joins the renew thread FIRST: an in-flight renewal after
        the backdate would make the lease look freshly held by a dead
        process, and a renewal just before it would 409 the backdate."""
        self._stop.set()
        thread = getattr(self, "_thread", None)
        if thread is not None:
            thread.join(timeout=2 * self.renew_seconds)
        if not self._is_leader.is_set():
            return
        backdated = (_now() - datetime.timedelta(days=1)).strftime(
            "%Y-%m-%dT%H:%M:%S.%fZ"
        )
        for _attempt in range(3):  # retry lost-update races
            try:
                lease = self.client.get_or_none(
                    LEASE_API_VERSION, "Lease", self.name, self.namespace
                )
                if not lease or lease.get("spec", {}).get(
                    "holderIdentity"
                ) != self.identity:
                    break
                lease["spec"]["renewTime"] = backdated
                self.client.update(lease)
                break
            except ApiError as e:
                if e.code != 409:
                    break
        self._is_leader.clear()
