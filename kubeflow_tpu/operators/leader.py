"""Lease-based leader election for replicated controller managers.

The controller-runtime capability the training-operator Deployment's
``replicas`` param promises ("leader-elected"): N manager pods run, one
holds a ``coordination.k8s.io/v1`` Lease and reconciles; the rest stand by
and take over when renewal lapses. Same semantics as client-go's
leaderelection package (acquire if unheld or expired, renew at
``renew_seconds`` intervals, lease valid ``lease_seconds``), built on the
platform's own client so it runs against the fake apiserver in tests.
"""

from __future__ import annotations

import datetime
import logging
import math
import threading
import time
import uuid

from kubeflow_tpu.k8s.client import ApiError, K8sClient, retry_on_conflict

log = logging.getLogger(__name__)

LEASE_API_VERSION = "coordination.k8s.io/v1"


def _now() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc)


class LeaderElector:
    def __init__(self, client: K8sClient, *, name: str,
                 namespace: str = "kubeflow",
                 identity: str | None = None,
                 lease_seconds: float = 15.0,
                 renew_seconds: float = 5.0,
                 renew_deadline_seconds: float | None = None):
        self.client = client
        self.name = name
        self.namespace = namespace
        self.identity = identity or f"{name}-{uuid.uuid4().hex[:8]}"
        self.lease_seconds = lease_seconds
        self.renew_seconds = renew_seconds
        # How long a leader rides out transient renewal failures before
        # abdicating (client-go's renewDeadline, default 2/3 of the lease:
        # 10 s for the 15 s default). STRICTLY less than lease_seconds, so
        # a leader cut off from the apiserver stops reconciling before any
        # standby can possibly seize the lease — no two-leader window.
        self.renew_deadline_seconds = (
            renew_deadline_seconds if renew_deadline_seconds is not None
            else lease_seconds * 2.0 / 3.0
        )
        self._stop = threading.Event()
        self._is_leader = threading.Event()
        # Expiry is judged from locally *observed* (holder, renewTime)
        # transitions in monotonic time, never by comparing the remote
        # renewTime against the local wall clock — inter-node clock skew
        # larger than lease_seconds must not let a standby seize a healthy
        # leader's lease (client-go leaderelection semantics).
        self._observed_record: tuple | None = None
        self._observed_at: float | None = None
        self._last_renew: float | None = None  # monotonic, successful renews

    # ------------------------------------------------------------------

    def _lease_body(self) -> dict:
        return {
            "apiVersion": LEASE_API_VERSION,
            "kind": "Lease",
            "metadata": {"name": self.name, "namespace": self.namespace},
            "spec": {
                "holderIdentity": self.identity,
                # Lease durations are integer seconds in the K8s API; round
                # up so a fractional lease_seconds never truncates to 0.
                "leaseDurationSeconds": math.ceil(self.lease_seconds),
                # metav1.MicroTime requires fractional seconds; isoformat()
                # drops them when microsecond == 0 (client-go uses
                # RFC3339Micro for exactly this reason).
                "renewTime": _now().strftime("%Y-%m-%dT%H:%M:%S.%fZ"),
            },
        }

    def try_acquire(self) -> bool:
        """One acquire-or-renew attempt. Returns current leadership."""
        try:
            lease = self.client.get_or_none(
                LEASE_API_VERSION, "Lease", self.name, self.namespace
            )
            if lease is None:
                self.client.create(self._lease_body())
                log.info("%s: acquired new lease as %s", self.name,
                         self.identity)
                self._last_renew = time.monotonic()
                self._is_leader.set()
                return True
            spec = lease.get("spec", {})
            holder = spec.get("holderIdentity")
            renew = spec.get("renewTime")
            if not holder:
                # Voluntary release (release() clears holderIdentity) —
                # the lease is explicitly up for grabs.
                expired = True
            else:
                record = (holder, renew)
                if record != self._observed_record:
                    self._observed_record = record
                    self._observed_at = time.monotonic()
                if not renew:
                    expired = True
                else:
                    age = time.monotonic() - self._observed_at
                    expired = age > spec.get("leaseDurationSeconds",
                                             self.lease_seconds)
            if holder == self.identity or expired:
                lease["spec"] = self._lease_body()["spec"]
                self.client.update(lease)  # CAS via resourceVersion
                if not self._is_leader.is_set():
                    log.info("%s: %s lease as %s", self.name,
                             "took over expired" if holder != self.identity
                             else "renewed", self.identity)
                self._last_renew = time.monotonic()
                self._is_leader.set()
                return True
            self._is_leader.clear()
            return False
        except ApiError as e:
            if e.code == 409 and not self._is_leader.is_set():
                # Lost an acquire race to another candidate — definitive.
                return False
            log.warning("%s: lease attempt failed: %s", self.name, e)
            # A transient failure (apiserver 5xx, or a spurious conflict a
            # flaky proxy injected on our own renewal — the next attempt
            # refetches the lease and retries with a fresh resourceVersion)
            # must not demote a leader whose lease is still valid. But only
            # until the renew DEADLINE: abdicating strictly before the
            # lease expires guarantees a cut-off leader stops reconciling
            # before any standby can seize the lease (client-go
            # renewDeadline semantics — no two-leader window).
            if self._is_leader.is_set() and self._last_renew is not None:
                age = time.monotonic() - self._last_renew
                if age <= self.renew_deadline_seconds:
                    return True
            self._is_leader.clear()
            return False

    @property
    def is_leader(self) -> bool:
        return self._is_leader.is_set()

    def wait_for_leadership(self, timeout: float | None = None) -> bool:
        """Block (acquiring in a loop) until this candidate leads.
        ``timeout=0`` makes a single non-blocking attempt."""
        end = time.monotonic() + timeout if timeout is not None else None
        while not self._stop.is_set():
            if self.try_acquire():
                return True
            if end and time.monotonic() > end:
                return False
            self._stop.wait(self.renew_seconds)
        return False

    def run(self) -> None:
        """Acquire-then-renew loop (daemon thread); leadership state is
        exposed via :attr:`is_leader`."""
        while not self._stop.is_set():
            self.try_acquire()
            self._stop.wait(self.renew_seconds)

    def start(self) -> threading.Thread:
        self._thread = threading.Thread(
            target=self.run, name=f"lease-{self.name}", daemon=True
        )
        self._thread.start()
        return self._thread

    def release(self) -> None:
        """Drop the lease on clean shutdown so a standby takes over fast.
        Release is *explicit* — holderIdentity is cleared (client-go
        ReleaseOnCancel semantics), never inferred from timestamp
        regression, which an NTP step on the leader could mimic. Stops and
        joins the renew thread FIRST: an in-flight renewal after the clear
        would make the lease look freshly held by a dead process, and a
        renewal just before it would 409 the clear."""
        self._stop.set()
        thread = getattr(self, "_thread", None)
        if thread is not None:
            thread.join(timeout=2 * self.renew_seconds)
        if not self._is_leader.is_set():
            return

        def _clear(client: K8sClient) -> None:
            lease = client.get_or_none(
                LEASE_API_VERSION, "Lease", self.name, self.namespace
            )
            if not lease or lease.get("spec", {}).get(
                "holderIdentity"
            ) != self.identity:
                return
            lease["spec"]["holderIdentity"] = ""
            client.update(lease)

        try:
            retry_on_conflict(self.client, _clear, attempts=3)
        except ApiError:
            pass  # best effort — the lease will expire on its own
        self._is_leader.clear()
