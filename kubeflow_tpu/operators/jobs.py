"""Training-job controller.

One controller covers JaxJob plus the five compatibility kinds. Behavior
mirrors the reference operators' contract (CRD surface
kubeflow/tf-training/tf-job-operator.libsonnet:52-96; TF_CONFIG injection
consumed at tf-controller-examples/tf-cnn/launcher.py:69-81) with the
TPU-native rendezvous replacing TF gRPC/MPI wiring:

- **Gang creation**: every replica pod is created in one reconcile pass; TPU
  jobs get GKE TPU nodeSelectors (accelerator + topology) so the scheduler
  lands the gang on one slice, and multislice jobs are split into per-slice
  gangs wired over DCN via megascale env.
- **Stable DNS**: each pod gets hostname + subdomain under a per-job headless
  service — `{job}-{type}-{i}.{job}.{ns}` — the address fabric every
  framework's env points at.
- **Status**: conditions (Created/Running/Restarting/Succeeded/Failed) +
  per-replica-type counters, the printer-column contract E2E tests assert
  (testing/tf_job_simple_test.py:91).
- **Policies**: restartPolicy per replica (Never/OnFailure/ExitCode/Always),
  runPolicy.backoffLimit, activeDeadlineSeconds, cleanPodPolicy
  (Running/All/None), ttlSecondsAfterFinished.
"""

from __future__ import annotations

import copy
import datetime
import json

from kubeflow_tpu.apis import jobs as api
from kubeflow_tpu.apis import scheduling as sched_api
from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.k8s.client import ApiError
from kubeflow_tpu.operators.base import Controller

POD_API = "v1"
LABEL_JOB = "kubeflow-tpu.org/job-name"
LABEL_KIND = "kubeflow-tpu.org/job-kind"
LABEL_REPLICA_TYPE = "kubeflow-tpu.org/replica-type"
LABEL_REPLICA_INDEX = "kubeflow-tpu.org/replica-index"

GKE_TPU_ACCEL_SELECTOR = "cloud.google.com/gke-tpu-accelerator"
GKE_TPU_TOPO_SELECTOR = "cloud.google.com/gke-tpu-topology"

# Replica type whose completion defines job success, in priority order (the
# tf-operator convention: chief/master if present, else workers).
_COMPLETION_PRIORITY = ("Chief", "Master", "Launcher", "Scheduler", "Worker")


def _now() -> str:
    return (
        datetime.datetime.now(datetime.timezone.utc)
        .replace(microsecond=0)
        .isoformat()
        .replace("+00:00", "Z")
    )


def _parse_time(ts: str) -> datetime.datetime:
    return datetime.datetime.fromisoformat(ts.replace("Z", "+00:00"))


class JobController(Controller):
    api_version = api.JOBS_API_VERSION
    resync_seconds = 10.0

    def __init__(self, client, kind: str = api.JAX_JOB_KIND):
        super().__init__(client)
        self.kind = kind

    def watched_kinds(self):
        return [(POD_API, "Pod")]

    # ------------------------------------------------------------------
    # reconcile
    # ------------------------------------------------------------------

    def reconcile(self, job: dict) -> None:
        job = copy.deepcopy(job)
        status = job.setdefault("status", {})
        state = status.get("state")

        if state in ("Succeeded", "Failed"):
            return self._handle_finished(job)

        try:
            api.validate_job(job)
        except api.JobValidationError as e:
            self._finish(job, "Failed", "InvalidSpec", str(e))
            return

        if not status.get("startTime"):
            status["startTime"] = _now()
            self._set_condition(job, api.COND_CREATED, "JobCreated",
                                f"{self.kind} created")

        self._ensure_service(job)
        pods = self._ensure_pods(job)
        self._update_status(job, pods)

    # ------------------------------------------------------------------
    # children
    # ------------------------------------------------------------------

    def _ensure_service(self, job: dict) -> None:
        name = job["metadata"]["name"]
        ns = job["metadata"]["namespace"]
        if self.client.get_or_none(POD_API, "Service", name, ns):
            return
        svc = k8s.headless_service(
            name=name,
            namespace=ns,
            selector={LABEL_JOB: name},
            ports=[{"name": "coordinator",
                    "port": api.DEFAULT_COORDINATOR_PORT}],
            labels={LABEL_JOB: name, LABEL_KIND: self.kind},
        )
        svc["metadata"]["ownerReferences"] = [k8s.object_ref(job)]
        self.client.create(svc)

    def _pod_name(self, job_name: str, rt: str, index: int) -> str:
        return f"{job_name}-{rt.lower()}-{index}"

    def _list_pods(self, job: dict) -> list[dict]:
        return self.client.list(
            POD_API, "Pod", job["metadata"]["namespace"],
            label_selector={LABEL_JOB: job["metadata"]["name"]},
        )

    def _rspec_for_pod(self, job: dict, pod: dict) -> dict:
        rt_label = pod["metadata"]["labels"].get(LABEL_REPLICA_TYPE, "")
        return next(
            (rs for rt, rs in job["spec"]["replicaSpecs"].items()
             if rt.lower() == rt_label), {},
        )

    def _gang_restart_if_needed(self, job: dict, existing: dict) -> dict:
        """JaxJob restart is all-or-nothing: a lone restarted process cannot
        rejoin a completed jax.distributed.initialize rendezvous, so a
        retryable worker failure restarts the whole gang. Pods in phase
        Unknown (node unreachable — slice host reclaimed) count as failed:
        waiting for the kubelet to come back would hang the collective."""
        failed = [p for p in existing.values()
                  if p.get("status", {}).get("phase") in ("Failed",
                                                          "Unknown")]
        retryable = [
            self._should_restart(
                p, self._rspec_for_pod(job, p).get("restartPolicy",
                                                   "OnFailure"))
            for p in failed
        ]
        # A permanently-failed replica must surface as ReplicaFailed in
        # _update_status, not be swallowed by a gang recreate.
        if not failed or not all(retryable):
            return existing
        ns = job["metadata"]["namespace"]
        for pod_name in existing:
            self.client.delete_if_exists(POD_API, "Pod", pod_name, ns)
        preempted = all(self._is_preempted(p) for p in failed)
        self._bump_restarts(job, preempted=preempted)
        self._set_condition(
            job, api.COND_RESTARTING,
            "GangPreempted" if preempted else "GangRestarting",
            ("slice preempted; rescheduling the gang"
             if preempted else
             "worker failed; restarting the whole gang (collective "
             "rendezvous is all-or-nothing)"),
        )
        return {}

    def _ensure_pods(self, job: dict) -> list[dict]:
        """Create missing pods (gang: all in one pass); handle restarts."""
        name = job["metadata"]["name"]
        ns = job["metadata"]["namespace"]
        existing = {p["metadata"]["name"]: p for p in self._list_pods(job)}
        if self.kind == api.JAX_JOB_KIND:
            existing = self._gang_restart_if_needed(job, existing)
        desired = []
        for rt, rspec in job["spec"]["replicaSpecs"].items():
            for i in range(rspec.get("replicas", 1)):
                desired.append((rt, i, rspec))

        # Scheduler-managed jobs (spec.priority/queue) create NO pods until
        # the cluster scheduler has reserved a full slice for the gang —
        # the placement annotation IS the reservation, so a gang is either
        # fully creatable or fully parked (all-or-nothing admission).
        managed = sched_api.is_managed(job)
        decided = sched_api.placement(job) if managed else None
        if managed and decided is not None:
            nodes = decided.get("nodes", [])
            if sched_api.elastic_spec(job) is not None:
                # Elastic grant: pods sit on the PREFIX of the granted
                # hosts; the grant may exceed the pod count (the extra
                # hosts are accelerator width the training loop meshes
                # over). Only a grant too small to seat every process
                # parks the gang — a shrink/grow rewrite above the pod
                # count must NOT churn pods, that is the whole point.
                if len(nodes) < len(desired):
                    decided = None
            elif len(nodes) != len(desired):
                decided = None  # stale reservation (gang size changed)

        pods = []
        for idx, (rt, i, rspec) in enumerate(desired):
            pod_name = self._pod_name(name, rt, i)
            pod = existing.get(pod_name)
            if pod is not None:
                phase = pod.get("status", {}).get("phase", "Pending")
                restart = rspec.get("restartPolicy", "OnFailure")
                # JaxJob restarts only as a whole gang (handled above): a
                # solo-recreated worker can't rejoin the collective, and a
                # declined gang restart must not churn pods or restartCount.
                if (phase == "Failed" and self.kind != api.JAX_JOB_KIND
                        and self._should_restart(pod, restart)):
                    self.client.delete(POD_API, "Pod", pod_name, ns)
                    self._bump_restarts(job, preempted=self._is_preempted(pod))
                    self._set_condition(
                        job, api.COND_RESTARTING, "PodRestarting",
                        f"replica {rt}/{i} restarting",
                    )
                    pod = None
                else:
                    pods.append(pod)
                    continue
            if managed and decided is None:
                continue  # queued: awaiting (re-)admission
            pod = self._build_pod(job, rt, i, rspec,
                                  placement=decided,
                                  node=(decided["nodes"][idx]
                                        if decided else None))
            try:
                pods.append(self.client.create(pod))
            except ApiError as e:
                if e.code != 409:
                    raise
        return pods

    @staticmethod
    def _is_preempted(pod: dict) -> bool:
        """Node preemption/shutdown killed the pod — an infrastructure
        event, not a workload failure. Signals: the kubelet's graceful-
        shutdown reasons on pod status, or the DisruptionTarget condition
        the eviction API sets. The TPU-specific reality this handles: spot/
        reserved slice reclaims take whole hosts at once, and the gang must
        reschedule (resuming from checkpoint) rather than burn its
        backoffLimit (SURVEY §5.3 — the elastic behavior the reference
        lacks)."""
        status = pod.get("status", {})
        if status.get("reason") in ("Preempted", "Shutdown", "Terminated",
                                    "NodeShutdown"):
            return True
        if any(
            c.get("type") == "DisruptionTarget" and c.get("status") == "True"
            for c in status.get("conditions", [])
        ):
            return True
        # Scheduler-initiated eviction: the cluster scheduler marks each
        # victim pod BEFORE delivering the SIGTERM, so the accounting
        # (preemptionCount, backoffLimit untouched) is correct even when
        # the pod's final phase carries no kubelet reason string.
        return bool(pod.get("metadata", {}).get("annotations", {}).get(
            sched_api.ANN_PREEMPTED_BY))

    def _should_restart(self, pod: dict, restart_policy: str) -> bool:
        if self._is_preempted(pod):
            return True  # preemption is always retryable, any policy
        if restart_policy in ("Always", "OnFailure"):
            return True
        if restart_policy == "ExitCode":
            # Retryable iff the main container exited nonzero with a
            # retryable code (SIGKILL'd / infra codes 128+ retry; 1-127 are
            # permanent — the tf-operator ExitCode contract).
            for cs in pod.get("status", {}).get("containerStatuses", []):
                code = cs.get("state", {}).get("terminated", {}).get("exitCode")
                if code is not None:
                    return code > 127
            return True
        return False

    def _bump_restarts(self, job: dict, *, preempted: bool = False) -> None:
        # Preemptions are tracked separately and do not count against
        # runPolicy.backoffLimit — infrastructure churn must not fail jobs.
        key = "preemptionCount" if preempted else "restartCount"
        job["status"][key] = job["status"].get(key, 0) + 1

    # ------------------------------------------------------------------
    # pod construction + env injection
    # ------------------------------------------------------------------

    def _build_pod(self, job: dict, rt: str, index: int, rspec: dict,
                   placement: dict | None = None,
                   node: str | None = None) -> dict:
        name = job["metadata"]["name"]
        ns = job["metadata"]["namespace"]
        pod = copy.deepcopy(rspec["template"])
        pod.setdefault("apiVersion", POD_API)
        pod.setdefault("kind", "Pod")
        meta = pod.setdefault("metadata", {})
        meta["name"] = self._pod_name(name, rt, index)
        meta["namespace"] = ns
        labels = meta.setdefault("labels", {})
        labels.update({
            LABEL_JOB: name,
            LABEL_KIND: self.kind,
            LABEL_REPLICA_TYPE: rt.lower(),
            LABEL_REPLICA_INDEX: str(index),
        })
        meta["ownerReferences"] = [k8s.object_ref(job)]
        spec = pod.setdefault("spec", {})
        # Stable DNS via the job's headless service.
        spec["hostname"] = meta["name"]
        spec["subdomain"] = name
        spec.setdefault("restartPolicy", "Never")

        if placement is not None:
            # Cluster-scheduler decision: this pod is pinned to its
            # reserved host on the reserved slice — the scheduler's
            # placement replaces the bare GKE nodeSelector path.
            ann = meta.setdefault("annotations", {})
            ann[sched_api.ANN_POOL] = placement.get("pool", "")
            ann[sched_api.ANN_SLICE] = placement.get("slice", "")
            if node:
                spec["nodeName"] = node
            sel = spec.setdefault("nodeSelector", {})
            sel[GKE_TPU_ACCEL_SELECTOR] = placement.get("pool", "")
            if placement.get("topology"):
                sel[GKE_TPU_TOPO_SELECTOR] = placement["topology"]
        else:
            tpu = job["spec"].get("tpu", {})
            if tpu.get("accelerator"):
                sel = spec.setdefault("nodeSelector", {})
                sel[GKE_TPU_ACCEL_SELECTOR] = tpu["accelerator"]
                if tpu.get("topology"):
                    sel[GKE_TPU_TOPO_SELECTOR] = tpu["topology"]

        env = self._rendezvous_env(job, rt, index)
        for container in spec.get("containers", []):
            existing = {e["name"] for e in container.setdefault("env", [])}
            container["env"].extend(
                {"name": k, "value": str(v)}
                for k, v in env.items() if k not in existing
            )
        return pod

    def _host(self, job_name: str, ns: str, rt: str, index: int) -> str:
        return f"{self._pod_name(job_name, rt, index)}.{job_name}.{ns}"

    def _replica_hosts(self, job: dict, rt: str, port: int | None = None):
        name = job["metadata"]["name"]
        ns = job["metadata"]["namespace"]
        n = job["spec"]["replicaSpecs"].get(rt, {}).get("replicas", 0)
        suffix = f":{port}" if port else ""
        return [f"{self._host(name, ns, rt, i)}{suffix}" for i in range(n)]

    def _rendezvous_env(self, job: dict, rt: str, index: int) -> dict:
        """Per-framework cluster env — the TF_CONFIG analogue family."""
        port = api.DEFAULT_COORDINATOR_PORT
        name = job["metadata"]["name"]
        ns = job["metadata"]["namespace"]
        specs = job["spec"]["replicaSpecs"]
        kind = self.kind

        common = {
            api.ENV_JOB_NAME: name,
            api.ENV_JOB_NAMESPACE: ns,
            api.ENV_JOB_KIND: kind,
        }

        if kind == api.JAX_JOB_KIND:
            workers = self._replica_hosts(job, "Worker")
            tpu = job["spec"].get("tpu", {})
            num_slices = tpu.get("numSlices", 1)
            hosts_per_slice = max(len(workers) // max(num_slices, 1), 1)
            env = {
                api.ENV_COORDINATOR_ADDRESS:
                    f"{self._host(name, ns, 'Worker', 0)}:{port}",
                api.ENV_COORDINATOR_PORT: port,
                api.ENV_NUM_PROCESSES: len(workers),
                api.ENV_PROCESS_ID: index,
                api.ENV_TPU_WORKER_HOSTNAMES: ",".join(workers),
                "TPU_WORKER_ID": index % hosts_per_slice,
            }
            if tpu.get("accelerator"):
                env[api.ENV_TPU_ACCELERATOR] = tpu["accelerator"]
            if tpu.get("topology"):
                env[api.ENV_TPU_TOPOLOGY] = tpu["topology"]
            if num_slices > 1:
                env[api.ENV_NUM_SLICES] = num_slices
                env[api.ENV_SLICE_ID] = index // hosts_per_slice
                env["MEGASCALE_COORDINATOR_ADDRESS"] = (
                    f"{self._host(name, ns, 'Worker', 0)}"
                )
            return common | env

        if kind == api.TF_JOB_KIND:
            cluster = {
                t.lower(): self._replica_hosts(job, t, port)
                for t in ("Chief", "PS", "Worker", "Evaluator") if t in specs
            }
            return common | {"TF_CONFIG": json.dumps({
                "cluster": cluster,
                "task": {"type": rt.lower(), "index": index},
            })}

        if kind == api.PYTORCH_JOB_KIND:
            n_workers = specs.get("Worker", {}).get("replicas", 0)
            return common | {
                "MASTER_ADDR": self._host(name, ns, "Master", 0),
                "MASTER_PORT": port,
                "WORLD_SIZE": 1 + n_workers,
                "RANK": 0 if rt == "Master" else index + 1,
            }

        if kind == api.MXNET_JOB_KIND:
            return common | {
                "DMLC_PS_ROOT_URI": self._host(name, ns, "Scheduler", 0),
                "DMLC_PS_ROOT_PORT": port,
                "DMLC_ROLE": rt.lower(),
                "DMLC_NUM_SERVER": specs.get("Server", {}).get("replicas", 0),
                "DMLC_NUM_WORKER": specs.get("Worker", {}).get("replicas", 0),
                "DMLC_WORKER_ID" if rt == "Worker" else "DMLC_SERVER_ID": index,
            }

        if kind == api.CHAINER_JOB_KIND:
            workers = self._replica_hosts(job, "Worker")
            return common | {
                "CHAINERMN_MASTER_ADDR": self._host(name, ns, "Master", 0),
                "CHAINERMN_MASTER_PORT": port,
                "CHAINERMN_NUM_PROCESSES": 1 + len(workers),
                "CHAINERMN_PROCESS_ID": 0 if rt == "Master" else index + 1,
            }

        if kind == api.MPI_JOB_KIND:
            # kubectl-delivery analogue: hostfile content via env (the
            # launcher writes it to disk), one slot per worker.
            workers = self._replica_hosts(job, "Worker")
            return common | {
                "OMPI_MCA_orte_default_hostfile": "/etc/mpi/hostfile",
                "MPI_HOSTFILE_CONTENT": "\n".join(
                    f"{w} slots=1" for w in workers
                ),
                "OMPI_MCA_orte_keep_fqdn_hostnames": "true",
            }

        raise ValueError(f"unknown kind {kind}")

    # ------------------------------------------------------------------
    # status
    # ------------------------------------------------------------------

    def _completion_replica_type(self, job: dict) -> str:
        specs = job["spec"]["replicaSpecs"]
        for rt in _COMPLETION_PRIORITY:
            if rt in specs and specs[rt].get("replicas", 1) > 0:
                return rt
        return next(iter(specs))

    def _update_status(self, job: dict, pods: list[dict]) -> None:
        status = job["status"]
        counts: dict[str, dict[str, int]] = {}
        for pod in pods:
            rt = pod["metadata"]["labels"].get(LABEL_REPLICA_TYPE, "")
            phase = pod.get("status", {}).get("phase", "Pending")
            bucket = {"Running": "active", "Succeeded": "succeeded",
                      "Failed": "failed"}.get(phase, "pending")
            by_bucket = counts.setdefault(rt, {})
            by_bucket[bucket] = by_bucket.get(bucket, 0) + 1
        status["replicaStatuses"] = counts

        run_policy = job["spec"].get("runPolicy", {})

        # Deadline.
        deadline = run_policy.get("activeDeadlineSeconds")
        if deadline and status.get("startTime"):
            age = (
                datetime.datetime.now(datetime.timezone.utc)
                - _parse_time(status["startTime"])
            ).total_seconds()
            if age > deadline:
                self._finish(job, "Failed", "DeadlineExceeded",
                             f"job ran longer than {deadline}s")
                return

        # Failure: a permanently-failed pod, or restart budget exhausted.
        backoff = run_policy.get("backoffLimit")
        if backoff is not None and status.get("restartCount", 0) > backoff:
            self._finish(job, "Failed", "BackoffLimitExceeded",
                         f"restarts exceeded backoffLimit={backoff}")
            return
        for pod in pods:
            if pod.get("status", {}).get("phase") != "Failed":
                continue
            rspec = self._rspec_for_pod(job, pod)
            if not self._should_restart(
                pod, rspec.get("restartPolicy", "OnFailure")
            ):
                self._finish(
                    job, "Failed", "ReplicaFailed",
                    f"pod {pod['metadata']['name']} failed permanently",
                )
                return

        # Success: every pod of the completion replica type succeeded.
        crt = self._completion_replica_type(job).lower()
        want = job["spec"]["replicaSpecs"][
            self._completion_replica_type(job)
        ].get("replicas", 1)
        done = counts.get(crt, {}).get("succeeded", 0)
        if want and done >= want:
            self._finish(job, "Succeeded", "JobSucceeded",
                         f"all {crt} replicas succeeded")
            return

        if any(
            p.get("status", {}).get("phase") == "Running" for p in pods
        ) and status.get("state") != "Running":
            status["state"] = "Running"
            self._set_condition(job, api.COND_RUNNING, "JobRunning",
                                "replicas are running")
        self._push_status(job)

    def _finish(self, job: dict, state: str, reason: str, message: str) -> None:
        job["status"]["state"] = state
        job["status"]["completionTime"] = _now()
        cond = api.COND_SUCCEEDED if state == "Succeeded" else api.COND_FAILED
        self._set_condition(job, cond, reason, message)
        self._push_status(job)
        self._clean_pods(job)

    def _handle_finished(self, job: dict) -> float | None:
        ttl = job["spec"].get("runPolicy", {}).get("ttlSecondsAfterFinished")
        if ttl is None:
            return None
        done_at = job["status"].get("completionTime")
        if not done_at:
            return None
        age = (
            datetime.datetime.now(datetime.timezone.utc)
            - _parse_time(done_at)
        ).total_seconds()
        if age >= ttl:
            self.client.delete_if_exists(
                self.api_version, self.kind, job["metadata"]["name"],
                job["metadata"]["namespace"],
            )
            return None
        # Requeue-after: wake exactly when the TTL lapses instead of
        # burning resync passes until then.
        return max(ttl - age, 0.1)

    def _clean_pods(self, job: dict) -> None:
        policy = job["spec"].get("runPolicy", {}).get("cleanPodPolicy",
                                                      "Running")
        if policy == "None":
            return
        for pod in self._list_pods(job):
            phase = pod.get("status", {}).get("phase", "Pending")
            if policy == "All" or phase in ("Running", "Pending"):
                self.client.delete_if_exists(
                    POD_API, "Pod", pod["metadata"]["name"],
                    pod["metadata"]["namespace"],
                )

    # Status writes go through Controller._push_status (refetch-and-reapply
    # on conflict): a reconcile racing the watch-driven requeue must not
    # park the job until the next resync.

    # Condition types the cluster scheduler owns: the lifecycle flip
    # below must not clobber them (the scheduler sets/clears its own).
    _SCHEDULER_CONDITIONS = (sched_api.COND_QUEUED,
                             sched_api.COND_UNSCHEDULABLE)

    def _set_condition(self, job: dict, ctype: str, reason: str,
                       message: str) -> None:
        conds = job["status"].setdefault("conditions", [])
        for c in conds:
            if c["type"] in self._SCHEDULER_CONDITIONS:
                continue
            c["status"] = "False" if c["type"] != ctype else c["status"]
        existing = next((c for c in conds if c["type"] == ctype), None)
        if existing and existing["status"] == "True":
            return
        cond = api.Condition(
            type=ctype, status="True", reason=reason, message=message,
            last_transition_time=_now(),
        ).to_dict()
        if existing:
            conds[conds.index(existing)] = cond
        else:
            conds.append(cond)
        if ctype == api.COND_CREATED:
            job["status"].setdefault("state", "Created")
        elif ctype in (api.COND_RUNNING, api.COND_RESTARTING):
            job["status"]["state"] = ctype

def make_job_controllers(client) -> list[JobController]:
    return [JobController(client, kind) for kind in api.ALL_JOB_KINDS]
