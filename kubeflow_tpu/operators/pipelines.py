"""Workflow (pipeline DAG) and Application controllers.

WorkflowController is the argo workflow-controller analogue
(kubeflow/argo/argo.libsonnet:89-165) specialized to the platform's needs:
tasks create Kubernetes objects (training-job CRs, serving Deployments) in
dependency order, with completion read from the created object's own status
— no sidecar executors or artifact store, because on this platform jobs
already publish results through their status and checkpoints through storage.

ApplicationController is the sync-application metacontroller hook analogue
(kubeflow/application/application.libsonnet:14-60): it aggregates the
readiness of everything matching the Application's selector into one status.
"""

from __future__ import annotations

import copy

from kubeflow_tpu.apis import jobs as jobs_api
from kubeflow_tpu.apis.pipelines import (
    APPLICATION_KIND,
    PHASE_FAILED,
    PHASE_PENDING,
    PHASE_RUNNING,
    PHASE_SUCCEEDED,
    PIPELINES_API_VERSION,
    WORKFLOW_KIND,
    toposort_tasks,
)
from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.k8s.client import ApiError
from kubeflow_tpu.operators.base import Controller

LABEL_WORKFLOW = "kubeflow-tpu.org/workflow"
LABEL_TASK = "kubeflow-tpu.org/workflow-task"

_TERMINAL = (PHASE_SUCCEEDED, PHASE_FAILED)


def _resource_phase(obj: dict) -> tuple[str, str]:
    """(phase, message) of a task's created object, by kind:

    - platform job kinds + Workflow: their controllers write
      ``status.state`` / ``status.phase`` (Succeeded/Failed are terminal);
    - Deployment/StatefulSet: Succeeded when fully ready (a serving task is
      "done" when it's up — the argo resource-template convention);
    - Pod: phase verbatim;
    - anything else: Succeeded once it exists (create-and-forget).
    """
    kind = obj.get("kind", "")
    status = obj.get("status", {})
    if kind in jobs_api.ALL_JOB_KINDS or "state" in status:
        state = status.get("state", PHASE_RUNNING)
        if state in _TERMINAL:
            return state, status.get("message", "")
        return PHASE_RUNNING, f"state={state}"
    if kind == WORKFLOW_KIND:
        phase = status.get("phase", PHASE_RUNNING)
        return (phase if phase in _TERMINAL else PHASE_RUNNING), ""
    if kind in ("Deployment", "StatefulSet"):
        want = obj.get("spec", {}).get("replicas", 1)
        ready = status.get("readyReplicas", 0)
        if ready >= want:
            return PHASE_SUCCEEDED, f"{ready}/{want} ready"
        return PHASE_RUNNING, f"{ready}/{want} ready"
    if kind == "Pod":
        phase = status.get("phase", PHASE_PENDING)
        if phase in _TERMINAL:
            return phase, ""
        return PHASE_RUNNING, f"phase={phase}"
    return PHASE_SUCCEEDED, "created"


class WorkflowController(Controller):
    api_version = PIPELINES_API_VERSION
    kind = WORKFLOW_KIND
    resync_seconds = 5.0

    def watched_kinds(self):
        # Tasks may create any kind; job CRs and Deployments cover the
        # train→serve hot path for event-driven wakeups, the resync loop
        # covers the rest.
        return [
            *((jobs_api.JOBS_API_VERSION, kind)
              for kind in jobs_api.ALL_JOB_KINDS),
            ("apps/v1", "Deployment"),
        ]

    def reconcile(self, wf: dict) -> None:
        wf = copy.deepcopy(wf)
        before = copy.deepcopy(wf.get("status", {}))
        status = wf.setdefault("status", {})
        if status.get("phase") in _TERMINAL:
            return
        tasks = wf["spec"]["tasks"]
        try:
            toposort_tasks(tasks)
        except ValueError as e:
            status.update(phase=PHASE_FAILED, message=f"invalid DAG: {e}")
            self.client.update_status(wf)
            return

        status.setdefault("phase", PHASE_RUNNING)
        task_status = status.setdefault("tasks", {})
        for t in tasks:
            task_status.setdefault(
                t["name"], {"phase": PHASE_PENDING, "message": ""}
            )

        failed = [n for n, s in task_status.items()
                  if s["phase"] == PHASE_FAILED]
        for t in tasks:
            ts = task_status[t["name"]]
            if ts["phase"] in _TERMINAL:
                continue
            deps = t.get("dependencies", [])
            if any(task_status[d]["phase"] == PHASE_FAILED for d in deps):
                ts.update(phase=PHASE_FAILED, message="dependency failed")
                continue
            if not all(task_status[d]["phase"] == PHASE_SUCCEEDED
                       for d in deps):
                continue  # stays Pending
            if failed:
                continue  # stop launching new work once anything failed
            try:
                live = self._ensure_resource(wf, t)
            except ApiError as e:
                # Malformed task resource (bad kind, schema reject): fail
                # the task visibly instead of log-and-retry forever.
                if 400 <= e.code < 500 and e.code != 409:
                    ts.update(phase=PHASE_FAILED,
                              message=f"create failed: {e}")
                    continue
                raise
            phase, message = _resource_phase(live)
            ts.update(phase=phase, message=message,
                      resourceName=live["metadata"]["name"],
                      resourceKind=live.get("kind", ""))

        phases = [task_status[t["name"]]["phase"] for t in tasks]
        if any(p == PHASE_FAILED for p in phases):
            # Fail only once nothing is still in flight (running tasks get
            # to finish; nothing new starts).
            if all(p in (*_TERMINAL, PHASE_PENDING) for p in phases):
                status["phase"] = PHASE_FAILED
                status["message"] = "task failed: " + ", ".join(
                    n for n, s in task_status.items()
                    if s["phase"] == PHASE_FAILED
                )
        elif all(p == PHASE_SUCCEEDED for p in phases):
            status["phase"] = PHASE_SUCCEEDED
            status["message"] = f"{len(tasks)} tasks completed"
        # Only write on change: an unconditional PUT emits MODIFIED, which
        # requeues this object — a self-triggering hot loop under run().
        if status != before:
            self.client.update_status(wf)

    # ------------------------------------------------------------------

    def _ensure_resource(self, wf: dict, task: dict) -> dict:
        """Create the task's object if absent; return the live object."""
        ns = wf["metadata"]["namespace"]
        resource = copy.deepcopy(task["resource"])
        meta = resource.setdefault("metadata", {})
        meta.setdefault("name", f"{wf['metadata']['name']}-{task['name']}")
        meta.setdefault("namespace", ns)
        labels = meta.setdefault("labels", {})
        labels[LABEL_WORKFLOW] = wf["metadata"]["name"]
        labels[LABEL_TASK] = task["name"]
        meta["ownerReferences"] = [k8s.object_ref(wf)]
        live = self.client.get_or_none(
            resource.get("apiVersion", "v1"), resource.get("kind", ""),
            meta["name"], meta["namespace"],
        )
        if live is not None:
            return live
        try:
            return self.client.create(resource)
        except ApiError as e:
            if e.code == 409:  # lost a create race with ourselves
                return self.client.get(
                    resource.get("apiVersion", "v1"),
                    resource.get("kind", ""), meta["name"], meta["namespace"],
                )
            raise


class ApplicationController(Controller):
    api_version = PIPELINES_API_VERSION
    kind = APPLICATION_KIND
    resync_seconds = 15.0

    # Kinds aggregated when spec.componentKinds is not given — the resource
    # families the platform deploys (application.libsonnet computes this
    # from deployed component manifests; declaring it keeps the controller
    # list-scoped instead of cluster-scanning).
    DEFAULT_KINDS = (
        ("apps/v1", "Deployment"),
        ("apps/v1", "StatefulSet"),
        ("v1", "Service"),
        *((jobs_api.JOBS_API_VERSION, kind)
          for kind in jobs_api.ALL_JOB_KINDS),
    )

    def reconcile(self, app: dict) -> None:
        app = copy.deepcopy(app)
        ns = app["metadata"]["namespace"]
        spec = app.get("spec", {})
        selector = spec.get("selector", {}).get("matchLabels", {})
        kinds = [
            (f"{ck['group']}/v1" if ck.get("group") else "v1", ck["kind"])
            for ck in spec.get("componentKinds", [])
        ] or list(self.DEFAULT_KINDS)

        components, ready = [], 0
        for api_version, kind in kinds:
            try:
                objs = self.client.list(
                    api_version, kind, namespace=ns,
                    label_selector=selector or None,
                )
            except ApiError:
                continue  # kind not installed on this cluster
            for obj in objs:
                phase, _ = _resource_phase(obj)
                is_ready = phase == PHASE_SUCCEEDED
                ready += int(is_ready)
                components.append({
                    "kind": kind,
                    "name": obj["metadata"]["name"],
                    "status": "Ready" if is_ready else phase,
                })

        before = copy.deepcopy(app.get("status", {}))
        status = app.setdefault("status", {})
        status["components"] = components
        status["componentsReady"] = f"{ready}/{len(components)}"
        status["assemblyPhase"] = (
            PHASE_SUCCEEDED if components and ready == len(components)
            else PHASE_PENDING
        )
        if status != before:  # avoid the self-triggering MODIFIED loop
            self.client.update_status(app)
