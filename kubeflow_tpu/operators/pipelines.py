"""Workflow (pipeline DAG) and Application controllers.

WorkflowController is the argo workflow-controller analogue
(kubeflow/argo/argo.libsonnet:89-165) specialized to the platform's needs:
tasks create Kubernetes objects (training-job CRs, serving Deployments) in
dependency order, with completion read from the created object's own status
— no sidecar executors or artifact store, because on this platform jobs
already publish results through their status and checkpoints through storage.

ApplicationController is the sync-application metacontroller hook analogue
(kubeflow/application/application.libsonnet:14-60): it aggregates the
readiness of everything matching the Application's selector into one status.
"""

from __future__ import annotations

import copy
import datetime
import os

from kubeflow_tpu.apis import jobs as jobs_api
from kubeflow_tpu.artifacts import (
    ENV_DIR as ARTIFACT_ENV_DIR,
    ENV_ROOT as ARTIFACT_ENV_ROOT,
    ArtifactRef,
    ArtifactStore,
)
from kubeflow_tpu.apis.pipelines import (
    APPLICATION_KIND,
    PHASE_FAILED,
    PHASE_PENDING,
    PHASE_RUNNING,
    PHASE_SUCCEEDED,
    PIPELINES_API_VERSION,
    SCHEDULED_WORKFLOW_KIND,
    WORKFLOW_KIND,
    toposort_tasks,
)
from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.k8s.client import ApiError
from kubeflow_tpu.operators.base import Controller
from kubeflow_tpu.operators.runstore import RunStore, SCHEDULE_LABEL
from kubeflow_tpu.utils.cron import CronSchedule

LABEL_WORKFLOW = "kubeflow-tpu.org/workflow"
LABEL_TASK = "kubeflow-tpu.org/workflow-task"


def _utcnow() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc)


def _stamp(dt: datetime.datetime) -> str:
    return dt.strftime("%Y-%m-%dT%H:%M:%SZ")


def _parse_stamp(ts: str) -> datetime.datetime:
    return datetime.datetime.fromisoformat(
        ts.replace("Z", "+00:00")
    )

_TERMINAL = (PHASE_SUCCEEDED, PHASE_FAILED)


def _resource_phase(obj: dict) -> tuple[str, str]:
    """(phase, message) of a task's created object, by kind:

    - platform job kinds + Workflow: their controllers write
      ``status.state`` / ``status.phase`` (Succeeded/Failed are terminal);
    - Deployment/StatefulSet: Succeeded when fully ready (a serving task is
      "done" when it's up — the argo resource-template convention);
    - Pod: phase verbatim;
    - anything else: Succeeded once it exists (create-and-forget).
    """
    kind = obj.get("kind", "")
    status = obj.get("status", {})
    if kind in jobs_api.ALL_JOB_KINDS or "state" in status:
        state = status.get("state", PHASE_RUNNING)
        if state in _TERMINAL:
            return state, status.get("message", "")
        return PHASE_RUNNING, f"state={state}"
    if kind == WORKFLOW_KIND:
        phase = status.get("phase", PHASE_RUNNING)
        return (phase if phase in _TERMINAL else PHASE_RUNNING), ""
    if kind in ("Deployment", "StatefulSet"):
        want = obj.get("spec", {}).get("replicas", 1)
        ready = status.get("readyReplicas", 0)
        if ready >= want:
            return PHASE_SUCCEEDED, f"{ready}/{want} ready"
        return PHASE_RUNNING, f"{ready}/{want} ready"
    if kind == "Pod":
        phase = status.get("phase", PHASE_PENDING)
        if phase in _TERMINAL:
            return phase, ""
        return PHASE_RUNNING, f"phase={phase}"
    return PHASE_SUCCEEDED, "created"


class WorkflowController(Controller):
    api_version = PIPELINES_API_VERSION
    kind = WORKFLOW_KIND
    resync_seconds = 5.0
    # Run-record retention for Workflows with no owning schedule.
    adhoc_history_limit = 50

    def __init__(self, client, now_fn=None, artifact_root=None,
                 artifact_claim: str = "kubeflow-artifacts"):
        super().__init__(client)
        self.runs = RunStore(client)
        self.artifacts = ArtifactStore(artifact_root)
        # PVC backing the store: mounted into every task pod at the store
        # root (and into the operator itself by the pipeline-operator
        # manifest) so controller and tasks see one filesystem. Empty
        # disables volume injection (single-host test kubelets share the
        # host filesystem already).
        self.artifact_claim = artifact_claim
        self._now = now_fn or _utcnow

    def watched_kinds(self):
        # Tasks may create any kind; job CRs and Deployments cover the
        # train→serve hot path for event-driven wakeups, the resync loop
        # covers the rest.
        return [
            *((jobs_api.JOBS_API_VERSION, kind)
              for kind in jobs_api.ALL_JOB_KINDS),
            ("apps/v1", "Deployment"),
        ]

    def reconcile(self, wf: dict) -> None:
        wf = copy.deepcopy(wf)
        before = copy.deepcopy(wf.get("status", {}))
        status = wf.setdefault("status", {})
        if status.get("phase") in _TERMINAL:
            # Heal the durable record if the original write was lost.
            self.runs.ensure_recorded(wf)
            return
        tasks = wf["spec"]["tasks"]
        try:
            toposort_tasks(tasks)
        except ValueError as e:
            status.update(phase=PHASE_FAILED, message=f"invalid DAG: {e}")
            self._push_status(wf)
            return

        status.setdefault("phase", PHASE_RUNNING)
        status.setdefault("startedAt", _stamp(self._now()))
        task_status = status.setdefault("tasks", {})
        for t in tasks:
            task_status.setdefault(
                t["name"], {"phase": PHASE_PENDING, "message": ""}
            )

        failed = [n for n, s in task_status.items()
                  if s["phase"] == PHASE_FAILED]
        for t in tasks:
            ts = task_status[t["name"]]
            if ts["phase"] in _TERMINAL:
                continue
            deps = t.get("dependencies", [])
            if any(task_status[d]["phase"] == PHASE_FAILED for d in deps):
                ts.update(phase=PHASE_FAILED, message="dependency failed")
                continue
            if not all(task_status[d]["phase"] == PHASE_SUCCEEDED
                       for d in deps):
                continue  # stays Pending
            try:
                # Once something failed, stop LAUNCHING new work — but
                # keep observing what's already in flight, or running
                # tasks would never reach a terminal state and the
                # workflow would hang in Running.
                live = self._ensure_resource(wf, t,
                                             create=not failed)
            except ApiError as e:
                # Malformed task resource (bad kind, schema reject): fail
                # the task visibly instead of log-and-retry forever. A
                # transient 4xx (429 load-shedding, 408 timeout) is NOT a
                # rejection — re-raise so the workqueue retries it.
                if (400 <= e.code < 500 and e.code != 409
                        and not e.transient):
                    ts.update(phase=PHASE_FAILED,
                              message=f"create failed: {e}")
                    continue
                raise
            if live is None:
                continue  # not created (workflow already failing)
            phase, message = _resource_phase(live)
            if phase == PHASE_FAILED and self._schedule_retry(wf, t, ts,
                                                              live):
                continue
            if phase == PHASE_SUCCEEDED and t.get("outputs"):
                # Index declared outputs into the run record (the KFP
                # output-artifact contract): a missing declared output is
                # a task failure, not a silent absence.
                phase, message = self._index_outputs(wf, t, ts, message)
            ts.update(phase=phase, message=message,
                      resourceName=live["metadata"]["name"],
                      resourceKind=live.get("kind", ""))

        phases = [task_status[t["name"]]["phase"] for t in tasks]
        if any(p == PHASE_FAILED for p in phases):
            # Fail only once nothing is still in flight (running tasks get
            # to finish; nothing new starts).
            if all(p in (*_TERMINAL, PHASE_PENDING) for p in phases):
                status["phase"] = PHASE_FAILED
                status["message"] = "task failed: " + ", ".join(
                    n for n, s in task_status.items()
                    if s["phase"] == PHASE_FAILED
                )
        elif all(p == PHASE_SUCCEEDED for p in phases):
            status["phase"] = PHASE_SUCCEEDED
            status["message"] = f"{len(tasks)} tasks completed"
        if status["phase"] in _TERMINAL and "finishedAt" not in status:
            status["finishedAt"] = _stamp(self._now())
        # Only write on change: an unconditional PUT emits MODIFIED, which
        # requeues this object — a self-triggering hot loop under run().
        # _push_status refetches-and-reapplies on 409, so losing a write
        # race against another manager costs a round-trip, not a resync.
        if status != before:
            self._push_status(wf)
            # Durable run record (pipeline-persistenceagent role) —
            # mirrors every status transition and survives CR deletion.
            self.runs.record(wf)
            if (status["phase"] in _TERMINAL
                    and not wf["metadata"].get("labels", {}).get(
                        SCHEDULE_LABEL)):
                # Scheduled runs are pruned by their schedule's
                # historyLimit; ad-hoc runs get a default retention so
                # records can't accumulate without bound.
                self.runs.prune_adhoc(wf["metadata"]["namespace"],
                                      self.adhoc_history_limit)

    def _schedule_retry(self, wf: dict, task: dict, ts: dict,
                        live: dict) -> bool:
        """Per-task retry with exponential backoff (argo retryStrategy
        analogue): delete the failed resource once the backoff elapses so
        the next reconcile recreates it. Returns True while a retry is
        pending/armed (the task must not be marked Failed yet)."""
        retries = int(task.get("retries", 0))
        restarts = int(ts.get("restarts", 0))
        if restarts >= retries:
            return False
        now = self._now()
        next_at = ts.get("nextRetryAt")
        if not next_at:
            backoff = float(task.get("retryBackoffSeconds", 10.0))
            backoff *= 2 ** restarts
            ts.update(
                phase=PHASE_RUNNING,
                message=(f"failed; retry {restarts + 1}/{retries} in "
                         f"{backoff:.0f}s"),
                nextRetryAt=_stamp(
                    now + datetime.timedelta(seconds=backoff)
                ),
            )
            return True
        if now < _parse_stamp(next_at):
            return True  # backoff still running
        try:
            self.client.delete(
                live.get("apiVersion", "v1"), live.get("kind", ""),
                live["metadata"]["name"], live["metadata"]["namespace"],
            )
        except ApiError as e:
            if e.code != 404:
                raise
        ts.pop("nextRetryAt", None)
        ts.update(phase=PHASE_PENDING, restarts=restarts + 1,
                  message=f"retry {restarts + 1}/{retries} launching")
        return True

    def _index_outputs(self, wf: dict, task: dict, ts: dict,
                       message: str) -> tuple[str, str]:
        """Record the task's declared outputs as artifacts. Outputs whose
        ``path`` differs from ``name`` are copied into place under the
        artifact name. Returns the (phase, message) the task lands on."""
        ns = wf["metadata"]["namespace"]
        wf_name = wf["metadata"]["name"]
        task_dir = os.path.realpath(
            self.artifacts.task_dir(ns, wf_name, task["name"])
        )
        recorded, missing = [], []
        for out in task["outputs"]:
            path = out.get("path", out["name"])
            src = os.path.realpath(os.path.join(task_dir, path))
            # A declared path must stay inside the task's own artifact
            # directory — otherwise a Workflow author could exfiltrate
            # arbitrary controller-readable files into the store.
            if src != task_dir and not src.startswith(task_dir + os.sep):
                return (PHASE_FAILED,
                        f"output {out['name']!r} path escapes the "
                        "artifact directory")
            try:
                ref = ArtifactRef(ns, wf_name, task["name"], out["name"])
                if not os.path.exists(src):
                    missing.append(out["name"])
                    continue
                if path != out["name"]:
                    self.artifacts.put(ref, src)
                recorded.append(self.artifacts.describe(ref))
            except ValueError as e:  # separator/dot-segment in the name
                return PHASE_FAILED, f"invalid output: {e}"
        if missing:
            return (PHASE_FAILED,
                    f"declared output(s) missing: {', '.join(missing)}")
        ts["artifacts"] = recorded
        return PHASE_SUCCEEDED, message

    # ------------------------------------------------------------------

    def _inject_artifact_env(self, resource: dict, ns: str, wf_name: str,
                             task_name: str) -> None:
        """Give every container of a pod-bearing task resource the
        artifact-store contract: the env (root + this task's output dir)
        AND the backing PVC mounted at the store root — without the
        volume, controller and task pods would write to different
        filesystems on a real cluster."""
        env = [
            {"name": ARTIFACT_ENV_ROOT, "value": self.artifacts.root},
            {"name": ARTIFACT_ENV_DIR,
             "value": self.artifacts.task_dir(ns, wf_name, task_name)},
        ]
        kind = resource.get("kind", "")
        pod_specs = []
        if kind == "Pod":
            pod_specs = [resource.get("spec", {})]
        elif "template" in resource.get("spec", {}):  # Job, Deployment, …
            pod_specs = [resource["spec"]["template"].get("spec", {})]
        elif "replicaSpecs" in resource.get("spec", {}):  # platform jobs
            pod_specs = [
                rs.get("template", {}).get("spec", {})
                for rs in resource["spec"]["replicaSpecs"].values()
            ]
        volume = {"name": "kubeflow-artifacts",
                  "persistentVolumeClaim":
                      {"claimName": self.artifact_claim}}
        mount = {"name": "kubeflow-artifacts",
                 "mountPath": self.artifacts.root}
        for spec in pod_specs:
            for container in spec.get("containers", []):
                have = {e.get("name") for e in container.get("env", [])}
                container.setdefault("env", []).extend(
                    e for e in env if e["name"] not in have
                )
                if self.artifact_claim and not any(
                        m.get("name") == mount["name"]
                        for m in container.get("volumeMounts", [])):
                    container.setdefault("volumeMounts", []).append(
                        dict(mount))
            if self.artifact_claim and not any(
                    v.get("name") == volume["name"]
                    for v in spec.get("volumes", [])):
                spec.setdefault("volumes", []).append(dict(volume))

    def _ensure_resource(self, wf: dict, task: dict,
                         create: bool = True) -> dict | None:
        """Create the task's object if absent; return the live object.
        ``create=False`` observes only (None when nothing exists)."""
        ns = wf["metadata"]["namespace"]
        resource = copy.deepcopy(task["resource"])
        meta = resource.setdefault("metadata", {})
        meta.setdefault("name", f"{wf['metadata']['name']}-{task['name']}")
        meta.setdefault("namespace", ns)
        labels = meta.setdefault("labels", {})
        labels[LABEL_WORKFLOW] = wf["metadata"]["name"]
        labels[LABEL_TASK] = task["name"]
        meta["ownerReferences"] = [k8s.object_ref(wf)]
        self._inject_artifact_env(resource, ns, wf["metadata"]["name"],
                                  task["name"])
        live = self.client.get_or_none(
            resource.get("apiVersion", "v1"), resource.get("kind", ""),
            meta["name"], meta["namespace"],
        )
        if live is not None or not create:
            return live
        try:
            return self.client.create(resource)
        except ApiError as e:
            if e.code == 409:  # lost a create race with ourselves
                return self.client.get(
                    resource.get("apiVersion", "v1"),
                    resource.get("kind", ""), meta["name"], meta["namespace"],
                )
            raise


class ScheduledWorkflowController(Controller):
    """Cron-triggered Workflow stamping — the pipeline-scheduledworkflow
    controller analogue (/root/reference/kubeflow/pipeline/
    pipeline-scheduledworkflow.libsonnet:1-60). Each fire time creates one
    Workflow from ``spec.workflowSpec`` (skipped, not queued, while
    ``maxConcurrency`` runs are in flight); completed stamped Workflows
    and their run records are pruned to ``spec.historyLimit``."""

    api_version = PIPELINES_API_VERSION
    kind = SCHEDULED_WORKFLOW_KIND
    resync_seconds = 5.0

    def __init__(self, client, now_fn=None):
        super().__init__(client)
        self.runs = RunStore(client)
        self._now = now_fn or _utcnow

    def reconcile(self, swf: dict) -> None:
        swf = copy.deepcopy(swf)
        before = copy.deepcopy(swf.get("status", {}))
        status = swf.setdefault("status", {})
        spec = swf["spec"]
        name = swf["metadata"]["name"]
        ns = swf["metadata"]["namespace"]

        try:
            schedule = CronSchedule.parse(spec["schedule"])
        except ValueError as e:
            status.update(conditions="Invalid", message=str(e))
            if status != before:
                self._push_status(swf)
            return
        if status.get("conditions") == "Invalid":
            # The schedule was fixed; clear the stale condition.
            status.pop("conditions", None)
            status.pop("message", None)

        # One stamped-Workflows LIST per reconcile, shared by the
        # concurrency check and history pruning.
        stamped = self._stamped(name, ns)
        if not spec.get("suspend"):
            self._fire_if_due(swf, schedule, status, stamped)

        limit = int(spec.get("historyLimit", 10))
        if limit:
            self._prune_history(name, ns, limit, stamped)
        if status != before:
            self._push_status(swf)

    # ------------------------------------------------------------------

    def _stamped(self, name: str, ns: str) -> list[dict]:
        return self.client.list(
            PIPELINES_API_VERSION, WORKFLOW_KIND, ns,
            label_selector={SCHEDULE_LABEL: name},
        )

    def _fire_if_due(self, swf: dict, schedule: CronSchedule,
                     status: dict, stamped: list[dict]) -> None:
        name = swf["metadata"]["name"]
        ns = swf["metadata"]["namespace"]
        now = self._now()
        last_s = status.get("lastScheduleTime")
        if last_s:
            # Strictly after the last consumed fire time.
            due = schedule.next_fire(_parse_stamp(last_s))
        else:
            # First fire: eligibility starts when THIS controller first
            # observed the schedule (recorded in status, measured on our
            # own clock — apiserver clock skew can neither suspend the
            # schedule nor backfill pre-observation fires). The anchor
            # minute itself is eligible.
            anchor_s = status.setdefault("observedTime", _stamp(now))
            start = _parse_stamp(anchor_s).replace(second=0,
                                                   microsecond=0)
            due = (start if schedule.matches(start)
                   else schedule.next_fire(start))
        status["nextScheduleTime"] = _stamp(schedule.next_fire(now))
        if due > now:
            return
        # Consume every elapsed fire time and stamp once for the latest —
        # a controller outage must not replay each missed fire (CronJob
        # catch-up semantics with an implicit deadline of one interval).
        while True:
            nxt = schedule.next_fire(due)
            if nxt > now:
                break
            due = nxt
        active = [
            wf for wf in stamped
            if wf.get("status", {}).get("phase") not in _TERMINAL
        ]
        # One fire per reconcile; the time is consumed either way —
        # at-capacity fires are skipped, not queued.
        status["lastScheduleTime"] = _stamp(due)
        if len(active) >= int(swf["spec"].get("maxConcurrency", 1)):
            status["runsSkipped"] = int(status.get("runsSkipped", 0)) + 1
            status["message"] = (
                f"fire at {_stamp(due)} skipped: {len(active)} runs active"
            )
            return
        run_name = f"{name}-{due.strftime('%Y%m%d%H%M')}"
        wf = {
            "apiVersion": PIPELINES_API_VERSION,
            "kind": WORKFLOW_KIND,
            "metadata": {
                "name": run_name,
                "namespace": ns,
                "labels": {SCHEDULE_LABEL: name},
                "ownerReferences": [k8s.object_ref(swf)],
            },
            "spec": copy.deepcopy(swf["spec"]["workflowSpec"]),
        }
        try:
            self.client.create(wf)
        except ApiError as e:
            if e.code != 409:  # already stamped for this fire time
                raise
        status["runsStarted"] = int(status.get("runsStarted", 0)) + 1
        status["message"] = f"started {run_name}"

    def _prune_history(self, name: str, ns: str, limit: int,
                       stamped: list[dict]) -> None:
        done = sorted(
            (wf for wf in stamped
             if wf.get("status", {}).get("phase") in _TERMINAL),
            key=lambda wf: wf.get("status", {}).get("startedAt", ""),
            reverse=True,
        )
        removed = 0
        for wf in done[limit:]:
            try:
                self.client.delete(PIPELINES_API_VERSION, WORKFLOW_KIND,
                                   wf["metadata"]["name"], ns)
                removed += 1
            except ApiError:
                pass
        # Records track stamped Workflows 1:1 — only touch the ConfigMap
        # store when something was actually deleted, not every resync.
        if removed:
            self.runs.prune(ns, name, limit)


class ApplicationController(Controller):
    api_version = PIPELINES_API_VERSION
    kind = APPLICATION_KIND
    resync_seconds = 15.0

    # Kinds aggregated when spec.componentKinds is not given — the resource
    # families the platform deploys (application.libsonnet computes this
    # from deployed component manifests; declaring it keeps the controller
    # list-scoped instead of cluster-scanning).
    DEFAULT_KINDS = (
        ("apps/v1", "Deployment"),
        ("apps/v1", "StatefulSet"),
        ("v1", "Service"),
        *((jobs_api.JOBS_API_VERSION, kind)
          for kind in jobs_api.ALL_JOB_KINDS),
    )

    def reconcile(self, app: dict) -> None:
        app = copy.deepcopy(app)
        ns = app["metadata"]["namespace"]
        spec = app.get("spec", {})
        selector = spec.get("selector", {}).get("matchLabels", {})
        kinds = [
            (f"{ck['group']}/v1" if ck.get("group") else "v1", ck["kind"])
            for ck in spec.get("componentKinds", [])
        ] or list(self.DEFAULT_KINDS)

        components, ready = [], 0
        for api_version, kind in kinds:
            try:
                objs = self.client.list(
                    api_version, kind, namespace=ns,
                    label_selector=selector or None,
                )
            except ApiError:
                continue  # kind not installed on this cluster
            for obj in objs:
                phase, _ = _resource_phase(obj)
                is_ready = phase == PHASE_SUCCEEDED
                ready += int(is_ready)
                components.append({
                    "kind": kind,
                    "name": obj["metadata"]["name"],
                    "status": "Ready" if is_ready else phase,
                })

        before = copy.deepcopy(app.get("status", {}))
        status = app.setdefault("status", {})
        status["components"] = components
        status["componentsReady"] = f"{ready}/{len(components)}"
        status["assemblyPhase"] = (
            PHASE_SUCCEEDED if components and ready == len(components)
            else PHASE_PENDING
        )
        if status != before:  # avoid the self-triggering MODIFIED loop
            self._push_status(app)
