"""Persisted workflow run history.

The pipeline-persistenceagent + backing-store role
(/root/reference/kubeflow/pipeline/pipeline-persistenceagent.libsonnet,
minio.libsonnet, mysql.libsonnet): every Workflow run leaves a durable
record that outlives the Workflow CR itself. TPU-platform recast: records
are ConfigMaps (the cluster's own durable KV store — no MySQL/minio
deployment to operate) labeled for listing, deliberately *not*
owner-referenced to the Workflow so deleting the CR keeps its history.
"""

from __future__ import annotations

import json

from kubeflow_tpu.k8s.client import ApiError, K8sClient

RUN_LABEL = "kubeflow-tpu.org/workflow-run"
SCHEDULE_LABEL = "kubeflow-tpu.org/scheduled-workflow"


class RunStore:
    def __init__(self, client: K8sClient):
        self.client = client

    @staticmethod
    def _record_name(workflow_name: str) -> str:
        return f"wfrun-{workflow_name}"

    def record(self, wf: dict) -> None:
        """Create or update the run record mirroring the workflow's
        current status. Called by the WorkflowController on start and on
        every status change through terminal."""
        meta = wf["metadata"]
        status = wf.get("status", {})
        record = {
            "workflow": meta["name"],
            "namespace": meta["namespace"],
            "scheduledWorkflow": meta.get("labels", {}).get(
                SCHEDULE_LABEL, ""
            ),
            "phase": status.get("phase", "Pending"),
            "message": status.get("message", ""),
            "startedAt": status.get("startedAt", ""),
            "finishedAt": status.get("finishedAt", ""),
            "tasks": status.get("tasks", {}),
            # Flattened output-artifact index (the minio/KFP artifact
            # listing): URIs stay resolvable through the artifact store
            # after the Workflow CR is deleted.
            "artifacts": [
                art
                for ts in status.get("tasks", {}).values()
                for art in ts.get("artifacts", [])
            ],
        }
        cm = {
            "apiVersion": "v1",
            "kind": "ConfigMap",
            "metadata": {
                "name": self._record_name(meta["name"]),
                "namespace": meta["namespace"],
                "labels": {
                    RUN_LABEL: "true",
                    **({SCHEDULE_LABEL: record["scheduledWorkflow"]}
                       if record["scheduledWorkflow"] else {}),
                },
            },
            "data": {"record.json": json.dumps(record, sort_keys=True)},
        }
        try:
            self.client.create(cm)
        except ApiError as e:
            if e.code != 409:
                raise
            live = self.client.get("v1", "ConfigMap",
                                   cm["metadata"]["name"],
                                   meta["namespace"])
            live["data"] = cm["data"]
            live["metadata"].setdefault("labels", {}).update(
                cm["metadata"]["labels"]
            )
            self.client.update(live)

    def ensure_recorded(self, wf: dict) -> None:
        """Heal a lost/stale record for a (terminal) workflow: a transient
        apiserver error during the original record() must not permanently
        lose the run's final state."""
        meta = wf["metadata"]
        phase = wf.get("status", {}).get("phase", "")
        cm = self.client.get_or_none(
            "v1", "ConfigMap", self._record_name(meta["name"]),
            meta["namespace"],
        )
        if cm is not None:
            try:
                if json.loads(cm["data"]["record.json"])["phase"] == phase:
                    return
            except (KeyError, ValueError):
                pass
        self.record(wf)

    def list_runs(self, namespace: str | None = None,
                  schedule: str | None = None) -> list[dict]:
        """Run records, newest-started first."""
        selector = {RUN_LABEL: "true"}
        if schedule:
            selector[SCHEDULE_LABEL] = schedule
        runs = []
        for cm in self.client.list("v1", "ConfigMap", namespace,
                                   label_selector=selector):
            try:
                runs.append(json.loads(cm["data"]["record.json"]))
            except (KeyError, ValueError):
                continue
        runs.sort(key=lambda r: r.get("startedAt", ""), reverse=True)
        return runs

    def prune(self, namespace: str, schedule: str, keep: int) -> int:
        """Keep the newest ``keep`` records for a schedule; delete the
        rest. Returns how many were removed."""
        if keep <= 0:
            return 0
        runs = self.list_runs(namespace, schedule=schedule)
        return self._delete_records(namespace, runs[keep:])

    def prune_adhoc(self, namespace: str, keep: int) -> int:
        """Retention for runs with no owning schedule — ad-hoc Workflows
        (CI one-offs) must not leak one ConfigMap per run forever."""
        if keep <= 0:
            return 0
        adhoc = [r for r in self.list_runs(namespace)
                 if not r.get("scheduledWorkflow")]
        return self._delete_records(namespace, adhoc[keep:])

    def _delete_records(self, namespace: str, runs: list[dict]) -> int:
        removed = 0
        for run in runs:
            try:
                self.client.delete(
                    "v1", "ConfigMap",
                    self._record_name(run["workflow"]), namespace,
                )
                removed += 1
            except ApiError:
                pass
        return removed
