"""Training-operator manager: `python -m kubeflow_tpu.operators`.

The binary the training-operator Deployment runs (the
`/opt/kubeflow/tf-operator.v1beta2` analogue,
kubeflow/tf-training/tf-job-operator.libsonnet:99-143). Runs the job
controllers for all six kinds plus the notebook/profile/study/benchmark
controllers in one manager process, watching the in-cluster apiserver.
"""

from __future__ import annotations

from kubeflow_tpu.runtime import controller_main


def make_all_controllers(client):
    from kubeflow_tpu.benchmark.controller import BenchmarkJobController
    from kubeflow_tpu.operators.certificates import (
        CertificateController,
        EndpointController,
        IssuerController,
    )
    from kubeflow_tpu.operators.experiment import ExperimentController
    from kubeflow_tpu.operators.inference import InferenceServiceController
    from kubeflow_tpu.operators.jobs import make_job_controllers
    from kubeflow_tpu.operators.notebooks import NotebookController
    from kubeflow_tpu.operators.pipelines import (
        ApplicationController,
        ScheduledWorkflowController,
        WorkflowController,
    )
    from kubeflow_tpu.operators.profiles import ProfileController
    from kubeflow_tpu.operators.rl import RLJobController
    from kubeflow_tpu.operators.rollout import RolloutController
    from kubeflow_tpu.scheduler.controller import SchedulerController
    from kubeflow_tpu.tuning.controller import StudyJobController

    return [
        *make_job_controllers(client),
        SchedulerController(client),
        InferenceServiceController(client),
        RolloutController(client),
        RLJobController(client),
        NotebookController(client),
        ProfileController(client),
        StudyJobController(client),
        ExperimentController(client),
        BenchmarkJobController(client),
        WorkflowController(client),
        ScheduledWorkflowController(client),
        ApplicationController(client),
        IssuerController(client),
        CertificateController(client),
        EndpointController(client),
    ]


def main(argv=None) -> int:
    return controller_main(
        argv, make_all_controllers,
        "kubeflow-tpu training-operator manager (all controllers)",
    )


if __name__ == "__main__":
    raise SystemExit(main())
