"""RolloutController: SLO-gated canary rollouts over the weight-push path.

The reference platform ships model upgrades as tf-serving version
policies behind ambassador's weighted routing — a new version gets a
slice of traffic, dashboards get watched, a human flips the weight. This
controller is that loop closed and made safe: an InferenceService whose
``spec.versions`` declares a second version is canaried by **pushing**
the candidate's weights into a named replica subset via
``DecoderFleet.broadcast_weights(version=..., members=[...])`` — no new
pods, the swap is the PR-15 zero-drain epoch install, ~1ms — and then
walking the candidate's traffic share 1% → 10% → 50% → 100%, each step
gated on the candidate cohort's TTFT/inter-token p99 and error rate
(scraped through the same ``scrape_signals`` exposition path the
autoscaler reads) staying within a configured ratio of the incumbent
cohort's.

Division of labor: this controller owns ``status.rollout`` (phase, step,
canary membership, epochs, breach evidence) and the weight pushes; the
InferenceServiceController stays the single writer of the router
Service annotation and *renders* ``status.rollout`` into the gateway's
hash-split route. Neither writes the other's surface, so the two
reconcile loops never fight.

The state machine is deliberately storage-less: everything a fresh
controller needs mid-walk is in the CR status plus the fleet's
``weights_versions()`` — an operator restart re-reads both and
continues the walk (or re-converges a half-landed rollback) without a
step of history.

Rollback is just a push: the incumbent's params go out at a FRESH
monotonic epoch (re-pushing the old epoch number would be refused by
canary replicas already holding the higher candidate epoch — stale
pushes are idempotent no-ops by design). A rollback racing a concurrent
``broadcast_weights`` therefore converges like any other epoch race:
the reconcile loop re-pushes at latest+1 until ``weights_versions()``
reports one uniform epoch across the live fleet.
"""

from __future__ import annotations

import logging
import math
import time

from kubeflow_tpu.apis.inference import (
    DEFAULT_AUTOSCALE,
    DEFAULT_ROLLOUT,
    INFERENCE_API_VERSION,
    INFERENCE_KIND,
    validate_versions,
)
from kubeflow_tpu.k8s.client import retry_on_conflict
from kubeflow_tpu.operators.base import Controller
from kubeflow_tpu.operators.inference import (
    REST_PORT,
    SignalCache,
    _http_fetch_signals,
)

log = logging.getLogger(__name__)

# Rollout phases. Shadow and Walking are live (the gateway splits /
# mirrors); Promoted, RolledBack, and Invalid are terminal for the
# current candidate — a new candidate (spec change) starts a new walk.
LIVE_PHASES = ("Shadow", "Walking")

# Latency floor for the incumbent baseline: a cold incumbent cohort
# whose p99 reads 0.0 must not make every candidate ratio infinite.
_BASELINE_FLOOR_S = 1e-3

# In-process fleet registry: the serving runtime (bench, tests, an
# embedded deployment) registers the DecoderFleet that backs an
# InferenceService so the controller can push weights into it.
_FLEETS: dict[tuple[str, str], object] = {}


def register_fleet(namespace: str, name: str, fleet) -> None:
    _FLEETS[(namespace, name)] = fleet


def unregister_fleet(namespace: str, name: str) -> None:
    _FLEETS.pop((namespace, name), None)


def _registry_fleet(namespace: str, name: str):
    return _FLEETS.get((namespace, name))


class RolloutController(Controller):
    """spec.versions → canary walk → Promoted | RolledBack.

    Injectables (tests and the bench drive all four):

    - ``fleet_for(ns, name)`` → the fleet handle (default: the
      in-process registry);
    - ``weights_for(ref)`` → a param pytree for a ``weightsRef``
      (default: None — without a resolver the controller parks the
      rollout in Pending rather than guessing);
    - ``fetch_metrics(addr)`` → signal dict | None (default: the HTTP
      exposition scrape), staleness-cached like the autoscaler's;
    - ``clock`` → monotonic seconds.
    """

    api_version = INFERENCE_API_VERSION
    kind = INFERENCE_KIND

    def __init__(self, client, *, fleet_for=None, weights_for=None,
                 fetch_metrics=None, clock=time.monotonic):
        super().__init__(client)
        self.fleet_for = fleet_for or _registry_fleet
        self.weights_for = weights_for or (lambda ref: None)
        self.fetch_metrics = fetch_metrics or _http_fetch_signals
        self.clock = clock
        self.signal_cache = SignalCache(
            lambda addr: self.fetch_metrics(addr), clock)

    # -- reconcile ----------------------------------------------------

    def reconcile(self, svc: dict) -> float | None:
        spec = svc.get("spec", {})
        versions = spec.get("versions")
        if not versions or len(versions) < 2:
            return None  # single-version service: nothing to roll out
        try:
            versions = validate_versions(versions)
            if spec.get("roles"):
                raise ValueError("spec.versions is not supported on a "
                                 "role-split service")
        except ValueError as e:
            self._set_rollout(svc, {"phase": "Invalid",
                                    "reason": str(e)})
            return None
        cfg = {**DEFAULT_ROLLOUT, **(spec.get("rollout") or {})}
        auto = {**DEFAULT_AUTOSCALE, **(spec.get("autoscale") or {})}
        incumbent, candidate = versions[0], versions[-1]

        ns = svc["metadata"]["namespace"]
        name = svc["metadata"]["name"]
        ro = dict((svc.get("status") or {}).get("rollout") or {})
        if (ro.get("candidate", {}).get("name") != candidate["name"]
                or ro.get("candidate", {}).get("weightsRef")
                != candidate["weightsRef"]
                or ro.get("incumbent", {}).get("name")
                != incumbent["name"]):
            ro = {}  # a different candidate: a new rollout starts

        fleet = self.fleet_for(ns, name)
        if fleet is None:
            self._set_rollout(svc, {"phase": "Pending",
                                    "reason": "no fleet handle",
                                    "candidate": dict(candidate),
                                    "incumbent": dict(incumbent)})
            return float(auto["scrapePeriodSeconds"])

        phase = ro.get("phase")
        if phase in ("Promoted", "RolledBack"):
            # Terminal for this candidate — but a half-landed final
            # push (rollback racing a concurrent broadcast, operator
            # killed mid-fan-out) may have left the fleet on mixed
            # epochs: keep converging until one uniform version.
            which = candidate if phase == "Promoted" else incumbent
            target = float(candidate["traffic"])
            if phase == "Promoted" and target < 100.0:
                return None  # steady-state A/B split: mixed on purpose
            if self._converged(fleet):
                return None
            params = self.weights_for(which["weightsRef"])
            if params is None:
                return None
            res = fleet.broadcast_weights(params)
            ro[("promotedEpoch" if phase == "Promoted"
                else "rolledBackEpoch")] = res["version"]
            self._set_rollout(svc, ro)
            return float(auto["scrapePeriodSeconds"])
        if phase == "Invalid":
            return None

        params = self.weights_for(candidate["weightsRef"])
        if params is None:
            self._set_rollout(svc, {"phase": "Pending",
                                    "reason": "weightsRef "
                                    f"{candidate['weightsRef']!r} "
                                    "unresolvable",
                                    "candidate": dict(candidate),
                                    "incumbent": dict(incumbent)})
            return float(auto["scrapePeriodSeconds"])

        steps = self._walk_steps(cfg, float(candidate["traffic"]))
        now = self.clock()
        if phase not in LIVE_PHASES:
            # Start: anchor the incumbent at whatever the fleet serves
            # NOW, claim the next epoch for the candidate.
            wv = fleet.weights_versions()
            ro = {
                "phase": "Shadow",
                "step": -1,
                "trafficPercent": 0.0,
                "shadowFraction": float(cfg["shadowFraction"]),
                "steps": steps,
                "candidate": {**candidate, "epoch": wv["latest"] + 1},
                "incumbent": {**incumbent, "epoch": wv["latest"]},
                "canaryMembers": [],
                "phaseStartedAt": now,
            }
        if float(ro.get("phaseStartedAt", now)) > now:
            # Monotonic clock restarted under us (operator restart):
            # re-anchor the dwell rather than waiting forever.
            ro["phaseStartedAt"] = now

        members = fleet.members()
        live = (fleet.live_members() if hasattr(fleet, "live_members")
                else members)
        step = int(ro.get("step", -1))
        traffic = steps[step] if 0 <= step < len(steps) else 0.0
        canary = self._canary_subset(
            ro.get("canaryMembers", []), members, live,
            steps[0] if step < 0 else traffic)
        ro["canaryMembers"] = canary
        ro["trafficPercent"] = traffic
        ro["phase"] = "Shadow" if step < 0 else "Walking"

        # Converge the canary onto the candidate epoch (idempotent:
        # already-installed members no-op; a replica that died and came
        # back, or just joined the subset at this step, installs now).
        res = fleet.broadcast_weights(
            params, version=int(ro["candidate"]["epoch"]), members=canary)
        if res["installed"]:
            ro["candidate"]["epoch"] = max(res["installed"].values())

        verdict = self._judge(svc, ro, cfg, auto, canary,
                              [m for m in members if m not in canary])
        if verdict["outcome"] == "breach":
            return self._rollback(svc, fleet, ro, auto, verdict["evidence"])
        if verdict["outcome"] == "hold":
            ro["gate"] = verdict.get("gate", {})
            self._set_rollout(svc, ro)
            return float(auto["scrapePeriodSeconds"])

        ro["gate"] = verdict.get("gate", {})
        dwell = float(cfg["shadowSeconds"] if step < 0
                      else cfg["stepSeconds"])
        if now - float(ro.get("phaseStartedAt", now)) >= dwell:
            if step + 1 < len(steps):
                ro["step"] = step + 1
                ro["trafficPercent"] = steps[step + 1]
                ro["phase"] = "Walking"
                ro["phaseStartedAt"] = now
                # Widen the subset to the new share NOW — the status
                # this reconcile writes is what the router renders, and
                # N% of traffic must never land on a subset sized for
                # the previous step.
                canary = self._canary_subset(
                    canary, members, live, ro["trafficPercent"])
                ro["canaryMembers"] = canary
                res = fleet.broadcast_weights(
                    params, version=int(ro["candidate"]["epoch"]),
                    members=canary)
                if res["installed"]:
                    ro["candidate"]["epoch"] = max(
                        res["installed"].values())
            else:
                return self._promote(svc, fleet, ro, auto, params)
        self._set_rollout(svc, ro)
        return float(auto["scrapePeriodSeconds"])

    # -- walk mechanics -----------------------------------------------

    @staticmethod
    def _walk_steps(cfg: dict, target: float) -> list[float]:
        """The traffic schedule, clipped to the candidate's declared
        steady-state share and always ending exactly on it."""
        steps = [float(s) for s in cfg["steps"] if 0 < float(s) < target]
        return steps + [target] if target > 0 else steps

    @staticmethod
    def _canary_subset(prev: list[str], members: list[str],
                       live: list[str], traffic: float) -> list[str]:
        """The named replicas holding the candidate epoch at this step:
        ceil(traffic% of the fleet), at least one. Sticky — members
        already canaried stay (their weights are already swapped);
        growth tops up from the TAIL of the sorted member list, the
        same stable end the autoscaler prunes from, so subset identity
        is deterministic and reconstructible."""
        members = sorted(members)
        if not members:
            return []
        want = max(1, math.ceil(len(members) * traffic / 100.0))
        keep = [m for m in members if m in set(prev)][:want]
        pool = [m for m in reversed(members)
                if m not in set(keep) and m in set(live)]
        for m in pool:
            if len(keep) >= want:
                break
            keep.append(m)
        return sorted(keep)

    def _scrape_cohort(self, ns: str, cohort: list[str],
                       staleness_s: float) -> tuple[list[dict], int, bool]:
        """(usable signals, scraped count, any_stale) for a member-name
        cohort. A held (stale) sample is usable for display but poisons
        the verdict — the caller holds instead of judging."""
        signals, scraped, any_stale = [], 0, False
        for m in cohort:
            sig, fresh = self.signal_cache.scrape(
                f"{m}.{ns}:{REST_PORT}", staleness_s)
            if sig is not None:
                signals.append(sig)
                scraped += 1
                any_stale = any_stale or not fresh
        return signals, scraped, any_stale

    def _judge(self, svc: dict, ro: dict, cfg: dict, auto: dict,
               canary: list[str], stable: list[str]) -> dict:
        """Gate verdict for this round: ``pass`` (advance on dwell),
        ``hold`` (stale or incomparable data — never decide on it), or
        ``breach`` (rollback, with evidence). Quorum is judged on
        SCRAPEABLE canary replicas — a dead/unobservable canary is a
        breach class of its own, not a metrics verdict."""
        ns = svc["metadata"]["namespace"]
        staleness = float(auto["signalStalenessSeconds"])
        cand_sigs, cand_n, cand_stale = self._scrape_cohort(
            ns, canary, staleness)
        if canary and cand_n / len(canary) < float(cfg["quorum"]):
            return {"outcome": "breach", "evidence": {
                "reason": "quorum-loss",
                "scrapedCanaries": cand_n,
                "canaryMembers": list(canary),
                "quorum": float(cfg["quorum"]),
            }}
        inc_sigs, _inc_n, inc_stale = self._scrape_cohort(
            ns, stable, staleness)
        if cand_stale or inc_stale:
            return {"outcome": "hold",
                    "gate": {"held": "stale scrape signals"}}
        if not stable or not inc_sigs or not cand_sigs:
            # Nothing to compare against (100% step, incumbent cohort
            # unobservable, or canary not yet emitting): no verdict.
            return {"outcome": "pass", "gate": {}}

        def _p99(sigs, key):
            return max(s.get(key, 0.0) for s in sigs)

        gate: dict = {}
        ratio = float(cfg["gateRatio"])
        for key, label in (("ttft_p99_s", "ttftP99"),
                           ("inter_token_p99_s", "interTokenP99")):
            cand = _p99(cand_sigs, key)
            inc = max(_p99(inc_sigs, key), _BASELINE_FLOOR_S)
            gate[label] = {"candidate": round(cand, 6),
                           "incumbent": round(inc, 6),
                           "limit": round(inc * ratio, 6)}
            if cand > inc * ratio:
                return {"outcome": "breach", "evidence": {
                    "reason": "gate-breach", "signal": label,
                    "candidate": round(cand, 6),
                    "incumbent": round(inc, 6),
                    "gateRatio": ratio,
                    "step": int(ro.get("step", -1)),
                    "trafficPercent": float(ro.get("trafficPercent", 0)),
                }}
        cand_err = _p99(cand_sigs, "error_rate")
        inc_err = _p99(inc_sigs, "error_rate")
        limit = max(inc_err * float(cfg["errorRateRatio"]),
                    float(cfg["errorRateFloor"]))
        gate["errorRate"] = {"candidate": round(cand_err, 6),
                             "incumbent": round(inc_err, 6),
                             "limit": round(limit, 6)}
        if cand_err > limit:
            return {"outcome": "breach", "evidence": {
                "reason": "gate-breach", "signal": "errorRate",
                "candidate": round(cand_err, 6),
                "incumbent": round(inc_err, 6),
                "limit": round(limit, 6),
                "step": int(ro.get("step", -1)),
                "trafficPercent": float(ro.get("trafficPercent", 0)),
            }}
        return {"outcome": "pass", "gate": gate}

    # -- terminal transitions -----------------------------------------

    def _rollback(self, svc: dict, fleet, ro: dict, auto: dict,
                  evidence: dict) -> float:
        """Rollback IS a push: the incumbent's params at a FRESH epoch,
        fleet-wide (the canary subset holds the higher candidate epoch,
        which refuses any replay of the old number — and pushing
        everyone makes the race with a concurrent broadcast converge by
        epoch monotonicity). The routing reset is the phase flip: the
        InferenceServiceController re-renders a plain route the moment
        status.rollout leaves the live phases."""
        evidence["at"] = round(self.clock(), 3)
        ro["phase"] = "RolledBack"
        ro["evidence"] = evidence
        params = self.weights_for(ro["incumbent"]["weightsRef"])
        if params is not None:
            res = fleet.broadcast_weights(params)
            ro["rolledBackEpoch"] = res["version"]
        self._set_rollout(svc, ro)
        log.warning("rollout %s/%s rolled back: %s",
                    svc["metadata"]["namespace"],
                    svc["metadata"]["name"], evidence)
        return float(auto["scrapePeriodSeconds"])

    def _promote(self, svc: dict, fleet, ro: dict, auto: dict,
                 params) -> float | None:
        """The walk completed every gated step: at a 100% target the
        candidate epoch goes fleet-wide (stragglers and revived
        replicas converge on this push); a <100% target leaves the
        declared steady-state split in place."""
        ro["phase"] = "Promoted"
        ro["trafficPercent"] = float(ro["candidate"]["traffic"])
        if float(ro["candidate"]["traffic"]) >= 100.0:
            res = fleet.broadcast_weights(
                params, version=int(ro["candidate"]["epoch"]))
            ro["promotedEpoch"] = res["version"]
        self._set_rollout(svc, ro)
        return float(auto["scrapePeriodSeconds"])

    @staticmethod
    def _converged(fleet) -> bool:
        """One uniform installed epoch across the live fleet."""
        wv = fleet.weights_versions()
        live = (fleet.live_members() if hasattr(fleet, "live_members")
                else fleet.members())
        epochs = {wv["installed"].get(m, 0) for m in live}
        return len(epochs) <= 1

    # -- status plumbing ----------------------------------------------

    def _set_rollout(self, svc: dict, ro: dict) -> None:
        """Write ONLY status.rollout on the live object (refetch +
        reapply on conflict) — the InferenceServiceController owns
        every other status key, and clobbering its fresh replica counts
        with our stale copy would ping-pong the two loops forever."""
        meta = svc["metadata"]

        def _write(client):
            current = client.get_or_none(
                svc["apiVersion"], svc["kind"], meta["name"],
                meta.get("namespace"))
            if current is None:
                return None
            status = dict(current.get("status") or {})
            if status.get("rollout") == ro:
                return current
            status["rollout"] = ro
            current["status"] = status
            return client.update_status(current)

        retry_on_conflict(self.client, _write)
        # Keep the in-memory copy coherent for callers inspecting svc.
        svc.setdefault("status", {})["rollout"] = ro
