"""Profile controller — multi-tenancy.

Port of components/profile-controller (Reconcile at
profile_controller.go:108-206, generateRole :207): each cluster-scoped
Profile expands into the user's namespace, a namespaced-admin Role, a
RoleBinding to the owner subject, and an optional ResourceQuota (the hook
where per-team TPU chip quotas land: `requests.google.com/tpu`).
"""

from __future__ import annotations

from kubeflow_tpu.apis.profiles import PROFILE_KIND, PROFILES_API_VERSION
from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.operators.base import Controller

ADMIN_ROLE = "namespace-admin"


class ProfileController(Controller):
    api_version = PROFILES_API_VERSION
    kind = PROFILE_KIND

    def reconcile(self, profile: dict) -> None:
        name = profile["metadata"]["name"]
        owner = profile.get("spec", {}).get("owner", {})

        if self.client.get_or_none("v1", "Namespace", name) is None:
            ns = k8s.namespace_obj(
                name, labels={"kubeflow-tpu.org/profile": name}
            )
            ns["metadata"]["ownerReferences"] = [k8s.object_ref(profile)]
            self.client.create(ns)

        if self.client.get_or_none(
            "rbac.authorization.k8s.io/v1", "Role", ADMIN_ROLE, name
        ) is None:
            role = k8s.role(
                ADMIN_ROLE, name,
                rules=[k8s.policy_rule(["*"], ["*"], ["*"])],
            )
            self.client.create(role)

        binding_name = f"{ADMIN_ROLE}-binding"
        if owner and self.client.get_or_none(
            "rbac.authorization.k8s.io/v1", "RoleBinding", binding_name, name
        ) is None:
            binding = k8s.role_binding(
                binding_name, name, ADMIN_ROLE,
                subjects=[{
                    "kind": owner.get("kind", "User"),
                    "name": owner.get("name", ""),
                    "apiGroup": "rbac.authorization.k8s.io",
                }],
            )
            self.client.create(binding)

        quota = profile.get("spec", {}).get("resourceQuota")
        if quota and self.client.get_or_none(
            "v1", "ResourceQuota", "profile-quota", name
        ) is None:
            self.client.create({
                "apiVersion": "v1",
                "kind": "ResourceQuota",
                "metadata": k8s.metadata("profile-quota", name),
                "spec": quota,
            })

        if profile.get("status", {}).get("state") != "Ready":
            profile = dict(profile, status={"state": "Ready"})
            self._push_status(profile)  # refetch-and-reapply on conflict
