"""Pipeline-operator entrypoint: `python -m kubeflow_tpu.operators.pipeline`
(the argo workflow-controller + application sync Deployment analogue,
kubeflow/argo/argo.libsonnet:89-165, kubeflow/application/
application.libsonnet:14-60)."""

from __future__ import annotations

from kubeflow_tpu.runtime import controller_main


def main(argv=None) -> int:
    from kubeflow_tpu.operators.pipelines import (
        ApplicationController,
        ScheduledWorkflowController,
        WorkflowController,
    )

    return controller_main(
        argv,
        lambda client: [WorkflowController(client),
                        ScheduledWorkflowController(client),
                        ApplicationController(client)],
        "kubeflow-tpu pipeline (workflow DAG + application) controller",
    )


if __name__ == "__main__":
    raise SystemExit(main())
