"""CRD controllers (the reference's operator layer, in-repo).

The reference imports its training-operator binaries as container images and
ships only their CRDs/RBAC/deployments (SURVEY.md §2.2); the controllers
themselves live here instead:

- :mod:`~kubeflow_tpu.operators.base` — watch+resync reconciler runtime (the
  controller-runtime analogue).
- :mod:`~kubeflow_tpu.operators.jobs` — the training-job controller covering
  JaxJob and the five compatibility kinds (TFJob, PyTorchJob, MXNetJob,
  ChainerJob, MPIJob): gang-scheduled pods, per-framework rendezvous env
  injection, status conditions, restart/backoff/clean-pod policies.
- :mod:`~kubeflow_tpu.operators.notebooks` — Notebook → StatefulSet+Service
  (components/notebook-controller port).
- :mod:`~kubeflow_tpu.operators.profiles` — Profile → namespace+RBAC
  (components/profile-controller port).

The cluster scheduler (gang placement, priorities, preemption) lives in
:mod:`kubeflow_tpu.scheduler` and runs on the same runtime.
"""

from kubeflow_tpu.operators.base import (
    Controller,
    RateLimiter,
    WorkQueue,
    run_controllers,
)
from kubeflow_tpu.operators.jobs import JobController
from kubeflow_tpu.operators.notebooks import NotebookController
from kubeflow_tpu.operators.profiles import ProfileController

__all__ = [
    "Controller",
    "RateLimiter",
    "WorkQueue",
    "run_controllers",
    "JobController",
    "NotebookController",
    "ProfileController",
]
