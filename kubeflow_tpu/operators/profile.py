"""Profile-controller entrypoint: `python -m kubeflow_tpu.operators.profile`
(the profile-controller manager binary, components/profile-controller)."""

from __future__ import annotations

from kubeflow_tpu.runtime import controller_main


def main(argv=None) -> int:
    from kubeflow_tpu.operators.profiles import ProfileController

    return controller_main(
        argv, lambda client: [ProfileController(client)],
        "kubeflow-tpu profile controller",
    )


if __name__ == "__main__":
    raise SystemExit(main())
