"""ExperimentController: closed-loop knob search against serving SLOs.

Katib's experiment loop fused with kubebench's measured runs
(kubeflow/katib studyjobcontroller.libsonnet; kubebench job templates):
reconcile an Experiment by fanning out measured trials of a registered
bench_serving scenario (serving/scenarios.py), feeding each trial's
objective — read from the histogram exposition through the same
``scrape_signals`` vector the autoscaler consumes — back into the
suggestion algorithm, and shipping the winning knob config through the
rollout controller as a candidate version with SLO gates and
auto-rollback as the safety net.

Trial 0 is always the scenario's checked-in defaults: the experiment's
verdict is *improvement over the baseline*, recorded in status, not an
absolute number.

Two trial modes:

- ``inprocess`` (default, the fast path): the trial boots a throwaway
  ContinuousDecoder inside the operator process via the scenario
  registry — no cluster round-trip, used by CI and tests;
- ``job``: the trial renders a **preemptible** JaxJob (low scheduler
  priority — trials are background load) running the same scenario via
  the bench CLI; a preempted trial is re-run with its recorded seed
  rather than poisoning the objective.

Reproducibility: one experiment seed (spec.seed) threads through both
suggestion sampling and scenario traffic generation; each trial's
derived seed is recorded in its status entry so a re-run observes the
same trace.
"""

from __future__ import annotations

import copy
import json
import logging
import os
import time

from kubeflow_tpu.apis.experiment import (
    EXPERIMENT_API_VERSION,
    EXPERIMENT_KIND,
)
from kubeflow_tpu.apis.inference import (
    INFERENCE_API_VERSION,
    INFERENCE_KIND,
    validate_versions,
)
from kubeflow_tpu.apis.jobs import JOBS_API_VERSION
from kubeflow_tpu.apis import scheduling as sched_api
from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.k8s.client import retry_on_conflict
from kubeflow_tpu.operators.base import OPERATOR_METRICS, Controller
from kubeflow_tpu.tuning.suggestions import (
    MedianEarlyStop,
    Observation,
    domains_from_spec,
    get_algorithm,
)

log = logging.getLogger(__name__)

LABEL_EXPERIMENT = "kubeflow-tpu.org/experiment-name"
LABEL_TRIAL = "kubeflow-tpu.org/trial-index"

# Background trials must lose every capacity fight: the scheduler
# preempts lowest-priority first, so trials sit well below the default.
TRIAL_PRIORITY = -100

# Bounded-cardinality experiment metrics (satellite): trial states are a
# closed enum, policies are the _ALGORITHMS registry, and the best-
# objective gauge is labeled by scenario (a small fixed registry) — no
# per-experiment or per-trial label anywhere.
_M_TRIALS = OPERATOR_METRICS.counter(
    "experiment_trials_total",
    "Experiment trials by terminal state", labels=("state",))
_M_BEST = OPERATOR_METRICS.gauge(
    "experiment_best_objective",
    "Best objective value observed, by scenario", labels=("scenario",))
_M_SUGGEST = OPERATOR_METRICS.counter(
    "tuning_suggestions_total",
    "Assignments proposed, by suggestion policy", labels=("policy",))

_TERMINAL = ("Succeeded", "Failed")


def _default_run_trial(scenario: str, assignments: dict, *, seed: int,
                       quick: bool = True) -> dict:
    # Imported lazily: the scenario registry pulls in the serving engine
    # (and jax with it), which job-mode-only deployments never need.
    from kubeflow_tpu.serving import scenarios
    return scenarios.run_trial(scenario, assignments, seed=seed,
                               quick=quick)


class ExperimentController(Controller):
    """Experiment CRD → measured trials → best config → rollout.

    Injectables (tests and CI drive all three):

    - ``run_trial(scenario, assignments, *, seed, quick)`` → trial
      result dict (default: the in-process scenario registry);
    - ``profile_dir`` → directory where per-trial BENCH-style profiles
      are written for ThroughputBook ingestion (default: off);
    - ``clock`` → wall-clock seconds for status timestamps.
    """

    api_version = EXPERIMENT_API_VERSION
    kind = EXPERIMENT_KIND
    resync_seconds = 10.0

    def __init__(self, client, *, run_trial=None, profile_dir=None,
                 clock=time.time):
        super().__init__(client)
        self.run_trial = run_trial or _default_run_trial
        self.profile_dir = profile_dir
        self.clock = clock

    def watched_kinds(self):
        return [(JOBS_API_VERSION, "JaxJob")]

    # -- reconcile ------------------------------------------------------

    def reconcile(self, exp: dict) -> float | None:
        exp = copy.deepcopy(exp)
        spec = exp["spec"]
        status = exp.setdefault("status", {})
        if status.get("state") in _TERMINAL:
            return None

        try:
            scenario, parameters = self._resolve_scenario(spec)
        except Exception as e:
            status["state"] = "Failed"
            status["reason"] = str(e)
            self._push_status(exp)
            return None

        status.setdefault("state", "Running")
        seed = int(spec.get("seed", 0))
        status["seed"] = seed
        trials = status.setdefault("trials", [])

        objective = spec.get("objective", {})
        metric = objective.get("objectiveMetricName",
                               scenario_objective(scenario))
        maximize = objective.get(
            "type", scenario_optimization(scenario)) == "maximize"

        mode = spec.get("trialMode", "inprocess")
        if mode == "job":
            self._collect_job_trials(exp, trials, metric, spec)

        finished = [t for t in trials if t["state"] in _TERMINAL]
        succeeded = [t for t in finished if t["state"] == "Succeeded"
                     and t.get("objectiveValue") is not None]
        failed = [t for t in finished if t["state"] == "Failed"]

        self._update_best(spec, status, succeeded, maximize)

        goal = objective.get("goal")
        best = status.get("bestObjectiveValue")
        goal_met = (goal is not None and best is not None
                    and (best >= goal if maximize else best <= goal))
        max_trials = int(spec.get("maxTrialCount", 12))
        if len(failed) > int(spec.get("maxFailedTrialCount", 3)):
            status["state"] = "Failed"
            status["reason"] = f"{len(failed)} trials failed"
        elif goal_met or len(finished) >= max_trials:
            active = [t for t in trials if t["state"] not in _TERMINAL]
            if not active:
                status["state"] = "Succeeded"
                self._promote(exp, spec, status)
        else:
            self._spawn_trials(exp, spec, scenario, parameters, trials,
                               maximize, metric, mode)
            finished = [t for t in trials if t["state"] in _TERMINAL]
            succeeded = [t for t in finished if t["state"] == "Succeeded"
                         and t.get("objectiveValue") is not None]
            self._update_best(spec, status, succeeded, maximize)
            if (len(finished) >= max_trials
                    and not [t for t in trials
                             if t["state"] not in _TERMINAL]):
                status["state"] = "Succeeded"
                self._promote(exp, spec, status)

        status["completedTrialCount"] = len(
            [t for t in trials if t["state"] in _TERMINAL])
        self._push_status(exp)
        return 1.0 if status["state"] == "Running" else None

    # -- scenario plumbing ------------------------------------------------

    @staticmethod
    def _resolve_scenario(spec: dict):
        """(scenario object | None, parameter list). An explicit
        spec.parameters list wins; otherwise the scenario's registered
        space. A spec naming an unknown scenario fails the experiment."""
        from kubeflow_tpu.serving import scenarios
        sc = scenarios.get_scenario(spec["scenario"])
        if sc.trial is None:
            raise ValueError(
                f"scenario {spec['scenario']!r} has no trial runner")
        parameters = spec.get("parameters") or list(sc.parameters)
        if not parameters:
            raise ValueError(
                f"scenario {spec['scenario']!r} declares no parameters")
        return sc, parameters

    @staticmethod
    def _trial_seed(seed: int, index: int) -> int:
        """Per-trial seed derived from the ONE experiment seed — stable
        across re-runs (a preempted trial re-observes the same trace)."""
        return seed * 100_003 + index

    # -- trial execution --------------------------------------------------

    def _spawn_trials(self, exp: dict, spec: dict, scenario,
                      parameters: list[dict], trials: list[dict],
                      maximize: bool, metric: str, mode: str) -> None:
        active = [t for t in trials if t["state"] not in _TERMINAL]
        budget = min(
            int(spec.get("parallelTrialCount", 2)) - len(active),
            int(spec.get("maxTrialCount", 12)) - len(trials),
        )
        if budget <= 0:
            return
        seed = int(spec.get("seed", 0))
        domains = domains_from_spec(parameters)
        policy = spec.get("algorithm", "tpe")
        # The proposer's stream is keyed off the experiment seed plus the
        # spawn point, so a controller restart replays identical
        # proposals for the same observation history.
        algo = get_algorithm(policy, domains, seed=seed * 1000 + len(trials))
        observations = [
            Observation(
                t["assignments"],
                t["objectiveValue"] if maximize else -t["objectiveValue"])
            for t in trials
            if t["state"] == "Succeeded"
            and t.get("objectiveValue") is not None
        ]
        defaults = dict(getattr(scenario, "defaults", {}) or {})
        for _ in range(budget):
            index = len(trials)
            if index == 0:
                # Baseline: the checked-in defaults, RECORDED as full
                # assignments so the proposers can place it on the unit
                # cube (a knob without a registered default sits at the
                # middle of its range).
                assignments: dict | None = {
                    d.name: defaults.get(d.name, d.from_unit(0.5))
                    for d in domains}
            else:
                assignments = algo.next(observations)
                _M_SUGGEST.labels(policy).inc()
            if assignments is None:  # space exhausted (grid)
                if not [t for t in trials if t["state"] not in _TERMINAL]:
                    exp["status"]["state"] = "Succeeded"
                    self._promote(exp, spec, exp["status"])
                return
            trial = {
                "index": index,
                "assignments": assignments,
                "seed": self._trial_seed(seed, index),
                "state": "Running",
                "mode": mode,
                "retries": 0,
            }
            trials.append(trial)
            if mode == "job":
                self._create_trial_job(exp, trial)
            else:
                self._run_inprocess(exp, spec, trial, metric)
                if trial["state"] == "Succeeded":
                    observations.append(Observation(
                        trial["assignments"],
                        trial["objectiveValue"] if maximize
                        else -trial["objectiveValue"]))

    def _run_inprocess(self, exp: dict, spec: dict, trial: dict,
                       metric: str) -> None:
        try:
            result = self.run_trial(
                spec["scenario"], dict(trial["assignments"]),
                seed=int(trial["seed"]), quick=True)
            value = result["objectives"][metric]
        except Exception as e:
            log.warning("experiment %s trial %d failed: %s",
                        exp["metadata"]["name"], trial["index"], e)
            trial["state"] = "Failed"
            trial["reason"] = str(e)
            _M_TRIALS.labels("failed").inc()
            return
        trial["state"] = "Succeeded"
        trial["objectiveValue"] = float(value)
        trial["objectives"] = {
            k: v for k, v in result["objectives"].items()
            if isinstance(v, (int, float))}
        trial["config"] = result.get("config", "")
        _M_TRIALS.labels("succeeded").inc()
        self._write_profile(exp, trial, result)

    def _write_profile(self, exp: dict, trial: dict, result: dict) -> None:
        """Per-trial BENCH-style profile: the exact shape
        ThroughputBook.from_bench_files ingests ({"parsed": {config,
        tokens_per_sec_per_chip, ...}}), so tuner measurements become
        scheduler capacity knowledge."""
        if not self.profile_dir:
            return
        path = os.path.join(
            self.profile_dir,
            f"BENCH_{exp['metadata']['name']}"
            f"_trial{trial['index']}.json")
        try:
            os.makedirs(self.profile_dir, exist_ok=True)
            with open(path, "w") as f:
                json.dump({"parsed": result}, f, indent=2, default=str)
            trial["profilePath"] = path
        except OSError as e:
            log.warning("profile write failed: %s", e)

    # -- job-mode trials ---------------------------------------------------

    def _trial_job_name(self, exp: dict, trial: dict) -> str:
        suffix = f"-r{trial['retries']}" if trial.get("retries") else ""
        return (f"{exp['metadata']['name']}-trial-"
                f"{trial['index']}{suffix}")

    def _create_trial_job(self, exp: dict, trial: dict) -> None:
        spec = exp["spec"]
        ns = exp["metadata"]["namespace"]
        name = self._trial_job_name(exp, trial)
        job = {
            "apiVersion": JOBS_API_VERSION,
            "kind": "JaxJob",
            "metadata": {
                **k8s.metadata(name, ns),
                "labels": {
                    LABEL_EXPERIMENT: exp["metadata"]["name"],
                    LABEL_TRIAL: str(trial["index"]),
                },
                "ownerReferences": [k8s.object_ref(exp)],
            },
            "spec": {
                # Preemptible background load: the scheduler may evict
                # this trial for any real workload; the controller
                # re-runs it with the same recorded seed.
                "priority": TRIAL_PRIORITY,
                "replicaSpecs": {
                    "Worker": {
                        "replicas": 1,
                        "restartPolicy": "Never",
                        "template": {"spec": {"containers": [{
                            "name": "trial",
                            "image": "kubeflow-tpu/bench:latest",
                            "command": [
                                "python", "bench_serving.py",
                                "--scenario", spec["scenario"],
                                "--seed", str(trial["seed"]),
                                "--quick",
                                "--assignments",
                                json.dumps(trial["assignments"],
                                           sort_keys=True),
                            ],
                        }]}},
                    },
                },
            },
        }
        self.client.create(job)
        trial["jobName"] = name
        trial["state"] = "Running"

    def _collect_job_trials(self, exp: dict, trials: list[dict],
                            metric: str, spec: dict) -> None:
        ns = exp["metadata"]["namespace"]
        stopper = self._early_stopper(spec)
        completed_curves = [
            t.get("curve") for t in trials
            if t["state"] == "Succeeded" and t.get("curve")]
        for trial in trials:
            if trial["state"] in _TERMINAL or "jobName" not in trial:
                continue
            job = self.client.get_or_none(
                JOBS_API_VERSION, "JaxJob", trial["jobName"], ns)
            if job is None:
                continue
            if self._job_preempted(job):
                # A preempted trial's measurement window was poisoned by
                # the eviction: throw the sample away and re-run the
                # SAME assignments at the SAME seed under a fresh job.
                _M_TRIALS.labels("preempted").inc()
                self.client.delete(
                    JOBS_API_VERSION, "JaxJob", trial["jobName"], ns)
                trial["retries"] = int(trial.get("retries", 0)) + 1
                self._create_trial_job(exp, trial)
                continue
            jstatus = job.get("status", {})
            jstate = jstatus.get("state")
            metrics = jstatus.get("metrics", {})
            curve = [(int(s), float(v))
                     for s, v in jstatus.get("metricsHistory", [])]
            if (jstate not in ("Succeeded", "Failed") and stopper
                    and curve
                    and stopper.should_stop(curve, completed_curves)):
                # Early stop: the partial measurement IS the observation
                # (underperforming, not broken).
                self.client.delete(
                    JOBS_API_VERSION, "JaxJob", trial["jobName"], ns)
                trial["state"] = "Succeeded"
                trial["earlyStopped"] = True
                trial["objectiveValue"] = float(curve[-1][1])
                trial["curve"] = [[s, v] for s, v in curve]
                _M_TRIALS.labels("early_stopped").inc()
                continue
            if jstate == "Succeeded":
                trial["state"] = "Succeeded"
                if metric in metrics:
                    trial["objectiveValue"] = float(metrics[metric])
                if curve:
                    trial["curve"] = [[s, v] for s, v in curve]
                _M_TRIALS.labels("succeeded").inc()
            elif jstate == "Failed":
                trial["state"] = "Failed"
                _M_TRIALS.labels("failed").inc()

    @staticmethod
    def _early_stopper(spec: dict) -> MedianEarlyStop | None:
        es = spec.get("earlyStop")
        if not es or es.get("policy", "median") != "median":
            return None
        return MedianEarlyStop(min_trials=int(es.get("minTrials", 3)))

    @staticmethod
    def _job_preempted(job: dict) -> bool:
        meta = job.get("metadata", {})
        if meta.get("annotations", {}).get(sched_api.ANN_PREEMPTED_BY):
            return True
        sched = job.get("status", {}).get("scheduling") or {}
        return bool(sched.get("preemptedBy"))

    # -- verdict + promotion ----------------------------------------------

    def _update_best(self, spec: dict, status: dict, succeeded: list[dict],
                     maximize: bool) -> None:
        if not succeeded:
            return
        best = (max if maximize else min)(
            succeeded, key=lambda t: t["objectiveValue"])
        status["bestObjectiveValue"] = best["objectiveValue"]
        status["bestTrialIndex"] = best["index"]
        status["bestAssignments"] = best["assignments"]
        _M_BEST.labels(spec.get("scenario", "?")).set(
            float(best["objectiveValue"]))
        baseline = next((t for t in succeeded if t["index"] == 0), None)
        if baseline is not None:
            status["baselineObjectiveValue"] = baseline["objectiveValue"]
            base = float(baseline["objectiveValue"])
            if base != 0:
                gain = (float(best["objectiveValue"]) - base) / abs(base)
                if not maximize:
                    gain = -gain
                status["improvementPercent"] = round(gain * 100.0, 3)

    def _promote(self, exp: dict, spec: dict, status: dict) -> None:
        """Ship the winner as a candidate version on the target
        InferenceService: the PR-16 RolloutController walks it under SLO
        gates and rolls back on breach — promotion is recorded here and
        reversible there."""
        promo = spec.get("promotion") or {}
        target = promo.get("target")
        if not target or status.get("bestAssignments") is None:
            return
        min_gain = float(promo.get("minImprovementPercent", 0.0))
        gain = status.get("improvementPercent")
        if gain is None or gain < min_gain:
            status["promotion"] = {
                "target": target, "skipped": True,
                "reason": f"improvement {gain}% below minimum "
                          f"{min_gain}%"}
            return
        ns = exp["metadata"]["namespace"]
        version_name = f"{exp['metadata']['name']}-tuned"
        engine = {k: v for k, v in status["bestAssignments"].items()
                  if k != "trainingSteps"}

        def _write(client):
            svc = client.get_or_none(
                INFERENCE_API_VERSION, INFERENCE_KIND, target, ns)
            if svc is None:
                return None
            sspec = svc.setdefault("spec", {})
            versions = sspec.get("versions") or [{
                "name": "incumbent",
                "weightsRef": promo.get(
                    "weightsRef", sspec.get("model", target)),
                "traffic": 100.0,
            }]
            incumbent = dict(versions[0])
            incumbent["traffic"] = 0.0
            candidate = {
                "name": version_name,
                "weightsRef": incumbent["weightsRef"],
                "traffic": 100.0,
                "engine": engine,
            }
            sspec["versions"] = validate_versions([incumbent, candidate])
            return client.update(svc)

        written = retry_on_conflict(self.client, _write)
        if written is None:
            status["promotion"] = {
                "target": target, "skipped": True,
                "reason": f"InferenceService {ns}/{target} not found"}
            return
        status["promotion"] = {
            "target": target,
            "version": version_name,
            "engine": engine,
            "improvementPercent": gain,
            "at": round(float(self.clock()), 3),
        }
        log.info("experiment %s promoted %s to %s/%s (gain %.2f%%)",
                 exp["metadata"]["name"], engine, ns, target, gain)


def scenario_objective(sc) -> str:
    return getattr(sc, "objective", "tokens_per_sec")


def scenario_optimization(sc) -> str:
    return getattr(sc, "optimization", "maximize")
