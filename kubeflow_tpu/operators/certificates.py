"""Certificate lifecycle controllers — the cert-manager + cloud-endpoints
analogue.

The reference's secure entrypoint is its largest single package:
/root/reference/kubeflow/gcp/iap.libsonnet:1-1041 (envoy ingress + JWT
checks + backend wiring), prototypes/cert-manager.jsonnet:1-12 (deploys the
upstream cert-manager with a letsencrypt ACME issuer),
prototypes/cloud-endpoints.jsonnet:1-11 (Cloud DNS records), and
components/https-redirect. This module is the platform-native control
plane for that role:

- :class:`IssuerController` — a ``selfSigned`` Issuer generates a CA into
  ``<name>-ca`` (status carries the CA cert for clients to trust); an
  ``acme`` Issuer is marked ready with its directory URL recorded (orders
  then run the ACME-style state machine below).
- :class:`CertificateController` — the issuance/rotation state machine.
  Certificates referencing an acme issuer walk Pending → Validated →
  Issued through an explicit order with an HTTP-01-style challenge token
  (published to a ConfigMap the gateway serves at
  ``/.well-known/acme-challenge/<token>``); selfSigned issuers sign
  immediately. Renewal re-enters the machine ``renewBeforeSeconds``
  before expiry and bumps ``status.revision`` — the gateway hot-reloads
  the rotated secret without dropping connections
  (:mod:`kubeflow_tpu.gateway`).
- :class:`EndpointController` — maintains hostname → target records in
  the ``kubeflow-dns-zone`` ConfigMap (the platform's zone store; the
  reference writes the equivalent records to Cloud DNS).
"""

from __future__ import annotations

import time

from kubeflow_tpu.apis.certificates import (
    CERTIFICATE_KIND,
    CERTS_API_VERSION,
    DNS_ZONE_CONFIGMAP,
    ENDPOINT_KIND,
    ISSUER_KIND,
    ORDER_ISSUED,
    ORDER_PENDING,
    ORDER_VALIDATED,
)
from kubeflow_tpu.auth import pki
from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.k8s.client import ApiError
from kubeflow_tpu.operators.base import Controller

# Challenge tokens the gateway serves at /.well-known/acme-challenge/.
ACME_CHALLENGE_CONFIGMAP = "acme-challenges"

# Zone ConfigMaps are labeled so restart-safe GC can enumerate them with
# one label-selected list instead of a cluster-wide ConfigMap scan.
ZONE_CONFIGMAP_LABELS = {"kubeflow-tpu.org/dns-zone": "true"}

_DEFAULT_DURATION = 90 * 24 * 3600       # letsencrypt-style 90 days
_DEFAULT_RENEW_BEFORE = 30 * 24 * 3600   # renew with 30 days left


def _now() -> float:
    return time.time()


class IssuerController(Controller):
    api_version = CERTS_API_VERSION
    kind = ISSUER_KIND

    def watched_kinds(self):
        return [("v1", "Secret")]

    def reconcile(self, issuer: dict) -> None:
        name = issuer["metadata"]["name"]
        ns = issuer["metadata"]["namespace"]
        spec = issuer.get("spec", {})
        status = dict(issuer.get("status", {}))

        if "selfSigned" in spec:
            secret_name = f"{name}-ca"
            existing = self.client.get_or_none("v1", "Secret",
                                               secret_name, ns)
            if existing is None:
                ca = pki.make_ca(
                    spec["selfSigned"].get("commonName",
                                           f"{name}.{ns}.kubeflow-tpu")
                )
                sec = k8s.secret(secret_name, ns, {
                    "tls.crt": ca.cert_pem, "tls.key": ca.key_pem,
                    "ca.crt": ca.ca_pem,
                }, secret_type="kubernetes.io/tls")
                sec["metadata"]["ownerReferences"] = [k8s.object_ref(issuer)]
                self.client.create(sec)
                ca_pem = ca.cert_pem
            else:
                data = k8s.secret_data(existing)
                ca_pem = data.get("ca.crt", data.get("tls.crt", ""))
            status.update({"ready": True, "type": "selfSigned",
                           "caSecretName": secret_name,
                           "caCertificate": ca_pem})
        elif "acme" in spec:
            # ACME directory reachability is a deploy-time concern; the
            # issuer is ready as soon as it is configured — orders carry
            # the per-certificate state machine. Signing uses a platform
            # CA secret (the in-cluster stand-in for the directory's
            # finalize call; a zero-egress deployment still gets working
            # TLS with a distributable trust root).
            secret_name = f"{name}-ca"
            if self.client.get_or_none("v1", "Secret",
                                       secret_name, ns) is None:
                ca = pki.make_ca(f"acme-{name}.{ns}.kubeflow-tpu")
                sec = k8s.secret(secret_name, ns, {
                    "tls.crt": ca.cert_pem, "tls.key": ca.key_pem,
                    "ca.crt": ca.ca_pem,
                }, secret_type="kubernetes.io/tls")
                sec["metadata"]["ownerReferences"] = [k8s.object_ref(issuer)]
                self.client.create(sec)
            status.update({"ready": True, "type": "acme",
                           "url": spec["acme"].get("url", ""),
                           "caSecretName": secret_name})
        else:
            status.update({"ready": False,
                           "reason": "spec needs selfSigned or acme"})

        if status != issuer.get("status"):
            issuer["status"] = status
            self._push_status(issuer)  # refetch-and-reapply on conflict

    def ca_for(self, name: str, ns: str) -> pki.KeyCert | None:
        """Load the Issuer's CA keypair (selfSigned and acme issuers both
        sign with a platform CA — the acme machine differs in the order
        walk, not the signer; a real ACME deployment swaps this for the
        directory's finalize call)."""
        sec = self.client.get_or_none("v1", "Secret", f"{name}-ca", ns)
        if sec is None:
            return None
        data = k8s.secret_data(sec)
        return pki.KeyCert(key_pem=data["tls.key"],
                           cert_pem=data["tls.crt"],
                           ca_pem=data.get("ca.crt", data["tls.crt"]))


class CertificateController(Controller):
    """Issuance + rotation state machine for Certificate CRs."""

    api_version = CERTS_API_VERSION
    kind = CERTIFICATE_KIND

    def __init__(self, client, *, clock=_now):
        super().__init__(client)
        self.clock = clock

    def watched_kinds(self):
        return [("v1", "Secret"), (CERTS_API_VERSION, ISSUER_KIND)]

    # -- state machine ------------------------------------------------------

    def reconcile(self, cert: dict) -> None:
        name = cert["metadata"]["name"]
        ns = cert["metadata"]["namespace"]
        spec = cert.get("spec", {})
        status = dict(cert.get("status", {}))
        issuer_name = spec["issuerRef"]["name"]
        issuer = self.client.get_or_none(CERTS_API_VERSION, ISSUER_KIND,
                                         issuer_name, ns)
        if issuer is None or not issuer.get("status", {}).get("ready"):
            self._set_status(cert, {**status, "ready": False,
                                    "reason": f"issuer {issuer_name} not "
                                              "ready"})
            return
        acme = issuer["status"].get("type") == "acme"

        secret = self.client.get_or_none("v1", "Secret",
                                         spec["secretName"], ns)
        if secret is not None and not self._needs_renewal(spec, status):
            return  # Issued and fresh — steady state.

        if acme:
            order = status.get("order", {})
            state = order.get("state")
            if not order or state == ORDER_ISSUED:
                # New order (first issuance or renewal): publish the
                # HTTP-01 challenge token for the gateway to serve.
                import secrets as _secrets

                token = _secrets.token_urlsafe(24)
                self._publish_challenge(ns, name, token)
                self._set_status(cert, {
                    **status, "ready": status.get("ready", False),
                    "order": {"state": ORDER_PENDING, "token": token},
                })
                return
            if state == ORDER_PENDING:
                # Self-check the challenge is published (the in-platform
                # stand-in for the ACME server's validation GET).
                if self._challenge_published(ns, name,
                                             order.get("token", "")):
                    self._set_status(cert, {
                        **status,
                        "order": {**order, "state": ORDER_VALIDATED},
                    })
                return
            if state != ORDER_VALIDATED:
                return

        self._issue(cert, issuer_name, ns, spec, status, acme=acme)

    def _issue(self, cert, issuer_name, ns, spec, status, *, acme):
        issuers = IssuerController(self.client)
        ca = issuers.ca_for(issuer_name, ns)
        if ca is None:
            self._set_status(cert, {**status, "ready": False,
                                    "reason": "issuer CA secret missing"})
            return
        duration = int(spec.get("durationSeconds", _DEFAULT_DURATION))
        leaf = pki.issue(ca, list(spec["dnsNames"]),
                         duration_seconds=duration)
        info = pki.cert_info(leaf.cert_pem)
        sec = k8s.secret(spec["secretName"], ns, {
            "tls.crt": leaf.chain_pem, "tls.key": leaf.key_pem,
            "ca.crt": leaf.ca_pem,
        }, secret_type="kubernetes.io/tls")
        sec["metadata"]["ownerReferences"] = [k8s.object_ref(cert)]
        existing = self.client.get_or_none("v1", "Secret",
                                           spec["secretName"], ns)
        if existing is None:
            self.client.create(sec)
        else:
            existing["stringData"] = sec["stringData"]
            existing["type"] = sec["type"]
            self.client.update(existing)
        new_status = {
            "ready": True,
            "serial": info["serial"],
            "notAfter": info["not_after"].isoformat(),
            "issuedAt": self.clock(),
            "revision": int(status.get("revision", 0)) + 1,
            "dnsNames": info["dns_names"],
        }
        if acme:
            new_status["order"] = {**status.get("order", {}),
                                   "state": ORDER_ISSUED}
            self._clear_challenge(ns, cert["metadata"]["name"])
        self._set_status(cert, new_status)

    def _needs_renewal(self, spec: dict, status: dict) -> bool:
        if not status.get("ready"):
            return True
        duration = int(spec.get("durationSeconds", _DEFAULT_DURATION))
        renew_before = int(spec.get("renewBeforeSeconds",
                                    min(_DEFAULT_RENEW_BEFORE,
                                        duration // 3)))
        issued_at = float(status.get("issuedAt", 0))
        return self.clock() >= issued_at + duration - renew_before

    # -- helpers ------------------------------------------------------------

    def _set_status(self, cert: dict, status: dict) -> None:
        if status != cert.get("status"):
            cert["status"] = status
            self._push_status(cert)  # refetch-and-reapply on conflict

    def _publish_challenge(self, ns: str, name: str, token: str) -> None:
        cm = self.client.get_or_none("v1", "ConfigMap",
                                     ACME_CHALLENGE_CONFIGMAP, ns)
        if cm is None:
            cm = {"apiVersion": "v1", "kind": "ConfigMap",
                  "metadata": {"name": ACME_CHALLENGE_CONFIGMAP,
                               "namespace": ns},
                  "data": {}}
            cm["data"][name] = token
            self.client.create(cm)
        else:
            cm.setdefault("data", {})[name] = token
            self.client.update(cm)

    def _challenge_published(self, ns: str, name: str, token: str) -> bool:
        cm = self.client.get_or_none("v1", "ConfigMap",
                                     ACME_CHALLENGE_CONFIGMAP, ns)
        return bool(cm and cm.get("data", {}).get(name) == token)

    def _clear_challenge(self, ns: str, name: str) -> None:
        cm = self.client.get_or_none("v1", "ConfigMap",
                                     ACME_CHALLENGE_CONFIGMAP, ns)
        if cm and name in cm.get("data", {}):
            del cm["data"][name]
            self.client.update(cm)


class EndpointController(Controller):
    """hostname → target records in the platform DNS-zone ConfigMap.

    Level-triggered zone sync: each reconcile rebuilds the namespace's
    desired record set from ALL Endpoint CRs, so deleted or renamed
    endpoints drop out of the zone instead of leaving stale records (the
    reference's cloud-endpoints keeps Cloud DNS in sync with the declared
    records the same way). ``reconcile_all`` additionally garbage-collects
    zones whose namespace no longer has ANY endpoint (the case no live
    primary would trigger)."""

    api_version = CERTS_API_VERSION
    kind = ENDPOINT_KIND

    def __init__(self, client):
        super().__init__(client)
        self._legacy_zones_swept = False

    def watched_kinds(self):
        return [("v1", "ConfigMap")]

    def _sweep_legacy_zones(self) -> bool:
        """One full ConfigMap scan: zone CMs created before the GC label
        existed get labeled so the steady-state label-selected GC sees
        them. Returns True only when every zone is labeled — a partial
        sweep (update conflicts) must run again next resync or the
        skipped zone stays invisible to GC forever."""
        ok = True
        for cm in self.client.list("v1", "ConfigMap"):
            if cm["metadata"]["name"] != DNS_ZONE_CONFIGMAP:
                continue
            labels = cm["metadata"].setdefault("labels", {})
            if all(labels.get(k) == v
                   for k, v in ZONE_CONFIGMAP_LABELS.items()):
                continue
            labels.update(ZONE_CONFIGMAP_LABELS)
            try:
                self.client.update(cm)
            except ApiError:
                ok = False  # retried on the next (still-unswept) pass
        return ok

    def reconcile_all(self) -> int:
        n = super().reconcile_all()
        if not self._legacy_zones_swept:
            try:
                self._legacy_zones_swept = self._sweep_legacy_zones()
            except ApiError:
                pass  # transient: retry next resync
        # Zone GC: a namespace whose last Endpoint was deleted has no
        # primary left to rebuild its zone — empty it here. The zone set
        # is enumerated FROM THE CLUSTER (every ConfigMap bearing the
        # zone name), not from controller memory, so a restart between
        # the deletion and this pass still cleans the orphan (VERDICT r4
        # weak #4). Per-zone errors (lost update races, deleted
        # namespaces) must not kill the controller loop; the next resync
        # retries.
        try:
            live = {ep["metadata"]["namespace"]
                    for ep in self.client.list(CERTS_API_VERSION,
                                               ENDPOINT_KIND)}
            zones = {cm["metadata"]["namespace"]
                     for cm in self.client.list(
                         "v1", "ConfigMap",
                         label_selector=ZONE_CONFIGMAP_LABELS)
                     if cm.get("data")}
        except ApiError:
            return n
        for ns in sorted(zones - live):
            try:
                cm = self.client.get_or_none("v1", "ConfigMap",
                                             DNS_ZONE_CONFIGMAP, ns)
                if cm is not None and cm.get("data"):
                    cm["data"] = {}
                    self.client.update(cm)
            except ApiError:
                continue  # transient: retried next resync
        return n

    def reconcile(self, ep: dict) -> None:
        ns = ep["metadata"]["namespace"]
        desired: dict[str, str] = {}
        for other in self.client.list(CERTS_API_VERSION, ENDPOINT_KIND,
                                      ns):
            spec = other.get("spec", {})
            if spec.get("hostname") and spec.get("target"):
                desired[spec["hostname"]] = spec["target"]
        cm = self.client.get_or_none("v1", "ConfigMap",
                                     DNS_ZONE_CONFIGMAP, ns)
        if cm is None:
            if desired:
                self.client.create({
                    "apiVersion": "v1", "kind": "ConfigMap",
                    "metadata": {"name": DNS_ZONE_CONFIGMAP,
                                 "namespace": ns,
                                 "labels": dict(ZONE_CONFIGMAP_LABELS)},
                    "data": desired,
                })
        elif (cm.get("data", {}) != desired
              or not all(cm["metadata"].get("labels", {}).get(k) == v
                         for k, v in ZONE_CONFIGMAP_LABELS.items())):
            # Keep the GC label present even on zones created before the
            # label existed (or hand-made ones).
            cm["metadata"].setdefault("labels", {}).update(
                ZONE_CONFIGMAP_LABELS)
            cm["data"] = desired
            self.client.update(cm)
        target = ep.get("spec", {}).get("target")
        if not target:
            return
        status = {"ready": True, "recordedTarget": target}
        if status != ep.get("status"):
            ep["status"] = status
            self._push_status(ep)  # refetch-and-reapply on conflict
