"""BenchmarkJob-controller entrypoint:
`python -m kubeflow_tpu.operators.benchmark` (the kubebench-operator,
kubeflow/kubebench/prototypes/kubebench-operator.jsonnet)."""

from __future__ import annotations

from kubeflow_tpu.runtime import controller_main


def main(argv=None) -> int:
    from kubeflow_tpu.benchmark.controller import BenchmarkJobController

    return controller_main(
        argv, lambda client: [BenchmarkJobController(client)],
        "kubeflow-tpu benchmark controller",
    )


if __name__ == "__main__":
    raise SystemExit(main())
