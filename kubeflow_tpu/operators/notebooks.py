"""Notebook controller.

Port of components/notebook-controller: Notebook CR → StatefulSet + Service,
status mirrored from the pod's container state
(notebook_controller.go:148-263, generateStatefulSet :265, generateService
:313). TPU-native twist: a notebook may request TPU chips, which adds the
`google.com/tpu` resource and the GKE TPU node selector instead of
nvidia.com/gpu.
"""

from __future__ import annotations

import copy

from kubeflow_tpu.apis.notebooks import (
    NOTEBOOKS_API_VERSION,
    NOTEBOOK_KIND,
    NOTEBOOK_PORT,
)
from kubeflow_tpu.manifests.images import NOTEBOOK as DEFAULT_NOTEBOOK_IMAGE
from kubeflow_tpu.apis.jobs import TPU_RESOURCE
from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.operators.base import Controller
from kubeflow_tpu.operators.jobs import GKE_TPU_ACCEL_SELECTOR

LABEL_NOTEBOOK = "kubeflow-tpu.org/notebook-name"


class NotebookController(Controller):
    api_version = NOTEBOOKS_API_VERSION
    kind = NOTEBOOK_KIND

    def watched_kinds(self):
        return [("apps/v1", "StatefulSet"), ("v1", "Pod")]

    def reconcile(self, nb: dict) -> None:
        nb = copy.deepcopy(nb)
        name = nb["metadata"]["name"]
        ns = nb["metadata"]["namespace"]

        sts = self._desired_statefulset(nb)
        existing = self.client.get_or_none("apps/v1", "StatefulSet", name, ns)
        if existing is None:
            self.client.create(sts)
        elif (
            existing.get("spec", {}).get("template") != sts["spec"]["template"]
            or existing.get("spec", {}).get("replicas") != sts["spec"]["replicas"]
        ):
            existing["spec"] = sts["spec"]
            self.client.update(existing)

        if self.client.get_or_none("v1", "Service", name, ns) is None:
            svc = k8s.service(
                name=name, namespace=ns,
                selector={LABEL_NOTEBOOK: name},
                ports=[{"name": "notebook", "port": NOTEBOOK_PORT,
                        "targetPort": NOTEBOOK_PORT}],
                labels={LABEL_NOTEBOOK: name},
            )
            svc["metadata"]["ownerReferences"] = [k8s.object_ref(nb)]
            self.client.create(svc)

        self._update_status(nb)

    def _desired_statefulset(self, nb: dict) -> dict:
        """Wrap the CR's pod template in a 1-replica StatefulSet, filling in
        a default jupyter container when the template is empty and expanding
        the tpu block into resources + node selector (the numGpus analogue)."""
        name = nb["metadata"]["name"]
        ns = nb["metadata"]["namespace"]
        spec = nb.get("spec", {})
        template = copy.deepcopy(spec.get("template", {})) or {}
        pod_spec = template.setdefault("spec", {})
        if not pod_spec.get("containers"):
            pod_spec["containers"] = [
                k8s.container(
                    "notebook",
                    DEFAULT_NOTEBOOK_IMAGE,
                    args=[
                        "jupyter", "lab", "--ip=0.0.0.0",
                        f"--port={NOTEBOOK_PORT}", "--no-browser",
                        "--allow-root",
                        f"--NotebookApp.base_url=/notebook/{ns}/{name}",
                    ],
                    ports={"notebook": NOTEBOOK_PORT},
                )
            ]
        tpu = spec.get("tpu", {})
        if tpu.get("chips"):
            main = pod_spec["containers"][0]
            resources = main.setdefault("resources", {})
            resources.setdefault("limits", {})[TPU_RESOURCE] = tpu["chips"]
            if tpu.get("accelerator"):
                pod_spec.setdefault("nodeSelector", {})[
                    GKE_TPU_ACCEL_SELECTOR
                ] = tpu["accelerator"]
        tmeta = template.setdefault("metadata", {})
        tmeta.setdefault("labels", {})[LABEL_NOTEBOOK] = name

        sts = {
            "apiVersion": "apps/v1",
            "kind": "StatefulSet",
            "metadata": k8s.metadata(name, ns, {LABEL_NOTEBOOK: name}),
            "spec": {
                "serviceName": name,
                "replicas": 0 if spec.get("suspend") else 1,
                "selector": {"matchLabels": {LABEL_NOTEBOOK: name}},
                "template": template,
            },
        }
        sts["metadata"]["ownerReferences"] = [k8s.object_ref(nb)]
        return sts

    def _update_status(self, nb: dict) -> None:
        """Mirror pod container state into status (the reference copies the
        first container state verbatim, notebook_controller.go:232-256)."""
        name = nb["metadata"]["name"]
        ns = nb["metadata"]["namespace"]
        pods = self.client.list(
            "v1", "Pod", ns, label_selector={LABEL_NOTEBOOK: name}
        )
        status: dict = {"readyReplicas": 0, "containerState": {}}
        for pod in pods:
            phase = pod.get("status", {}).get("phase")
            if phase == "Running":
                status["readyReplicas"] += 1
            cstates = pod.get("status", {}).get("containerStatuses", [])
            if cstates:
                status["containerState"] = cstates[0].get("state", {})
        nb = copy.deepcopy(nb)
        nb["status"] = status
        self._push_status(nb)  # refetch-and-reapply on conflict
