"""RLJob controller: lower one RL workload into two scheduler-managed
gangs.

One RLJob CR becomes

- ``<name>-learner`` — a JaxJob running the minimal RL learner loop
  (``python -m kubeflow_tpu.train.rl``) at HIGH priority, non-
  preemptible: the learner is the job; killing it loses optimizer
  state between checkpoints.
- ``<name>-actors`` — a JaxJob whose workers each run a continuous-
  decoding model server (the rollout fleet) at LOW priority,
  preemptible, and ELASTIC over ``[minReplicas, maxReplicas]`` hosts:
  the PR-10/14 gang scheduler may shrink the pool live or preempt it
  outright to seat higher-priority work — losing actors costs rollout
  throughput, never correctness, and the learner's next weight push
  re-converges whatever comes back.

Both children carry ``spec.priority``/``spec.queue``, which opts them
into scheduler-managed gang placement (apis/scheduling.py): the
learner gang admits all-or-nothing, the actor pool is the first
capacity reclaimed under pressure. The learner reaches its actors
server-to-server (headless-service pod DNS, the same addressing the
gang rendezvous uses) and streams weights at their ``:weights``
endpoints — bytes never transit the gateway.

Runs on the self-healing :class:`~kubeflow_tpu.operators.base.Controller`
runtime like every other controller in the manager.
"""

from __future__ import annotations

import copy
import json
import logging

from kubeflow_tpu.apis import jobs as jobs_api
from kubeflow_tpu.apis.rl import (
    DEFAULT_ACTOR_PRIORITY,
    DEFAULT_LEARNER_PRIORITY,
    DEFAULT_PUSH_EVERY_STEPS,
    DEFAULT_WEIGHTS_MAX_LAG,
    RL_API_VERSION,
    RL_KIND,
    RLJobValidationError,
    validate_rl_job,
)
from kubeflow_tpu.k8s import objects as k8s
from kubeflow_tpu.manifests import images
from kubeflow_tpu.operators.base import Controller

log = logging.getLogger(__name__)

REST_PORT = 8500
RLJOB_LABEL = "kubeflow-tpu.org/rl-job"
ROLE_LABEL = "kubeflow-tpu.org/rl-role"

# Env var carrying the actor pool's model-server addresses into the
# learner pod (comma-separated host:port).
ENV_RL_ACTORS = "KUBEFLOW_TPU_RL_ACTORS"


def _phase_of(children: list[dict]) -> str:
    """Aggregate child JaxJob states into one RLJob phase. The LEARNER
    decides success (actors serve until torn down); any failed child
    fails the job."""
    states = [((c.get("status") or {}).get("state") or "Pending")
              for c in children]
    if any(s == "Failed" for s in states):
        return "Failed"
    if not children:
        return "Pending"
    learner_state = states[0]
    if learner_state == "Succeeded":
        return "Succeeded"
    if any(s == "Running" for s in states):
        return "Running"
    return "Pending"


class RLJobController(Controller):
    """RLJob CR → learner JaxJob + elastic preemptible actor JaxJob."""

    api_version = RL_API_VERSION
    kind = RL_KIND

    def watched_kinds(self):
        return [(jobs_api.JOBS_API_VERSION, jobs_api.JAX_JOB_KIND)]

    # -- child shaping -------------------------------------------------

    @staticmethod
    def learner_name(name: str) -> str:
        return f"{name}-learner"

    @staticmethod
    def actors_name(name: str) -> str:
        return f"{name}-actors"

    @staticmethod
    def actor_addrs(name: str, ns: str, replicas: int) -> list[str]:
        """Actor model-server addresses, one per worker pod, the pod-DNS
        spelling the JaxJob headless service resolves."""
        actors = RLJobController.actors_name(name)
        return [f"{actors}-worker-{i}.{actors}.{ns}:{REST_PORT}"
                for i in range(replicas)]

    def _learner_job(self, rl: dict) -> dict:
        name = rl["metadata"]["name"]
        ns = rl["metadata"]["namespace"]
        spec = rl.get("spec", {})
        learner = spec.get("learner") or {}
        rollout = spec.get("rollout") or {}
        weights = spec.get("weights") or {}
        actors = spec.get("actors") or {}
        replicas = int(learner.get("replicas", 1))
        cfg = {
            "model": spec["model"],
            "steps": int(learner.get("steps", 100)),
            "batch_size": int(learner.get("batchSize", 4)),
            "push_every_steps": int(learner.get(
                "pushEverySteps", DEFAULT_PUSH_EVERY_STEPS)),
            "prompt_len": int(rollout.get("promptLen", 8)),
            "max_new_tokens": int(rollout.get("maxNewTokens", 16)),
            "weights_max_lag": int(weights.get(
                "maxLag", DEFAULT_WEIGHTS_MAX_LAG)),
        }
        if learner.get("optimizer"):
            cfg["optimizer"] = dict(learner["optimizer"])
        template = {
            "spec": {
                "containers": [
                    k8s.container(
                        "learner",
                        spec.get("image") or images.PLATFORM,
                        command=["python", "-m", "kubeflow_tpu.train.rl",
                                 json.dumps(cfg, sort_keys=True)],
                        env={ENV_RL_ACTORS: ",".join(self.actor_addrs(
                            name, ns,
                            int(actors.get("replicas", 2))))},
                        resources=jobs_api.tpu_resources(
                            int(learner.get("tpuChipsPerReplica", 0))),
                    )
                ],
                "restartPolicy": "Never",
            }
        }
        job = {
            "apiVersion": jobs_api.JOBS_API_VERSION,
            "kind": jobs_api.JAX_JOB_KIND,
            "metadata": k8s.metadata(
                self.learner_name(name), ns,
                {RLJOB_LABEL: name, ROLE_LABEL: "learner"}),
            "spec": {
                "replicaSpecs": {
                    "Worker": {"replicas": replicas,
                               "restartPolicy": "OnFailure",
                               "template": template}
                },
                # Scheduler-managed gang at the HIGH priority: all-or-
                # nothing admission, never sacrificed for its own
                # actors.
                "priority": int(learner.get(
                    "priority", DEFAULT_LEARNER_PRIORITY)),
                "preemptible": False,
                "runPolicy": {"cleanPodPolicy": "Running"},
            },
        }
        if learner.get("queue"):
            job["spec"]["queue"] = learner["queue"]
        if spec.get("tpu"):
            job["spec"]["tpu"] = dict(spec["tpu"])
        return job

    def _actors_job(self, rl: dict) -> dict:
        name = rl["metadata"]["name"]
        ns = rl["metadata"]["namespace"]
        spec = rl.get("spec", {})
        actors = spec.get("actors") or {}
        replicas = int(actors.get("replicas", 2))
        lo = int(actors.get("minReplicas", replicas))
        hi = int(actors.get("maxReplicas", max(replicas, lo)))
        engine = dict(actors.get("engine") or {})
        # The rollout fleet serves the live weight-push path, which
        # rides the paged pool; continuous mode is what update_weights
        # swaps under.
        engine.setdefault("kv_layout", "paged")
        args = [f"--model-name={spec['model']}",
                f"--rest-port={REST_PORT}",
                "--decode-mode=continuous"]
        for key in sorted(engine):
            val = engine[key]
            flag = "--" + key.replace("_", "-")
            if isinstance(val, bool):
                if val:
                    args.append(flag)
            else:
                args.append(f"{flag}={val}")
        template = {
            "spec": {
                "containers": [
                    k8s.container(
                        "actor",
                        spec.get("image") or images.PLATFORM,
                        command=["python", "-m", "kubeflow_tpu.serving"],
                        args=args,
                        ports={"rest": REST_PORT},
                        resources=jobs_api.tpu_resources(
                            int(actors.get("tpuChipsPerReplica", 0))),
                    )
                ],
                "restartPolicy": "Never",
            }
        }
        job = {
            "apiVersion": jobs_api.JOBS_API_VERSION,
            "kind": jobs_api.JAX_JOB_KIND,
            "metadata": k8s.metadata(
                self.actors_name(name), ns,
                {RLJOB_LABEL: name, ROLE_LABEL: "actors"}),
            "spec": {
                "replicaSpecs": {
                    "Worker": {"replicas": replicas,
                               "restartPolicy": "OnFailure",
                               "template": template}
                },
                # LOW priority + preemptible + elastic: the first
                # capacity the scheduler reclaims, shrunk live before
                # killed (PR-14), and rollouts resume on whatever the
                # next weight push finds.
                "priority": int(actors.get(
                    "priority", DEFAULT_ACTOR_PRIORITY)),
                "preemptible": True,
                "elastic": {"minReplicas": lo,
                            "maxReplicas": max(hi, lo)},
                "runPolicy": {"cleanPodPolicy": "Running"},
            },
        }
        if actors.get("queue"):
            job["spec"]["queue"] = actors["queue"]
        if spec.get("tpu"):
            job["spec"]["tpu"] = dict(spec["tpu"])
        return job

    # -- reconcile -----------------------------------------------------

    def reconcile(self, rl: dict) -> None:
        rl = copy.deepcopy(rl)
        name = rl["metadata"]["name"]
        ns = rl["metadata"]["namespace"]
        try:
            validate_rl_job(rl)
        except RLJobValidationError as e:
            rl["status"] = {**(rl.get("status") or {}),
                            "phase": "Failed", "reason": str(e)}
            self._push_status(rl)
            return
        ref = k8s.object_ref(rl)
        children = []
        for child in (self._learner_job(rl), self._actors_job(rl)):
            child["metadata"]["ownerReferences"] = [ref]
            existing = self.client.get_or_none(
                child["apiVersion"], child["kind"],
                child["metadata"]["name"], ns)
            if existing is None:
                self.client.create(child)
                children.append(child)
            else:
                if existing.get("spec") != child["spec"]:
                    existing["spec"] = child["spec"]
                    existing = self.client.update(existing) or existing
                children.append(existing)
        status = {
            "phase": _phase_of(children),
            "learner": {
                "job": self.learner_name(name),
                "state": ((children[0].get("status") or {})
                          .get("state") or "Pending"),
            },
            "actors": {
                "job": self.actors_name(name),
                "state": ((children[1].get("status") or {})
                          .get("state") or "Pending"),
                "replicas": int((rl["spec"].get("actors") or {})
                                .get("replicas", 2)),
            },
        }
        # Surface the learner's published metrics (train.rl publishes
        # into its job status like every training loop) so one kubectl
        # get shows the loop's weight-push progress.
        learner_metrics = ((children[0].get("status") or {})
                           .get("metrics") or {})
        if "weights_version" in learner_metrics:
            status["weightsVersion"] = int(
                learner_metrics["weights_version"])
        rl["status"] = {**(rl.get("status") or {}), **status}
        self._push_status(rl)
